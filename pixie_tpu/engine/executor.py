"""Fragment executor: lowers a plan DAG to jitted batch kernels and runs it.

This replaces the reference's push-based ExecutionGraph interpreter
(src/carnot/exec/exec_graph.cc:177-295, exec_node.h Prepare/Open/Consume/Generate)
with compilation: every maximal Source→(Map|Filter|Limit)*→(Agg|Sink) chain
becomes ONE jitted function over fixed-shape padded batches.  Filters never
compact on device — they refine a validity mask (XLA static shapes); compaction
happens host-side at sinks.  Blocking aggregates carry a device-resident state
pytree across batches (the streaming loop is host-driven), exactly the structure
that later distributes: the same state merged over a mesh axis with collectives.

Blocking operators (Agg finalize, Join, Union) materialize host batches; chains
re-stream from those.  Joins/unions run host-side in numpy in v1 (they see small
aggregated inputs in the target workloads); the device hash-join is a perf-phase
upgrade tracked in SURVEY.md §7.

Group-by strategy (see ops/groupby.py): every key must be reducible to a dense
code — dictionary columns natively, raw int columns via a query-time dictionary
built in a host pre-scan of the cursor snapshot, and `px.bin(time)`-derived
window keys via range arithmetic. Anything else is rejected until the sort-based
fallback lands.
"""
from __future__ import annotations

import contextlib as _contextlib
import dataclasses
import time as _time
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from pixie_tpu.engine.eval import ExprCompiler, SVal, apply_lut, apply_lut_np
from pixie_tpu.engine import autotune as _autotune
from pixie_tpu.engine import resident, transfer
from pixie_tpu.native import codegen as _codegen
from pixie_tpu.engine.result import QueryResult
from pixie_tpu.plan.plan import (
    AggOp,
    Call,
    Column,
    FilterOp,
    JoinOp,
    LimitOp,
    Literal,
    MapOp,
    MemorySinkOp,
    MemorySourceOp,
    OTelExportSinkOp,
    Plan,
    RemoteSourceOp,
    ResultSinkOp,
    UDTFSourceOp,
    UnionOp,
)
from pixie_tpu.status import CompilerError, Internal, InvalidArgument, Unimplemented
from pixie_tpu.table.dictionary import Dictionary
from pixie_tpu.types import STORAGE_DTYPE, ColumnSchema, DataType as DT, Relation

from pixie_tpu.ops.groupby import next_pow2

INT64_MIN = np.iinfo(np.int64).min
INT64_MAX = np.iinfo(np.int64).max
MAX_GROUPS = 1 << 22
#: Sorted-fallback device reduction chunk (rows per update step).
SORT_AGG_CHUNK = 1 << 20
#: Minimum window-bin bucket: keeps the compiled group space stable across
#: streaming polls whose deltas span few windows.
MIN_WINDOW_BINS = 1 << 6


#: All-null sentinel for dict-valued pickers: equals _identity_for(int32,
#: "min") so an all-null group's state stays at the identity and decodes null.
PICKER_NULL_SENTINEL = np.iinfo(np.int32).max


def _decode_picker_codes(vals, d: Dictionary) -> np.ndarray:
    """Picker state codes → int32 dictionary codes; out-of-range (all-null
    sentinel) becomes -1 (null)."""
    codes = np.asarray(vals, dtype=np.int64)
    return np.where((codes < 0) | (codes >= d.size), -1, codes).astype(np.int32)


class GroupKeyFallback(Unimplemented):
    """Raised when group keys are not expressible as bounded dense codes
    (computed numeric keys, float keys, cardinality beyond MAX_GROUPS).
    The executor catches it and reruns the aggregate through the sort-based
    path (SURVEY §7 hard parts; reference capability: exec/agg_node.h's hash
    map has no cardinality bound)."""
MIN_BUCKET = 1 << 10
from pixie_tpu import flags as _flags

# Persistent jit cache: with PX_JIT_CACHE_DIR set, XLA compilations persist
# across processes (jax's compilation cache), so a restarted agent's first
# interactive query warms from disk instead of paying a fresh XLA compile.
_JIT_CACHE_DIR = _flags.define_str(
    "PX_JIT_CACHE_DIR", "",
    "directory for jax's persistent compilation cache (empty = off)")
if _JIT_CACHE_DIR:
    try:
        jax.config.update("jax_compilation_cache_dir", _JIT_CACHE_DIR)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    except Exception:  # older jax without the knobs: feature degrades silently
        pass

#: Feed coalescing target: sealed storage batches (64K-ish, the reference's
#: compaction granularity) are merged into large device feeds so a typical
#: query is ONE device execution.  Sized at 16M rows (~0.5 GB at 32 B/row)
#: because on remote/tunneled runtimes each execution has a large fixed cost —
#: fewer, bigger launches win decisively over streaming many small batches.
FEED_ROWS = _flags.define_int(
    "PX_FEED_ROWS", 1 << 24, "feed coalescing target (rows per device feed)"
)


# -------------------------------------------------------------- kernel cache
# Compiled chain kernels are reused across queries (the reference re-walks its
# exec-node tree per query; we must NOT re-jit per query or XLA compile time
# dominates).  Sound because cache keys capture everything baked into a kernel:
# the chain structure, input dtypes, and (id, size) of every input dictionary —
# dictionaries are append-only, so same (id, size) ⇒ identical content ⇒
# identical LUTs.  Data-dependent aggregation state (intdevice key sets, window
# origins) is covered by including the table's rows_written in agg signatures.
import collections as _collections
import json as _json
import threading as _threading

_KERNEL_CACHE: "_collections.OrderedDict[str, tuple]" = _collections.OrderedDict()
_KERNEL_CACHE_MAX = 128
#: concurrent agent executors (cluster thread pool) share these caches
_CACHE_LOCK = _threading.Lock()


def _cache_get(sig):
    if sig is None:
        return None
    with _CACHE_LOCK:
        got = _KERNEL_CACHE.get(sig)
        if got is not None:
            _KERNEL_CACHE.move_to_end(sig)
        return got


def _cache_put(sig, value):
    if sig is None:
        return
    with _CACHE_LOCK:
        _KERNEL_CACHE[sig] = value
        while len(_KERNEL_CACHE) > _KERNEL_CACHE_MAX:
            _KERNEL_CACHE.popitem(last=False)


#: op fields the streaming poller / matview maintainer PATCH between runs
#: (stream.py: source since/stop row ids + carried limit budgets;
#: maintainer.py: delta scan bounds) — everything else on a plan op is
#: immutable after compile
_VOLATILE_OP_FIELDS = ("n", "since_row_id", "stop_row_id")


def _op_sig(op) -> dict:
    # Memoized on the op instance: plan ops are structurally immutable after
    # compile, and warm interactive queries re-sign the same plan objects
    # every few ms — re-walking the op/expression tree per query was
    # measurable fast-path latency.  (copy.copy in the distributed planner
    # carries the memo; only `id` changes there, and `id` is excluded.)
    # The VOLATILE fields above are re-read live on every call: they are
    # runtime-patched per poll, and a stale signature would let the chain
    # cache serve a kernel with last poll's baked-in budget/scan bounds.
    got = op.__dict__.get("_op_sig_cache")
    if got is None:
        d = op.to_dict()
        d.pop("id", None)
        op.__dict__["_op_sig_cache"] = got = d
        return got
    for f in _VOLATILE_OP_FIELDS:
        if f in got:
            got[f] = getattr(op, f)
    return got


#: blocking-op intermediates cache kernels by dictionary CONTENT; above this
#: size fingerprinting costs more than the compile it saves
CONTENT_SIG_MAX_DICT = 1 << 16

#: (table uid, column) → (sorted unique values, scanned-to row id).  Tables
#: are append-only (expiry only drops rows), so the set is maintained
#: incrementally: each refresh scans only rows past the watermark.  This
#: keys intdevice agg kernels by VALUE-SET CONTENT instead of rows_written —
#: a streaming poll with no new key values then reuses the compiled kernel
#: instead of rebuilding it every poll.
_KEY_UNIQUES: "_collections.OrderedDict[tuple, tuple]" = _collections.OrderedDict()
_KEY_UNIQUES_MAX = 64
#: beyond this cardinality the set stops being tracked (the agg would take
#: the sorted fallback anyway); monotonic, so the overflow mark is permanent
_KEY_UNIQUES_CAP = MAX_GROUPS
_KEY_OVERFLOW = "overflow"


def _int_key_uniques(table, col: str, src) -> Optional[np.ndarray]:
    """Cumulative sorted unique values of `col` over a contiguous covered
    row-id range [lo, hi), extended/rebased from THIS query's snapshot cursor.

    Scanning the live table instead of the snapshot would race ring-buffer
    expiry: a value pinned in the query's feed could be missing from the
    fresh scan and searchsorted would silently fold its rows into a
    neighboring group.  Rows are immutable and row ids monotone, so values
    inside [lo, hi) were observed live by the scan that covered them — any
    snapshot whose rows all sit in [lo, hi) gets a valid (possibly strict
    superset) value set.  Returns None when the set overflows
    _KEY_UNIQUES_CAP (caller falls back to per-query prescan / sorted agg).

    Coverage rules (advisor r3 high + r4 review finding):
      * time-bounded cursors skip whole live batches — they neither consult
        nor update the cache (caller prescans this query's own snapshot);
      * a cursor reaching BELOW lo (an old pinned snapshot after a rebase)
        gets None — its rows may hold values the cache never saw;
      * a cursor starting past hi (expiry gap [hi, start) was never scanned)
        REBASES the entry to its own contiguous coverage instead of killing
        the cache for the table's remaining lifetime — expired rows can only
        be yielded by older pinned cursors, which the lo bound now rejects.
    """
    if (getattr(src, "start_time", None) is not None
            or getattr(src, "stop_time", None) is not None):
        return None
    if getattr(src, "since_row_id", None) is None:
        return None  # not a table Cursor — no coverage guarantee
    items = [(rb, rid) for rb, rid, _gen in src]
    key = (table.uid, col)
    with _CACHE_LOCK:
        entry = _KEY_UNIQUES.get(key)
    vals, lo, hi = entry if entry is not None else (None, 0, 0)
    if vals is _KEY_OVERFLOW:
        return None
    cfirst = min((rid for _rb, rid in items), default=None)
    if cfirst is None:  # empty snapshot: nothing to encode, superset is fine
        return vals if vals is not None else np.empty(0, dtype=np.int64)
    if vals is not None and cfirst < lo:
        return None  # pinned rows below cached coverage: prescan, keep entry
    rebase = vals is None or cfirst > hi
    parts = [] if rebase else [vals]
    cover = cfirst if rebase else hi
    base_lo = cfirst if rebase else lo
    changed = rebase
    for rb, rid in items:  # a cursor's batches are row-contiguous
        end = rid + rb.num_valid
        if end <= cover:
            continue
        if rid > cover:
            return None  # non-contiguous cursor (unexpected): refuse
        off = max(0, cover - rid)
        arr = rb.columns[col][off: rb.num_valid]
        if len(arr):
            parts.append(np.unique(arr))
            changed = True
        cover = end
    if changed:
        vals = (np.unique(np.concatenate(parts)) if parts
                else np.empty(0, dtype=np.int64))
        with _CACHE_LOCK:
            if len(vals) > _KEY_UNIQUES_CAP:
                _KEY_UNIQUES[key] = (_KEY_OVERFLOW, base_lo, cover)
                return None
            _KEY_UNIQUES[key] = (vals, base_lo, cover)
            while len(_KEY_UNIQUES) > _KEY_UNIQUES_MAX:
                _KEY_UNIQUES.popitem(last=False)
    return vals


def _group_source_column(chain, name: str):
    """Resolve a group name back through chain Maps to a direct source
    column name, or None if it is computed (any non-rename expression)."""
    from pixie_tpu.plan.plan import Column

    for op in reversed(chain):
        if isinstance(op, MapOp):
            e = next((ex for n, ex in op.exprs if n == name), None)
            if not isinstance(e, Column):
                return None
            name = e.name
    return name


def _dict_fingerprint(d) -> int:
    """Content hash of a Dictionary (process-local; cache is in-process)."""
    return hash(tuple(str(v) for v in d.values()))


# ------------------------------------------------- small-input device policy
#: content-signature key hashing is O(rows); only hash small intermediates
SMALL_HOST_INPUT_ROWS = 1 << 15

#: Inputs at or under this row count dispatch on the CPU backend.  Rationale
#: (measured on the axon-tunneled v5e): after the first device→host readback
#: the TPU runtime drops PERMANENTLY into a ~100 ms-per-operation synchronous
#: mode, so every TPU execution/readback costs ~100 ms regardless of size —
#: while XLA-CPU scatter aggs run 1M rows in ~8 ms.  The crossover where the
#: TPU's bandwidth wins back the fixed ~200 ms (1 exec + 1 readback) is a few
#: million rows.  This is ALSO why kernels must minimize executions per query.
CPU_CROSSOVER_ROWS = _flags.define_int(
    "PX_CPU_CROSSOVER_ROWS", 1 << 22,
    "inputs at/below this row count run on the CPU backend",
)

_CPU_DEVICE: "object" = None  # resolved lazily; False = unavailable


def _cpu_device():
    global _CPU_DEVICE
    if _CPU_DEVICE is None:
        try:
            _CPU_DEVICE = jax.devices("cpu")[0]
        except Exception:
            _CPU_DEVICE = False
    return _CPU_DEVICE


def _src_rows(src) -> Optional[int]:
    if isinstance(src, HostBatch):
        return src.num_rows
    try:
        return src.num_rows()
    except Exception:
        return None


def _route_backend(src, scale: int = 1) -> str:
    """Backend for this input.  `scale` is the distributed fan-out (number of
    data agents executing the same fragment): routing must consider the
    QUERY's size, not the local shard's — 8 agents each holding 2M rows are a
    16M-row query, and pushing each shard to XLA-CPU throws away the TPU win
    that partial aggregation exists to deliver (round-3 config-4 regression).
    """
    n = _src_rows(src)
    # read through the flag registry (not the import-time constant) so the
    # crossover is live-tunable — the static arm the autotune A/B bench and
    # the tail-guard fallback both pin against
    if n is not None and _cpu_device() is not False and \
            n * max(1, scale) <= int(_flags.get("PX_CPU_CROSSOVER_ROWS")):
        return "cpu"
    return "tpu"


def _iter_call_fns(expr):
    """Yield every Call fn name in an expression tree."""
    if isinstance(expr, Call):
        yield expr.fn
        for a in expr.args:
            yield from _iter_call_fns(a)


def _chain_uses_volatile(chain, registry) -> bool:
    """True if any chain expression calls a volatile (metadata-reading) UDF —
    such kernels bake snapshot-derived LUTs and must cache per state epoch."""
    for op in chain:
        exprs = []
        if isinstance(op, MapOp):
            exprs = [e for _n, e in op.exprs]
        elif isinstance(op, FilterOp):
            exprs = [op.expr]
        for e in exprs:
            for fn in _iter_call_fns(e):
                if registry.is_volatile(fn):
                    return True
    return False


# ------------------------------------------------------------ device feed cache
# The TPU-native analog of the reference's cold store (table/table.h hot/cold
# partitions): sealed batches are immutable, so their assembled, padded device
# feeds are cached in HBM keyed by the seal gens.  Repeat queries then stream
# ZERO bytes host→device — essential when the chip is remote (tunneled PCIe/DCN
# transfers run at ~100 MB/s and would dominate every query).
_DEVICE_CACHE: "_collections.OrderedDict[tuple, dict]" = _collections.OrderedDict()
_DEVICE_CACHE_BYTES = 0
_DEVICE_CACHE_MAX = _flags.define_int(
    "PIXIE_TPU_DEVICE_CACHE_MB", 4096,
    "HBM feed cache budget (MB); the PEM table-memory-budget analog",
) << 20


def _device_cache_get(key):
    with _CACHE_LOCK:
        got = _DEVICE_CACHE.get(key)
        if got is not None:
            _DEVICE_CACHE.move_to_end(key)
        return got


def _device_cache_put(key, cols: dict):
    global _DEVICE_CACHE_BYTES
    nbytes = sum(v.nbytes for v in cols.values())
    if nbytes > _DEVICE_CACHE_MAX:
        return
    with _CACHE_LOCK:
        _DEVICE_CACHE[key] = cols
        _DEVICE_CACHE_BYTES += nbytes
        while _DEVICE_CACHE_BYTES > _DEVICE_CACHE_MAX and _DEVICE_CACHE:
            _k, v = _DEVICE_CACHE.popitem(last=False)
            _DEVICE_CACHE_BYTES -= sum(x.nbytes for x in v.values())


def _device_cache_pop(key):
    """Drop one entry (the resident tier adopted its arrays — keeping both
    would pin the same bytes twice)."""
    global _DEVICE_CACHE_BYTES
    with _CACHE_LOCK:
        got = _DEVICE_CACHE.pop(key, None)
        if got is not None:
            _DEVICE_CACHE_BYTES -= sum(x.nbytes for x in got.values())


def clear_device_cache():
    global _DEVICE_CACHE_BYTES
    with _CACHE_LOCK:
        _DEVICE_CACHE.clear()
        _DEVICE_CACHE_BYTES = 0


def _bucket(n: int, cap: int) -> int:
    return min(max(next_pow2(n), MIN_BUCKET), max(cap, MIN_BUCKET))


# --------------------------------------------------------------------- batches


@dataclasses.dataclass
class HostBatch:
    """Materialized intermediate (compacted, host numpy)."""

    dtypes: dict[str, DT]
    dicts: dict[str, Dictionary]
    cols: dict[str, np.ndarray]

    @property
    def num_rows(self) -> int:
        for v in self.cols.values():
            return len(v)
        return 0


# ----------------------------------------------------------------- group keys


@dataclasses.dataclass
class GroupKey:
    name: str
    kind: str  # "dict" | "intdevice" | "window"
    card: int  # pow2-bucketed static cardinality
    out_dtype: DT
    dictionary: Optional[Dictionary] = None  # dict/intdevice
    #: source column the intdevice key reads (differs from `name` when a Map
    #: renamed the column).
    src_name: str = ""
    # window params
    width: int = 0
    t0_bin: int = 0
    key_sval: Optional[SVal] = None  # device codes builder (dict/window)
    #: luts entry holding the sorted unique values (intdevice: in-kernel
    #: searchsorted replaces host-side per-batch encoding).
    lut_name: str = ""


class _ChainCtx:
    """Symbolic column environment threaded through a chain of transforms."""

    def __init__(
        self,
        dtypes: dict[str, DT],
        dicts: dict[str, Dictionary],
        registry,
        visible: Optional[list[str]] = None,
    ):
        self.sym: dict[str, SVal] = {}
        self.provenance: dict[str, object] = {}
        #: default output columns — the fed columns minus internals (e.g. a
        #: time_ column fetched only to evaluate row-level time bounds).
        self.visible: list[str] = list(visible) if visible is not None else list(dtypes)
        self.registry = registry
        self.ec = ExprCompiler(dtypes, dicts, registry)
        # Seed with input columns.
        for name, dt in dtypes.items():
            self.sym[name] = self.ec.compile(Column(name))
            self.provenance[name] = Column(name)
        # Redirect column resolution to the evolving symbolic env.
        self.ec._compile_column = self._resolve_column  # type: ignore[method-assign]

    def _resolve_column(self, expr: Column) -> SVal:
        v = self.sym.get(expr.name)
        if v is None:
            raise CompilerError(f"column {expr.name!r} not found; have {sorted(self.sym)}")
        return v

    def apply_map(self, op: MapOp):
        new_sym = {}
        new_prov = {}
        for name, expr in op.exprs:
            new_sym[name] = self.ec.compile(expr)
            # Track one level of provenance for window-key detection, resolving
            # pass-through renames to their origin.
            if isinstance(expr, Column):
                new_prov[name] = self.provenance.get(expr.name, expr)
            else:
                new_prov[name] = expr
        self.ec._memo.clear()  # column meanings changed; don't reuse SVals
        self.sym = new_sym
        self.provenance = new_prov
        self.visible = [n for n, _ in op.exprs]

    def compile_predicate(self, op: FilterOp) -> SVal:
        v = self.ec.compile(op.expr)
        if v.dtype != DT.BOOLEAN:
            raise CompilerError(f"filter expression has type {v.dtype.name}, want BOOLEAN")
        return v


# ---------------------------------------------------------------- chain kernel


class ChainKernel:
    """Compiles Source/HostBatch → transforms → (agg | output) into one jit fn."""

    def __init__(
        self,
        in_dtypes: dict[str, DT],
        in_dicts: dict[str, Dictionary],
        transforms: list,
        registry,
        time_col: Optional[str],
        visible: Optional[list[str]] = None,
    ):
        self.ctx = _ChainCtx(in_dtypes, in_dicts, registry, visible)
        self.registry = registry
        self.time_col = time_col
        self.steps = []  # ("map", op) applied symbolically; ("filter", sval); ("limit", i)
        #: per-LimitOp budgets, in chain order — each limit step tracks its OWN
        #: remaining budget (a single min-collapsed budget under-returns when a
        #: filter between two limits drops admitted rows).
        self.limit_ns: list[int] = []
        for op in transforms:
            if isinstance(op, MapOp):
                self.ctx.apply_map(op)
            elif isinstance(op, FilterOp):
                self.steps.append(("filter", self.ctx.compile_predicate(op)))
            elif isinstance(op, LimitOp):
                self.steps.append(("limit", len(self.limit_ns)))
                self.limit_ns.append(int(op.n))
            else:
                raise Internal(f"non-streamable op {op.kind} in chain")

    @property
    def has_limit(self) -> bool:
        return bool(self.limit_ns)

    def init_limits(self) -> np.ndarray:
        """Initial per-limit remaining budgets (shape [max(1, n_limits)]).
        Numpy on purpose: an eager jnp.asarray would be a fixed-cost device
        op per query; as a jit argument it rides the execution's upload."""
        ns = self.limit_ns or [INT64_MAX]
        return np.asarray(ns, dtype=np.int64)

    @property
    def luts(self) -> dict[str, np.ndarray]:
        return self.ctx.ec.luts

    def _base_mask(self, env, n, n_valid, t_lo, t_hi):
        mask = jnp.arange(n) < n_valid
        if self.time_col is not None and self.time_col in env["cols"]:
            t = env["cols"][self.time_col]
            mask = mask & (t >= t_lo) & (t < t_hi)
        return mask

    def _apply_steps(self, env, mask, limits):
        """Apply filter/limit steps. Returns (mask, consumed[n_limits]).

        `limits` is the per-limit remaining-budget vector (shape
        [max(1, n_limits)]).  consumed[i] counts limit i's slots used by THIS
        batch — rows reaching that limit step, capped at its remaining budget.
        The host subtracts the whole vector from `remaining`: decrementing by
        the final output count instead would let later batches emit rows past
        a limit whenever a downstream filter drops admitted rows.
        """
        consumed = [jnp.int64(0)] * max(1, len(self.limit_ns))
        for kind, sv in self.steps:
            if kind == "filter":
                mask = mask & sv.build(env)
            else:  # limit; sv = budget index
                # Scalar `limits` broadcasts one shared budget (SPMD callers
                # pass INT64_MAX); the executor always passes the per-limit
                # vector from init_limits().  Two limits sharing one scalar
                # budget would silently mis-account, so reject at trace time.
                if jnp.ndim(limits) == 0 and len(self.limit_ns) > 1:
                    raise Internal(
                        "chains with multiple LimitOps need the per-limit "
                        "budget vector (ChainKernel.init_limits()), not a scalar"
                    )
                rem = limits[sv] if jnp.ndim(limits) else limits
                reaching = jnp.sum(mask.astype(jnp.int64))
                mask = mask & (jnp.cumsum(mask.astype(jnp.int64)) <= rem)
                consumed[sv] = jnp.minimum(reaching, rem)
        return mask, jnp.stack(consumed)

    def make_output_step(self, out_names: list[str]):
        """→ jit fn(cols, n_valid, t_lo, t_hi, limit_remaining, luts)
        → (out_cols, count, consumed) with selected rows COMPACTED to the front
        on device (stable partition by mask), so the host can read back exactly
        `count` rows. Also returns (dtypes, dicts) of outputs."""
        sym = self.ctx.sym
        missing = [n for n in out_names if n not in sym]
        if missing:
            raise CompilerError(f"output columns {missing} not found; have {sorted(sym)}")
        out_dtypes = {n: sym[n].dtype for n in out_names}
        out_dicts = {n: sym[n].dictionary for n in out_names if sym[n].dictionary is not None}
        builders = [(n, sym[n].build) for n in out_names]

        def step(cols, n_valid, t_lo, t_hi, limit_remaining, luts):
            env = {"cols": cols, "luts": luts}
            n = _first_len(cols)
            mask = self._base_mask(env, n, n_valid, t_lo, t_hi)
            mask, consumed = self._apply_steps(env, mask, limit_remaining)
            # Stable front-compaction: selected rows keep order at the front.
            order = jnp.argsort(jnp.logical_not(mask), stable=True)
            outs = {}
            for name, b in builders:
                v = b(env)
                v = jnp.broadcast_to(v, (n,)) if v.ndim == 0 else v
                outs[name] = jnp.take(v, order)
            return outs, jnp.sum(mask.astype(jnp.int64)), consumed

        return jax.jit(step), out_dtypes, out_dicts

    def make_partial_agg_step(self, keys, udas, num_groups: int, init_specs):
        """→ jit fn(cols, n_valid, t_lo, t_hi, luts) → partial state.

        Identity state is created INSIDE the trace, so per-feed calls are
        mutually independent — crucial on runtimes where dependent executions
        serialize (each feed's partial dispatches without waiting).  Pair with
        `make_merge_states` to combine the partials in one stacked reduction.
        """
        raw = self.make_agg_step(keys, udas, num_groups, jit=False)
        spec = list(init_specs)

        n_lim = max(1, len(self.limit_ns))

        def step(cols, n_valid, t_lo, t_hi, luts):
            state = {name: uda.init(num_groups, in_dt) for name, uda, in_dt in spec}
            new_state, _cnt, _consumed = raw(
                cols, n_valid, t_lo, t_hi,
                jnp.full((n_lim,), INT64_MAX, dtype=jnp.int64), luts, state
            )
            return new_state

        return jax.jit(step)

    @staticmethod
    def merge_states_fn(reduce_tree):
        """Traceable fn(*states) → merged state: ONE stacked reduction per
        leaf, op per leaf from `reduce_tree` ("add"|"min"|"max").  The single
        source of truth for device-side state merging (per-feed AND the
        cross-agent gang merge)."""
        fns = {"add": (lambda s: jnp.sum(s, axis=0)),
               "min": (lambda s: jnp.min(s, axis=0)),
               "max": (lambda s: jnp.max(s, axis=0))}

        def merge(*states):
            return jax.tree.map(
                lambda op, *leaves: fns[op](jnp.stack(leaves)),
                reduce_tree,
                *states,
                is_leaf=lambda x: isinstance(x, str),
            )

        return merge

    @staticmethod
    def make_merge_states(udas):
        """→ jit fn(*states) → merged state (flat dependency graph: N
        partials merge in a single execution)."""
        reduce_tree = {name: uda.reduce_ops() for name, uda, _vb in udas}
        return jax.jit(ChainKernel.merge_states_fn(reduce_tree))

    @staticmethod
    def make_merge_states_np(udas):
        """→ fn(*numpy_states) → merged numpy state, on HOST.  Per-feed
        partials are pulled in one overlapped readback wave and merged here:
        a device-side merge would cost one more execution, and on the tunneled
        runtime every execution is a fixed ~100 ms round-trip."""
        reduce_tree = {name: uda.reduce_ops() for name, uda, _vb in udas}
        fns = {"add": (lambda *ls: np.sum(np.stack(ls), axis=0)),
               "min": (lambda *ls: np.min(np.stack(ls), axis=0)),
               "max": (lambda *ls: np.max(np.stack(ls), axis=0))}

        def merge(*states):
            if len(states) == 1:
                return states[0]
            return jax.tree.map(
                lambda op, *leaves: fns[op](*leaves),
                reduce_tree,
                *states,
                is_leaf=lambda x: isinstance(x, str),
            )

        return merge

    def make_agg_step(self, keys: list[GroupKey], udas: list, num_groups: int, jit: bool = True):
        """→ jit fn(cols, n_valid, t_lo, t_hi, limit_remaining, luts, state)
        → (state, count). udas: list of (out_name, UDA, value_builder|None)."""
        from pixie_tpu.ops.groupby import combine_codes, encode_against

        key_builders = []
        for k in keys:
            if k.kind == "intdevice":
                src_name, lut_name = k.src_name, k.lut_name
                key_builders.append(
                    lambda env, s=src_name, l=lut_name: encode_against(
                        env["luts"][l], env["cols"][s]
                    )
                )
            elif k.kind == "dict":
                key_builders.append(k.key_sval.build)
            else:  # window: origin is a runtime scalar in luts (streaming)
                sv, w, t0name = k.key_sval, k.width, k.lut_name
                key_builders.append(
                    lambda env, sv=sv, w=w, t0name=t0name: (
                        sv.build(env) // w - env["luts"][t0name][0]
                    ).astype(jnp.int32)
                )
        cards = [k.card for k in keys]

        def step(cols, n_valid, t_lo, t_hi, limit_remaining, luts, state):
            env = {"cols": cols, "luts": luts}
            n = _first_len(cols)
            mask = self._base_mask(env, n, n_valid, t_lo, t_hi)
            mask, consumed = self._apply_steps(env, mask, limit_remaining)
            if keys:
                # literal group keys (df.node = 'x') build scalar codes —
                # broadcast to row length so the segment scatter sees [n]
                code_arrays = [
                    jnp.broadcast_to(c, (n,)) if c.ndim == 0 else c
                    for c in (kb(env) for kb in key_builders)
                ]
                # Null keys (code -1, e.g. unmatched left-join fills) drop out
                # of the aggregate (pandas dropna semantics); without this,
                # combine_codes would clamp them into group 0.
                for k, c in zip(keys, code_arrays):
                    if k.kind == "dict":
                        mask = mask & (c >= 0)
                gid, _ = combine_codes(code_arrays, cards)
            else:
                gid = jnp.zeros(n, dtype=jnp.int32)
            new_state = {}
            for out_name, uda, vb in udas:
                v = None
                if vb is not None:
                    v = vb(env)
                    v = jnp.broadcast_to(v, (n,)) if v.ndim == 0 else v
                new_state[out_name] = uda.update(state[out_name], gid, v, mask, num_groups)
            return new_state, jnp.sum(mask.astype(jnp.int64)), consumed

        # Kept unjitted for the SPMD lifter (parallel.spmd.spmd_agg_step wraps it
        # in shard_map over a mesh axis).
        self.raw_agg_step = step
        if not jit:
            return step
        return jax.jit(step, donate_argnums=(6,))


def _first_len(cols: dict) -> int:
    for v in cols.values():
        return v.shape[0]
    return 0


# ------------------------------------------------------------ column pruning
def _expr_columns(e) -> set:
    from pixie_tpu.plan.plan import Call, Column

    if isinstance(e, Column):
        return {e.name}
    if isinstance(e, Call):
        out = set()
        for a in e.args:
            out |= _expr_columns(a)
        return out
    return set()


def _prune_to_needed(head, chain, dtypes, dicts, names, visible, time_col,
                     needed_end: set):
    """Narrow the feed (and the chain's Map projections) to the columns the
    consumer actually reads.  Feeding unused columns wastes host→device
    bandwidth and, on the CPU route, memcpy + mask work per query (the
    compiler prunes PxL plans, but hand-built / remote plans arrive
    unpruned).  The hidden time column stays whenever the source has time
    bounds (names carries it beyond `visible` in that case).

    Returns (dtypes, dicts, names, visible, chain') — chain' has Map exprs
    for dropped outputs removed, since the kernel evaluates every listed
    expr (an unneeded expr over a pruned input would fail to resolve).
    """
    chain, req = _chain_required_columns(chain, set(needed_end))
    keep_visible = [n for n in visible if n in req]
    if not keep_visible and visible:
        keep_visible = [visible[0]]  # row count still needs one column
    keep = list(keep_visible)
    has_bounds = (getattr(head, "start_time", None) is not None
                  or getattr(head, "stop_time", None) is not None)
    if has_bounds and time_col is not None and time_col not in keep \
            and time_col in names:
        keep.append(time_col)
    dtypes = {n: dtypes[n] for n in keep}
    dicts = {n: dicts[n] for n in keep if n in dicts}
    return dtypes, dicts, keep, keep_visible, chain


def _chain_required_columns(chain, needed: set):
    """Backward dataflow through Map (full-list projection semantics) and
    Filter: -> (pruned_chain, required_source_columns)."""
    new_rev = []
    for op in reversed(chain):
        if isinstance(op, MapOp):
            defined = {name for name, _ in op.exprs}
            kept = [(name, ex) for name, ex in op.exprs if name in needed]
            out = set()
            for _name, ex in kept:
                out |= _expr_columns(ex)
            needed = out | (needed - defined)
            op = (dataclasses.replace(op, exprs=kept)
                  if len(kept) != len(op.exprs) else op)
        elif isinstance(op, FilterOp):
            needed = needed | _expr_columns(op.expr)
        new_rev.append(op)
    return list(reversed(new_rev)), needed


# -------------------------------------------------------------------- executor


def _state_on_cpu(state) -> bool:
    """True if every leaf of a partial state lives on host/CPU."""
    for leaf in jax.tree.leaves(state):
        if isinstance(leaf, np.ndarray):
            continue
        if isinstance(leaf, jax.Array):
            try:
                if any(d.platform != "cpu" for d in leaf.devices()):
                    return False
            except Exception:
                return False
        else:
            return False
    return True


@dataclasses.dataclass
class _DeferredState:
    """Un-pulled partial-agg state: per-feed device partials + the host merge
    to run after the (batched) readback."""

    partials: list
    merge_fn: Callable
    #: pre-merged state of any CPU-resident feeds (hot remainder), merged on
    #: host at defer time; folded in at finish
    host_state: object = None


@dataclasses.dataclass
class _DeferredPartial:
    """An agg_state channel payload whose readback is deferred: the cluster
    pulls `partials` (for ALL agents in one transfer wave) and then calls
    finish(pulled) -> PartialAggBatch.

    When every agent's `layout_fp` matches (same group-key value sets /
    dictionaries / UDA layout), the cluster instead merges ALL agents' states
    ON DEVICE (gang_merge_states) and finishes once on the merged state —
    the TPU-native tree reduction of SURVEY §2.5 P2, and 8x fewer readback
    bytes on a slow tunnel."""

    partials: list
    finish: Callable
    #: state-layout fingerprint; None = never gang-merge (e.g. sorted path)
    layout_fp: object = None
    #: finish on an ALREADY-MERGED state_np (gang path)
    finish_state: Optional[Callable] = None
    #: {out_name: reduce-op pytree} for the device merge
    reduce_tree: object = None
    #: CPU-feed state merged at defer time (not part of `partials`)
    host_state: object = None
    #: host merge fn for folding host_state into a pulled/merged state
    host_merge: Optional[Callable] = None


#: jitted state packers keyed by (treedef, leaf specs): on a remote/tunneled
#: runtime every pulled LEAF pays a round trip, so the agg state (several
#: arrays: per-UDA accumulators + seen counts) is concatenated into ONE
#: buffer per distinct dtype in the same device program and unpacked from
#: the pulled buffers on host — the readback batched into the kernel's final
#: step.  Grouping is by dtype (not a single bitcast byte buffer) because
#: this runtime's X64 rewrite cannot compile bitcast-converts of 64-bit
#: element types.
_PACK_CACHE: dict = {}


@dataclasses.dataclass
class _PackedState:
    """A partial state living on device as per-dtype packed buffers."""

    buf: object  # tuple of concatenated per-dtype arrays
    unpack: Callable


def _state_packer(sample_state):
    """(pack_jit, unpack_np) for states shaped like `sample_state`, or None
    when packing cannot reduce the pulled leaf count (already one leaf per
    dtype) — the pack is a separate jitted dispatch, so a no-gain pack is
    pure overhead."""
    leaves, treedef = jax.tree.flatten(sample_state)
    spec = tuple((tuple(l.shape), np.dtype(l.dtype).str) for l in leaves)
    key = (treedef, spec)
    got = _PACK_CACHE.get(key)
    if got is not None:
        return got
    dtypes = sorted({d for _s, d in spec})
    if len(spec) <= len(dtypes):
        _PACK_CACHE[key] = None
        return None

    def pack(state):
        ls, _ = jax.tree.flatten(state)
        groups = {d: [] for d in dtypes}
        for x, (_shape, d) in zip(ls, spec):
            groups[d].append(x.reshape(-1))
        return tuple(jnp.concatenate(groups[d]) for d in dtypes)

    def unpack(bufs):
        offs = {d: 0 for d in dtypes}
        bufs_np = {d: np.asarray(b) for d, b in zip(dtypes, bufs)}
        out = []
        for shape, d in spec:
            n = int(np.prod(shape, dtype=np.int64))
            out.append(bufs_np[d][offs[d]: offs[d] + n].reshape(shape))
            offs[d] += n
        return jax.tree.unflatten(treedef, out)

    got = (jax.jit(pack), unpack)
    if len(_PACK_CACHE) > 128:
        _PACK_CACHE.clear()
    _PACK_CACHE[key] = got
    return got


@dataclasses.dataclass
class _FinalizedCol:
    """An output column finalized ON DEVICE and already pulled: the agg
    finalize step must run finalize_from_device on it instead of
    finalize_host on state bytes."""

    col: np.ndarray


#: jitted merge(+device finalize) of per-feed partials, keyed by the agg's
#: UDA spec — the single execution that replaces N per-feed state pulls +
#: a host merge with one small readback wave
_MERGE_FINALIZE_CACHE: dict = {}


def _device_finalize_split(udas_by_name, finalize_ok: bool = True):
    """state → (finals, rest) closure shared by the merge and fused paths:
    device-finalizable outputs run finalize_device, the rest pass through
    for the host finalize step."""
    fin = {name: uda for name, uda in udas_by_name.items()
           if finalize_ok and uda.device_finalize}

    def split(state):
        finals = {k: fin[k].finalize_device(state[k]) for k in fin}
        rest = {k: v for k, v in state.items() if k not in fin}
        return finals, rest

    return split


def _merge_finalize_fn(spec_key, reduce_tree, udas_by_name,
                       finalize_ok: bool = True):
    fn = _MERGE_FINALIZE_CACHE.get(spec_key)
    if fn is None:
        merge = ChainKernel.merge_states_fn(reduce_tree)
        finalize = _device_finalize_split(udas_by_name, finalize_ok)

        def run(*states):
            return finalize(merge(*states) if len(states) > 1 else states[0])

        fn = jax.jit(run)
        if len(_MERGE_FINALIZE_CACHE) > 64:
            _MERGE_FINALIZE_CACHE.clear()
        _MERGE_FINALIZE_CACHE[spec_key] = fn
    return fn


#: fused single-feed partial+finalize executions, keyed by the chain's cache
#: sig (which pins the kernel's structure, dictionaries, and key sets)
_FUSED_FINALIZE_CACHE: dict = {}


def _fused_partial_finalize(fuse_key, udas_by_name, partial_step):
    """ONE device execution for the single-feed warm query: the per-feed
    partial update and the device finalize trace TOGETHER, so a forced-TPU
    interactive query (1M rows = one coalesced feed) pays one execution +
    one small readback wave instead of two chained executions — on tunneled
    runtimes every execution bills a fixed ~100 ms RTT, so this is the
    difference between sitting on the D2H wave-RTT floor and 2x it."""
    fn = _FUSED_FINALIZE_CACHE.get(fuse_key)
    if fn is None:
        finalize = _device_finalize_split(udas_by_name)

        def run(cols, n_valid, t_lo, t_hi, luts):
            return finalize(partial_step(cols, n_valid, t_lo, t_hi, luts))

        fn = jax.jit(run)
        if len(_FUSED_FINALIZE_CACHE) > 64:
            _FUSED_FINALIZE_CACHE.clear()
        _FUSED_FINALIZE_CACHE[fuse_key] = fn
    return fn


#: jitted cross-agent state merges, keyed by (layout_fp, arity) — a fresh
#: jit per query would recompile the merge every time
_GANG_MERGE_CACHE: dict = {}


def gang_merge_states(deferred: list) -> object:
    """Merge every agent's per-feed device partials into ONE device state.
    Caller guarantees equal layout_fp across `deferred`."""
    flat: list = []
    for d in deferred:
        flat.extend(d.partials)
    if len(flat) == 1:
        return flat[0]
    key = (deferred[0].layout_fp, len(flat))
    fn = _GANG_MERGE_CACHE.get(key)
    if fn is None:
        # same stacked reduction as ChainKernel.make_merge_states, built from
        # the payload's reduce_tree (the kernel's udas aren't in scope here)
        fn = jax.jit(ChainKernel.merge_states_fn(deferred[0].reduce_tree))
        if len(_GANG_MERGE_CACHE) > 64:
            _GANG_MERGE_CACHE.clear()
        _GANG_MERGE_CACHE[key] = fn
    return fn(*flat)


#: multi-query gang fusion: fuse the distinct partial-agg chains of one
#: shared scan (the fused-batch agent-plan shape, serving/batching.py) into
#: ONE jitted program per wave — N queries pay one device dispatch per feed
#: instead of N, and the whole gang reads back in one transfer wave
_MQ_FUSION = _flags.define_int(
    "PX_MQ_FUSION", -1,
    "fuse sibling partial-agg chains sharing one scan into a single jitted "
    "multi-query program per feed wave (the batched-query device path): "
    "-1 = auto (on iff a real accelerator backs the dispatch devices — "
    "the gang amortizes per-execution RTT, while on XLA-CPU the extra "
    "per-chain-set compiles cost more than they save), 0 = never, "
    "1 = always (tests / forced proof)")

_HAS_ACCEL: "Optional[bool]" = None


def _mq_fusion_enabled() -> bool:
    v = int(_flags.get("PX_MQ_FUSION"))
    if v == 0:
        return False
    if v >= 1:
        return True
    global _HAS_ACCEL
    if _HAS_ACCEL is None:
        try:
            _HAS_ACCEL = any(d.platform != "cpu" for d in jax.devices())
        except Exception:  # pragma: no cover — backend init failure
            _HAS_ACCEL = False
    return _HAS_ACCEL


@dataclasses.dataclass
class _AggSetup:
    """One aggregate's prepared execution state (see _agg_setup)."""

    op: AggOp
    head: object
    chain: list
    sig: Optional[str]
    dtypes: dict
    dicts: dict
    src: object
    names: list
    visible: list
    time_col: Optional[str]
    cap: int
    kern: "ChainKernel"
    keys: list
    udas: list
    in_types: dict
    init_specs: list
    num_groups: int
    seen_name: str
    step: Callable
    partial_step: Callable
    merge_fn: Callable
    spmd_step: Optional[Callable]
    val_dicts: dict
    lut_over: dict


class PlanExecutor:
    def __init__(self, plan: Plan, table_store, registry=None, inputs=None,
                 mesh="auto", analyze: bool = False, udtf_ctx=None,
                 otel_exporter=None, route_scale: int = 1,
                 force_backend: Optional[str] = None):
        from pixie_tpu.udf import registry as default_registry

        self.plan = plan
        self.store = table_store
        self.registry = registry or default_registry
        #: channel id → HostBatch injected by the cluster layer (remote edges;
        #: reference: GRPCRouter demuxing inbound streams, grpc_router.h:52)
        self.inputs: dict[str, HostBatch] = inputs or {}
        self._materialized: dict[int, HostBatch] = {}
        self.stats = {"rows_scanned": 0, "rows_output": 0, "batches": 0, "compile_s": 0.0}
        #: per-kernel / per-blocking-op timing records (the reference's
        #: ExecNodeStats analog, exec_node.h:41; grain = compiled unit).
        self.op_stats: list[dict] = []
        self._stat_stack: list[dict] = []
        #: analyze mode (reference ExecutePlan(analyze=true), carnot.cc:318):
        #: synchronizes the device after every feed so per-kernel wall times
        #: measure real execution, not async dispatch.
        self.analyze = analyze
        #: ambient state for UDTF sources (udf.udtf.UDTFContext); None builds
        #: a local-view context on demand.
        self.udtf_ctx = udtf_ctx
        #: override transport for OTel export sinks (tests inject a collector;
        #: None resolves from each sink's endpoint config).
        self.otel_exporter = otel_exporter
        #: distributed fan-out: how many data agents run this same fragment.
        #: CPU/TPU routing multiplies local input sizes by this so a sharded
        #: query routes by its TOTAL size (see _route_backend).
        self.route_scale = max(1, int(route_scale))
        #: adaptive-routing decisions taken for this query, one per size
        #: bucket (engine/autotune.py; empty with PX_AUTOTUNE=0)
        self._at_route: dict[str, dict] = {}
        #: pin the dispatch backend regardless of input size.  The streaming
        #: executor pins "cpu": every poll delta would re-UPLOAD its rows to
        #: a remote TPU (hot data is host-resident), so size-based routing is
        #: wrong for polls however large the delta.
        self.force_backend = force_backend
        #: colocated-agent mode (LocalCluster): partial-agg channels return
        #: device-resident state (_DeferredPartial) instead of pulling — the
        #: cluster coalesces ALL agents' readbacks into ONE transfer wave.
        #: On a remote/tunneled device each sync readback pays a fixed RTT,
        #: so 8 agents pulling separately cost ~8 waves (measured: 430 ms vs
        #: 160 ms single-store for the same total rows).
        self.defer_agg_pull = False
        # Device mesh for SPMD aggregation: every unlimited agg shards its
        # feeds over all local devices and merges state with in-program
        # collectives (the reference's per-PEM fan-out + Kelvin merge becomes
        # mesh axes + psum — SURVEY §2.5).  "auto" = all local devices when >1.
        if mesh == "auto":
            from pixie_tpu.parallel.spmd import default_mesh

            mesh = default_mesh()
        self.mesh = mesh
        if mesh is not None:
            # the XLA-CPU collective-serialization workaround is a GATED
            # decision (parallel.spmd.collective_gate), recorded per query
            # like the device-join gate so rounds can audit it
            from pixie_tpu.parallel.spmd import collective_gate

            gate = {k: v for k, v in collective_gate(mesh).items()
                    if k != "_key"}
            self.stats.setdefault("device", {})["collective_gate"] = gate

    # ------------------------------------------------------------- routing
    def _backend_for(self, src) -> str:
        if self.force_backend is not None:
            return self.force_backend
        static = _route_backend(src, self.route_scale)
        if not _autotune.enabled() or _cpu_device() is False:
            return static
        n = _src_rows(src)
        if n is None:
            return static
        # one decision per size bucket per executor: every _backend_for
        # call for this query's inputs routes consistently (fast paths ask
        # repeatedly), and stats["autotune"] carries exactly the decisions
        # this query ran under
        bucket = _autotune.size_bucket(n * self.route_scale)
        dec = self._at_route.get(bucket)
        if dec is None:
            dec = _autotune.MODEL.decide(
                _autotune.GATE_CPU_CROSSOVER, "agg", bucket,
                "cpu" if static == "cpu" else "device", ("cpu", "device"))
            self._at_route[bucket] = dec
            self.stats.setdefault("autotune", []).append(dec)
        return "cpu" if dec["arm"] == "cpu" else "tpu"

    def _device_ctx(self, src):
        if self._backend_for(src) == "cpu" and _cpu_device() is not False:
            return jax.default_device(_cpu_device())
        return _contextlib.nullcontext()

    # -------------------------------------------------------------- exec stats
    @_contextlib.contextmanager
    def _timed(self, label: str, ops: list[int]):
        """Record a wall-time frame; nesting attributes child time so
        self_ns = wall_ns - nested frames (exec_node.h self/total split).

        The parent is captured at ENTER and the frame is removed by identity:
        frames opened inside generators close at exhaustion/GC, not in LIFO
        order, so a plain stack pop could discharge someone else's frame.
        """
        rec = {"ops": ops, "label": label, "wall_ns": 0, "rows_out": 0,
               "bytes_out": 0, "_child_ns": 0,
               # wall-clock anchor so the frame adapts into a trace span
               # (self-telemetry) without extra timing calls
               "t0_unix_ns": _time.time_ns()}
        parent = self._stat_stack[-1] if self._stat_stack else None
        self._stat_stack.append(rec)
        t0 = _time.perf_counter_ns()
        try:
            yield rec
        finally:
            rec["wall_ns"] = _time.perf_counter_ns() - t0
            try:
                self._stat_stack.remove(rec)
            except ValueError:
                pass
            if parent is not None and "_child_ns" in parent:
                # A parent that already closed (abandoned generator finalized
                # late) has popped its _child_ns; skip attribution then.
                parent["_child_ns"] += rec["wall_ns"]
            rec["self_ns"] = rec["wall_ns"] - rec.pop("_child_ns")
            self.op_stats.append(rec)

    def _emit_op_spans(self) -> None:
        """Adapt the per-op exec stats into trace spans (near-zero cost: the
        frames already carry wall-clock anchors; under no active trace this
        is one ContextVar read)."""
        from pixie_tpu import trace

        if not self.op_stats or trace.current() is None:
            return
        for rec in self.op_stats:
            t0 = rec.get("t0_unix_ns")
            if t0 is None:
                continue
            trace.event_span(rec["label"], t0, rec["wall_ns"],
                             rows_out=rec.get("rows_out", 0))

    def _chain_label(self, head, chain, terminal: str = "") -> str:
        parts = []
        if isinstance(head, MemorySourceOp):
            parts.append(f"scan({head.table})")
        elif isinstance(head, RemoteSourceOp):
            parts.append(f"remote({head.channel})")
        else:
            parts.append(head.kind)
        parts.extend(op.kind for op in chain)
        if terminal:
            parts.append(terminal)
        return "->".join(parts)

    # ------------------------------------------------------------ plan walking
    def _upstream_chain(self, op):
        """Walk up through streamable transforms. Returns (head, [transforms...])."""
        chain = []
        cur = op
        while isinstance(cur, (MapOp, FilterOp, LimitOp)):
            chain.append(cur)
            parents = self.plan.parents(cur)
            if len(parents) != 1:
                raise Internal(f"transform {cur.kind} must have exactly one parent")
            cur = parents[0]
        return cur, list(reversed(chain))

    def _input_of(self, head):
        """head is a Source or blocking op.

        Returns (dtypes, dicts, src, feed_names, visible_names, time_col, cap).
        feed_names may include a hidden time_ column fetched only so row-level
        time bounds can be applied; visible_names excludes it.
        """
        if isinstance(head, MemorySourceOp):
            table = self.store.table(head.table)
            if head.tablet is not None:
                from pixie_tpu.table.tablets import TabletsGroup

                if not isinstance(table, TabletsGroup):
                    raise InvalidArgument(
                        f"table {head.table!r} is not tabletized (tablet="
                        f"{head.tablet!r} requested)"
                    )
                table = table.tablet(head.tablet)
            if head.since_row_id is not None or head.stop_row_id is not None:
                cursor = table.cursor_since(
                    head.since_row_id or 0, head.stop_row_id,
                    head.start_time, head.stop_time,
                )
            else:
                cursor = table.cursor(head.start_time, head.stop_time)
            visible = list(head.columns or table.relation.names())
            names = list(visible)
            has_bounds = head.start_time is not None or head.stop_time is not None
            if has_bounds and table.time_col is not None and table.time_col not in names:
                names.append(table.time_col)
            dtypes = {n: table.relation.dtype(n) for n in names}
            dicts = {n: table.dictionaries[n] for n in names if n in table.dictionaries}
            return dtypes, dicts, cursor, names, visible, table.time_col, table.batch_rows
        hb = self._eval_blocking(head)
        return hb.dtypes, hb.dicts, hb, list(hb.cols), list(hb.cols), None, MIN_BUCKET

    def _heat_recorder(self, src):
        """Shard-heat accounting hook shared by every scan path (the
        coalescing `_feed`, np_partial's fused window loop, the wholeplan
        native loop): a per-stream FeedRecorder, or None when tracing is
        off or `src` is not a storage cursor — flag-off never touches the
        model."""
        from pixie_tpu import observe as _observe

        table = getattr(src, "table", None)
        if table is None or not _observe.enabled():
            return None
        from pixie_tpu.table import heat as _heat

        return _heat.FeedRecorder(
            table, getattr(self.store, "node_name", "") or "local")

    def _note_shard_rows(self, per_shard) -> None:
        """Per-shard placement accounting for SPMD feeds: accumulates each
        feed's per-shard valid rows and keeps the skew ratio (max/mean shard
        rows) visible — stats["shard_rows"]/["shard_skew_frac"] plus the
        px_shard_skew_frac gauge.  1.0 = perfectly even placement; row-major
        block sharding should stay near 1 except at uneven tails."""
        rows = [int(x) for x in np.asarray(per_shard).reshape(-1)]
        acc = self.stats.get("shard_rows")
        if not isinstance(acc, list) or len(acc) != len(rows):
            acc = [0] * len(rows)
        acc = [a + r for a, r in zip(acc, rows)]
        self.stats["shard_rows"] = acc
        mean = sum(acc) / max(len(acc), 1)
        skew = (max(acc) / mean) if mean > 0 else 1.0
        self.stats["shard_skew_frac"] = round(skew, 4)
        from pixie_tpu import metrics as _metrics

        _metrics.gauge_set(
            "px_shard_skew_frac", skew,
            help_="max/mean rows per mesh shard over this process's latest "
                  "SPMD query feeds (placement-skew visibility; 1.0 = even)")

    # ------------------------------------------------------------- stream feed
    def _predicted_single_feed(self, src, cap) -> bool:
        """Exact feed count from snapshot metadata (mirrors _feed's flush
        logic: hot remainder flushes pending sealed rows; sealed rows
        coalesce to the feed target).  Cursors are immutable snapshots, so
        the prediction cannot be invalidated by concurrent writes."""
        if isinstance(src, HostBatch):
            return True
        target = max(cap, FEED_ROWS)
        cold_gens = getattr(src, "cold_gens", None) or frozenset()
        # metadata iteration: sizing feeds must never materialize data —
        # iter_meta answers from counts, so a mostly-cold retention window
        # costs zero decodes here
        meta = (src.iter_meta() if hasattr(src, "iter_meta")
                else ((rb.num_valid, rid, gen) for rb, rid, gen in src))
        feeds = pend_rows = 0
        pend_cold = False
        for n, _row_id, gen in meta:
            if n == 0:
                continue
            is_cold = gen in cold_gens
            if pend_rows and (gen is None or is_cold != pend_cold):
                feeds += 1
                pend_rows = 0
            pend_cold = is_cold
            pend_rows += n
            if pend_rows >= target:
                feeds += 1
                pend_rows = 0
        if pend_rows:
            feeds += 1
        return feeds <= 1

    def _feed(self, src, names, cap, spmd: bool = False,
              backend: str = "tpu"):
        """Yield (cols np dict padded, n_valid) host batches.

        Cursor batches (storage granularity) are coalesced into ~FEED_ROWS
        device feeds: fewer kernel dispatches and transfers, and the bucketed
        shapes repeat so XLA's shape cache stays warm.

        spmd=True (the unlimited-agg path): cacheable feeds are placed SHARDED
        over the mesh, so repeat SPMD queries stream zero bytes and reshard
        nothing.  Single-device consumers (select/limit/join kernels) must NOT
        receive sharded inputs — their jits would get implicitly
        GSPMD-partitioned — so the placement (and the cache key) is gated on
        the consumer.
        """
        if isinstance(src, HostBatch):
            n = src.num_rows
            # Materialized intermediates can exceed the stream cap (e.g. many
            # groups out of an agg): bucket to their own pow2 size.
            bucket = max(MIN_BUCKET, next_pow2(max(n, 1)))
            cols = {k: _pad(src.cols[k], bucket) for k in names}
            yield cols, n
            return

        target = max(cap, FEED_ROWS)
        table_id = src.table.uid
        n_dev = self.mesh.size if (spmd and self.mesh is not None) else 1
        # Shard-heat accounting (table/heat.py): one recorder per feed
        # stream, bumped per coalesced emit with the serving tier.  Gated on
        # the tracing master switch — flag-off never touches the model.
        heat_rec = self._heat_recorder(src)

        def emit(parts, gens, n, cold=False):
            # Sealed-only feeds are immutable → serve/place them from the HBM
            # feed cache; anything touching the hot remainder streams fresh.
            # CPU-routed queries keep feeds as (cached) numpy — device_put to
            # TPU would commit the inputs there and defeat the routing.
            # Cold-tier feeds are decode-on-read by design: caching them
            # (resident or HBM) would promote through the back door and pin
            # the demoted window in memory — promotion is the cold tier's
            # explicit, read-heat-driven call.
            cacheable = (not cold
                         and all(g is not None for g in gens)
                         and not getattr(src, "is_delta", False))
            if cacheable and backend == "tpu":
                # Pinned-resident tier first: unlike the gen-tuple-keyed HBM
                # cache below, a new seal FOLDS into the resident buffer
                # (only the delta rows cross the link) instead of
                # invalidating the whole feed — the warm interactive query
                # then uploads zero bytes (engine/resident.py).  A legacy
                # cache entry for this exact feed (e.g. from a transient
                # budget fallback) is handed over for ADOPTION and then
                # dropped, so the bytes are never uploaded or pinned twice.
                # SPMD consumers (n_dev > 1) get the SHARDED-resident tier:
                # the same entry pinned column-wise across the mesh with a
                # NamedSharding, so warm sharded queries reshard nothing
                # and ingest deltas fold shard-local.
                sharding = None
                if n_dev > 1:
                    from jax.sharding import NamedSharding, PartitionSpec as P
                    from pixie_tpu.parallel.spmd import AGENT_AXIS

                    sharding = NamedSharding(self.mesh, P(AGENT_AXIS))
                lkey = (table_id, tuple(gens), tuple(names), n_dev, backend)
                got = resident.feed(table_id, tuple(names), gens, cap,
                                    parts, n,
                                    prewarmed=_device_cache_get(lkey),
                                    sharding=sharding, n_dev=n_dev)
                if got is not None:
                    _device_cache_pop(lkey)
                    rcols, h2d = got
                    self.stats["resident_feeds"] = (
                        self.stats.get("resident_feeds", 0) + 1)
                    self.stats["h2d_bytes"] = (
                        self.stats.get("h2d_bytes", 0) + h2d)
                    if heat_rec is not None:
                        heat_rec.record(parts, gens, "resident")
                    return rcols, n
            dkey = ((table_id, tuple(gens), tuple(names), n_dev, backend)
                    if cacheable else None)
            if dkey is not None:
                cached = _device_cache_get(dkey)
                if cached is not None:
                    self.stats["feed_cache_hits"] = self.stats.get("feed_cache_hits", 0) + 1
                    if heat_rec is not None:
                        heat_rec.record(parts, gens, "hbm_cache")
                    return dict(cached), n
            # Single-copy assembly: write every storage batch straight into the
            # padded bucket buffer (concatenate-then-pad would copy twice).
            # The bucket must hold n even when accumulation overshot `target`
            # (storage batch sizes don't necessarily divide the feed target).
            bucket = max(_bucket(n, target), next_pow2(max(n, 1)))
            cols = resident.assemble_padded(parts, names, bucket)
            if dkey is not None:
                if backend == "cpu":
                    dev = cols  # host arrays ARE the cpu-backend feed
                elif n_dev > 1 and bucket % n_dev == 0:
                    from jax.sharding import NamedSharding, PartitionSpec as P
                    from pixie_tpu.parallel.spmd import AGENT_AXIS

                    sh = NamedSharding(self.mesh, P(AGENT_AXIS))
                    dev = {k: jax.device_put(v, sh) for k, v in cols.items()}
                else:
                    dev = jax.device_put(cols)
                _device_cache_put(dkey, dev)
                cols = dict(dev)
            if backend != "cpu":
                # transfer accounting: a fresh device_put above, or a
                # numpy hot/delta feed that uploads at dispatch — either
                # way these bucketed bytes cross host->device (the stat
                # the zero-H2D warm-query assertion reads; LUT/limit
                # scalars are kilobytes and excluded)
                self.stats["h2d_bytes"] = (
                    self.stats.get("h2d_bytes", 0)
                    + sum(v.nbytes for v in cols.values()))
            if heat_rec is not None:
                heat_rec.record(parts, gens, "cold" if cold else "stream")
            if cold:
                # read-heat promotion hook (data-plane, not gated on the
                # observe switch): enough decodes of the same cold batch
                # move it back to RAM (PL_COLD_PROMOTE_READS)
                tier = getattr(src.table, "cold", None)
                if tier is not None:
                    tier.note_reads(gens)
            return cols, n

        cold_gens = getattr(src, "cold_gens", None) or frozenset()
        pend, gens, nrows = [], [], 0
        pend_cold = False
        for rb, _row_id, gen in src:  # cursor
            n = rb.num_valid
            if n == 0:
                continue
            is_cold = gen in cold_gens
            # The hot remainder (gen None) must not join a sealed feed: sealed
            # feeds are immutable and HBM-cached, the hot tail changes every
            # write — mixing them would force a full re-upload per query.
            # Cold↔RAM boundaries flush for the dual reason: a cold batch in
            # a RAM feed would poison the cacheable-feed key (and vice versa
            # hide RAM rows inside a never-cached cold feed).
            if pend and (gen is None or is_cold != pend_cold):
                yield emit(pend, gens, nrows, pend_cold)
                pend, gens, nrows = [], [], 0
            pend_cold = is_cold
            pend.append({k: rb.columns[k][:n] for k in names})
            gens.append(gen)
            nrows += n
            self.stats["rows_scanned"] += n
            self.stats["batches"] += 1
            if nrows >= target:
                yield emit(pend, gens, nrows, pend_cold)
                pend, gens, nrows = [], [], 0
        if pend:
            yield emit(pend, gens, nrows, pend_cold)

    # ---------------------------------------------------------------- blocking
    def _eval_blocking(self, op) -> HostBatch:
        got = self._materialized.get(op.id)
        if got is not None:
            return got
        if isinstance(op, AggOp):
            label = f"agg(by={op.groups})"
        elif isinstance(op, RemoteSourceOp):
            label = f"remote({op.channel})"
        else:
            label = op.kind
        with self._timed(label, [op.id]) as rec:
            if isinstance(op, AggOp):
                out = self._run_agg(op)
            elif isinstance(op, JoinOp):
                out = self._run_join(op)
            elif isinstance(op, UnionOp):
                out = self._run_union(op)
            elif isinstance(op, MemorySourceOp):
                out = self._consume_to_batch(op, [])
            elif isinstance(op, UDTFSourceOp):
                out = self._run_udtf(op)
            elif isinstance(op, RemoteSourceOp):
                got = self.inputs.get(op.channel)
                if got is None:
                    raise Internal(f"no input injected for channel {op.channel!r}")
                out = got
            else:
                raise Internal(f"unexpected blocking op {op.kind}")
            rec["rows_out"] = out.num_rows
            rec["bytes_out"] = sum(v.nbytes for v in out.cols.values())
        self._materialized[op.id] = out
        return out

    def _chain_cache_sig(
        self, head, chain, dtypes, dicts, extra, include_times: bool = False
    ) -> Optional[str]:
        """Cache signature for a kernel over this chain; None = not cacheable.

        Table-headed chains: dictionaries are append-only, so (id, size) pins
        exact content (the table uid keeps id() stable).  Blocking-op heads
        (join/agg intermediates) get FRESH dictionary objects per query, so
        identity can't pin them — they cache by dictionary CONTENT fingerprint
        instead (small dicts only; hashing a huge dict would cost more than
        the compile it saves).  Without this, every query re-jits its
        post-join/post-agg kernels — the dominant cost of multi-stage plans.
        Source time bounds are RUNTIME args (t_lo/t_hi), so they are excluded
        from the signature unless the kernel bakes them (window aggs) —
        otherwise every '-5m'-style relative query would re-jit.
        """
        if not isinstance(head, MemorySourceOp):
            if any(d.size > CONTENT_SIG_MAX_DICT for d in dicts.values()):
                return None
            key = {
                "reg": self.registry.uid,
                "head": "blocking",
                "chain": [_op_sig(op) for op in chain],
                "dtypes": {n: int(t) for n, t in dtypes.items()},
                "dicts": {n: (d.size, _dict_fingerprint(d))
                          for n, d in dicts.items()},
                "extra": extra,
            }
            if _chain_uses_volatile(chain, self.registry):
                from pixie_tpu.metadata import state as _mdstate

                key["md_epoch"] = _mdstate.global_manager().epoch
            return _json.dumps(key, sort_keys=True, default=str)
        table = self.store.table(head.table)
        # _op_sig memoizes its dict on the op; copy before popping so the
        # shared cache keeps its time bounds for include_times=True callers.
        src_sig = dict(_op_sig(head))
        # Row-id bounds are pure runtime cursor state (streaming resume
        # tokens); kernels never bake them.
        src_sig.pop("since_row_id", None)
        src_sig.pop("stop_row_id", None)
        # The scan's column LIST is not kernel state either: chains prune
        # to the columns they read, and the pruned dtypes/dicts are in the
        # signature below.  A fused batch plan widens the shared scan to
        # the member-column union (plan.fusion._merge_pruned_scans) —
        # without this pop, every batch composition would re-jit kernels
        # identical to the solo-warmed ones.
        src_sig.pop("columns", None)
        if not include_times:
            src_sig.pop("start_time", None)
            src_sig.pop("stop_time", None)
        key = {
            "reg": self.registry.uid,
            "table": (head.table, table.uid),
            "src": src_sig,
            "chain": [_op_sig(op) for op in chain],
            "dtypes": {n: int(t) for n, t in dtypes.items()},
            "dicts": {n: (id(d), d.size) for n, d in dicts.items()},
            "extra": extra,
        }
        if _chain_uses_volatile(chain, self.registry):
            # Metadata UDFs bake the K8sSnapshot into LUTs at kernel-build
            # time; a new epoch must miss the cache even when no dictionary
            # grew (e.g. a pod rename reuses every existing string).
            from pixie_tpu.metadata import state as _mdstate

            key["md_epoch"] = _mdstate.global_manager().epoch
        return _json.dumps(key, sort_keys=True, default=str)

    def _consume_chain(self, terminal_parent, out_names=None):
        """Run the chain feeding `terminal_parent` through an output step.

        Returns (out_dtypes, out_dicts, iterator of (np_cols, np_mask)).
        """
        head, chain = self._upstream_chain(terminal_parent)

        # Fast path: a bare blocking op feeding a sink (the common shape for
        # aggregated results) is already a host batch — plain column selection,
        # no kernel (and no per-query XLA compile of a trivial projection).
        if not chain and not isinstance(head, MemorySourceOp):
            hb = self._eval_blocking(head)
            sel = out_names if out_names is not None else list(hb.cols)
            missing = [n for n in sel if n not in hb.cols]
            if missing:
                raise CompilerError(f"output columns {missing} not found")
            out_dtypes = {n: hb.dtypes[n] for n in sel}
            out_dicts = {n: hb.dicts[n] for n in sel if n in hb.dicts}

            def gen_direct():
                yield {n: hb.cols[n] for n in sel}, hb.num_rows

            return out_dtypes, out_dicts, sel, gen_direct()

        dtypes, dicts, src, names, visible, time_col, cap = self._input_of(head)
        if out_names is not None:
            dtypes, dicts, names, visible, chain = _prune_to_needed(
                head, chain, dtypes, dicts, names, visible, time_col,
                set(out_names),
            )
        sig = self._chain_cache_sig(
            head, chain, dtypes, dicts,
            ("out", tuple(out_names) if out_names is not None else None),
        )
        cached = _cache_get(sig)
        if cached is not None:
            kern, step, out_dtypes, out_dicts, out_names = cached
        else:
            kern = ChainKernel(dtypes, dicts, chain, self.registry, time_col, visible)
            if out_names is None:
                out_names = list(kern.ctx.visible)
            step, out_dtypes, out_dicts = kern.make_output_step(out_names)
            _cache_put(sig, (kern, step, out_dtypes, out_dicts, out_names))
        t_lo, t_hi = _time_bounds(head)
        luts = kern.luts

        label = self._chain_label(head, chain, "select")
        op_ids = [head.id] + [op.id for op in chain]

        def gen():
            # Double-buffered readback pipeline: every feed's step dispatches
            # async (limit budgets carried as a DEVICE vector, no host sync in
            # the dispatch path); one feed behind, the previous wave's count
            # lands (its async copy started at dispatch) and its count-sliced
            # outputs start their D2H copy — so that transfer is in flight
            # WHILE the current wave computes; two feeds behind, the sliced
            # outputs materialize and yield.  With a remote TPU each readback
            # pays a fixed RTT; here every wave's copy is issued under a later
            # wave's compute, so the RTTs hide instead of serializing at the
            # end (transfer.AsyncPull records the overlap split per wave).
            from collections import deque

            with self._timed(label, op_ids) as rec, self._device_ctx(src):
                has_limit = kern.has_limit
                remaining = kern.init_limits()
                computing: deque = deque()  # (outs, cnt): compute dispatched
                pulling: deque = deque()    # (AsyncPull, rows): D2H in flight
                feed_ns = []

                def start_readback(overlapped: bool):
                    outs, cnt = computing.popleft()
                    c = int(np.asarray(cnt))
                    pulling.append(
                        (transfer.pull_async({k: v[:c] for k, v in outs.items()}),
                         c))
                    if overlapped:
                        rec["pipelined_waves"] = rec.get("pipelined_waves", 0) + 1
                        self.stats["pipelined_waves"] = (
                            self.stats.get("pipelined_waves", 0) + 1)

                def emit_ready():
                    h, c = pulling.popleft()
                    cols_np = h.wait()
                    rec["rows_out"] += c
                    rec["bytes_out"] += sum(v.nbytes for v in cols_np.values())
                    return cols_np, c

                for cols, n_valid in self._feed(
                        src, names, cap, backend=self._backend_for(src)):
                    tf0 = _time.perf_counter_ns()
                    outs, cnt, consumed = step(
                        cols, np.int64(n_valid), t_lo, t_hi, remaining, luts
                    )
                    if has_limit:
                        # Only limit queries need the budget threaded (chains
                        # the per-feed executions); unlimited scans stay
                        # independent.
                        remaining = remaining - consumed
                    if self.analyze:
                        jax.block_until_ready(outs)
                        feed_ns.append(_time.perf_counter_ns() - tf0)
                    if isinstance(cnt, jax.Array):
                        # the count rides home under this wave's own compute
                        cnt.copy_to_host_async()
                    computing.append((outs, cnt))
                    if len(computing) >= 2:
                        start_readback(overlapped=True)
                    while len(pulling) >= 2:
                        yield emit_ready()
                if self.analyze and feed_ns:
                    rec["feed_ns"] = feed_ns
                if has_limit:
                    # Surface each LimitOp's remaining budget (chain order) —
                    # the streaming executor carries these across polls;
                    # decrementing by emitted rows instead would over-deliver
                    # when a filter follows a limit.
                    rec["limit_remaining"] = [
                        int(x) for x in np.asarray(jax.device_get(remaining))
                    ]
                while computing:
                    start_readback(overlapped=False)
                while pulling:
                    yield emit_ready()

        return out_dtypes, out_dicts, out_names, gen()

    def _consume_to_batch(self, terminal_parent, out_names=None) -> HostBatch:
        out_dtypes, out_dicts, out_names, gen = self._consume_chain(terminal_parent, out_names)
        parts = [c for c, _ in gen]
        cols = {
            n: (
                np.concatenate([p[n] for p in parts])
                if parts
                else np.empty(0, STORAGE_DTYPE[out_dtypes[n]])
            )
            for n in out_names
        }
        return HostBatch(out_dtypes, out_dicts, cols)

    # --------------------------------------------------------------------- agg
    def _plan_group_keys(self, op: AggOp, kern: ChainKernel, src, head) -> list[GroupKey]:
        keys = []
        for name in op.groups:
            sv = kern.ctx.sym.get(name)
            if sv is None:
                raise CompilerError(f"group key {name!r} not found")
            if sv.dictionary is not None:
                keys.append(
                    GroupKey(
                        name,
                        "dict",
                        next_pow2(max(sv.dictionary.size, 1)),
                        sv.dtype,
                        sv.dictionary,
                        key_sval=sv,
                    )
                )
                continue
            # A bin key gets window-range semantics ONLY over the source time
            # column — px.bin over a value column must go through the generic
            # paths or it would collapse into bogus time-range bins.
            wk = _window_key(kern.ctx.provenance.get(name), kern.time_col)
            if wk is not None and sv.dtype in (DT.TIME64NS, DT.INT64):
                width = wk
                t_min, t_max = _source_time_range(src, head)
                t0_bin = t_min // width
                nbins = int(t_max // width - t0_bin) + 1
                # The window ORIGIN is a runtime parameter (fed through the
                # luts dict, see _refresh_window_keys), NOT baked into the
                # kernel: streaming polls and shifting '-5m' ranges then reuse
                # one compiled kernel.  Only the bin-count bucket is static;
                # it grows (cache bust) if a later range spans more bins.
                t0name = kern.ctx.ec._add_lut(np.asarray([t0_bin], dtype=np.int64))
                keys.append(
                    GroupKey(
                        name,
                        "window",
                        next_pow2(max(nbins, MIN_WINDOW_BINS)),
                        sv.dtype,
                        width=width,
                        t0_bin=int(t0_bin),
                        key_sval=sv,
                        lut_name=t0name,
                    )
                )
                continue
            if sv.dtype in (DT.INT64, DT.TIME64NS, DT.BOOLEAN):
                prov = kern.ctx.provenance.get(name)
                if not isinstance(prov, Column):
                    raise GroupKeyFallback(
                        f"group key {name!r} is a computed numeric column"
                    )
                # Device-side encoding: the uniques come from the per-table
                # incremental union when available (matches the kernel-cache
                # signature and costs O(new rows)); otherwise one prescan
                # over this query's cursor.  Sorted, so dictionary code ==
                # sorted position; the kernel maps value→code against a
                # small runtime array — no per-batch host encode.
                from pixie_tpu.table.table import Table as _Table

                qd = Dictionary()
                u = None
                if isinstance(head, MemorySourceOp) and head.tablet is None:
                    t = self.store.table(head.table)
                    if type(t) is _Table and prov.name in t.relation:
                        u = _int_key_uniques(t, prov.name, src)
                if u is not None:
                    qd.encode(u.tolist())
                else:
                    _prescan_unique(src, prov.name, qd, sort=True)
                vals = np.asarray(qd.values(), dtype=np.int64)
                lut_name = kern.ctx.ec._add_lut(vals)
                keys.append(
                    GroupKey(
                        name,
                        "intdevice",
                        next_pow2(max(qd.size, 1)),
                        sv.dtype,
                        qd,
                        src_name=prov.name,
                        lut_name=lut_name,
                    )
                )
                continue
            raise GroupKeyFallback(f"group key {name!r} has type {sv.dtype.name}")
        total = 1
        for k in keys:
            total *= k.card
        if total > MAX_GROUPS:
            raise GroupKeyFallback(
                f"group cardinality bound {total} exceeds {MAX_GROUPS}"
            )
        return keys

    def _run_agg(self, op: AggOp) -> HostBatch:
        try:
            keys, udas, state_np, seen_name, in_types, val_dicts = self._agg_state(op)
        except GroupKeyFallback:
            return self._run_agg_sorted(op)
        return self._finalize_agg(op, keys, udas, state_np, seen_name, in_types,
                                  val_dicts)

    # -------------------------------------------------- sort-based agg fallback
    def _sorted_group_reduce(self, op: AggOp):
        """Sort-based groupby for keys with no bounded dense code space.

        Two phases, matching the SURVEY §7 design: (1) the chain's compiled
        select kernel materializes group-key + value columns (device work);
        (2) the host sorts/uniques the composite key — the analog of the
        reference's unbounded hash map (exec/agg_node.h:55-140) — and the
        per-group reduction goes back to the device as chunked masked segment
        reductions over the exact group ids.

        Returns (group_cols, dtypes, dicts, udas, in_types, state_np, G,
        val_dicts) — val_dicts maps dict-valued picker outputs to the
        dictionary their code-state decodes through.
        """
        self.stats["sorted_agg_fallbacks"] = self.stats.get("sorted_agg_fallbacks", 0) + 1
        parent = self.plan.parents(op)[0]
        need = list(dict.fromkeys(
            [*op.groups, *[ae.arg for ae in op.values if ae.arg is not None]]
        ))
        hb = self._consume_to_batch(parent, need)
        cols, out_dtypes, out_dicts = hb.cols, hb.dtypes, hb.dicts
        n = hb.num_rows

        # ---- composite key factorization (host sort).
        valid = np.ones(n, dtype=bool)
        per_inv, per_card = [], []
        for g in op.groups:
            arr = cols[g]
            if g in out_dicts:
                valid &= arr >= 0  # null keys drop out (pandas dropna)
            elif arr.dtype.kind == "f":
                valid &= ~np.isnan(arr)  # NaN keys drop out (pandas dropna)
            u, inv = np.unique(arr, return_inverse=True)
            per_inv.append(inv.astype(np.int64))
            per_card.append(len(u))
        total_card = 1
        for c in per_card:
            total_card *= max(c, 1)
        if total_card < (1 << 62):
            comp = per_inv[0]
            for inv, card in zip(per_inv[1:], per_card[1:]):
                comp = comp * card + inv
        else:
            # mixed radix would overflow int64: unique over the record rows
            _u, comp = np.unique(np.rec.fromarrays(per_inv), return_inverse=True)
            comp = comp.astype(np.int64)
        vrows = np.nonzero(valid)[0]
        uniq_comp, first_in_valid = (
            np.unique(comp[vrows], return_index=True)
            if len(vrows)
            else (np.empty(0, dtype=np.int64), np.empty(0, dtype=np.int64))
        )
        G = len(uniq_comp)
        rep_rows = vrows[first_in_valid]  # one representative row per group
        group_cols = {g: cols[g][rep_rows] for g in op.groups}
        Gb = max(next_pow2(max(G, 1)), 1)
        gid_np = np.searchsorted(uniq_comp, comp).clip(0, Gb - 1).astype(np.int32)

        # ---- device reduction over exact gids, chunked.
        udas, in_types, init_pairs = [], {}, []
        val_dicts: dict[str, Dictionary] = {}
        dict_val_cols: set[str] = set()
        for ae in op.values:
            uda = self.registry.uda(ae.fn)
            in_dt = None
            in_types[ae.out_name] = None
            if ae.arg is not None:
                if ae.arg in out_dicts:
                    if not uda.dict_ok:
                        raise Unimplemented(
                            f"aggregate {ae.fn} over string column {ae.arg!r}"
                        )
                    in_types[ae.out_name] = out_dtypes[ae.arg]
                    in_dt = np.int32
                    val_dicts[ae.out_name] = out_dicts[ae.arg]
                    dict_val_cols.add(ae.arg)
                else:
                    if getattr(uda, "needs_dict", False):
                        raise Unimplemented(
                            f"aggregate {ae.fn} requires a string "
                            f"(dictionary-encoded) input column, got "
                            f"{ae.arg!r}"
                        )
                    in_types[ae.out_name] = out_dtypes[ae.arg]
                    in_dt = STORAGE_DTYPE[out_dtypes[ae.arg]]
            elif not uda.nullary:
                raise CompilerError(f"aggregate {ae.fn} requires an input column")
            udas.append((ae.out_name, uda, ae.arg))
            init_pairs.append((ae.out_name, uda, in_dt))
        val_names = sorted({vn for _o, _u, vn in udas if vn is not None})
        # null codes must never win the picker's min-reduction
        for vn in dict_val_cols:
            c = cols[vn]
            cols = {**cols,
                    vn: np.where(c >= 0, c, PICKER_NULL_SENTINEL).astype(np.int32)}

        # The jitted update closure is cached per (registry, agg spec, Gb):
        # jax.jit then reuses traces across calls/polls instead of recompiling
        # the reduction every invocation.
        upd_key = (
            "sorted_upd", self.registry.uid,
            tuple((ae.out_name, ae.fn, ae.arg) for ae in op.values), Gb,
        )
        cached_upd = _cache_get(_json.dumps(upd_key))
        if cached_upd is not None:
            upd, udas = cached_upd
        else:
            spec = list(udas)

            def upd(state, gid, mask, vals, spec=spec):
                new = {}
                for out_name, uda, vname in spec:
                    v = vals[vname] if vname is not None else None
                    new[out_name] = uda.update(state[out_name], gid, v, mask, Gb)
                return new

            upd = jax.jit(upd, donate_argnums=(0,))
            _cache_put(_json.dumps(upd_key), (upd, udas))
        with self._timed(f"sorted_agg(by={op.groups}, G={G})", [op.id]), \
                self._device_ctx(hb):
            # state init happens inside the device context so the donated
            # accumulators live on the dispatch device (CPU for small batches)
            state = {name: uda.init(Gb, in_dt)
                     for name, uda, in_dt in init_pairs}
            for off in range(0, n, SORT_AGG_CHUNK):
                end = min(off + SORT_AGG_CHUNK, n)
                bucket = max(next_pow2(end - off), MIN_BUCKET)
                gid_c = _pad(gid_np[off:end], bucket)
                mask_c = np.zeros(bucket, dtype=bool)
                mask_c[: end - off] = valid[off:end]
                vals_c = {vn: _pad(cols[vn][off:end], bucket) for vn in val_names}
                state = upd(state, gid_c, mask_c, vals_c)
                if self.analyze:
                    jax.block_until_ready(state)
            state_np = transfer.pull(state)
        return (group_cols, out_dtypes, out_dicts, udas, in_types, state_np, G,
                val_dicts)

    def _run_agg_sorted(self, op: AggOp) -> HostBatch:
        (group_cols, in_dtypes, in_dicts, udas, in_types, state_np, G,
         val_dicts) = self._sorted_group_reduce(op)
        dtypes: dict[str, DT] = {}
        dicts: dict[str, Dictionary] = {}
        cols: dict[str, np.ndarray] = {}
        for g in op.groups:
            dtypes[g] = in_dtypes[g]
            cols[g] = group_cols[g]
            if g in in_dicts:
                dicts[g] = in_dicts[g]
        for out_name, uda, _vn in udas:
            if getattr(uda, "needs_dict", False):
                # model-fit UDA: finalize over the input DICTIONARY (unique
                # values + multiplicities), emitting fresh strings
                full = uda.finalize_dict(state_np[out_name],
                                         val_dicts[out_name])
            else:
                full = uda.finalize_host(state_np[out_name])
            vals = np.asarray(full)[:G]
            out_dt = uda.out_type(in_types[out_name]) if not uda.nullary else uda.out_type(None)
            if out_name in val_dicts and not getattr(uda, "needs_dict", False):
                cols[out_name] = _decode_picker_codes(vals, val_dicts[out_name])
                dicts[out_name] = val_dicts[out_name]
                dtypes[out_name] = out_dt
                continue
            if out_dt == DT.STRING:
                d = Dictionary()
                cols[out_name] = d.encode(vals)
                dicts[out_name] = d
            else:
                cols[out_name] = vals.astype(STORAGE_DTYPE[out_dt], copy=False)
            dtypes[out_name] = out_dt
        return HostBatch(dtypes, dicts, cols)

    def _sorted_partial_batch(self, op: AggOp):
        """Distributed partial for the sorted path: group key VALUES + dense
        state sliced to the seen groups (same wire shape as _partial_agg_batch)."""
        from pixie_tpu.parallel.partial import PartialAggBatch

        (group_cols, in_dtypes, in_dicts, udas, in_types, state_np, G,
         val_dicts) = self._sorted_group_reduce(op)
        if val_dicts:
            raise Internal(
                "dict-valued aggregates must ship rows, not partial state "
                "(the distributed planner cuts them as rows channels)"
            )
        key_cols, key_dtypes = {}, {}
        for g in op.groups:
            key_dtypes[g] = in_dtypes[g]
            if g in in_dicts:
                key_cols[g] = np.asarray(in_dicts[g].decode(group_cols[g]), dtype=object)
            else:
                key_cols[g] = group_cols[g]
        states = {
            out_name: jax.tree.map(lambda x: np.asarray(x)[:G], state_np[out_name])
            for out_name, _uda, _vn in udas
        }
        return PartialAggBatch(
            key_cols=key_cols, key_dtypes=key_dtypes, states=states,
            in_types=dict(in_types),
        )

    def _agg_setup(self, op: AggOp):
        """Everything `_agg_state` needs BEFORE the feed loop runs: chain
        walk, pruning, cache signatures, the fetched-or-built kernel bundle,
        and per-run window-origin refresh.  Factored out so the multi-query
        gang (`_multi_partial_agg`) can prepare N member aggregates against
        one shared scan and fuse their per-wave steps into a single jitted
        program.  Raises GroupKeyFallback exactly like `_agg_state`."""
        head, chain = self._upstream_chain(self.plan.parents(op)[0])
        dtypes, dicts, src, names, visible, time_col, cap = self._input_of(head)
        needed = set(op.groups) | {ae.arg for ae in op.values
                                   if ae.arg is not None}
        dtypes, dicts, names, visible, chain = _prune_to_needed(
            head, chain, dtypes, dicts, names, visible, time_col, needed,
        )

        # Agg kernels bake data-dependent key sets (intdevice uniques, window
        # origins) unless every group key is dictionary-backed; cover that with
        # the table's rows_written in the signature.
        sig = None
        fb_sig = None
        if isinstance(head, MemorySourceOp):
            # The fallback DECISION memo is data-independent (no rows_written/
            # times): once keys prove non-dense, falling back stays correct as
            # the table grows — and streaming polls must hit this memo FIRST,
            # before any keyset work, so doomed aggs skip the union scan.
            fb_sig = self._chain_cache_sig(
                head, chain, dtypes, dicts, ["agg_fallback", _op_sig(op)]
            )
            if _cache_get(fb_sig) == "group_key_fallback":
                raise GroupKeyFallback(f"agg {op.id}: cached fallback decision")
            extra = ["agg", _op_sig(op), ("mesh", self.mesh.size if self.mesh else 0)]
            table = self.store.table(head.table)
            windowish = _windowish_groups(chain, table.time_col)
            # Only intdevice keys bake data (their unique-value sets); window
            # origins are runtime parameters (_refresh_window_keys), so
            # windowed/dict-keyed aggs reuse one kernel across polls/ranges.
            # Direct-source int keys sign by VALUE-SET CONTENT (incremental
            # union, O(new rows)): a streaming poll without new key values
            # reuses the kernel instead of rebuilding per rows_written.
            # Tabletized tables (TabletsGroup) have no uid/row-id surface
            # for the union cache — they take the rows_written signature.
            from pixie_tpu.table.table import Table as _Table

            data_dependent = False
            plain_table = type(table) is _Table and head.tablet is None
            for g in op.groups:
                if g in dicts or g in windowish:
                    continue
                src_col = _group_source_column(chain, g)
                u = None
                if plain_table and src_col is not None \
                        and src_col in table.relation and src_col not in dicts:
                    u = _int_key_uniques(table, src_col, src)
                if u is not None:
                    extra.append(("keyset", g, len(u), hash(u.tobytes())))
                else:
                    data_dependent = True
            if data_dependent:
                extra.append(table.stats()["rows_written"])
            sig = self._chain_cache_sig(
                head, chain, dtypes, dicts, extra, include_times=data_dependent
            )
        else:
            # Blocking-op-headed agg (e.g. the post-join re-aggregation):
            # content-keyed caching.  Non-dict group keys bake their unique
            # value sets into the kernel, so their column content joins the
            # signature (small host batches only — hashing is O(rows)).
            extra = ["agg", _op_sig(op),
                     ("mesh", self.mesh.size if self.mesh else 0)]
            cacheable = True
            non_dict = [g for g in op.groups if g not in dicts]
            if non_dict:
                # Computed keys derive from source columns through the chain;
                # hashing the REQUIRED source columns pins the baked value
                # sets regardless of where in the chain the key is built.
                if (isinstance(src, HostBatch)
                        and src.num_rows <= SMALL_HOST_INPUT_ROWS):
                    _unused, req = _chain_required_columns(chain, set(non_dict))
                    for c in sorted(req):
                        if c in src.cols:
                            extra.append(
                                ("keyhash", c, hash(src.cols[c].tobytes())))
                        else:
                            cacheable = False
                            break
                else:
                    cacheable = False
            if cacheable:
                sig = self._chain_cache_sig(head, chain, dtypes, dicts, extra)
                fb_sig = self._chain_cache_sig(
                    head, chain, dtypes, dicts,
                    ["agg_fallback", _op_sig(op)])
        if _cache_get(fb_sig) == "group_key_fallback":
            raise GroupKeyFallback(f"agg {op.id}: cached fallback decision")
        for _attempt in range(2):
            built = self._agg_kernel(op, sig, fb_sig, dtypes, dicts, chain,
                                     time_col, visible, src, head)
            (kern, keys, udas, in_types, init_specs, num_groups, seen_name,
             step, partial_step, merge_fn, spmd_step, val_dicts) = built
            ok, keys, lut_over = self._refresh_window_keys(keys, src, head)
            if ok:
                break
            # A cached kernel's window-bin bucket is too small for this run's
            # time span: drop it and rebuild with the larger card.
            with _CACHE_LOCK:
                _KERNEL_CACHE.pop(sig, None)
        else:
            # Both attempts failed: concurrent ingest grew the time span
            # between the rebuild's range read and the refresh.  Running with
            # a stale bucket would silently alias windows — fail loudly.
            raise Internal(
                "window-bin bucket overflowed twice (concurrent ingest "
                "outpacing kernel rebuild); retry the query"
            )
        return _AggSetup(
            op=op, head=head, chain=chain, sig=sig, dtypes=dtypes,
            dicts=dicts, src=src, names=names, visible=visible,
            time_col=time_col, cap=cap, kern=kern, keys=keys, udas=udas,
            in_types=in_types, init_specs=init_specs, num_groups=num_groups,
            seen_name=seen_name, step=step, partial_step=partial_step,
            merge_fn=merge_fn, spmd_step=spmd_step, val_dicts=val_dicts,
            lut_over=lut_over)

    def _agg_state(self, op: AggOp):
        """Run the aggregation and pull the raw state (shared by the local
        finalize path and the distributed partial path)."""
        s = self._agg_setup(op)
        # one named binding per line: the body below was written against
        # these locals, and per-line assignment can't transpose fields the
        # way a parallel-tuple unpack could
        head = s.head
        chain = s.chain
        sig = s.sig
        src = s.src
        names = s.names
        cap = s.cap
        kern = s.kern
        keys = s.keys
        udas = s.udas
        in_types = s.in_types
        init_specs = s.init_specs
        num_groups = s.num_groups
        seen_name = s.seen_name
        step = s.step
        partial_step = s.partial_step
        merge_fn = s.merge_fn
        spmd_step = s.spmd_step
        val_dicts = s.val_dicts
        lut_over = s.lut_over
        dtypes = s.dtypes
        dicts = s.dicts
        time_col = s.time_col
        # Small host-batch inputs dispatch on the CPU backend (compile is the
        # dominant cost at this scale); the SPMD path stays on the mesh.
        dev_ctx = (self._device_ctx(src)
                   if spmd_step is None else _contextlib.nullcontext())
        with dev_ctx:
            t_lo, t_hi = _time_bounds(head)
            luts = {**kern.luts, **lut_over} if lut_over else kern.luts
            with self._timed(
                self._chain_label(head, chain, "partial_agg"),
                ([head.id] if head.id >= 0 else []) + [o.id for o in chain],
            ) as rec:
                self._feed_rec = rec if self.analyze else None
                from pixie_tpu.engine import np_partial

                if (self._backend_for(src) == "cpu" and spmd_step is None
                        and np_partial.eligible(kern, keys, udas, val_dicts,
                                                t_lo, t_hi, src)
                        and np_partial.value_args_ok(kern, op, names)):
                    # CPU streaming/poll fast path: bincount-shaped numpy +
                    # native histogram scatter at memory speed, identical
                    # state layout (see np_partial module docstring)
                    state_np = np_partial.run(
                        self, src, names, cap, kern, keys, init_specs,
                        num_groups, t_lo, t_hi, luts,
                        np_partial.value_args(kern, op))
                    self.stats["np_fast_polls"] = self.stats.get(
                        "np_fast_polls", 0) + 1
                elif (prog := self._wholeplan_program(
                        sig, kern, chain, op, keys, init_specs, dtypes,
                        dicts, names, time_col, src, val_dicts,
                        spmd_step)) is not None \
                        and _codegen.applicable(prog, t_lo, t_hi):
                    # Whole-plan native loop (Flare): the ENTIRE fused
                    # scan->filter->map->partial-agg chain runs as one
                    # compiled pass straight off the storage batches —
                    # no feed coalescing, no masks, no per-op kernels
                    # (native/wholeplan.cc via native/codegen.py)
                    state_np = _codegen.run(self, prog, src, num_groups,
                                            init_specs, t_lo, t_hi, luts)
                    self.stats["wholeplan_native"] = self.stats.get(
                        "wholeplan_native", 0) + 1
                else:
                    state_np = self._agg_feed_loop(
                        kern, step, partial_step, merge_fn, spmd_step,
                        init_specs, num_groups,
                        src, names, cap, t_lo, t_hi, luts, fuse_key=sig,
                    )
                self._feed_rec = None
        if self._at_route and rec.get("wall_ns"):
            # fold the measured chain wall into the routing decision that
            # picked this backend (per-arm cost model, engine/autotune.py)
            n = _src_rows(src)
            if n is not None:
                dec = self._at_route.get(
                    _autotune.size_bucket(n * self.route_scale))
                if dec is not None:
                    _autotune.MODEL.observe_decision(
                        dec, rec["wall_ns"] / 1e9)
        return keys, udas, state_np, seen_name, in_types, val_dicts

    def _wholeplan_program(self, sig, kern, chain, op, keys, init_specs,
                           dtypes, dicts, names, time_col, src, val_dicts,
                           spmd_step):
        """Fetch-or-lower the native whole-plan micro-program for this agg
        chain (engine.plancache.native_programs, keyed by the same chain
        signature that pins the kernel bundle).  None = out of scope —
        the interpreted kernel path runs instead."""
        if (self._backend_for(src) != "cpu" or spmd_step is not None
                or val_dicts or not hasattr(src, "__iter__")):
            return None
        # the flag is re-read HERE, outside the program cache: a cached
        # program must not outlive an operator flipping the kill switch,
        # and flag-off-at-first-query must not poison the sig with None
        if not _flags.get("PX_WHOLEPLAN_NATIVE"):
            return None
        from pixie_tpu.engine.plancache import native_programs

        # window-bin buckets can GROW under an unchanged chain sig (the
        # rebuild loop above); the baked cards join the key so a stale
        # program can never alias windows
        psig = None if sig is None else (sig, tuple(k.card for k in keys))
        return native_programs.get_or_lower(
            psig,
            lambda: _codegen.lower(kern, chain, op, keys, init_specs,
                                   dtypes, dicts, names, time_col))

    def _refresh_window_keys(self, keys, src, head):
        """Per-run window-origin resolution.

        Returns (ok, keys', lut_overrides).  keys' holds fresh GroupKey copies
        with this run's t0_bin, and lut_overrides carries the runtime origin
        scalars — per-run values never mutate the cached kernel, so concurrent
        queries over different time ranges can share it.  ok=False means the
        kernel's static bin bucket can't hold this run's span (rebuild)."""
        if not any(k.kind == "window" for k in keys):
            return True, keys, {}
        t_min, t_max = _source_time_range(src, head)
        out, over = [], {}
        for k in keys:
            if k.kind != "window":
                out.append(k)
                continue
            t0 = int(t_min // k.width)
            nbins = int(t_max // k.width) - t0 + 1
            if nbins > k.card:
                return False, keys, {}
            out.append(dataclasses.replace(k, t0_bin=t0))
            over[k.lut_name] = np.asarray([t0], dtype=np.int64)
        return True, out, over

    def _agg_kernel(self, op, sig, fb_sig, dtypes, dicts, chain, time_col,
                    visible, src, head):
        """Fetch-or-build the compiled agg kernel bundle for `op`."""
        cached = _cache_get(sig)
        if cached is not None:
            return cached
        kern = ChainKernel(dtypes, dicts, chain, self.registry, time_col, visible)
        try:
            keys = self._plan_group_keys(op, kern, src, head)
        except GroupKeyFallback:
            _cache_put(fb_sig, "group_key_fallback")
            raise
        num_groups = 1
        for k in keys:
            num_groups *= k.card

        # UDA instances + value builders (+ implicit row counter for
        # seen-groups).
        udas = []
        init_specs = []
        seen_name = "__seen"
        val_dicts: dict[str, Dictionary] = {}
        from pixie_tpu.udf.udf import CountUDA

        in_types: dict[str, DT | None] = {}
        for ae in [*op.values]:
            uda = self.registry.uda(ae.fn)
            vb = None
            in_dtype = None
            in_types[ae.out_name] = None
            if ae.arg is not None:
                sv = kern.ctx.sym.get(ae.arg)
                if sv is None:
                    raise CompilerError(f"agg input column {ae.arg!r} not found")
                if sv.dictionary is not None:
                    if not uda.dict_ok:
                        raise Unimplemented(
                            f"aggregate {ae.fn} over string column {ae.arg!r}"
                        )
                    # Dict-valued picker: aggregate over CODES (null code -1
                    # masked to the min-identity so it never wins); the
                    # finalize step decodes back through the dictionary.
                    b = sv.build

                    def vb(env, b=b):
                        v = b(env)
                        return jnp.where(v >= 0, v, jnp.int32(PICKER_NULL_SENTINEL))

                    in_dtype = np.int32
                    in_types[ae.out_name] = sv.dtype
                    val_dicts[ae.out_name] = sv.dictionary
                else:
                    if getattr(uda, "needs_dict", False):
                        raise Unimplemented(
                            f"aggregate {ae.fn} requires a string "
                            f"(dictionary-encoded) input column, got "
                            f"{ae.arg!r}"
                        )
                    vb = sv.build
                    in_dtype = STORAGE_DTYPE[sv.dtype]
                    in_types[ae.out_name] = sv.dtype
            elif not uda.nullary:
                raise CompilerError(f"aggregate {ae.fn} requires an input column")
            udas.append((ae.out_name, uda, vb))
            init_specs.append((ae.out_name, uda, in_dtype))
        seen_uda = CountUDA()
        udas.append((seen_name, seen_uda, None))
        init_specs.append((seen_name, seen_uda, None))

        step = kern.make_agg_step(keys, udas, num_groups)
        partial_step = kern.make_partial_agg_step(keys, udas, num_groups, init_specs)
        merge_fn = kern.make_merge_states_np(udas)
        spmd_step = None
        if self.mesh is not None:
            from pixie_tpu.parallel.spmd import reduce_tree_for, spmd_partial_step

            reduce_tree = reduce_tree_for(udas)
            specs = list(init_specs)

            def init_fn(specs=specs, g=num_groups):
                return {name: uda.init(g, in_dt) for name, uda, in_dt in specs}

            spmd_step = spmd_partial_step(
                kern.raw_agg_step, init_fn, reduce_tree,
                len(kern.limit_ns), self.mesh,
            )
        bundle = (kern, keys, udas, in_types, init_specs, num_groups,
                  seen_name, step, partial_step, merge_fn, spmd_step, val_dicts)
        _cache_put(sig, bundle)
        return bundle

    def _agg_feed_loop(self, kern, step, partial_step, merge_fn, spmd_step,
                       init_specs, num_groups, src, names, cap, t_lo, t_hi,
                       luts, fuse_key=None):
        """Drive the feeds through the agg step and pull the final state.

        State init is LAZY: creating identity state eagerly would dispatch
        one device op per UDA leaf before any feed runs — fixed-cost ops the
        tunneled runtime bills at ~100 ms each.  The partial path inits
        inside its trace; only the budget-threaded limit path (and the
        no-feed fallback) materializes identities here.
        """
        state = None
        if kern.has_limit:
            # Limit queries must thread the budgets, so the feed steps chain;
            # the budgets stay a device vector (no per-feed host sync).
            state = {name: uda.init(num_groups, in_dt)
                     for name, uda, in_dt in init_specs}
            remaining = kern.init_limits()
            for cols, n_valid in self._feed(
                    src, names, cap, backend=self._backend_for(src)):
                state, cnt, consumed = step(
                    cols, np.int64(n_valid), t_lo, t_hi, remaining, luts, state
                )
                remaining = remaining - consumed
                if self.analyze:
                    jax.block_until_ready(state)
        else:
            # No limit → per-feed partials are INDEPENDENT executions (init
            # inside the trace).  Dependent executions serialize badly on
            # remote runtimes; this keeps the device pipeline flat: N parallel
            # steps + ONE overlapped readback wave + a HOST merge (a device
            # merge would be one more fixed-cost execution).  With a mesh,
            # each feed shards row-wise over ALL devices and merges
            # per-device state in-program via psum/pmin/pmax (the reference's
            # PEM-partial → Kelvin-finalize, but over ICI).
            partials = []
            n_dev = self.mesh.size if self.mesh is not None else 1
            backend = ("tpu" if spmd_step is not None
                       else self._backend_for(src))
            # Accelerator-backend feeds normally end in a DEVICE merge (+
            # device finalize) with one small readback — raw states stay
            # unpacked for it.  Packing only pays on the paths that still
            # pull per-feed states (defer / mixed CPU partials).  The SPMD
            # path qualifies too: its per-feed states are already in-mesh
            # merged (replicated), and the merge+finalize jit runs under
            # GSPMD like any other consumer.
            device_merge_ok = (backend == "tpu"
                               and not getattr(self, "_defer_active", False))
            # Single-feed fusion: when the snapshot metadata predicts exactly
            # one feed (the interactive warm-query shape — 1M rows coalesce
            # into one feed), the first feed is held back undispatched and
            # partial+finalize run as ONE fused execution below instead of
            # two chained ones.  Multi-feed queries never hold: the device
            # would idle through the next feed's host-side assembly, undoing
            # the compute/transfer overlap.  (The dispatch-on-second-arrival
            # fallback in the loop stays as a safety net.)
            fuse_ok = (fuse_key is not None and not self.analyze
                       and spmd_step is None and device_merge_ok
                       and not getattr(self, "_partial_wire", False)
                       and self._predicted_single_feed(src, cap))
            held = None

            def dispatch_plain(cols, n_valid):
                # A small NUMPY feed (typically the hot remainder of a
                # big table) dispatches on CPU even in a TPU-routed
                # query: it would otherwise cost one more fixed-price
                # TPU execution; the host merge unifies the partials.
                bucket = _first_len(cols)
                first = next(iter(cols.values()))
                small_np = (isinstance(first, np.ndarray)
                            and bucket <= int(
                                _flags.get("PX_CPU_CROSSOVER_ROWS"))
                            and _cpu_device() is not False)
                if small_np and device_merge_ok:
                    # A device-merged query keeps its small feeds (the
                    # hot remainder) ON the accelerator: executions are
                    # cheap async dispatches, while a CPU partial here
                    # would force the mixed pull path — megabytes of
                    # sketch state over the tunnel instead of one
                    # device merge + a kilobyte readback.
                    small_np = False
                ctx = (jax.default_device(_cpu_device()) if small_np
                       else _contextlib.nullcontext())
                with ctx:
                    p = partial_step(cols, np.int64(n_valid), t_lo,
                                     t_hi, luts)
                    if not small_np and backend == "tpu" \
                            and not device_merge_ok \
                            and not getattr(self, "_defer_active",
                                            False):
                        # pack the multi-leaf state into one buffer per
                        # dtype (an extra async dispatch): each pulled
                        # leaf costs a round trip on a tunneled runtime
                        # (deferred partials stay raw — the gang merge
                        # reduces leaf-wise)
                        pk = _state_packer(p)
                        if pk is not None:
                            packer, unpack = pk
                            p = _PackedState(packer(p), unpack)
                partials.append(p)

            for cols, n_valid in self._feed(src, names, cap,
                                            spmd=spmd_step is not None,
                                            backend=backend):
                if fuse_ok and held is None and not partials:
                    held = (cols, n_valid)
                    continue
                if held is not None:
                    dispatch_plain(*held)
                    held = None
                bucket = _first_len(cols)
                if spmd_step is not None and bucket % n_dev == 0:
                    from pixie_tpu.parallel.spmd import per_shard_valid

                    nv = per_shard_valid(n_valid, bucket, n_dev)
                    partials.append(spmd_step(cols, nv, t_lo, t_hi, luts))
                    self.stats["spmd_feeds"] = self.stats.get("spmd_feeds", 0) + 1
                    self._note_shard_rows(nv)
                else:
                    dispatch_plain(cols, n_valid)
                if self.analyze:
                    tf0 = _time.perf_counter_ns()
                    jax.block_until_ready(
                        partials[-1].buf
                        if isinstance(partials[-1], _PackedState)
                        else partials[-1])
                    rec = getattr(self, "_feed_rec", None)
                    if rec is not None:
                        rec.setdefault("feed_ns", []).append(
                            _time.perf_counter_ns() - tf0)
            if held is not None:
                # exactly ONE feed: the fused execution computes partial
                # state AND finalizes on device in a single dispatch — one
                # execution + one small readback wave is the whole query
                fn = _fused_partial_finalize(
                    fuse_key,
                    {name: uda for name, uda, _dt in init_specs},
                    partial_step)
                finals, rest = fn(held[0], np.int64(held[1]), t_lo, t_hi,
                                  luts)
                finals_np, rest_np = transfer.pull((finals, rest))
                self.stats["fused_single_feed"] = self.stats.get(
                    "fused_single_feed", 0) + 1
                out = dict(rest_np)
                for k, v in finals_np.items():
                    out[k] = _FinalizedCol(v)
                return out
            if partials:
                # deferral is scoped to the distributed partial path
                # (_partial_agg_batch) — the local finalize path reads the
                # pulled state dict directly and must never see a
                # _DeferredState
                if getattr(self, "_defer_active", False):
                    # Split CPU-resident partials (small numpy feeds, e.g.
                    # the hot remainder) from accelerator ones: CPU states
                    # merge on host for free, and must NOT ride into the
                    # device gang merge — that would UPLOAD each one back to
                    # the accelerator.
                    dev, host = [], []
                    for p in partials:
                        (host if _state_on_cpu(p) else dev).append(p)
                    host_state = (merge_fn(*transfer.pull(host))
                                  if host else None)
                    if not dev:
                        return host_state
                    return _DeferredState(dev, merge_fn, host_state)
                if device_merge_ok:
                    # ONE device execution merges every per-feed partial and
                    # finalizes large-state UDAs (sketch → quantiles) in
                    # place, so the readback wave carries kilobytes of
                    # answers instead of megabytes of state — on a tunneled
                    # runtime (~24 MB/s, ~100 ms/pull) state bytes are the
                    # dominant e2e cost (reference bar: zero-copy batch
                    # handoff, exec_graph.cc:177-260).
                    udas_by_name = {name: uda
                                    for name, uda, _dt in init_specs}
                    rt = {name: uda.reduce_ops()
                          for name, uda, _dt in init_specs}
                    # the distributed partial path ships RAW state (it must
                    # stay mergeable across agents): device-merge the feeds
                    # but never finalize
                    fin_ok = not getattr(self, "_partial_wire", False)
                    spec_key = ("mfz", fin_ok, tuple(
                        (name, type(uda).__qualname__,
                         getattr(uda, "q", None))
                        for name, uda, _dt in init_specs))
                    finals, rest = _merge_finalize_fn(
                        spec_key, rt, udas_by_name,
                        finalize_ok=fin_ok)(*partials)
                    finals_np, rest_np = transfer.pull((finals, rest))
                    out = dict(rest_np)
                    for k, v in finals_np.items():
                        out[k] = _FinalizedCol(v)
                    return out
                pulled = transfer.pull(
                    [p.buf if isinstance(p, _PackedState) else p
                     for p in partials])
                states = [
                    p.unpack(buf) if isinstance(p, _PackedState) else buf
                    for p, buf in zip(partials, pulled)
                ]
                return merge_fn(*states)

        if state is None:  # no feeds at all: identity state
            state = {name: uda.init(num_groups, in_dt)
                     for name, uda, in_dt in init_specs}
        return transfer.pull(state)

    def _decode_key_column(self, k: GroupKey, codes: np.ndarray):
        """Seen-group codes → (np column, dictionary|None) for key k."""
        if k.kind == "dict":
            return codes.astype(np.int32), k.dictionary
        if k.kind == "intdevice":
            vals = k.dictionary.decode(codes)
            return np.asarray(vals, dtype=STORAGE_DTYPE[k.out_dtype]), None
        return ((codes.astype(np.int64) + k.t0_bin) * k.width).astype(np.int64), None

    def _partial_agg_batch(self, op: AggOp):
        """Distributed partial path: seen groups as VALUES + raw UDA state
        (see pixie_tpu.parallel.partial.PartialAggBatch)."""
        self._defer_active = self.defer_agg_pull
        self._partial_wire = True  # ship raw state; no device finalize
        try:
            keys, udas, state_np, seen_name, in_types, val_dicts = self._agg_state(op)
        except GroupKeyFallback:
            return self._sorted_partial_batch(op)
        finally:
            self._defer_active = False
            self._partial_wire = False
        if val_dicts:
            raise Internal(
                "dict-valued aggregates must ship rows, not partial state "
                "(the distributed planner cuts them as rows channels)"
            )
        if isinstance(state_np, _DeferredState):
            deferred = state_np

            def finish_state(merged, self=self, keys=keys, udas=udas,
                             seen_name=seen_name, in_types=in_types):
                return self._finish_partial_batch(
                    keys, udas, merged, seen_name, in_types)

            def finish(pulled, finish_state=finish_state, deferred=deferred):
                states = list(pulled)
                if deferred.host_state is not None:
                    states.append(deferred.host_state)
                return finish_state(deferred.merge_fn(*states))

            return _DeferredPartial(
                deferred.partials, finish,
                layout_fp=self._partial_layout_fp(keys, udas, in_types,
                                                  seen_name),
                finish_state=finish_state,
                reduce_tree={name: uda.reduce_ops()
                             for name, uda, _vb in udas},
                host_state=deferred.host_state,
                host_merge=deferred.merge_fn,
            )
        return self._finish_partial_batch(keys, udas, state_np, seen_name,
                                          in_types)

    @staticmethod
    def _partial_layout_fp(keys, udas, in_types, seen_name):
        """Fingerprint of the partial state's LAYOUT + key code spaces.  Two
        agents with equal fingerprints produce states indexed identically
        (same composite group-code meaning), so their states may merge on
        device BEFORE decode.  Dictionaries fingerprint by CONTENT — two
        stores ingesting different values hash apart and take the host
        value-keyed merge instead."""
        key_fp = []
        for k in keys:
            d_fp = (_dict_fingerprint(k.dictionary)
                    if k.dictionary is not None else None)
            key_fp.append((k.name, k.kind, k.card, int(k.out_dtype), d_fp,
                           k.width, k.t0_bin))
        uda_fp = tuple((name, type(uda).__name__) for name, uda, _vb in udas)
        return (tuple(key_fp), uda_fp, seen_name,
                tuple(sorted((k, -1 if v is None else int(v))
                             for k, v in in_types.items())))

    def _finish_partial_batch(self, keys, udas, state_np, seen_name, in_types):
        from pixie_tpu.parallel.partial import PartialAggBatch

        seen_counts = np.asarray(state_np[seen_name])
        if keys:
            gids = np.nonzero(seen_counts > 0)[0]
        else:
            gids = np.array([0])
        key_cols: dict = {}
        key_dtypes: dict = {}
        if keys:
            from pixie_tpu.ops.groupby import split_codes

            codes = split_codes(gids, [k.card for k in keys])
            for k, kc in zip(keys, codes):
                key_dtypes[k.name] = k.out_dtype
                col, d = self._decode_key_column(k, kc)
                if d is not None:
                    # ship VALUES — each agent has a private code space
                    key_cols[k.name] = np.asarray(d.decode(col), dtype=object)
                else:
                    key_cols[k.name] = col
        states = {}
        for out_name, _uda, _vb in udas:
            if out_name == seen_name:
                continue
            states[out_name] = jax.tree.map(lambda x: np.asarray(x)[gids], state_np[out_name])
        return PartialAggBatch(
            key_cols=key_cols, key_dtypes=key_dtypes, states=states,
            in_types={k: v for k, v in in_types.items()},
        )

    # ------------------------------------------------- multi-query gang
    def _gang_agg_payloads(self) -> dict:
        """{channel: PartialAggBatch} for agg_state sinks executed as ONE
        fused multi-query gang — ≥2 distinct partial aggs sharing a single
        MemorySourceOp (the fused-batch agent-plan shape).  Empty when
        fusion is off or inapplicable; such sinks run per-sink as before."""
        if not _mq_fusion_enabled() or self.analyze:
            return {}
        groups: dict[int, list] = {}
        for sink in self.plan.sinks():
            if not isinstance(sink, ResultSinkOp) \
                    or sink.payload != "agg_state":
                continue
            parent = self.plan.parents(sink)[0]
            if not (isinstance(parent, AggOp) and parent.partial):
                continue
            try:
                head, _chain = self._upstream_chain(
                    self.plan.parents(parent)[0])
            except Internal:
                continue
            if isinstance(head, MemorySourceOp):
                groups.setdefault(head.id, []).append((sink.channel, parent))
        out: dict = {}
        for g in groups.values():
            # one agg feeding several channels computes ONCE — dedup by op
            # identity before fusing, then fan the payload out per channel
            uniq, seen = [], set()
            for _c, p in g:
                if id(p) not in seen:
                    seen.add(id(p))
                    uniq.append(p)
            if len(uniq) < 2:
                continue
            got = self._multi_partial_agg(uniq)
            if got is None:
                continue
            for cid, parent in g:
                out[cid] = got[parent.id]
        if out and _autotune.enabled():
            # record-only gate: the fusion choice is baked into compiled
            # kernels at trace time, so the model attributes it but never
            # flips it per query (flipping would churn the program cache —
            # tuning it from measured wave RTT on accelerator hardware is
            # the documented ROADMAP remainder)
            self.stats.setdefault("autotune", []).append({
                "gate": _autotune.GATE_MQ_FUSION, "plan_class": "agg",
                "size_bucket": _autotune.size_bucket(len(out)),
                "arm": "fused", "static_arm": "fused", "source": "static",
                "model_ms": None, "static_ms": None, "n": len(out)})
        return out

    def _multi_partial_agg(self, ops: list) -> Optional[dict]:
        """Execute N partial aggregates over ONE shared scan as a fused
        multi-query device program: each feed wave runs a single jitted
        execution computing EVERY member's partial state (the members'
        own partial_steps traced together, states stacked in the output
        tuple), and the whole gang's states read back in one transfer
        wave — wave RTT and H2D amortize across the batch.  Returns
        {op.id: PartialAggBatch}, or None when any member is out of scope
        (callers run the per-sink path; results are bit-identical either
        way because the fused program calls each member's own unchanged
        kernel over the same feed contents)."""
        spmd = self.mesh is not None
        setups = []
        for op in ops:
            try:
                s = self._agg_setup(op)
            except GroupKeyFallback:
                return None  # per-sink path reruns via the sorted fallback
            if (s.sig is None or s.kern.has_limit or s.val_dicts
                    or (spmd and s.spmd_step is None)):
                return None
            if not setups and not spmd \
                    and self._backend_for(s.src) != "tpu":
                # CPU-routed queries keep the per-member np_partial /
                # wholeplan-native loops (memory-speed paths the fused jit
                # does not beat); the gang amortizes ACCELERATOR wave RTT —
                # decided on the FIRST setup so a bail wastes only one
                return None
            setups.append(s)
        # the EARLIEST member's snapshot feeds the gang: later setups'
        # prescanned key sets cover at least its rows (tables are
        # append-only), so every member's kernel can encode every fed row
        src = setups[0].src
        union_names: list[str] = []
        for s in setups:
            for n in s.names:
                if n not in union_names:
                    union_names.append(n)
        # member sigs already carry the mesh size, so plain and spmd gangs
        # can never collide under one fused-program cache key
        fkey = ("mq",) + tuple(s.sig for s in setups)
        fused = _cache_get(fkey)
        if fused is None:
            steps = tuple(s.partial_step for s in setups)

            def fused_fn(cols, n_valid, t_lo, t_hi, luts, steps=steps):
                return tuple(st(cols, n_valid, t_lo, t_hi, l)
                             for st, l in zip(steps, luts))

            fused_spmd = None
            if spmd:
                from pixie_tpu.parallel.spmd import (
                    reduce_tree_for,
                    spmd_multi_partial_step,
                )

                specs = []
                for s in setups:
                    init = list(s.init_specs)
                    g = s.num_groups
                    specs.append((
                        s.kern.raw_agg_step,
                        lambda init=init, g=g: {
                            name: uda.init(g, in_dt)
                            for name, uda, in_dt in init},
                        reduce_tree_for(s.udas),
                        len(s.kern.limit_ns),
                    ))
                fused_spmd = spmd_multi_partial_step(specs, self.mesh)
            fused = (jax.jit(fused_fn), fused_spmd)
            _cache_put(fkey, fused)
        fused_plain, fused_spmd = fused
        t_lo, t_hi = _time_bounds(setups[0].head)
        luts = tuple(
            ({**s.kern.luts, **s.lut_over} if s.lut_over else s.kern.luts)
            for s in setups)
        n_dev = self.mesh.size if spmd else 1
        per_member: list[list] = [[] for _ in setups]
        with self._timed(f"mq_gang[{len(setups)}]", [op.id for op in ops]):
            for cols, n_valid in self._feed(src, union_names, setups[0].cap,
                                            spmd=spmd, backend="tpu"):
                bucket = _first_len(cols)
                if spmd and bucket % n_dev == 0:
                    from pixie_tpu.parallel.spmd import per_shard_valid

                    nv = per_shard_valid(n_valid, bucket, n_dev)
                    states = fused_spmd(cols, nv, t_lo, t_hi, luts)
                    self.stats["spmd_feeds"] = (
                        self.stats.get("spmd_feeds", 0) + 1)
                    self._note_shard_rows(nv)
                else:
                    states = fused_plain(cols, np.int64(n_valid), t_lo,
                                         t_hi, luts)
                for parts, st in zip(per_member, states):
                    parts.append(st)
            self.stats["mq_waves"] = (self.stats.get("mq_waves", 0)
                                      + len(per_member[0]))
            pull = []
            for s, parts in zip(setups, per_member):
                if not parts:  # empty scan: identity state per member
                    pull.append({name: uda.init(s.num_groups, in_dt)
                                 for name, uda, in_dt in s.init_specs})
                elif len(parts) == 1:
                    pull.append(parts[0])
                else:
                    # same per-member device merge the unbatched partial
                    # path runs (finalize_ok False: raw state must stay
                    # mergeable across agents) — shared cache key included
                    rt = {name: uda.reduce_ops()
                          for name, uda, _dt in s.init_specs}
                    by_name = {name: uda for name, uda, _dt in s.init_specs}
                    spec_key = ("mfz", False, tuple(
                        (name, type(uda).__qualname__,
                         getattr(uda, "q", None))
                        for name, uda, _dt in s.init_specs))
                    _finals, rest = _merge_finalize_fn(
                        spec_key, rt, by_name, finalize_ok=False)(*parts)
                    pull.append(rest)
            pulled = transfer.pull(pull)
        out = {}
        for s, state_np in zip(setups, pulled):
            out[s.op.id] = self._finish_partial_batch(
                s.keys, s.udas, state_np, s.seen_name, s.in_types)
        self.stats["mq_fused"] = self.stats.get("mq_fused", 0) + len(setups)
        return out

    def run_agent(self) -> dict:
        """Execute an AGENT plan: returns {channel: payload} where payload is a
        HostBatch (rows channels) or PartialAggBatch (agg_state channels)."""
        from pixie_tpu.plan.plan import PartitionSinkOp

        out = {}
        t0 = _time.perf_counter_ns()
        gang = self._gang_agg_payloads()
        for sink in self.plan.sinks():
            if isinstance(sink, PartitionSinkOp):
                # hash-partitioned shuffle edge: one rows channel per bucket.
                # With a multi-device mesh whose size matches n_parts, the
                # exchange is ONE lax.all_to_all over the mesh (the ICI
                # shuffle of SURVEY §2.5; reference splitter.h:114-155);
                # otherwise the host hash/sort/split exchange.  Both assign
                # partitions by identical value hashes, so mixed producers
                # interoperate.
                from pixie_tpu.parallel.repartition import (
                    mesh_partition_exchange,
                    partition_ids,
                    split_host_batch,
                )

                parent = self.plan.parents(sink)[0]
                hb = self._materialize_parent(parent)
                if (self.mesh is not None
                        and self.mesh.size == sink.n_parts
                        and hb.num_rows > 0):
                    buckets = mesh_partition_exchange(
                        hb, sink.keys, sink.n_parts, self.mesh)
                    self.stats["mesh_shuffles"] = (
                        self.stats.get("mesh_shuffles", 0) + 1)
                else:
                    part = partition_ids(hb, sink.keys, sink.n_parts)
                    buckets = split_host_batch(hb, part, sink.n_parts)
                for p, bucket in enumerate(buckets):
                    out[f"{sink.prefix}{p}"] = bucket
                continue
            if not isinstance(sink, ResultSinkOp):
                raise Internal(f"agent plan sink {sink.kind} is not a ResultSink")
            parent = self.plan.parents(sink)[0]
            if sink.payload == "agg_state":
                if not (isinstance(parent, AggOp) and parent.partial):
                    raise Internal("agg_state channel must be fed by a partial AggOp")
                if sink.channel in gang:
                    out[sink.channel] = gang[sink.channel]
                else:
                    out[sink.channel] = self._partial_agg_batch(parent)
            else:
                out[sink.channel] = self._materialize_parent(parent)
        self.stats["wall_ns"] = _time.perf_counter_ns() - t0
        self.stats["operators"] = self.op_stats
        self._emit_op_spans()
        return out

    def run_agent_stream(self, agg_chunk_groups: int = 0):
        """Execute an AGENT plan as a chunk stream: yields (channel, payload)
        in wave order — one HostBatch per readback wave for rows channels
        (each wave's D2H rode under a later wave's compute, engine.transfer),
        per group-slice for agg_state channels (`agg_chunk_groups` > 0 caps
        the slice), per bucket for partition sinks.  The networked agent
        ships each yield as its own wire frame, so the broker's incremental
        fold starts while this executor is still computing; run_agent is the
        barrier shape of the same walk.

        Chunks of one channel are yielded in order, but consumers must not
        rely on it: the broker-side folds (PartialAggFold / HostBatchUnion)
        are order-insensitive by construction.
        """
        from pixie_tpu.plan.plan import PartitionSinkOp

        t0 = _time.perf_counter_ns()
        gang = self._gang_agg_payloads()
        for sink in self.plan.sinks():
            if isinstance(sink, PartitionSinkOp):
                from pixie_tpu.parallel.repartition import (
                    mesh_partition_exchange,
                    partition_ids,
                    split_host_batch,
                )

                parent = self.plan.parents(sink)[0]
                hb = self._materialize_parent(parent)
                if (self.mesh is not None
                        and self.mesh.size == sink.n_parts
                        and hb.num_rows > 0):
                    buckets = mesh_partition_exchange(
                        hb, sink.keys, sink.n_parts, self.mesh)
                    self.stats["mesh_shuffles"] = (
                        self.stats.get("mesh_shuffles", 0) + 1)
                else:
                    part = partition_ids(hb, sink.keys, sink.n_parts)
                    buckets = split_host_batch(hb, part, sink.n_parts)
                for p, bucket in enumerate(buckets):
                    yield f"{sink.prefix}{p}", bucket
                continue
            if not isinstance(sink, ResultSinkOp):
                raise Internal(f"agent plan sink {sink.kind} is not a ResultSink")
            parent = self.plan.parents(sink)[0]
            if sink.payload == "agg_state":
                if not (isinstance(parent, AggOp) and parent.partial):
                    raise Internal("agg_state channel must be fed by a partial AggOp")
                pb = (gang[sink.channel] if sink.channel in gang
                      else self._partial_agg_batch(parent))
                n = pb.num_groups
                if agg_chunk_groups > 0 and n > agg_chunk_groups:
                    from pixie_tpu.parallel.partial import slice_partial

                    for a in range(0, n, agg_chunk_groups):
                        idx = np.arange(a, min(a + agg_chunk_groups, n))
                        yield sink.channel, slice_partial(pb, idx)
                else:
                    yield sink.channel, pb
            else:
                out_dtypes, out_dicts, out_names, gen = self._consume_chain(parent)
                sent = False
                for cols, _c in gen:
                    sent = True
                    yield sink.channel, HostBatch(
                        dict(out_dtypes), dict(out_dicts),
                        {name: cols[name] for name in out_names})
                if not sent:
                    # the channel contract is ≥1 payload: an empty scan still
                    # ships one zero-row chunk carrying the dtypes/dicts
                    yield sink.channel, HostBatch(
                        dict(out_dtypes), dict(out_dicts),
                        {name: np.empty(0, STORAGE_DTYPE[out_dtypes[name]])
                         for name in out_names})
        self.stats["wall_ns"] = _time.perf_counter_ns() - t0
        self.stats["operators"] = self.op_stats
        self._emit_op_spans()

    def _finalize_agg(self, op, keys, udas, state_np, seen_name, in_types=None,
                      val_dicts=None) -> HostBatch:
        from pixie_tpu.ops.groupby import split_codes

        seen_counts = np.asarray(state_np[seen_name])
        if keys:
            gids = np.nonzero(seen_counts > 0)[0]
        else:
            gids = np.array([0])  # group-by-none always emits one row
        dtypes: dict[str, DT] = {}
        dicts: dict[str, Dictionary] = {}
        cols: dict[str, np.ndarray] = {}
        if keys:
            codes = split_codes(gids, [k.card for k in keys])
            for k, kc in zip(keys, codes):
                dtypes[k.name] = k.out_dtype
                if k.kind == "dict":
                    cols[k.name] = kc.astype(np.int32)
                    dicts[k.name] = k.dictionary
                elif k.kind == "intdevice":
                    vals = k.dictionary.decode(kc)
                    cols[k.name] = np.asarray(vals, dtype=STORAGE_DTYPE[k.out_dtype])
                else:  # window
                    cols[k.name] = ((kc.astype(np.int64) + k.t0_bin) * k.width).astype(
                        np.int64
                    )
        for out_name, uda, _vb in udas:
            if out_name == seen_name:
                continue
            st = state_np[out_name]
            if isinstance(st, _FinalizedCol):
                full = uda.finalize_from_device(st.col)
            elif getattr(uda, "needs_dict", False):
                full = uda.finalize_dict(
                    jax.tree.map(lambda x: x, st), val_dicts[out_name])
            else:
                full = uda.finalize_host(jax.tree.map(lambda x: x, st))
            vals = np.asarray(full)[gids]
            # Use the DECLARED input DataType so e.g. min(time_) stays TIME64NS
            # (matching the compile-time schema); fall back to array inference
            # for callers that bypass _run_agg.
            if uda.nullary:
                out_dt = uda.out_type(None)
            elif in_types is not None and out_name in in_types:
                out_dt = uda.out_type(in_types[out_name])
            else:
                out_dt = uda.out_type(_dtype_of(full))
            if (val_dicts and out_name in val_dicts
                    and not getattr(uda, "needs_dict", False)):
                # dict-valued picker: the state holds CODES; out-of-range
                # (all-null group sentinel) decodes to null
                cols[out_name] = _decode_picker_codes(vals, val_dicts[out_name])
                dicts[out_name] = val_dicts[out_name]
                dtypes[out_name] = out_dt
                continue
            if out_dt == DT.STRING:
                d = Dictionary()
                cols[out_name] = d.encode(vals)
                dicts[out_name] = d
            else:
                cols[out_name] = vals.astype(STORAGE_DTYPE[out_dt], copy=False)
            dtypes[out_name] = out_dt
        return HostBatch(dtypes, dicts, cols)

    # -------------------------------------------------------------------- udtf
    def _run_udtf(self, op: UDTFSourceOp) -> HostBatch:
        """Materialize a table-generating function (reference
        exec/udtf_source_node.*): one columnar batch from a host fn."""
        from pixie_tpu.types import is_dict_encoded
        from pixie_tpu.udf.udtf import UDTFContext

        u = self.registry.udtf(op.name)
        # The serialized schema (when present) is authoritative for the output
        # relation — a remote plan's view of the UDTF wins over whatever
        # version is registered locally.
        relation = (
            Relation.from_dict(op.schema) if op.schema is not None else u.relation
        )
        ctx = self.udtf_ctx
        if ctx is None:
            from pixie_tpu.metadata import state as _mdstate

            m = _mdstate.global_manager()
            ctx = UDTFContext(
                table_store=self.store, registry=self.registry,
                asid=m.current().asid, node_name=m.current().node_name,
            )
        cols_raw = u.fn(ctx, **(op.args or {}))
        dtypes, dicts, cols = {}, {}, {}
        for c in relation:
            if c.name not in cols_raw:
                raise Internal(
                    f"UDTF {op.name} did not produce declared column {c.name!r}"
                )
            vals = list(cols_raw[c.name])
            dtypes[c.name] = c.data_type
            if is_dict_encoded(c.data_type):
                if c.data_type == DT.UINT128:
                    # tuples would np-broadcast into 2-D object arrays inside
                    # Dictionary.encode; normalize to UInt128 scalars.
                    from pixie_tpu.types import UInt128

                    vals = [
                        UInt128(*v) if isinstance(v, (tuple, list)) else v
                        for v in vals
                    ]
                d = Dictionary()
                cols[c.name] = d.encode(vals)
                dicts[c.name] = d
            else:
                cols[c.name] = np.asarray(vals, dtype=STORAGE_DTYPE[c.data_type])
        return HostBatch(dtypes, dicts, cols)

    # -------------------------------------------------------------------- join
    def _run_join(self, op: JoinOp) -> HostBatch:
        """Equijoin with full many-to-many expansion, inner/left/right/outer.

        Reference: exec/equijoin_node.h + planpb JoinOperator
        (plan.proto:301-316).  Redesigned as a sort/searchsorted join over
        factorized composite key codes (no hash table): the left side is
        sorted once, each right row binary-searches its match range, and
        m:n pairs expand with a repeat/offset vector — all O((n+m) log n)
        columnar numpy, the same structure the device path reuses for the
        unique-build fast case.  Null keys (dict code -1 or untranslatable
        values) never match but their rows still surface as unmatched in
        left/right/outer joins (pandas semantics).
        """
        parents = self.plan.parents(op)
        if len(parents) != 2:
            raise Internal("join needs two parents")
        left = self._materialize_parent(parents[0])
        right = self._materialize_parent(parents[1])
        if len(op.left_on) != len(op.right_on):
            raise CompilerError("join requires equal-length key lists")
        if op.how not in ("inner", "left", "right", "outer"):
            raise Unimplemented(f"join how={op.how!r}")
        nl, nr = left.num_rows, right.num_rows

        if not op.left_on:
            # Empty key lists = cross join (the bundled cluster script uses
            # merge(left_on=[], right_on=[]) to attach a 1-row time window).
            # When either side is empty, left/right/outer keep the other
            # side's rows with null fills (every row is unmatched).
            lidx = np.repeat(np.arange(nl, dtype=np.int64), nr)
            ridx = np.tile(np.arange(nr, dtype=np.int64), nl)
            if nr == 0 and op.how in ("left", "outer"):
                lidx = np.arange(nl, dtype=np.int64)
                ridx = np.full(nl, -1, dtype=np.int64)
            elif nl == 0 and op.how in ("right", "outer"):
                ridx = np.arange(nr, dtype=np.int64)
                lidx = np.full(nr, -1, dtype=np.int64)
            return self._join_output(op, left, right, lidx, ridx)

        # Factorize each key pair into a shared integer code space; nulls
        # (dict code -1) are tracked separately and excluded from matching.
        lcodes, rcodes = [], []
        lnull = np.zeros(nl, dtype=bool)
        rnull = np.zeros(nr, dtype=bool)
        for lk, rk in zip(op.left_on, op.right_on):
            lv, rv = left.cols[lk], right.cols[rk]
            ld, rd = left.dicts.get(lk), right.dicts.get(rk)
            if (ld is None) != (rd is None):
                raise CompilerError(f"join key {lk}/{rk}: dictionary/plain mismatch")
            if ld is not None:
                lnull |= lv < 0
                if rd is not ld:
                    rv = apply_lut_np(rd.translate_to(ld, insert=False), rv)
                rnull |= rv < 0
            lcodes.append(np.asarray(lv))
            rcodes.append(np.asarray(rv))
        lc, rc = _composite_codes(lcodes, rcodes)

        from pixie_tpu.ops import join_device as _jd  # defines the flag

        at_dec = None
        if min(nl, nr) >= (1 << 16):
            # the gate is AUTO by default: measured H2D bandwidth on
            # accelerators, native-kernel availability on CPU — and the
            # decision is recorded so it is observable, not silent
            gate = _jd.device_join_gate()
            self.stats.setdefault("device", {})["join_gate"] = {
                k: v for k, v in gate.items() if k != "flag"}
            if _autotune.enabled() and gate.get("flag") == -1:
                # under autotune the threshold heuristic becomes the
                # STATIC arm of a measured device-vs-host cost model;
                # epsilon probes keep the unfavored arm's cost current.
                # Both arms return the same matched-pair SET (pair ORDER
                # is unspecified by the join contract either way).
                # Forced flag settings (0/1) are never overridden.
                at_dec = _autotune.MODEL.decide(
                    _autotune.GATE_DEVICE_JOIN, "join",
                    _autotune.size_bucket(min(nl, nr)),
                    "device" if gate["enabled"] else "host",
                    ("device", "host"))
                self.stats.setdefault("autotune", []).append(at_dec)
        else:
            gate = {"enabled": False}
        use_device = (at_dec["arm"] == "device" if at_dec is not None
                      else gate["enabled"])
        t_match0 = _time.perf_counter_ns()
        if use_device:
            # device radix-bucketed match phase (ops/join_device.py):
            # sentinel out the nulls so they can't match (-1 vs -2), then
            # the device kernel returns the same pair/mask contract
            lcx = np.where(lnull, np.int64(-1), lc)
            rcx = np.where(rnull, np.int64(-2), rc)
            lidx, ridx, l_matched, r_matched = _jd.device_join_codes(
                lcx, rcx)
            self.stats["device_joins"] = self.stats.get(
                "device_joins", 0) + 1
        else:
            lidx, ridx, l_matched, r_matched = _match_pairs(
                lc, rc, lnull, rnull)
        if at_dec is not None:
            _autotune.MODEL.observe_decision(
                at_dec, (_time.perf_counter_ns() - t_match0) / 1e9)
            # joins often run inside repartition-stage executors whose
            # stats dict is consumed, not forwarded — the event buffer is
            # the durable telemetry path for this gate
            _autotune.MODEL.record_row(at_dec)
        lsel, rsel = [lidx], [ridx]
        if op.how in ("left", "outer"):
            lum = np.nonzero(~l_matched)[0]
            lsel.append(lum)
            rsel.append(np.full(len(lum), -1, dtype=np.int64))
        if op.how in ("right", "outer"):
            rum = np.nonzero(~r_matched)[0]
            lsel.append(np.full(len(rum), -1, dtype=np.int64))
            rsel.append(rum)
        lsel = np.concatenate(lsel)
        rsel = np.concatenate(rsel)
        return self._join_output(op, left, right, lsel, rsel)

    def _join_output(self, op, left, right, lsel, rsel) -> HostBatch:
        dtypes, dicts, cols = {}, {}, {}
        outputs = op.output or _default_join_output(left, right)
        for side, col, out_name in outputs:
            src_b = left if side == "left" else right
            sel = lsel if side == "left" else rsel
            cols[out_name] = _take_with_nulls(
                src_b.cols[col], sel, src_b.dtypes[col]
            )
            dtypes[out_name] = src_b.dtypes[col]
            if col in src_b.dicts:
                dicts[out_name] = src_b.dicts[col]
        return HostBatch(dtypes, dicts, cols)

    def _run_union(self, op: UnionOp) -> HostBatch:
        parents = self.plan.parents(op)
        batches = [self._materialize_parent(p) for p in parents]
        first = batches[0]
        cols: dict[str, np.ndarray] = {}
        dicts: dict[str, Dictionary] = {}
        for name, dt in first.dtypes.items():
            parts = []
            if name in first.dicts:
                target = Dictionary(first.dicts[name].values())
                dicts[name] = target
                for b in batches:
                    lut = b.dicts[name].translate_to(target, insert=True)
                    parts.append(apply_lut_np(lut, b.cols[name]))
            else:
                parts = [b.cols[name] for b in batches]
            cols[name] = np.concatenate(parts) if parts else np.empty(0)
        return HostBatch(dict(first.dtypes), dicts, cols)

    def _materialize_parent(self, parent) -> HostBatch:
        head, chain = self._upstream_chain(parent)
        if not chain and not isinstance(head, MemorySourceOp):
            return self._eval_blocking(head)
        return self._consume_to_batch(parent)

    # ------------------------------------------------------------------- otel
    def _run_otel_sink(self, sink: OTelExportSinkOp) -> None:
        """Export parent rows as OTLP (reference exec/otel_export_sink_node.*)."""
        from pixie_tpu.engine.otel import batch_to_otlp, make_exporter

        parent = self.plan.parents(sink)[0]
        hb = self._materialize_parent(parent)
        with self._timed("otel_export", [sink.id]) as rec:
            payload = batch_to_otlp(hb, sink.config)
            export = make_exporter(sink.config, self.otel_exporter)
            export(payload)
            n_metrics = sum(
                len(m["gauge"]["dataPoints"] if "gauge" in m else m["summary"]["dataPoints"])
                for rm in payload.get("resourceMetrics", [])
                for sm in rm["scopeMetrics"]
                for m in sm["metrics"]
            )
            n_spans = sum(
                len(ss["spans"])
                for rs in payload.get("resourceSpans", [])
                for ss in rs["scopeSpans"]
            )
            rec["rows_out"] = hb.num_rows
            self.stats["otel_datapoints"] = self.stats.get("otel_datapoints", 0) + n_metrics
            self.stats["otel_spans"] = self.stats.get("otel_spans", 0) + n_spans

    # -------------------------------------------------------------------- run
    def run(self) -> dict[str, QueryResult]:
        results = {}
        t0 = _time.perf_counter_ns()
        for sink in self.plan.sinks():
            if isinstance(sink, OTelExportSinkOp):
                self._run_otel_sink(sink)
                continue
            if not isinstance(sink, MemorySinkOp):
                raise Internal(f"plan sink {sink.kind} is not a MemorySink")
            parent = self.plan.parents(sink)[0]
            out_dtypes, out_dicts, out_names, gen = self._consume_chain(
                parent, sink.columns
            )
            parts = [c for c, _ in gen]
            cols = {
                n: (
                    np.concatenate([p[n] for p in parts])
                    if parts
                    else np.empty(0, STORAGE_DTYPE[out_dtypes[n]])
                )
                for n in out_names
            }
            from pixie_tpu.engine.semantics import sink_relation

            rel = sink_relation(self.plan, sink, out_names, out_dtypes,
                                self.store, self.registry)
            nrows = len(next(iter(cols.values()))) if cols else 0
            self.stats["rows_output"] += nrows
            results[sink.name] = QueryResult(
                name=sink.name,
                relation=rel,
                columns=cols,
                dictionaries={n: d for n, d in out_dicts.items()},
                exec_stats=dict(self.stats),
            )
        self.stats["wall_ns"] = _time.perf_counter_ns() - t0
        self.stats["operators"] = self.op_stats
        self._emit_op_spans()
        for r in results.values():
            r.exec_stats["wall_ns"] = self.stats["wall_ns"]
            r.exec_stats["operators"] = self.op_stats
        return results


# --------------------------------------------------------------------- helpers


def _pad(arr: np.ndarray, n: int) -> np.ndarray:
    if len(arr) == n:
        return arr
    out = np.zeros(n, dtype=arr.dtype)
    out[: len(arr)] = arr
    return out


def _time_bounds(head) -> tuple[np.int64, np.int64]:
    if isinstance(head, MemorySourceOp):
        lo = INT64_MIN if head.start_time is None else int(head.start_time)
        hi = INT64_MAX if head.stop_time is None else int(head.stop_time)
        return np.int64(lo), np.int64(hi)
    return np.int64(INT64_MIN), np.int64(INT64_MAX)


def _windowish_groups(chain, time_col: Optional[str]) -> dict[str, int]:
    """Group-key names whose FINAL definition in the chain is px.bin over the
    time column (candidates for runtime-origin window keys; used for cache-sig
    planning BEFORE the kernel is built).

    Tracks each Map's full output list in order — a later redefinition of the
    column to anything else drops its window-ness (matching the provenance
    resolution in _plan_group_keys), while a plain rename passes it through.
    """
    out: dict[str, int] = {}
    for op in chain:
        if not isinstance(op, MapOp):
            continue
        new: dict[str, int] = {}
        for name, e in op.exprs:
            w = _window_key(e, time_col)
            if w is not None:
                new[name] = w
            elif isinstance(e, Column) and e.name in out:
                new[name] = out[e.name]  # passthrough keeps window-ness
        out = new
    return out


def _window_key(expr, time_col: Optional[str]) -> Optional[int]:
    """Detect Call(bin, (Column(time_col), Literal w)) → window width, else
    None.  The binned argument must be the source's time column — only then do
    the baked t0_bin/nbins range semantics hold."""
    if (
        isinstance(expr, Call)
        and expr.fn == "bin"
        and len(expr.args) == 2
        and time_col is not None
        and isinstance(expr.args[0], Column)
        and expr.args[0].name == time_col
    ):
        w = expr.args[1]
        if isinstance(w, Literal) and isinstance(w.value, int) and w.value > 0:
            return int(w.value)
    return None


def _source_time_range(src, head) -> tuple[int, int]:
    if isinstance(src, HostBatch):
        raise Unimplemented("window group keys require a table source")
    if src.table.time_col is None:
        raise Unimplemented("window group keys require a time_ column")
    rng = src.time_range()  # O(batches): sealed bounds cached at seal time
    t_min, t_max = rng if rng is not None else (0, 0)
    if isinstance(head, MemorySourceOp):
        if head.start_time is not None:
            t_min = max(t_min, int(head.start_time))
        if head.stop_time is not None:
            t_max = min(t_max, int(head.stop_time) - 1)
    return t_min, max(t_min, t_max)


def _prescan_unique(src, col: str, qd: Dictionary, sort: bool = False):
    """Populate qd with the column's unique values; sort=True assigns codes in
    sorted order (required by the intdevice searchsorted encoding)."""
    if isinstance(src, HostBatch):
        vals = np.unique(src.cols[col]) if sort else src.cols[col]
        qd.encode(vals)
        return
    if sort:
        parts = [rb.columns[col][: rb.num_valid] for rb, _rid, _gen in src]
        parts = [p for p in parts if len(p)]
        if parts:
            qd.encode(np.unique(np.concatenate([np.unique(p) for p in parts])))
        return
    for rb, _rid, _gen in src:
        arr = rb.columns[col][: rb.num_valid]
        if len(arr):
            qd.encode(np.unique(arr))


def _composite_codes(
    lkeys: list[np.ndarray], rkeys: list[np.ndarray]
) -> tuple[np.ndarray, np.ndarray]:
    """Factorize both sides' (multi-)key rows into one shared int64 code space
    so matching reduces to integer comparison.

    Each key pair factorizes separately FIRST (np.unique collapses NaN on 1-D
    float arrays, giving pandas' NaN==NaN merge semantics), then the per-key
    code columns combine — structured-array comparison over floats would treat
    NaNs as distinct and make join behavior depend on key count.
    """
    nl = len(lkeys[0]) if lkeys else 0
    per = []
    for l, r in zip(lkeys, rkeys):
        _u, inv = np.unique(np.concatenate([l, r]), return_inverse=True)
        per.append(inv.astype(np.int64))
    if len(per) == 1:
        comb = per[0]
    else:
        _u, comb = np.unique(np.rec.fromarrays(per), return_inverse=True)
        comb = comb.astype(np.int64)
    return comb[:nl], comb[nl:]


def _match_pairs(
    lc: np.ndarray, rc: np.ndarray, lnull: np.ndarray, rnull: np.ndarray
) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """All matching (left_row, right_row) pairs with m:n expansion.

    Returns (lidx, ridx, l_matched[nl], r_matched[nr]).  Sort the valid left
    rows by code; each valid right row finds its [lo, hi) match range by
    binary search and contributes hi-lo pairs.
    """
    nl, nr = len(lc), len(rc)
    lvalid = np.nonzero(~lnull)[0]
    order = lvalid[np.argsort(lc[lvalid], kind="stable")]
    sorted_keys = lc[order]
    if nr >= (1 << 20):
        # Large probe sides: binary-searching RANDOM keys over a big sorted
        # array is memory-latency-bound (measured 29 s for 16M x 16M);
        # sorting the probes first makes consecutive searches cache-local
        # (1.3 s) and the extra sort+scatter-back pays for itself 5x over.
        rorder = np.argsort(rc, kind="stable")
        rs = rc[rorder]
        lo = np.empty(nr, np.int64)
        hi = np.empty(nr, np.int64)
        lo[rorder] = np.searchsorted(sorted_keys, rs, side="left")
        hi[rorder] = np.searchsorted(sorted_keys, rs, side="right")
    else:
        lo = np.searchsorted(sorted_keys, rc, side="left")
        hi = np.searchsorted(sorted_keys, rc, side="right")
    counts = np.where(rnull, 0, hi - lo)
    total = int(counts.sum())
    ridx = np.repeat(np.arange(nr, dtype=np.int64), counts)
    offsets = np.cumsum(counts) - counts
    within = np.arange(total, dtype=np.int64) - np.repeat(offsets, counts)
    lidx = order[np.repeat(lo, counts) + within]
    l_matched = np.zeros(nl, dtype=bool)
    l_matched[lidx] = True
    r_matched = counts > 0
    return lidx, ridx, l_matched, r_matched


def _take_with_nulls(arr: np.ndarray, sel: np.ndarray, dt: DT) -> np.ndarray:
    """arr[sel] with sel == -1 producing the type's null fill."""
    if len(arr) == 0:
        out = np.zeros(len(sel), dtype=arr.dtype)
        miss = np.ones(len(sel), dtype=bool)
    else:
        out = arr[np.clip(sel, 0, len(arr) - 1)]
        miss = sel < 0
    if miss.any():
        out = out.copy()
        out[miss] = _null_value(dt)
    return out


def _default_join_output(left: HostBatch, right: HostBatch):
    out = []
    for c in right.cols:
        out.append(("right", c, c))
    for c in left.cols:
        if c not in right.cols:
            out.append(("left", c, c))
    return out


def _null_value(dt: DT):
    if dt == DT.FLOAT64:
        return np.nan
    if dt in (DT.STRING, DT.UINT128):
        return -1  # code -1 decodes to None
    return 0


def _dtype_of(arr) -> DT:
    d = np.asarray(arr).dtype
    if d.kind == "f":
        return DT.FLOAT64
    if d.kind in "iu":
        return DT.INT64
    if d.kind == "b":
        return DT.BOOLEAN
    return DT.STRING


def execute_plan(plan: Plan, table_store, registry=None,
                 analyze: bool = False) -> dict[str, QueryResult]:
    """Compile + run a plan against a table store; returns {sink_name: QueryResult}."""
    return PlanExecutor(plan, table_store, registry, analyze=analyze).run()

"""Whole-plan native codegen for the sub-crossover CPU path.

Lowers a fused scan→filter→map→partial-agg chain into the micro-program
`native/wholeplan.cc` executes as ONE compiled loop (Flare, PAPERS.md: below
the accelerator crossover, per-op kernels with intermediate arrays lose to a
single fused loop).  The lowering is conservative and total: anything it
cannot reproduce EXACTLY (computed map expressions, dict-column predicates,
limits, unsupported UDAs) returns None and the executor keeps the
interpreted jitted-kernel path — so the native loop is a pure fast path,
never a semantics fork.

Supported shapes (the interactive dashboard family):
  * chain: Filter steps of ``Column <cmp> Literal`` (or a bare BOOLEAN
    column) over numeric source columns, Map steps that are pure renames —
    plus the planner's ``time_ = px.bin(time_, w)`` window rewrite when the
    binned name is consumed ONLY as a window group key and the query is
    time-unbounded (the np_partial admission rule);
  * group keys: dict codes (null-drop), intdevice (searchsorted against the
    kernel's sorted-unique LUT), window bins;
  * UDAs: count/sum/mean/min/max/any/variance/stddev + the log-histogram
    quantile sketch (p50/p99/quantiles) — state layouts leaf-identical to
    the jitted kernels, accumulated in row order (the order numpy bincount
    and XLA-CPU's scatter walk), int64 sums wrapping mod 2^64.

Programs are structural (column names + op codes); per-run values (window
origins, intdevice LUTs) resolve from the kernel's luts at run time, so one
lowered program serves every poll/range that reuses the compiled kernel.
Lowered programs are cached per plan signature in
`engine.plancache.native_programs`.
"""
from __future__ import annotations

import ctypes
import dataclasses
import math
import threading
from typing import Optional

import numpy as np

from pixie_tpu import flags as _flags

_flags.define_bool(
    "PX_WHOLEPLAN_NATIVE", True,
    "fuse sub-crossover scan->filter->map->partial-agg chains into the "
    "native whole-plan loop (native/wholeplan.cc); 0 = interpreted "
    "jitted-kernel path only")

# column dtype tags (wholeplan.cc DT_*)
_DT_I64, _DT_F64, _DT_I32, _DT_U8 = 0, 1, 2, 3
_NP_TO_TAG = {np.dtype(np.int64): _DT_I64, np.dtype(np.float64): _DT_F64,
              np.dtype(np.int32): _DT_I32, np.dtype(np.bool_): _DT_U8}

_CMP_OPS = {"equal": 0, "not_equal": 1, "less": 2, "less_equal": 3,
            "greater": 4, "greater_equal": 5}
#: literal-on-the-left flip: lit < col  ==  col > lit
_FLIP = {0: 0, 1: 1, 2: 4, 3: 5, 4: 2, 5: 3}

_UNBOUNDED_LO, _UNBOUNDED_HI = -(1 << 62), (1 << 62)

#: sentinel for map outputs produced by the window-bin rewrite: readable
#: ONLY as a window group key
_WINDOW_ONLY = object()


@dataclasses.dataclass
class Program:
    """A lowered whole-plan micro-program (structural; run-time bindings —
    LUTs, window origins, state buffers — resolve per run)."""

    cols: list          # ordered source column names the loop reads
    col_tags: list      # wholeplan.cc dtype tag per column
    filters: list       # (col_idx, op, is_float, ival, fval)
    time_idx: int       # column index for time bounds, -1 = never bounded
    keys: list          # (kind, col_idx, card, width, lut_name)
    aggs: list          # (kind, out_name, value_col_idx)
    requires_unbounded: bool
    hist_width: int
    inv_log_gamma: float
    min_value: float


def _native():
    from pixie_tpu.native.build import load_native

    lib = load_native()
    if lib is not None and hasattr(lib, "px_wholeplan_run"):
        return lib
    return None


def _resolve_filter(expr, env, dtypes, dicts):
    """Lower one FilterOp expression under the rename env `env`
    (post-map name -> source column name).  → (col, op, isf, ival, fval)
    or None."""
    from pixie_tpu.plan.plan import Call, Column, Literal
    from pixie_tpu.types import DataType as DT

    if isinstance(expr, Column):  # bare boolean column: col != 0
        src = env.get(expr.name)
        if src is None or src is _WINDOW_ONLY or src in dicts:
            return None
        if dtypes.get(src) != DT.BOOLEAN:
            return None
        return (src, _CMP_OPS["not_equal"], 0, 0, 0.0)
    if not isinstance(expr, Call) or expr.fn not in _CMP_OPS \
            or len(expr.args) != 2:
        return None
    a, b = expr.args
    op = _CMP_OPS[expr.fn]
    if isinstance(a, Literal) and isinstance(b, Column):
        a, b, op = b, a, _FLIP[op]
    if not (isinstance(a, Column) and isinstance(b, Literal)):
        return None
    src = env.get(a.name)
    if src is None or src is _WINDOW_ONLY or src in dicts:
        return None
    if dtypes.get(src) not in (DT.INT64, DT.TIME64NS, DT.FLOAT64, DT.BOOLEAN):
        return None
    v = b.value
    if isinstance(v, bool):
        v = int(v)
    if not isinstance(v, (int, float)):
        return None
    col_f = dtypes[src] == DT.FLOAT64
    isf = 1 if (col_f or isinstance(v, float)) else 0
    return (src, op, isf, int(v) if not isf else 0,
            float(v) if isf else 0.0)


def _lower_chain(chain, names, dtypes, dicts, time_col):
    """Walk the chain: → (filters lowered to source columns, final rename
    env, window_bin {name: width}) or None when any step is out of scope."""
    from pixie_tpu.plan.plan import Call, Column, Literal, FilterOp, LimitOp, MapOp

    env = {n: n for n in names}
    filters = []
    window_bin: dict = {}
    for op_ in chain:
        if isinstance(op_, MapOp):
            new_env = {}
            new_windows = {}
            for name, e in op_.exprs:
                if isinstance(e, Column):
                    got = env.get(e.name)
                    if got is None:
                        return None
                    new_env[name] = got
                    if e.name in window_bin:
                        new_windows[name] = window_bin[e.name]
                elif (isinstance(e, Call) and e.fn == "bin"
                        and len(e.args) == 2
                        and isinstance(e.args[0], Column)
                        and env.get(e.args[0].name) == time_col
                        and isinstance(e.args[1], Literal)
                        and isinstance(e.args[1].value, int)):
                    # the planner's window rewrite: consumable only as a
                    # window group key (codegen bins the RAW time column)
                    new_env[name] = _WINDOW_ONLY
                    new_windows[name] = int(e.args[1].value)
                else:
                    return None
            env = new_env
            window_bin = new_windows
        elif isinstance(op_, FilterOp):
            f = _resolve_filter(op_.expr, env, dtypes, dicts)
            if f is None:
                return None
            filters.append(f)
        elif isinstance(op_, LimitOp):
            return None
        else:
            return None
    return filters, env, window_bin


def lower(kern, chain, op, keys, init_specs, dtypes, dicts, names,
          time_col) -> Optional[Program]:
    """Lower one agg chain into a Program, or None when out of scope."""
    from pixie_tpu.engine.np_partial import source_col, value_args
    from pixie_tpu.ops.sketch import LogHistogram
    from pixie_tpu.udf.udf import (
        AnyUDA, CountUDA, MaxUDA, MeanUDA, MinUDA, QuantileUDA, QuantilesUDA,
        StddevUDA, SumUDA, VarianceUDA,
    )

    # NOTE: the PX_WHOLEPLAN_NATIVE kill switch is checked by the CALLER
    # (executor._wholeplan_program) outside the program cache — a cached
    # program must not bypass a live flag flip in either direction; native
    # availability IS safe to bake (process-constant).
    if _native() is None:
        return None
    if kern.has_limit:
        return None
    lowered = _lower_chain(chain, names, dtypes, dicts, time_col)
    if lowered is None:
        return None
    filters, env, window_bin = lowered

    cols: list = []
    tags: list = []

    def col_idx(src_name) -> Optional[int]:
        if src_name not in names:
            return None
        from pixie_tpu.types import STORAGE_DTYPE

        tag = _NP_TO_TAG.get(STORAGE_DTYPE[dtypes[src_name]])
        if tag is None:
            return None
        if src_name in cols:
            return cols.index(src_name)
        cols.append(src_name)
        tags.append(tag)
        return len(cols) - 1

    f_rows = []
    for src, fop, isf, iv, fv in filters:
        ci = col_idx(src)
        if ci is None:
            return None
        f_rows.append((ci, fop, isf, iv, fv))

    requires_unbounded = False
    k_rows = []
    for k in keys:
        if k.kind == "dict":
            src = source_col(kern, k.name)
            if src is None or src not in dicts:
                return None
            ci = col_idx(src)
            if ci is None:
                return None
            k_rows.append((0, ci, k.card, 0, ""))
        elif k.kind == "intdevice":
            src = source_col(kern, k.src_name or k.name)
            if src is None:
                return None
            ci = col_idx(src)
            if ci is None:
                return None
            k_rows.append((1, ci, k.card, 0, k.lut_name))
        elif k.kind == "window":
            if env.get(k.name) is not _WINDOW_ONLY \
                    or window_bin.get(k.name) != k.width:
                return None
            ci = col_idx(time_col)
            if ci is None:
                return None
            requires_unbounded = True  # raw-time binning ≠ bounded post-map
            k_rows.append((2, ci, k.card, k.width, k.lut_name))
        else:
            return None

    vargs = value_args(kern, op)
    a_rows = []
    for name, uda, in_dt in init_specs:
        src = vargs.get(name)  # None for the implicit __seen counter
        if src is None and not isinstance(uda, CountUDA):
            return None
        ci = 0
        if src is not None:
            # value columns must be plain pass-through source columns (the
            # np_partial rule); dict-coded values never reach here
            # (executor gates on val_dicts)
            if src is _WINDOW_ONLY or src not in names or src in dicts:
                return None
            ci = col_idx(src)
            if ci is None:
                return None
        if isinstance(uda, CountUDA):
            kind = 0
        elif isinstance(uda, SumUDA):
            kind = 1 if np.dtype(in_dt).kind != "f" else 2
        elif isinstance(uda, MeanUDA):
            kind = 3
        elif isinstance(uda, (MinUDA, AnyUDA, MaxUDA)):
            is_max = isinstance(uda, MaxUDA)
            if np.dtype(in_dt).kind == "f":
                kind = 7 if is_max else 6
            else:
                kind = 5 if is_max else 4
        elif isinstance(uda, (QuantileUDA, QuantilesUDA)):
            kind = 8
        elif isinstance(uda, (VarianceUDA, StddevUDA)):
            kind = 9
        else:
            return None
        a_rows.append((kind, name, ci))

    # time bounds: applicable only when the raw time column rides the feed
    time_idx = -1
    if time_col is not None and time_col in names and not requires_unbounded:
        ti = col_idx(time_col)
        if ti is not None:
            time_idx = ti
    lh = LogHistogram()
    return Program(
        cols=cols, col_tags=tags, filters=f_rows, time_idx=time_idx,
        keys=k_rows, aggs=a_rows, requires_unbounded=requires_unbounded,
        hist_width=lh.width, inv_log_gamma=1.0 / math.log(lh.gamma),
        min_value=lh.min_value,
    )


def applicable(prog: Optional[Program], t_lo, t_hi) -> bool:
    """Per-run admission: a cached program still refuses runs it cannot
    reproduce (bounded time with no time column / window raw-binning)."""
    if prog is None:
        return False
    unbounded = int(t_lo) <= _UNBOUNDED_LO and int(t_hi) >= _UNBOUNDED_HI
    if unbounded:
        return True
    return not prog.requires_unbounded and prog.time_idx >= 0


def _acc_np(in_dt) -> np.dtype:
    d = np.dtype(in_dt)
    return np.dtype(np.int64) if d.kind == "b" else d


def _ident_np(dtype, op: str):
    d = np.dtype(dtype)
    if d.kind == "f":
        return np.inf if op == "min" else -np.inf
    info = np.iinfo(d)
    return info.max if op == "min" else info.min


def _alloc_state(prog: Program, init_specs, num_groups):
    """Identity state with the EXACT leaf layout of uda.init (dtypes,
    dict keys, identity fills — udf.udf + ops/groupby._identity_for), as
    writable numpy the native loop accumulates in place.  Pure numpy on
    purpose: uda.init dispatches jax ops, a measurable per-query cost at
    interactive latencies; parity with the jitted layouts is pinned by
    tests/test_wholeplan.py."""
    G = num_groups
    kinds = {name: kind for kind, name, _ci in prog.aggs}
    state = {}
    for name, _uda, in_dt in init_specs:
        kind = kinds[name]
        if kind == 0:
            state[name] = np.zeros(G, np.int64)
        elif kind in (1, 2):
            state[name] = np.zeros(G, _acc_np(in_dt))
        elif kind == 3:
            state[name] = {"sum": np.zeros(G, np.float64),
                           "count": np.zeros(G, np.int64)}
        elif kind in (4, 6):
            acc = _acc_np(in_dt)
            state[name] = np.full(G, _ident_np(acc, "min"), acc)
        elif kind in (5, 7):
            acc = _acc_np(in_dt)
            state[name] = np.full(G, _ident_np(acc, "max"), acc)
        elif kind == 8:
            state[name] = np.zeros((G, prog.hist_width), np.float32)
        else:
            state[name] = {"sum": np.zeros(G, np.float64),
                           "sumsq": np.zeros(G, np.float64),
                           "count": np.zeros(G, np.int64)}
    return state


def _merge_into(prog: Program, dst: dict, src: dict) -> None:
    """Fold one batch partial into the accumulated state, in place.
    Reduction op per leaf mirrors uda.reduce_ops (add everywhere except
    the min/max extrema)."""
    for kind, name, _ci in prog.aggs:
        d, s = dst[name], src[name]
        if kind in (4, 6):
            np.minimum(d, s, out=d)
        elif kind in (5, 7):
            np.maximum(d, s, out=d)
        elif isinstance(d, dict):
            for leaf in d:
                d[leaf] += s[leaf]
        else:
            d += s


def _agg_ptrs(prog: Program, state: dict):
    """→ (kinds i32[n], cols i32[n], s0 void*[n], s1, s2)."""
    n = len(prog.aggs)
    kinds = np.zeros(n, np.int32)
    acols = np.zeros(n, np.int32)
    s0 = (ctypes.c_void_p * n)()
    s1 = (ctypes.c_void_p * n)()
    s2 = (ctypes.c_void_p * n)()
    for i, (kind, name, ci) in enumerate(prog.aggs):
        kinds[i] = kind
        acols[i] = ci
        st = state[name]
        if kind == 3:  # mean
            s0[i] = st["sum"].ctypes.data
            s1[i] = st["count"].ctypes.data
        elif kind == 9:  # variance
            s0[i] = st["sum"].ctypes.data
            s1[i] = st["sumsq"].ctypes.data
            s2[i] = st["count"].ctypes.data
        else:
            s0[i] = st.ctypes.data
    return kinds, acols, s0, s1, s2


#: above this many rows the batch fan-out engages (the pool + per-batch
#: partial states only pay off once the loop dominates)
_PARALLEL_MIN_ROWS = 1 << 17

_THREADS = _flags.define_int(
    "PX_WHOLEPLAN_THREADS", 0,
    "whole-plan loop worker threads (batches fan out, partial states "
    "merge in batch order); 0 = min(8, cpu_count)")


def _nthreads() -> int:
    import os

    v = int(_flags.get("PX_WHOLEPLAN_THREADS"))
    return v if v > 0 else min(8, os.cpu_count() or 1)


_POOL = None
_POOL_LOCK = threading.Lock()


def _pool():
    """Persistent worker pool: creating one per query is measurable at
    interactive latencies.  Sized for the flag's current value; workers are
    daemon threads, so process exit never blocks on it."""
    global _POOL
    with _POOL_LOCK:
        if _POOL is None:
            from concurrent.futures import ThreadPoolExecutor

            _POOL = ThreadPoolExecutor(
                max_workers=max(_nthreads() - 1, 1),
                thread_name_prefix="px-wholeplan")
        return _POOL


class _Bound:
    """The per-run constant arguments of px_wholeplan_run, converted to
    ctypes ONCE (per-batch conversion was measurable at interactive
    latencies)."""

    def __init__(self, prog: Program, luts, t_lo, t_hi, num_groups):
        P = ctypes.POINTER

        def as_p(a, ct):
            return a.ctypes.data_as(P(ct))

        nk = len(prog.keys)
        k_kind = np.zeros(nk, np.int32)
        k_col = np.zeros(nk, np.int32)
        k_card = np.zeros(nk, np.int64)
        k_width = np.zeros(nk, np.int64)
        k_t0 = np.zeros(nk, np.int64)
        k_lut = (ctypes.c_void_p * max(nk, 1))()
        k_lut_len = np.zeros(max(nk, 1), np.int64)
        self._keep = [k_kind, k_col, k_card, k_width, k_t0, k_lut_len]
        for i, (kind, ci, card, width, lut_name) in enumerate(prog.keys):
            k_kind[i], k_col[i], k_card[i], k_width[i] = \
                kind, ci, card, width
            if kind == 1:
                lut = np.ascontiguousarray(np.asarray(luts[lut_name]),
                                           dtype=np.int64)
                self._keep.append(lut)
                k_lut[i] = lut.ctypes.data
                k_lut_len[i] = len(lut)
            elif kind == 2:
                k_t0[i] = int(np.asarray(luts[lut_name])[0])

        nf = len(prog.filters)
        f_col = np.zeros(max(nf, 1), np.int32)
        f_op = np.zeros(max(nf, 1), np.int32)
        f_isf = np.zeros(max(nf, 1), np.int32)
        f_ival = np.zeros(max(nf, 1), np.int64)
        f_fval = np.zeros(max(nf, 1), np.float64)
        self._keep += [f_col, f_op, f_isf, f_ival, f_fval]
        for i, (ci, fop, isf, iv, fv) in enumerate(prog.filters):
            f_col[i], f_op[i], f_isf[i], f_ival[i], f_fval[i] = \
                ci, fop, isf, iv, fv

        unbounded = int(t_lo) <= _UNBOUNDED_LO and int(t_hi) >= _UNBOUNDED_HI
        col_tags = np.asarray(prog.col_tags, np.int32)
        self._keep.append(col_tags)
        self.ncols = len(prog.cols)
        # the argument tuple up to (but excluding) the per-batch
        # (n, col_ptrs) pair and the per-state agg pointers
        self.mid_args = (
            as_p(col_tags, ctypes.c_int32),
            ctypes.c_int32(nf), as_p(f_col, ctypes.c_int32),
            as_p(f_op, ctypes.c_int32), as_p(f_isf, ctypes.c_int32),
            as_p(f_ival, ctypes.c_int64), as_p(f_fval, ctypes.c_double),
            ctypes.c_int32(-1 if unbounded else prog.time_idx),
            ctypes.c_int64(int(t_lo)), ctypes.c_int64(int(t_hi)),
            ctypes.c_int32(nk), as_p(k_kind, ctypes.c_int32),
            as_p(k_col, ctypes.c_int32), as_p(k_card, ctypes.c_int64),
            as_p(k_width, ctypes.c_int64), as_p(k_t0, ctypes.c_int64),
            k_lut, as_p(k_lut_len, ctypes.c_int64),
            ctypes.c_int64(num_groups),
        )
        self.tail_args = (
            ctypes.c_int64(prog.hist_width),
            ctypes.c_float(prog.inv_log_gamma),
            ctypes.c_float(prog.min_value),
        )


def _run_batch(lib, prog, bound, batch_cols, n, agg_args):
    kinds, acols, s0, s1, s2 = agg_args
    # min length 1: a count-only program reads no columns at all, but the
    # pointer array itself must stay a valid allocation
    col_ptrs = (ctypes.c_void_p * max(bound.ncols, 1))()
    for i, a in enumerate(batch_cols):
        col_ptrs[i] = a.ctypes.data
    lib.px_wholeplan_run(
        ctypes.c_int64(n), ctypes.c_int32(bound.ncols), col_ptrs,
        *bound.mid_args,
        ctypes.c_int32(len(prog.aggs)),
        kinds.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        acols.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        s0, s1, s2, *bound.tail_args)


def run(executor, prog: Program, src, num_groups, init_specs, t_lo, t_hi,
        luts) -> dict:
    """Drive the whole-plan loop straight off the storage batches (no
    coalescing, no padding, no masks) → accumulated partial state dict,
    leaf-identical to the jitted kernel path's pulled state.

    Batches fan out over a small thread pool (the ctypes call releases the
    GIL) with per-batch partial states merged IN BATCH ORDER — results are
    deterministic regardless of scheduling."""
    lib = _native()
    bound = _Bound(prog, luts, t_lo, t_hi, num_groups)
    heat_rec = executor._heat_recorder(src)
    batches = []
    total = 0
    for rb, _row_id, _gen in src:
        n = rb.num_valid
        if n == 0:
            continue
        if heat_rec is not None:
            heat_rec.record_batch(rb, n, _gen)
        cols = []
        for cname in prog.cols:
            a = rb.columns[cname][:n]
            if not a.flags.c_contiguous:
                a = np.ascontiguousarray(a)
            cols.append(a)
        batches.append((cols, n))
        total += n
    executor.stats["rows_scanned"] += total
    executor.stats["batches"] += len(batches)

    if not batches:
        return _alloc_state(prog, init_specs, num_groups)
    nthreads = min(_nthreads(), len(batches))
    if total < _PARALLEL_MIN_ROWS or nthreads == 1:
        state = _alloc_state(prog, init_specs, num_groups)
        agg_args = _agg_ptrs(prog, state)
        for cols, n in batches:
            _run_batch(lib, prog, bound, cols, n, agg_args)
        return state

    # one contiguous batch RANGE per worker, each into its own state,
    # merged in range order — deterministic regardless of scheduling, and
    # only nthreads partial states to allocate/merge
    per = -(-len(batches) // nthreads)
    ranges = [batches[i: i + per] for i in range(0, len(batches), per)]
    partials = [None] * len(ranges)

    def work(i):
        st = _alloc_state(prog, init_specs, num_groups)
        args = _agg_ptrs(prog, st)
        for cols, n in ranges[i]:
            _run_batch(lib, prog, bound, cols, n, args)
        partials[i] = st

    futs = [_pool().submit(work, i) for i in range(1, len(ranges))]
    work(0)
    for f in futs:
        f.result()
    state = partials[0]
    for st in partials[1:]:
        _merge_into(prog, state, st)
    return state

from pixie_tpu.native.build import load_native

__all__ = ["load_native"]

"""Build + load the native runtime library (ctypes, no pybind11).

`native/*.cc` compiles lazily into `native/libpixie_native.so` with g++ on
first use; loading is cached.  Everything native-backed has a pure-Python
fallback, so a missing toolchain degrades performance, never correctness
(set PIXIE_TPU_NO_NATIVE=1 to force the fallback).
"""
from __future__ import annotations

import ctypes
import pathlib
import subprocess
import threading

from pixie_tpu import flags as _flags

_flags.define_str(
    "PIXIE_TPU_NO_NATIVE", "",
    "force the pure-Python fallbacks even when the g++ toolchain is "
    "available (perf A/B and toolchain-bug escape hatch).  A kill switch: "
    "ANY value except ''/0/false/no/off disables native.  Live: read at "
    "first load_native() use, not import", live=True)


def _no_native() -> bool:
    # historic semantics preserved: any non-empty value disables native
    # unless it is an explicit falsy spelling — a kill switch must not
    # fail silently on a non-canonical truthy value
    val = str(_flags.get("PIXIE_TPU_NO_NATIVE")).strip().lower()
    return bool(val) and val not in ("0", "false", "no", "off")

_flags.define_str(
    "PX_NATIVE_SANITIZE", "",
    "sanitizer build mode for the native STANDALONE test harnesses "
    "(tests/test_native_sanitize.py): 'address' = ASan+UBSan, 'thread' = "
    "TSan over the concurrent pthread driver (the slow lane).  Sanitizers "
    "never apply to the ctypes .so — they need an instrumented host binary",
    live=True)

#: g++ flags per sanitizer mode (the harness tests compile with these)
SANITIZER_ARGS = {
    "address": ["-fsanitize=address,undefined", "-fno-omit-frame-pointer"],
    "thread": ["-fsanitize=thread", "-fno-omit-frame-pointer"],
}

_REPO = pathlib.Path(__file__).resolve().parent.parent.parent
_SRC_DIR = _REPO / "native"
_SO_PATH = _SRC_DIR / "libpixie_native.so"

_lock = threading.Lock()
_lib = None
_tried = False


def _build() -> bool:
    srcs = sorted(_SRC_DIR.glob("*.cc"))
    if not srcs:
        return False
    if _SO_PATH.exists():
        newest = max(s.stat().st_mtime for s in srcs)
        if _SO_PATH.stat().st_mtime >= newest:
            return True
    cmd = [
        "g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-pthread",
        "-o", str(_SO_PATH),
        *[str(s) for s in srcs],
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return True
    except (subprocess.SubprocessError, FileNotFoundError, OSError):
        return False


def load_native():
    """ctypes handle to the native library, or None (fallback mode)."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if _no_native():
            return None
        if not _build():
            return None
        try:
            lib = ctypes.CDLL(str(_SO_PATH))
        except OSError:
            return None
        lib.px_dict_new.restype = ctypes.c_void_p
        lib.px_dict_free.argtypes = [ctypes.c_void_p]
        lib.px_dict_size.argtypes = [ctypes.c_void_p]
        lib.px_dict_size.restype = ctypes.c_int64
        lib.px_dict_encode_ucs4.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_void_p, ctypes.c_void_p,
        ]
        lib.px_dict_encode_ucs4.restype = ctypes.c_int64
        lib.px_dict_insert_ucs4.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
        ]
        lib.px_dict_insert_ucs4.restype = ctypes.c_int32
        # radix hash join (native/join.cc) — guard with hasattr so a stale
        # .so built before the kernel existed degrades to the XLA path
        # instead of raising at load time
        # whole-plan fused loop (native/wholeplan.cc) — args are passed as
        # explicit ctypes objects by codegen.py, so only the return type
        # needs declaring; hasattr-guarded like the join for stale .so files
        if hasattr(lib, "px_wholeplan_run"):
            lib.px_wholeplan_run.restype = ctypes.c_int64
        if hasattr(lib, "px_join_run"):
            lib.px_join_run.argtypes = [
                ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p,
                ctypes.c_int64, ctypes.c_void_p,
            ]
            lib.px_join_run.restype = ctypes.c_void_p
            lib.px_join_fetch.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ]
            lib.px_join_free.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib

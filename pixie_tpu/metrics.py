"""Self-observability: Prometheus-text metrics registry + structured logging.

Reference: the C++ Prometheus registry (src/common/metrics/metrics.h), per-table
gauges (table/table_metrics.h), and the Go services' /metrics endpoints
(src/shared/services/metrics/).  Services expose `render()` over their
transport ({"msg": "metrics"} on the broker) and anything in-process can
scrape via the module API.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Optional

_lock = threading.Lock()
_counters: dict[tuple, float] = {}
_gauges: dict[tuple, float] = {}
_gauge_fns: dict[str, tuple[str, Callable[[], dict]]] = {}
#: key -> {"bounds": tuple, "counts": per-bucket (non-cumulative), "sum", "count"}
_hists: dict[tuple, dict] = {}
_help: dict[str, str] = {}


def _key(name: str, labels: Optional[dict]) -> tuple:
    return (name, tuple(sorted((labels or {}).items())))


#: per-family capped label-id sets (see capped_label)
_label_ids: dict[str, set] = {}
MAX_LABEL_IDS = 256
OTHER_LABEL = "__other__"


def capped_label(family: str, ident: str, cap: int = MAX_LABEL_IDS) -> str:
    """Bound the distinct label values one id-space (tenant ids, agent
    names) can mint: the first `cap` ids get their own series, everything
    after shares OTHER_LABEL.  Counter series in this registry are
    immortal, so an unbounded id flood would otherwise grow process memory
    (and /metrics output) forever."""
    ident = str(ident)
    with _lock:
        s = _label_ids.setdefault(family, set())
        if ident in s:
            return ident
        if len(s) < cap:
            s.add(ident)
            return ident
    return OTHER_LABEL


def counter_inc(name: str, value: float = 1.0, labels: Optional[dict] = None,
                help_: str = "") -> None:
    with _lock:
        k = _key(name, labels)
        _counters[k] = _counters.get(k, 0.0) + value
        if help_:
            _help.setdefault(name, help_)


def gauge_set(name: str, value: float, labels: Optional[dict] = None,
              help_: str = "") -> None:
    with _lock:
        _gauges[_key(name, labels)] = float(value)
        if help_:
            _help.setdefault(name, help_)


def histogram_observe(name: str, value: float, bounds: tuple,
                      labels: Optional[dict] = None, help_: str = "") -> None:
    """Record one observation into a histogram with DECLARED bucket bounds
    (Prometheus text `_bucket`/`_sum`/`_count` rendering; bounds are upper
    bounds, +Inf is implicit).  First observation fixes the bounds for that
    (name, labels) series; later calls must pass the same bounds."""
    bounds = tuple(float(b) for b in bounds)
    with _lock:
        k = _key(name, labels)
        h = _hists.get(k)
        if h is None:
            if any(b2 <= b1 for b1, b2 in zip(bounds, bounds[1:])):
                raise ValueError(f"histogram {name}: bounds must increase")
            h = {"bounds": bounds, "counts": [0] * len(bounds),
                 "sum": 0.0, "count": 0}
            _hists[k] = h
            if help_:
                _help.setdefault(name, help_)
        elif h["bounds"] != bounds:
            raise ValueError(f"histogram {name}: bounds redeclared")
        h["sum"] += float(value)
        h["count"] += 1
        for i, b in enumerate(h["bounds"]):
            if value <= b:
                h["counts"][i] += 1
                break


def counter_value(name: str, labels: Optional[dict] = None) -> float:
    """Current value of a counter series (0.0 when never incremented) —
    the in-process read side tests and the serving load harness use to
    audit admitted/shed/goodput accounting without scraping /metrics."""
    with _lock:
        return _counters.get(_key(name, labels), 0.0)


def counter_series(name: str) -> dict[tuple, float]:
    """All label-series of one counter: {labels-tuple: value}."""
    with _lock:
        return {labels: v for (n, labels), v in _counters.items()
                if n == name}


def hist_quantile(name: str, q: float,
                  labels: Optional[dict] = None) -> Optional[float]:
    """Quantile estimate from a registered histogram's bucket counts
    (linear interpolation inside the covering bucket — the
    histogram_quantile() semantic).  Returns None for an unknown or empty
    series; observations past the last finite bound clamp to it.  This is
    the read side the load harness and the self-metrics sampler use, so
    reported percentiles come from the SAME registry ops scrape."""
    if not 0.0 <= q <= 1.0:
        raise ValueError(f"hist_quantile: q={q} outside [0, 1]")
    with _lock:
        h = _hists.get(_key(name, labels))
        if h is None or h["count"] == 0:
            return None
        bounds, counts, total = h["bounds"], list(h["counts"]), h["count"]
    rank = q * total
    cum, lo = 0, 0.0
    for b, c in zip(bounds, counts):
        if c > 0 and cum + c >= rank:
            frac = min(max((rank - cum) / c, 0.0), 1.0)
            return lo + (b - lo) * frac
        cum += c
        lo = b
    # the remaining mass sits in the implicit +Inf bucket: the honest
    # answer without an upper bound is the last finite boundary
    return bounds[-1]


def snapshot() -> list[tuple]:
    """Everything registered, as (kind, name, labels_tuple, value) rows —
    the metrics-as-data read surface (observe.sample_metrics_rows folds it
    into self_telemetry.metrics).  Histograms contribute their sum/count
    plus interpolated p50/p99; lazy gauge fns are evaluated OUTSIDE the
    registry lock (they run user code), like render()."""
    with _lock:
        counters = dict(_counters)
        gauges = dict(_gauges)
        gauge_fns = dict(_gauge_fns)
        hist_keys = [k for k, h in _hists.items() if h["count"] > 0]
        hist_sums = {k: (_hists[k]["sum"], _hists[k]["count"])
                     for k in hist_keys}
    out: list[tuple] = []
    for (name, labels), v in sorted(counters.items()):
        out.append(("counter", name, labels, v))
    for (name, labels), v in sorted(gauges.items()):
        out.append(("gauge", name, labels, v))
    for name, (_help, fn) in sorted(gauge_fns.items()):
        try:
            vals = fn()
        except Exception:
            continue
        for labels, v in sorted(vals.items()):
            lt = (labels if isinstance(labels, tuple)
                  else tuple(sorted(labels.items())))
            out.append(("gauge", name, lt, float(v)))
    for name, labels in sorted(hist_keys):
        s, c = hist_sums[(name, labels)]
        out.append(("hist_sum", name, labels, s))
        out.append(("hist_count", name, labels, float(c)))
        for q, kind in ((0.5, "hist_p50"), (0.99, "hist_p99")):
            v = hist_quantile(name, q, dict(labels))
            if v is not None:
                out.append((kind, name, labels, v))
    return out


def register_gauge_fn(name: str, fn: Callable[[], dict], help_: str = "") -> None:
    """Lazy gauge: fn() -> {labels-tuple-or-frozen-dict: value} evaluated at
    render time (per-table sizes, registry liveness, ...)."""
    with _lock:
        _gauge_fns[name] = (help_, fn)


def has_gauge_fn(name: str) -> bool:
    with _lock:
        return name in _gauge_fns


def unregister_gauge_fn(name: str) -> None:
    """Drop a lazy gauge (service shutdown — keeps the module-global registry
    from pinning dead objects alive)."""
    with _lock:
        _gauge_fns.pop(name, None)


def _fmt_labels(labels: tuple) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in labels)
    return "{" + inner + "}"


def render() -> str:
    """Prometheus text exposition of everything registered."""
    lines = []
    with _lock:
        counters = dict(_counters)
        gauges = dict(_gauges)
        gauge_fns = dict(_gauge_fns)
        hists = {k: {"bounds": h["bounds"], "counts": list(h["counts"]),
                     "sum": h["sum"], "count": h["count"]}
                 for k, h in _hists.items()}
        helps = dict(_help)
    seen = set()
    for (name, labels), v in sorted(counters.items()):
        if name not in seen:
            seen.add(name)
            if name in helps:
                lines.append(f"# HELP {name} {helps[name]}")
            lines.append(f"# TYPE {name} counter")
        lines.append(f"{name}{_fmt_labels(labels)} {v:g}")
    for (name, labels), v in sorted(gauges.items()):
        if name not in seen:
            seen.add(name)
            if name in helps:
                lines.append(f"# HELP {name} {helps[name]}")
            lines.append(f"# TYPE {name} gauge")
        lines.append(f"{name}{_fmt_labels(labels)} {v:g}")
    for (name, labels), h in sorted(hists.items()):
        if name not in seen:
            seen.add(name)
            if name in helps:
                lines.append(f"# HELP {name} {helps[name]}")
            lines.append(f"# TYPE {name} histogram")
        cum = 0
        for b, c in zip(h["bounds"], h["counts"]):
            cum += c
            lt = labels + (("le", f"{b:g}"),)
            lines.append(f"{name}_bucket{_fmt_labels(lt)} {cum}")
        lines.append(
            f"{name}_bucket{_fmt_labels(labels + (('le', '+Inf'),))} "
            f"{h['count']}")
        lines.append(f"{name}_sum{_fmt_labels(labels)} {h['sum']:g}")
        lines.append(f"{name}_count{_fmt_labels(labels)} {h['count']}")
    for name, (help_, fn) in sorted(gauge_fns.items()):
        try:
            vals = fn()
        except Exception:
            continue
        if name not in seen:
            seen.add(name)
            if help_:
                lines.append(f"# HELP {name} {help_}")
            lines.append(f"# TYPE {name} gauge")
        for labels, v in sorted(vals.items()):
            lt = labels if isinstance(labels, tuple) else tuple(sorted(labels.items()))
            lines.append(f"{name}{_fmt_labels(lt)} {v:g}")
    return "\n".join(lines) + "\n"


def reset_for_testing() -> None:
    with _lock:
        _counters.clear()
        _gauges.clear()
        _gauge_fns.clear()
        _hists.clear()
        _help.clear()
        _label_ids.clear()


# ------------------------------------------------------------------- logging


def log(level: str, msg: str, **fields) -> None:
    """Structured log line (glog/logrus analog): level, ts, msg, k=v fields."""
    import json
    import sys

    rec = {"ts": time.time(), "level": level, "msg": msg, **fields}
    print(json.dumps(rec), file=sys.stderr, flush=True)


def info(msg: str, **fields) -> None:
    log("info", msg, **fields)


def warn(msg: str, **fields) -> None:
    log("warn", msg, **fields)


def error(msg: str, **fields) -> None:
    log("error", msg, **fields)

"""Self-observability: Prometheus-text metrics registry + structured logging.

Reference: the C++ Prometheus registry (src/common/metrics/metrics.h), per-table
gauges (table/table_metrics.h), and the Go services' /metrics endpoints
(src/shared/services/metrics/).  Services expose `render()` over their
transport ({"msg": "metrics"} on the broker) and anything in-process can
scrape via the module API.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Optional

_lock = threading.Lock()
_counters: dict[tuple, float] = {}
_gauges: dict[tuple, float] = {}
_gauge_fns: dict[str, tuple[str, Callable[[], dict]]] = {}
_help: dict[str, str] = {}


def _key(name: str, labels: Optional[dict]) -> tuple:
    return (name, tuple(sorted((labels or {}).items())))


def counter_inc(name: str, value: float = 1.0, labels: Optional[dict] = None,
                help_: str = "") -> None:
    with _lock:
        k = _key(name, labels)
        _counters[k] = _counters.get(k, 0.0) + value
        if help_:
            _help.setdefault(name, help_)


def gauge_set(name: str, value: float, labels: Optional[dict] = None,
              help_: str = "") -> None:
    with _lock:
        _gauges[_key(name, labels)] = float(value)
        if help_:
            _help.setdefault(name, help_)


def register_gauge_fn(name: str, fn: Callable[[], dict], help_: str = "") -> None:
    """Lazy gauge: fn() -> {labels-tuple-or-frozen-dict: value} evaluated at
    render time (per-table sizes, registry liveness, ...)."""
    with _lock:
        _gauge_fns[name] = (help_, fn)


def unregister_gauge_fn(name: str) -> None:
    """Drop a lazy gauge (service shutdown — keeps the module-global registry
    from pinning dead objects alive)."""
    with _lock:
        _gauge_fns.pop(name, None)


def _fmt_labels(labels: tuple) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in labels)
    return "{" + inner + "}"


def render() -> str:
    """Prometheus text exposition of everything registered."""
    lines = []
    with _lock:
        counters = dict(_counters)
        gauges = dict(_gauges)
        gauge_fns = dict(_gauge_fns)
        helps = dict(_help)
    seen = set()
    for (name, labels), v in sorted(counters.items()):
        if name not in seen:
            seen.add(name)
            if name in helps:
                lines.append(f"# HELP {name} {helps[name]}")
            lines.append(f"# TYPE {name} counter")
        lines.append(f"{name}{_fmt_labels(labels)} {v:g}")
    for (name, labels), v in sorted(gauges.items()):
        if name not in seen:
            seen.add(name)
            if name in helps:
                lines.append(f"# HELP {name} {helps[name]}")
            lines.append(f"# TYPE {name} gauge")
        lines.append(f"{name}{_fmt_labels(labels)} {v:g}")
    for name, (help_, fn) in sorted(gauge_fns.items()):
        try:
            vals = fn()
        except Exception:
            continue
        if name not in seen:
            seen.add(name)
            if help_:
                lines.append(f"# HELP {name} {help_}")
            lines.append(f"# TYPE {name} gauge")
        for labels, v in sorted(vals.items()):
            lt = labels if isinstance(labels, tuple) else tuple(sorted(labels.items()))
            lines.append(f"{name}{_fmt_labels(lt)} {v:g}")
    return "\n".join(lines) + "\n"


def reset_for_testing() -> None:
    with _lock:
        _counters.clear()
        _gauges.clear()
        _gauge_fns.clear()
        _help.clear()


# ------------------------------------------------------------------- logging


def log(level: str, msg: str, **fields) -> None:
    """Structured log line (glog/logrus analog): level, ts, msg, k=v fields."""
    import json
    import sys

    rec = {"ts": time.time(), "level": level, "msg": msg, **fields}
    print(json.dumps(rec), file=sys.stderr, flush=True)


def info(msg: str, **fields) -> None:
    log("info", msg, **fields)


def warn(msg: str, **fields) -> None:
    log("warn", msg, **fields)


def error(msg: str, **fields) -> None:
    log("error", msg, **fields)

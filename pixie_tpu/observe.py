"""Query flight recorder: every query becomes data served by the engine.

The engine grew a deep stack of invisible fast paths — plan cache, matview
serves, resident feeds, batched dispatch, failover/hedging — but PR 1's
spans give a timeline, not attribution: nothing answers "which fast paths
fired for THIS query and where did its time actually go?".  This module
closes that loop with the system's own machinery (the Tailwind argument:
accelerator query frameworks need honest end-to-end accounting):

  * **Per-query profiles** — the broker and `LocalCluster` assemble, from
    the per-query `stats` they already collect plus explicit phase timers,
    one structured row per query: admission wait, compile, plan split,
    dispatch/exec, merge ns; h2d/d2h bytes; rows scanned/output; and the
    full cache/fault provenance (plan-cache and split-cache hits, matview
    hit/stale serves, resident feeds, batch membership + dedup slot,
    failover routes, hedges/evictions/retries).  Rows ingest through the
    NORMAL write path into ``self_telemetry.query_profiles`` (+ per-op
    ``self_telemetry.op_stats``), so PxL scripts and standing matviews
    dashboard the engine at O(delta) like any other telemetry.
  * **EXPLAIN ANALYZE** — ``execute_script(explain=True)`` (CLI
    ``run --explain``) returns the annotated plan tree with per-op ns,
    rows, bytes and the provenance block, correct for distributed,
    batched (member demux), matview-hit, and failover-served queries.
  * **Metrics as data** — ``sample_metrics_rows`` folds the whole metrics
    registry (counters, gauges, histogram sum/count/p50/p99 via
    ``metrics.hist_quantile``) into ``self_telemetry.metrics`` rows; the
    broker/agents run it on a `PL_SELF_METRICS_S` cron cadence.

Everything here is gated on ``PL_TRACING_ENABLED`` (profiles ride the same
master switch as spans): with tracing off no profile is assembled, no row
is written, and query results are bit-identical to the uninstrumented
path.  ``explain=True`` is an explicit per-query opt-in that works either
way (it assembles the profile for the answer without recording it).
"""
from __future__ import annotations

import json
import threading
import time
from typing import Optional

from pixie_tpu import flags, metrics, trace
from pixie_tpu.types import DataType as DT, Relation, SemanticType as ST

flags.define_float(
    "PL_SELF_METRICS_S", 0.0,
    "cadence (seconds) for folding the metrics registry into "
    "self_telemetry.metrics (and evaluating PL_SLO burn rates); 0 disables "
    "the sampler")

#: per-query op rows kept in self_telemetry.op_stats (a pathological plan
#: with thousands of compiled chains must not flood the telemetry table)
MAX_OP_ROWS = 128

#: telemetry rows buffered per process; rows arriving at a full buffer are
#: dropped (counted) until a flush drains it — the flight recorder must
#: never become the memory leak it exists to catch
MAX_PENDING_ROWS = 4096

PROFILES_TABLE = "self_telemetry.query_profiles"
OP_STATS_TABLE = "self_telemetry.op_stats"
METRICS_TABLE = "self_telemetry.metrics"
ALERTS_TABLE = "self_telemetry.alerts"
SCALE_EVENTS_TABLE = "self_telemetry.scale_events"
SHARD_HEAT_TABLE = "self_telemetry.shard_heat"
STORAGE_STATE_TABLE = "self_telemetry.storage_state"
AUTOTUNE_TABLE = "self_telemetry.autotune"

PROFILES_RELATION = Relation.of(
    ("time_", DT.TIME64NS, ST.ST_TIME_NS),
    ("query_id", DT.STRING),
    ("tenant", DT.STRING),
    ("service", DT.STRING),
    ("status", DT.STRING),
    ("error", DT.STRING),
    ("wall_ns", DT.INT64, ST.ST_DURATION_NS),
    ("admission_wait_ns", DT.INT64, ST.ST_DURATION_NS),
    ("compile_ns", DT.INT64, ST.ST_DURATION_NS),
    ("plan_split_ns", DT.INT64, ST.ST_DURATION_NS),
    ("exec_ns", DT.INT64, ST.ST_DURATION_NS),
    ("merge_ns", DT.INT64, ST.ST_DURATION_NS),
    ("accounted_ns", DT.INT64, ST.ST_DURATION_NS),
    ("agents", DT.INT64),
    ("rows_scanned", DT.INT64),
    ("rows_output", DT.INT64),
    ("h2d_bytes", DT.INT64, ST.ST_BYTES),
    ("d2h_bytes", DT.INT64, ST.ST_BYTES),
    ("plan_cache_hit", DT.INT64),
    ("split_cache_hit", DT.INT64),
    ("matview_eligible", DT.INT64),
    ("matview_hits", DT.INT64),
    ("matview_stale", DT.INT64),
    ("matview_rows_folded", DT.INT64),
    ("resident_feeds", DT.INT64),
    ("batch_size", DT.INT64),
    ("batch_slot", DT.INT64),
    ("failover", DT.STRING),
    ("hedged", DT.INT64),
    ("evictions", DT.INT64),
    ("retries", DT.INT64),
    ("chunks_discarded", DT.INT64),
    ("degraded", DT.INT64),
)

OP_STATS_RELATION = Relation.of(
    ("time_", DT.TIME64NS, ST.ST_TIME_NS),
    ("query_id", DT.STRING),
    ("agent", DT.STRING),
    ("op", DT.STRING),
    ("wall_ns", DT.INT64, ST.ST_DURATION_NS),
    ("self_ns", DT.INT64, ST.ST_DURATION_NS),
    ("rows_out", DT.INT64),
    ("bytes_out", DT.INT64, ST.ST_BYTES),
)

METRICS_RELATION = Relation.of(
    ("time_", DT.TIME64NS, ST.ST_TIME_NS),
    ("service", DT.STRING),
    ("name", DT.STRING),
    ("labels", DT.STRING),
    ("kind", DT.STRING),
    ("value", DT.FLOAT64),
)

ALERTS_RELATION = Relation.of(
    ("time_", DT.TIME64NS, ST.ST_TIME_NS),
    ("slo", DT.STRING),
    ("tenant", DT.STRING),
    ("window", DT.STRING),
    ("burn_rate", DT.FLOAT64),
    ("threshold", DT.FLOAT64),
    ("objective", DT.FLOAT64),
    ("state", DT.STRING),
)

#: autoscaler control-loop decisions (serving/elastic.py): every spawn,
#: retire, hand-off and refused retire lands here with the smoothed
#: pressure that drove it and the live agent count after it — the fleet's
#: own sizing history is queryable like any other telemetry
SCALE_EVENTS_RELATION = Relation.of(
    ("time_", DT.TIME64NS, ST.ST_TIME_NS),
    ("action", DT.STRING),
    ("agent", DT.STRING),
    ("reason", DT.STRING),
    ("pressure", DT.FLOAT64),
    ("agents", DT.INT64),
)

#: the storage-side twin of the query profiles (pixie_tpu.table.heat): the
#: decayed per-(table, shard, serving tier, batch-age bucket) access model,
#: folded on the PL_SELF_METRICS_S cron.  `skew` is the per-table max/mean
#: shard heat — the signal the shard rebalancer (ROADMAP item 2) reads.
SHARD_HEAT_RELATION = Relation.of(
    ("time_", DT.TIME64NS, ST.ST_TIME_NS),
    ("table_name", DT.STRING),
    ("shard", DT.STRING),
    ("tier", DT.STRING),
    ("age_bucket", DT.STRING),
    ("rows_scanned", DT.INT64),
    ("bytes", DT.INT64, ST.ST_BYTES),
    ("heat", DT.FLOAT64),
    ("skew", DT.FLOAT64),
    ("last_access", DT.TIME64NS, ST.ST_TIME_NS),
)

#: per-(agent, table) storage accounting: what each agent actually HOLDS —
#: hot rows, sealed batches with their age histogram (JSON {bucket: count}),
#: journal bytes/segments on disk, resident-tier and matview state bytes,
#: and replication lag as the sealed-vs-acked watermark delta per peer
#: (`peer_lag` is JSON {peer: batches}; `repl_lag_batches` its max).  The
#: journal/replication columns are per-table (journals are per-table files;
#: lag is stamped on every row of the owning agent for joinability).
STORAGE_STATE_RELATION = Relation.of(
    ("time_", DT.TIME64NS, ST.ST_TIME_NS),
    ("agent", DT.STRING),
    ("table_name", DT.STRING),
    ("hot_rows", DT.INT64),
    ("sealed_batches", DT.INT64),
    ("sealed_bytes", DT.INT64, ST.ST_BYTES),
    ("age_histogram", DT.STRING),
    ("resident_bytes", DT.INT64, ST.ST_BYTES),
    ("matview_bytes", DT.INT64, ST.ST_BYTES),
    ("journal_bytes", DT.INT64, ST.ST_BYTES),
    ("journal_segments", DT.INT64),
    ("repl_lag_batches", DT.INT64),
    ("peer_lag", DT.STRING),
    ("cold_bytes", DT.INT64, ST.ST_BYTES),
    ("cold_segments", DT.INT64),
)

#: adaptive-gate decision stream (engine/autotune.py): every profile-fed
#: gate decision (and every tail-guard fallback, source="fallback") with
#: the model inputs that drove it — "why did this query take this path"
#: is a PxL query, not a debugger session
AUTOTUNE_RELATION = Relation.of(
    ("time_", DT.TIME64NS, ST.ST_TIME_NS),
    ("query_id", DT.STRING),
    ("gate", DT.STRING),
    ("plan_class", DT.STRING),
    ("size_bucket", DT.STRING),
    ("arm", DT.STRING),
    ("static_arm", DT.STRING),
    ("source", DT.STRING),
    ("model_ms", DT.FLOAT64),
    ("static_ms", DT.FLOAT64),
    ("observed_ms", DT.FLOAT64),
    ("reason", DT.STRING),
)

SELF_TABLES: dict[str, Relation] = {
    PROFILES_TABLE: PROFILES_RELATION,
    OP_STATS_TABLE: OP_STATS_RELATION,
    METRICS_TABLE: METRICS_RELATION,
    ALERTS_TABLE: ALERTS_RELATION,
    SCALE_EVENTS_TABLE: SCALE_EVENTS_RELATION,
    SHARD_HEAT_TABLE: SHARD_HEAT_RELATION,
    STORAGE_STATE_TABLE: STORAGE_STATE_RELATION,
    AUTOTUNE_TABLE: AUTOTUNE_RELATION,
}


def enabled() -> bool:
    """Profiles ride the tracing master switch: fully off means no profile
    is assembled and results are bit-identical to the uninstrumented path."""
    return trace.enabled()


# ------------------------------------------------------------ table storage


def ensure_table(store, table: str):
    """Get-or-create one self-telemetry table (raced creations fold into
    the winner — same contract as trace.ensure_table)."""
    if not store.has(table):
        try:
            store.create(table, SELF_TABLES[table], batch_rows=1024)
        except Exception:
            pass  # lost a creation race; the table exists now
    return store.table(table)


def ensure_self_tables(store) -> None:
    """Create every flight-recorder table in `store` (agents call this
    before registration so the broker's registry knows the schemas from
    the first handshake)."""
    for table in SELF_TABLES:
        ensure_table(store, table)


def write_rows(store, table: str, rows: list[dict]) -> int:
    """Append telemetry rows (dicts in the table's relation) through the
    normal table write path — the same path user telemetry takes."""
    if not rows:
        return 0
    import numpy as np

    rel = SELF_TABLES[table]
    t = ensure_table(store, table)
    cols: dict = {}
    for c in rel:
        if c.data_type == DT.STRING:
            cols[c.name] = [str(r.get(c.name, "")) for r in rows]
        elif c.data_type == DT.FLOAT64:
            cols[c.name] = np.asarray(
                [float(r.get(c.name, 0.0) or 0.0) for r in rows],
                dtype=np.float64)
        else:
            cols[c.name] = np.asarray(
                [int(r.get(c.name, 0) or 0) for r in rows], dtype=np.int64)
    t.write(cols)
    return len(rows)


class RowBuffer:
    """Bounded per-process buffer of pending telemetry rows, grouped by
    table.  The broker drains it into its ship-to-agent path at query end;
    LocalCluster flushes into an agent store once `flush_rows` accumulate
    — the batch is sized so the amortized per-query write cost stays well
    under the observe_overhead gate's 5% ceiling (per-row table writes
    WERE the tax the gate caught at threshold 32)."""

    def __init__(self, flush_rows: int = 256,
                 max_rows: int = MAX_PENDING_ROWS):
        self.flush_rows = int(flush_rows)
        self.max_rows = int(max_rows)
        self._lock = threading.Lock()
        self._rows: dict[str, list[dict]] = {}
        self._n = 0
        self.dropped = 0

    def add(self, table: str, rows: list[dict]) -> None:
        if not rows:
            return
        dropped_now = 0
        with self._lock:
            for r in rows:
                if self._n >= self.max_rows:
                    dropped_now += 1
                    continue
                self._rows.setdefault(table, []).append(r)
                self._n += 1
            self.dropped += dropped_now
        if dropped_now:
            metrics.counter_inc(
                "px_telemetry_rows_dropped_total", float(dropped_now),
                help_="telemetry rows dropped by a full flight-recorder "
                      "buffer (bounded per process)")

    def __len__(self) -> int:
        with self._lock:
            return self._n

    def drain(self) -> dict[str, list[dict]]:
        with self._lock:
            out, self._rows, self._n = self._rows, {}, 0
        return out

    def flush_into(self, store, force: bool = False) -> int:
        """Write pending rows into `store` once the flush threshold is
        reached (or unconditionally with force=True).  Returns rows
        written; write failures are counted, never raised."""
        with self._lock:
            if self._n == 0 or (not force and self._n < self.flush_rows):
                return 0
        n = 0
        for table, rows in self.drain().items():
            try:
                n += write_rows(store, table, rows)
            except Exception:
                metrics.counter_inc(
                    "px_telemetry_write_errors_total", float(len(rows)),
                    help_="telemetry rows that failed to persist to the "
                          "local store")
        return n


# --------------------------------------------------------- profile assembly


def _agent_dicts(stats: dict) -> dict[str, dict]:
    return {a: s for a, s in (stats.get("agents") or {}).items()
            if isinstance(s, dict)}


def build_profile(query_id: str, tenant: str, service: str,
                  start_unix_ns: int, wall_ns: int, stats: dict,
                  status: str = "ok", error: str = "",
                  ) -> tuple[dict, list[dict]]:
    """One (profile_row, op_rows) pair from the per-query `stats` the
    broker/LocalCluster already assemble plus the phase timers they stamp
    into ``stats["phases"]``.  Every field is attribution of measured work;
    nothing is modeled."""
    phases = stats.get("phases") or {}
    serving = stats.get("serving") or {}
    fastpath = stats.get("fastpath") or {}
    fault = stats.get("fault") or {}
    batch = stats.get("batch") or {}
    mv = stats.get("matview") or {}
    merger = stats.get("merger") or {}
    agents = _agent_dicts(stats)

    mv_hits = int(mv.get("agents_hit", 0))
    mv_stale = 0
    for s in agents.values():
        info = s.get("matview")
        if isinstance(info, dict):
            if not mv and info.get("hit"):
                mv_hits += 1
            if info.get("hit") and info.get("stale"):
                mv_stale += 1

    op_rows: list[dict] = []

    def _op_sources():
        for a, s in agents.items():
            yield a, s.get("operators") or []
        yield "merger", merger.get("operators") or []

    d2h = 0
    for a, recs in _op_sources():
        for rec in recs:
            if not isinstance(rec, dict):
                continue
            d2h += int(rec.get("bytes_out", 0) or 0)
            if len(op_rows) < MAX_OP_ROWS:
                op_rows.append({
                    "time_": int(rec.get("t0_unix_ns") or start_unix_ns),
                    "query_id": query_id,
                    "agent": a,
                    "op": str(rec.get("label", "")),
                    "wall_ns": int(rec.get("wall_ns", 0) or 0),
                    "self_ns": int(rec.get("self_ns",
                                           rec.get("wall_ns", 0)) or 0),
                    "rows_out": int(rec.get("rows_out", 0) or 0),
                    "bytes_out": int(rec.get("bytes_out", 0) or 0),
                })

    rows_scanned = sum(int(s.get("rows_scanned", 0) or 0)
                       for s in agents.values())
    admission_ns = int(float(serving.get("queued_ms") or 0.0) * 1e6)
    compile_ns = int(phases.get("compile_ns", 0) or 0)
    split_ns = int(phases.get("plan_split_ns", 0) or 0)
    exec_ns = int(phases.get("exec_ns", 0) or 0)
    if exec_ns == 0 and agents:
        exec_ns = max(int(s.get("wall_ns",
                                float(s.get("exec_s", 0.0)) * 1e9) or 0)
                      for s in agents.values())
    merge_ns = int(phases.get("merge_ns", 0) or 0)
    accounted = admission_ns + compile_ns + split_ns + exec_ns + merge_ns

    profile = {
        "time_": int(start_unix_ns),
        "query_id": query_id,
        "tenant": str(tenant or ""),
        "service": service,
        "status": status,
        "error": str(error or "")[:200],
        "wall_ns": int(wall_ns),
        "admission_wait_ns": admission_ns,
        "compile_ns": compile_ns,
        "plan_split_ns": split_ns,
        "exec_ns": exec_ns,
        "merge_ns": merge_ns,
        "accounted_ns": accounted,
        "agents": len(agents),
        "rows_scanned": rows_scanned,
        "rows_output": int(merger.get("rows_output", 0) or 0),
        "h2d_bytes": sum(int(s.get("h2d_bytes", 0) or 0)
                         for s in agents.values()),
        "d2h_bytes": d2h,
        "plan_cache_hit": int(bool(fastpath.get("plan_cache_hit"))),
        "split_cache_hit": int(bool(fastpath.get("split_cache_hit"))),
        "matview_eligible": int(mv.get("eligible_agents", 0) or 0),
        "matview_hits": mv_hits,
        "matview_stale": mv_stale,
        "matview_rows_folded": int(mv.get("rows_folded", 0) or 0),
        "resident_feeds": sum(int(s.get("resident_feeds", 0) or 0)
                              for s in agents.values()),
        "batch_size": int(batch.get("size", 0) or 0),
        "batch_slot": int(batch.get("slot", -1) if batch else -1),
        "failover": (json.dumps(fault.get("failover"), sort_keys=True)
                     if fault.get("failover") else ""),
        "hedged": int(fault.get("hedged", 0) or 0),
        "evictions": int(fault.get("evictions", 0) or 0),
        "retries": int(fault.get("rounds", 0) or 0),
        "chunks_discarded": int(fault.get("chunks_discarded", 0) or 0),
        "degraded": int(bool(serving.get("degraded"))),
    }
    # adaptive-gate provenance rides the profile as a non-relation key
    # (write_rows only persists relation columns; the full decision rows
    # land in self_telemetry.autotune via autotune.rows_from_stats)
    at = stats.get("autotune") or any(
        isinstance(s, dict) and s.get("autotune")
        for s in agents.values())
    if at:
        from pixie_tpu.engine import autotune as _autotune

        profile["autotune"] = _autotune.summary_from_stats(stats)
    return profile, op_rows


# ----------------------------------------------------------- EXPLAIN ANALYZE


def _ms(ns) -> str:
    return f"{int(ns or 0) / 1e6:.2f}ms"


def _provenance_lines(profile: dict) -> list[str]:
    out = []
    out.append(
        f"  plan cache: {'HIT' if profile['plan_cache_hit'] else 'miss'}"
        f"   split cache: {'HIT' if profile['split_cache_hit'] else 'miss'}")
    if profile["matview_eligible"] or profile["matview_hits"]:
        stale = (f" ({profile['matview_stale']} stale)"
                 if profile["matview_stale"] else "")
        out.append(
            f"  matview: {profile['matview_hits']}/"
            f"{profile['matview_eligible'] or profile['matview_hits']} "
            f"agent fragments served from standing view state{stale}, "
            f"{profile['matview_rows_folded']} delta rows folded")
    if profile["resident_feeds"]:
        out.append(f"  resident tier: {profile['resident_feeds']} "
                   f"device-resident feeds (h2d {profile['h2d_bytes']}B)")
    if profile["batch_size"]:
        out.append(
            f"  batched: member of a fused batch of {profile['batch_size']} "
            f"(computed slot q{profile['batch_slot']}, results demuxed)")
    if profile["failover"]:
        out.append(f"  failover: shards served by replicas "
                   f"{profile['failover']}")
    if profile["hedged"] or profile["evictions"] or profile["retries"]:
        out.append(
            f"  fault recovery: {profile['retries']} re-dispatch rounds, "
            f"{profile['evictions']} evictions, {profile['hedged']} hedges, "
            f"{profile['chunks_discarded']} chunks discarded")
    if profile["degraded"]:
        out.append("  degraded dispatch (stale-while-revalidate views, "
                   "narrowed ack window)")
    if profile.get("autotune"):
        out.append(f"  autotune: {profile['autotune']}")
    return out


def render_explain(profile: dict, op_rows: list[dict],
                   plan_text: Optional[str] = None) -> str:
    """The EXPLAIN ANALYZE text: the logical plan tree, the measured phase
    breakdown (with % of e2e wall), per-op device/host ns per agent, and
    the provenance block — assembled entirely from the profile, so it is
    correct for whatever path actually served the query (batched members,
    matview hits, failover serves included)."""
    wall = max(int(profile.get("wall_ns", 0)), 1)
    lines = ["== EXPLAIN ANALYZE =="]
    if plan_text:
        lines.append("-- plan:")
        lines.extend(plan_text.splitlines())
    lines.append(
        f"-- phases (e2e {_ms(wall)}, "
        f"{100.0 * profile['accounted_ns'] / wall:.0f}% attributed):")
    for key, label in (("admission_wait_ns", "admission wait"),
                       ("compile_ns", "compile"),
                       ("plan_split_ns", "plan split"),
                       ("exec_ns", "dispatch+exec"),
                       ("merge_ns", "merge")):
        ns = int(profile.get(key, 0) or 0)
        lines.append(f"  {label:<16} {_ms(ns):>10}  "
                     f"{100.0 * ns / wall:5.1f}%")
    if op_rows:
        lines.append("-- operators (per compiled unit):")
        lines.append(f"  {'agent':<10} {'op':<44} {'wall':>10} "
                     f"{'self':>10} {'rows':>10}")
        for r in op_rows:
            lines.append(
                f"  {r['agent'][:10]:<10} {r['op'][:44]:<44} "
                f"{_ms(r['wall_ns']):>10} {_ms(r['self_ns']):>10} "
                f"{r['rows_out']:>10}")
    lines.append("-- provenance:")
    lines.extend(_provenance_lines(profile))
    lines.append(
        f"-- io: scanned {profile['rows_scanned']} rows on "
        f"{profile['agents']} agents, h2d {profile['h2d_bytes']}B, "
        f"d2h {profile['d2h_bytes']}B, output {profile['rows_output']} rows")
    return "\n".join(lines)


def explain_local(plan, exec_stats: dict, wall_ns: int,
                  query_id: str = "local") -> str:
    """EXPLAIN rendering for the single-process path (CLI demo data): adapt
    one executor's exec_stats into the profile shape."""
    from pixie_tpu.plan.debug import explain as plan_explain

    stats = {"agents": {"local": dict(exec_stats)},
             "merger": {"rows_output": exec_stats.get("rows_output", 0)}}
    profile, op_rows = build_profile(
        query_id, "", "local", time.time_ns(), wall_ns, stats)
    return render_explain(profile, op_rows, plan_text=plan_explain(plan))


# ------------------------------------------------------------ metrics-as-data


def sample_metrics_rows(service: str,
                        now_ns: Optional[int] = None) -> list[dict]:
    """Fold the metrics registry into self_telemetry.metrics rows: every
    counter/gauge series, evaluated lazy gauges, and histogram sum/count
    plus p50/p99 read through metrics.hist_quantile — the registry becomes
    a queryable table instead of a scrape-only text page."""
    now_ns = int(now_ns if now_ns is not None else time.time_ns())

    def row(name, labels, kind, value):
        return {"time_": now_ns, "service": service, "name": name,
                "labels": json.dumps(dict(labels), sort_keys=True)
                if labels else "", "kind": kind, "value": float(value)}

    out = []
    for kind, name, labels, value in metrics.snapshot():
        out.append(row(name, labels, kind, value))
    return out

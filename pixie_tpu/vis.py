"""Vis spec parsing — the per-script visualization contract.

Reference: src/api/proto/vispb/vis.proto:58-303 — each bundled script ships a
`vis.json` declaring variables (typed, defaulted), global funcs (script entry
points + arg bindings), and widgets (display spec per func output).  The CLI
uses this to run a script exactly as the Live UI would: resolve variables,
execute every referenced func, attach the widget display kind to each output.
"""
from __future__ import annotations

import dataclasses
import json
from typing import Optional

#: per-variable-type fallback when a vis variable has no defaultValue
TYPE_DEFAULTS = {
    "PX_STRING": "-5m",
    "PX_SERVICE": "default/svc",
    "PX_POD": "default/pod",
    "PX_NAMESPACE": "default",
    "PX_NODE": "node-1",
    "PX_INT64": "10",
    "PX_FLOAT64": "1.0",
    "PX_BOOLEAN": "true",
}


@dataclasses.dataclass(frozen=True)
class Variable:
    name: str
    type: str
    default: Optional[str]
    description: str = ""


@dataclasses.dataclass(frozen=True)
class FuncCall:
    name: str
    #: arg name -> ("variable", var_name) | ("value", literal)
    args: tuple


@dataclasses.dataclass(frozen=True)
class Widget:
    name: str
    kind: str  # short display-spec type, e.g. "TimeseriesChart"
    func: Optional[FuncCall]
    global_output: Optional[str]
    #: raw displaySpec dict (column bindings for renderers)
    display: dict = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class VisSpec:
    variables: list[Variable]
    global_funcs: dict[str, FuncCall]  # outputName -> func
    widgets: list[Widget]

    def variable_values(self, overrides: Optional[dict] = None) -> dict[str, str]:
        out = {}
        for v in self.variables:
            if overrides and v.name in overrides:
                out[v.name] = overrides[v.name]
            elif v.default is not None:
                out[v.name] = v.default
            else:
                out[v.name] = TYPE_DEFAULTS.get(v.type, "")
        return out

    def executions(self, overrides: Optional[dict] = None) -> list[tuple[str, str, dict]]:
        """[(output_name, func_name, resolved_args)] — everything the UI would
        run, deduped."""
        values = self.variable_values(overrides)

        def resolve(fc: FuncCall) -> dict:
            out = {}
            for name, (kind, v) in fc.args:
                out[name] = values[v] if kind == "variable" else v
            return out

        seen = set()
        runs = []
        for out_name, fc in self.global_funcs.items():
            key = (fc.name, tuple(sorted(resolve(fc).items())))
            if key not in seen:
                seen.add(key)
                runs.append((out_name, fc.name, resolve(fc)))
        for w in self.widgets:
            if w.func is not None:
                args = resolve(w.func)
                key = (w.func.name, tuple(sorted(args.items())))
                if key not in seen:
                    seen.add(key)
                    runs.append((w.name, w.func.name, args))
        return runs

    def widget_displays(self) -> dict[str, "Widget"]:
        """output/widget name -> Widget (kind + display column bindings).
        Keyed exactly like executions(): globalFuncOutputName for global
        funcs, the WIDGET name for inline funcs."""
        out = {}
        for w in self.widgets:
            target = w.global_output or w.name
            out[target] = w
        return out

    def widget_kinds(self) -> dict[str, str]:
        """output/widget name -> display kind (table, TimeseriesChart, ...)."""
        return {name: w.kind for name, w in self.widget_displays().items()}


def _parse_func(d: dict) -> FuncCall:
    args = []
    for a in d.get("args", []):
        if "variable" in a:
            args.append((a["name"], ("variable", a["variable"])))
        else:
            args.append((a["name"], ("value", a.get("value"))))
    return FuncCall(name=d["name"], args=tuple(args))


def parse_vis(data) -> VisSpec:
    """Parse a vis.json dict (or JSON text)."""
    if isinstance(data, (str, bytes)):
        data = json.loads(data)
    variables = [
        Variable(
            name=v["name"], type=v.get("type", "PX_STRING"),
            default=v.get("defaultValue"), description=v.get("description", ""),
        )
        for v in data.get("variables", [])
    ]
    gfuncs = {
        gf["outputName"]: _parse_func(gf["func"])
        for gf in data.get("globalFuncs", [])
    }
    widgets = []
    for w in data.get("widgets", []):
        spec_type = w.get("displaySpec", {}).get("@type", "")
        kind = spec_type.rsplit(".", 1)[-1] if spec_type else "Table"
        widgets.append(Widget(
            name=w.get("name", ""), kind=kind,
            func=_parse_func(w["func"]) if "func" in w else None,
            global_output=w.get("globalFuncOutputName"),
            display=dict(w.get("displaySpec", {})),
        ))
    return VisSpec(variables=variables, global_funcs=gfuncs, widgets=widgets)

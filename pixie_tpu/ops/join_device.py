"""Device equijoin kernels: sort/searchsorted match phase on TPU.

Reference: exec/equijoin_node.h builds a hash table and probes it row by
row.  A hash build/probe is hostile to TPU (pointer chasing, dynamic
growth); the TPU-native formulation sorts the build side once and binary-
searches each probe row — O((n+m) log n) in fully vectorized XLA ops, the
same structure as the host join (executor._run_join) so results are
identical.

Two phases keep shapes static under jit:
  1. `match_ranges`: sort build side + searchsorted lo/hi bounds per probe
     row (+ total pair count) — ONE device execution.
  2. `expand_pairs`: given the (pulled, now-static) total, expand the m:n
     pairs into gather indices — one more execution.

Deployment reality (measured, documented in COMPONENTS.md): this pays only
when both sides are already device-resident — the tunneled dev runtime
moves ~24 MB/s per direction, so uploading host-resident join partitions
costs more than the host match itself.  The executor therefore gates the
device path on PX_DEVICE_JOIN (default off ⇒ host numpy), keeping the
kernel available for direct-attached deployments where H2D is PCIe/HBM
speed.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from pixie_tpu import flags

DEVICE_JOIN = flags.define_int(
    "PX_DEVICE_JOIN", 0,
    "1 = run large equijoin match phases on the accelerator (worth it only "
    "when transfers are PCIe/HBM speed, not over a tunneled runtime)")


@jax.jit
def match_ranges(build_codes: jax.Array, probe_codes: jax.Array):
    """Sorted-join phase 1.

    Returns (order, lo, hi, total):
      order: argsort of build_codes (maps sorted position → original row)
      lo/hi: per-probe-row match range [lo, hi) into the SORTED build side
      total: Σ (hi - lo) — the number of matched pairs
    """
    order = jnp.argsort(build_codes, stable=True)
    skey = build_codes[order]
    lo = jnp.searchsorted(skey, probe_codes, side="left")
    hi = jnp.searchsorted(skey, probe_codes, side="right")
    return order, lo, hi, jnp.sum((hi - lo).astype(jnp.int64))


from functools import partial


@partial(jax.jit, static_argnames=("total",))
def _expand(order, lo, counts, total):
    starts = jnp.cumsum(counts) - counts  # exclusive prefix
    # pair p belongs to the probe row r with starts[r] <= p; its slot
    # within the run is p - starts[r]
    p = jnp.arange(total, dtype=jnp.int64)
    r = jnp.searchsorted(starts, p, side="right") - 1
    slot = p - starts[r]
    bpos = lo[r] + slot
    return order[bpos], r


def expand_pairs(order, lo, hi, total: int):
    """Sorted-join phase 2 (static `total` from phase 1's pulled scalar):
    → (build_idx[total], probe_idx[total]) original-row gather indices."""
    if total == 0:
        return (jnp.zeros((0,), jnp.int64), jnp.zeros((0,), jnp.int64))
    return _expand(order, lo, hi - lo, total)


@jax.jit
def _matched_masks(order, lo, hi, bidx):
    pm = hi > lo
    bm = jnp.zeros(order.shape, jnp.bool_).at[bidx].set(True, mode="drop")
    return bm, pm


def device_join_codes(build_codes: np.ndarray, probe_codes: np.ndarray):
    """Full device join over composite int64 key codes (host convenience:
    uploads, matches, pulls indices).  → (build_idx, probe_idx,
    build_matched[nb] bool, probe_matched[np] bool) — the same contract the
    host `_match_pairs` provides, so the executor's output/unmatched logic
    is shared."""
    from pixie_tpu.engine import transfer

    b = jax.device_put(np.ascontiguousarray(build_codes))
    p = jax.device_put(np.ascontiguousarray(probe_codes))
    order, lo, hi, total = match_ranges(b, p)
    total = int(total)
    bidx_d, pidx_d = expand_pairs(order, lo, hi, total)
    bm_d, pm_d = _matched_masks(order, lo, hi, bidx_d)
    bidx, pidx, bm, pm = transfer.pull([bidx_d, pidx_d, bm_d, pm_d])
    return (np.asarray(bidx), np.asarray(pidx), np.asarray(bm),
            np.asarray(pm))

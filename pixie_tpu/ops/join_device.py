"""Device equijoin kernels: radix-bucketed match/expand on the dispatch device.

Reference: exec/equijoin_node.h builds a hash table and probes it row by
row.  A row-at-a-time hash build/probe is hostile to accelerators (pointer
chasing, dynamic growth); r5 measured that the first TPU-native shape — one
full-width stable argsort with an iota payload + global searchsorteds — is
hostile too (868K rows/s at 16M x 16M: the variadic comparator sort and the
DRAM-random binary searches dominate).  This round reshapes the kernel for
the hardware (Flare/Tailwind's lesson in PAPERS.md):

  * RADIX-PACKED PARTITION SORT: each side packs ``code << idx_bits | row``
    into ONE int64 and a values-only sort both radix-partitions the rows
    (the key's high bits are the bucket) and orders every bucket — no
    payload tensor rides the sort (measured 10x cheaper than stable argsort
    on XLA-CPU, half the shuffled bytes on a TPU bitonic sort), and the
    original row index is a mask away.
  * PER-BUCKET MATCH + EXPAND: B = pow2 buckets sliced out of the sorted
    arrays; each bucket builds a bucket-local first-position LUT
    (scatter-min over its dense local code span + a reverse min-scan), so
    probe lookups are cache-shaped gathers, and expands its pairs with a
    boundary-scatter cumsum — all shapes pow2-padded so compiled kernels
    are reused across buckets; buckets dispatch over a small thread pool.
  * NATIVE CPU KERNEL: when the dispatch device IS XLA-CPU the buffer is
    host memory, so the honest device kernel is the pthread radix hash join
    in native/join.cc running zero-copy on the same bytes (measured ~10x
    the XLA formulation at 16M x 16M).  Accelerator backends always use the
    XLA path.

Gate: PX_DEVICE_JOIN is now AUTO by default (-1).  The old deployment
reality stands — over a ~24 MB/s tunneled runtime, uploading host-resident
partitions costs more than the host match — but instead of a static
default-off flag the executor now asks `device_join_enabled()`, which
measures H2D bandwidth once per process (`engine/transfer.h2d_bandwidth_probe`,
the upload sibling of `wave_rtt_floor`) and enables the device path when the
link is direct-attached class (or when the CPU-native kernel applies, where
there is no upload at all).  The probe result and decision are recorded in
`stats["device"]` and as px_* gauges, so the gate is observable, not silent.
"""
from __future__ import annotations

import threading
from concurrent.futures import ThreadPoolExecutor
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from pixie_tpu import flags

DEVICE_JOIN = flags.define_int(
    "PX_DEVICE_JOIN", -1,
    "-1 = auto (measured H2D probe on accelerators; native kernel on CPU), "
    "0 = force host match, 1 = force device kernel")

MIN_H2D_MBPS = flags.define_int(
    "PX_DEVICE_JOIN_MIN_H2D_MBPS", 1000,
    "auto-gate threshold: enable the accelerator join when the measured "
    "host->device bandwidth reaches this (PCIe direct-attach is >10000; "
    "a tunneled dev runtime measures ~24)")

#: rows per radix bucket for the XLA kernel (B = pow2 covering n/this)
_BUCKET_TARGET_ROWS = 1 << 17
_MAX_BUCKETS = 1 << 12
#: below this many rows per side the flat (1-bucket) shape is used
_MIN_BUCKETED_ROWS = 1 << 18


from pixie_tpu.ops.groupby import next_pow2 as _next_pow2


def _bucket_count(nb: int, npr: int) -> int:
    """Radix bucket count for one join shape — shared by the kernel and the
    caller's LUT-size guard so the two can never drift."""
    if max(nb, npr) < _MIN_BUCKETED_ROWS:
        return 1
    return min(_next_pow2((nb + npr) // _BUCKET_TARGET_ROWS), _MAX_BUCKETS)


# ----------------------------------------------------------- legacy kernel
# Full-width argsort + searchsorted formulation (r4).  Kept as the fallback
# for code spaces too wide to radix-pack (arbitrary raw int64 keys — the
# executor's unique-inverse codes always pack) and for its unit tests.


@jax.jit
def match_ranges(build_codes: jax.Array, probe_codes: jax.Array):
    """Sorted-join phase 1 (legacy full-width form).

    Returns (order, lo, hi, total):
      order: argsort of build_codes (maps sorted position → original row)
      lo/hi: per-probe-row match range [lo, hi) into the SORTED build side
      total: Σ (hi - lo) — the number of matched pairs
    """
    order = jnp.argsort(build_codes, stable=True)
    skey = build_codes[order]
    lo = jnp.searchsorted(skey, probe_codes, side="left")
    hi = jnp.searchsorted(skey, probe_codes, side="right")
    return order, lo, hi, jnp.sum((hi - lo).astype(jnp.int64))


@partial(jax.jit, static_argnames=("total",))
def _expand(order, lo, counts, total):
    starts = jnp.cumsum(counts) - counts  # exclusive prefix
    # pair p belongs to the probe row r with starts[r] <= p; its slot
    # within the run is p - starts[r]
    p = jnp.arange(total, dtype=jnp.int64)
    r = jnp.searchsorted(starts, p, side="right") - 1
    slot = p - starts[r]
    bpos = lo[r] + slot
    return order[bpos], r


def expand_pairs(order, lo, hi, total: int):
    """Sorted-join phase 2 (static `total` from phase 1's pulled scalar):
    → (build_idx[total], probe_idx[total]) original-row gather indices."""
    if total == 0:
        return (jnp.zeros((0,), jnp.int64), jnp.zeros((0,), jnp.int64))
    return _expand(order, lo, hi - lo, total)


def _legacy_join_codes(b, p):
    from pixie_tpu.engine import transfer

    order, lo, hi, total = match_ranges(b, p)
    total = int(total)
    bidx_d, pidx_d = expand_pairs(order, lo, hi, total)
    bidx, pidx = transfer.pull([bidx_d, pidx_d])
    return np.asarray(bidx), np.asarray(pidx)


# ----------------------------------------------------- bucketed XLA kernel


@partial(jax.jit, static_argnames=("ib", "pad"))
def _pack_sort(codes, ib, pad):
    """Radix-packed values-only partition sort of one side.

    key = code << ib | row: the sort groups equal codes (high bits) and the
    original row rides the low bits — no payload operand.  `pad` sentinel
    rows (MAX key) let per-bucket pow2 slices read past the end safely.
    """
    n = codes.shape[0]
    k = (codes.astype(jnp.int64) << ib) | jnp.arange(n, dtype=jnp.int64)
    s = jnp.sort(k)
    if pad:
        s = jnp.concatenate([s, jnp.full((pad,), jnp.int64(2) ** 62,
                                         jnp.int64)])
    return s


@partial(jax.jit, static_argnames=("extra",))
def _append_pad(s, extra):
    """Grow the sentinel tail (rare: a heavily skewed bucket whose pow2 cap
    overruns the standard pad)."""
    return jnp.concatenate([s, jnp.full((extra,), jnp.int64(2) ** 62,
                                        jnp.int64)])


@partial(jax.jit, static_argnames=("cap_b", "cap_p", "kloc", "ib"))
def _bucket_match(sb, sp, bs, nb, ps, npr, c0, cap_b, cap_p, kloc, ib):
    """Match one bucket: per-probe-slot (count, lo) into the bucket's sorted
    build slice, via a bucket-local dense first-position LUT.

    The LUT (`offf[c]` = first sorted position with local code ≥ c) comes
    from a scatter-min of positions + a reverse min-scan — both over the
    bucket's own code span, so the working set is cache-sized.  Pads carry
    local code `kloc`, which lands in the LUT's boundary slot and cannot
    produce counts (their probe slots are masked).
    """
    bsl = jax.lax.dynamic_slice(sb, (bs,), (cap_b,))
    psl = jax.lax.dynamic_slice(sp, (ps,), (cap_p,))
    vp = jnp.arange(cap_p) < npr
    bc = jnp.minimum((bsl >> ib) - c0, kloc).astype(jnp.int32)
    pc = jnp.where(vp, jnp.minimum((psl >> ib) - c0, kloc),
                   kloc).astype(jnp.int32)
    off = jnp.full((kloc + 2,), cap_b, jnp.int32).at[bc].min(
        jnp.arange(cap_b, dtype=jnp.int32), mode="drop")
    off = off.at[kloc + 1].min(jnp.int32(nb))
    offf = jax.lax.associative_scan(jnp.minimum, off, reverse=True)
    cnt_by_code = offf[1:] - offf[:-1]
    cntP = jnp.where(vp, cnt_by_code[pc], 0)
    return cntP.astype(jnp.int32), offf[pc].astype(jnp.int32), jnp.sum(
        cntP.astype(jnp.int64))


@partial(jax.jit, static_argnames=("cap_t", "ib"))
def _bucket_expand(cntP, loP, sb, sp, bs, ps, total, cap_t, ib):
    """Expand one bucket's (count, lo) ranges into original-row pairs.

    Probe-run boundaries scatter 1s at each run start (indices are sorted —
    the starts cumsum is monotone) and a cumsum recovers the probe slot per
    pair; the build row then sits `j` past the run's first sorted position.
    Both original indices are the packed keys' low bits — no order arrays.
    """
    starts = jnp.cumsum(cntP) - cntP
    z = jnp.zeros((cap_t,), jnp.int32).at[starts].add(
        1, mode="drop", indices_are_sorted=True)
    r = jnp.cumsum(z) - 1
    pos = jnp.arange(cap_t, dtype=jnp.int32)
    valid = pos < total
    rr = jnp.where(valid, r, 0)
    j = pos - starts[rr]
    spos = loP[rr] + j
    mask = (jnp.int64(1) << ib) - 1
    bidx = sb[bs + spos] & mask
    pidx = sp[ps + rr] & mask
    return jnp.where(valid, bidx, -1), jnp.where(valid, pidx, -1)


def _xla_bucketed_join(b, p, max_code: int, nthreads: int | None = None):
    """Radix-bucketed sorted join on the XLA device → (bidx, pidx) numpy.

    `b`/`p` are device (or host) int64 code arrays with codes in
    [0, max_code]; the caller guarantees packability
    (bits(max_code) + bits(rows) ≤ 62).
    """
    from pixie_tpu.engine import transfer

    nb, npr = int(b.shape[0]), int(p.shape[0])
    K = int(max_code) + 1
    ib = max(max(nb, npr) - 1, 1).bit_length()
    B = _bucket_count(nb, npr)
    # equal spans by construction: every bucket covers exactly `kloc` codes,
    # so out-of-bucket rows in an over-read slice always clamp into the
    # LUT's boundary slot instead of polluting a narrower bucket's cells
    kloc = -(-K // B)
    edges = np.arange(B + 1, dtype=np.int64) * kloc
    pad = _next_pow2(max(nb, npr) * 4 // B) if B > 1 else _next_pow2(
        max(nb, npr))
    if nthreads is None:
        import os

        nthreads = min(4, os.cpu_count() or 1)
    with ThreadPoolExecutor(2) as ex:
        fb = ex.submit(_pack_sort, jnp.asarray(b), ib, pad)
        fp = ex.submit(_pack_sort, jnp.asarray(p), ib, pad)
        sb, sp = fb.result(), fp.result()
    dedges = jnp.asarray(edges << ib)
    bb = np.asarray(jnp.searchsorted(sb[:nb], dedges))
    pb = np.asarray(jnp.searchsorted(sp[:npr], dedges))
    bsz, psz = bb[1:] - bb[:-1], pb[1:] - pb[:-1]
    cap_bs = [_next_pow2(int(s)) for s in bsz]
    cap_ps = [_next_pow2(int(s)) for s in psz]
    # a pow2 cap may overrun the sentinel tail under heavy skew — grow it
    over_b = max(int(bb[i]) + cap_bs[i] for i in range(B)) - (nb + pad)
    over_p = max(int(pb[i]) + cap_ps[i] for i in range(B)) - (npr + pad)
    if over_b > 0:
        sb = _append_pad(sb, _next_pow2(over_b))
    if over_p > 0:
        sp = _append_pad(sp, _next_pow2(over_p))
    res = [None] * B

    def match(i):
        res[i] = _bucket_match(sb, sp, int(bb[i]), int(bsz[i]), int(pb[i]),
                               int(psz[i]), int(edges[i]), cap_bs[i],
                               cap_ps[i], kloc, ib)

    with ThreadPoolExecutor(nthreads) as ex:
        list(ex.map(match, range(B)))
    totals = np.asarray(jax.device_get([r[2] for r in res]))
    outs = [None] * B

    def expand(i):
        t = int(totals[i])
        if t == 0:
            return
        outs[i] = _bucket_expand(res[i][0], res[i][1], sb, sp, int(bb[i]),
                                 int(pb[i]), t, _next_pow2(t), ib) + (t,)

    with ThreadPoolExecutor(nthreads) as ex:
        list(ex.map(expand, range(B)))
    parts = transfer.pull([(o[0], o[1]) for o in outs if o])
    total = int(totals.sum())
    bidx = np.empty(total, np.int64)
    pidx = np.empty(total, np.int64)
    at = 0
    for (bo, po), o in zip(parts, (o for o in outs if o)):
        t = o[2]
        bidx[at:at + t] = np.asarray(bo)[:t]
        pidx[at:at + t] = np.asarray(po)[:t]
        at += t
    return bidx, pidx


# ------------------------------------------------------ native CPU kernel


def native_join_available() -> bool:
    from pixie_tpu.native import load_native

    lib = load_native()
    return lib is not None and hasattr(lib, "px_join_run")


def _native_join(bh: np.ndarray, ph: np.ndarray):
    import ctypes

    from pixie_tpu.native import load_native

    lib = load_native()
    bh = np.ascontiguousarray(bh, dtype=np.int64)
    ph = np.ascontiguousarray(ph, dtype=np.int64)
    total = ctypes.c_int64(0)
    h = lib.px_join_run(
        bh.ctypes.data_as(ctypes.c_void_p), len(bh),
        ph.ctypes.data_as(ctypes.c_void_p), len(ph), ctypes.byref(total))
    try:
        n = total.value
        bidx = np.empty(n, np.int64)
        pidx = np.empty(n, np.int64)
        if n:
            lib.px_join_fetch(h, bidx.ctypes.data_as(ctypes.c_void_p),
                              pidx.ctypes.data_as(ctypes.c_void_p))
    finally:
        lib.px_join_free(h)
    return bidx, pidx


# ------------------------------------------------------------- entry points


def _dispatch_backend() -> str:
    from pixie_tpu.ops.groupby import dispatch_backend

    return dispatch_backend()


def device_join_codes(build_codes, probe_codes):
    """Full device join over composite int64 key codes → (build_idx,
    probe_idx, build_matched[nb] bool, probe_matched[np] bool) — the same
    contract the host `_match_pairs` provides, so the executor's
    output/unmatched logic is shared.  Pair ORDER is unspecified.

    Inputs may be host numpy or device-resident jax arrays.  Dispatch:
    native radix hash join when the dispatch device is XLA-CPU (zero-copy
    on the same bytes), radix-bucketed XLA kernel otherwise; raw code
    spaces too wide to radix-pack fall back to the legacy full-width
    sort/searchsorted kernel.
    """
    nb, npr = int(build_codes.shape[0]), int(probe_codes.shape[0])
    if nb == 0 or npr == 0:
        z = np.zeros(0, np.int64)
        return z, z.copy(), np.zeros(nb, bool), np.zeros(npr, bool)
    path = join_path()
    if path == "native_cpu":
        bidx, pidx = _native_join(np.asarray(build_codes),
                                  np.asarray(probe_codes))
    else:
        b = jnp.asarray(build_codes)
        p = jnp.asarray(probe_codes)
        # packability check: codes must be >= some floor and narrow enough
        # for code << idx_bits | idx to stay positive in int64
        cmin, cmax = jax.device_get(
            [jnp.minimum(jnp.min(b), jnp.min(p)),
             jnp.maximum(jnp.max(b), jnp.max(p))])
        ib = max(max(nb, npr) - 1, 1).bit_length()
        shift = -int(cmin) if cmin < 0 else 0
        width = int(cmax) + shift
        # packable + dense enough that the per-bucket LUT stays bounded;
        # sparse/wide raw code spaces keep the legacy full-width kernel
        # (the executor's unique-inverse codes are always dense)
        if (width >= 0 and width.bit_length() + ib <= 62
                and (width + 1) // _bucket_count(nb, npr) <= (1 << 24)):
            if shift:
                b = b + shift
                p = p + shift
            bidx, pidx = _xla_bucketed_join(b, p, width)
        else:
            bidx, pidx = _legacy_join_codes(b, p)
    bm = np.zeros(nb, bool)
    pm = np.zeros(npr, bool)
    bm[bidx] = True
    pm[pidx] = True
    return bidx, pidx, bm, pm


def join_path() -> str:
    """Which kernel `device_join_codes` will take right now:
    "native_cpu" or "xla_bucketed"."""
    if _dispatch_backend() == "cpu" and native_join_available():
        return "native_cpu"
    return "xla_bucketed"


# ---------------------------------------------------------------- auto-gate

_gate_lock = threading.Lock()
_gate_cache: dict | None = None
#: transfer.probe_epoch() at the cached decision: a probe expiry or
#: explicit invalidate_probes() bumps the epoch, which re-opens the gate
#: decision too (it was derived from the now-dead H2D figure)
_gate_epoch: int = -1


def device_join_gate(refresh: bool = False) -> dict:
    """The process-wide device-join gating decision, measured once.

    → {"enabled", "reason", "path", "h2d_mbps" (accelerators only),
       "flag"}.  PX_DEVICE_JOIN forces it (0/1); -1 = auto:
      * CPU dispatch: on iff the native kernel loaded — there is no
        transfer at all, and the native radix join beats the numpy host
        match (~3x at 16M x 16M).
      * accelerator: on iff the MEASURED H2D bandwidth
        (transfer.h2d_bandwidth_probe) reaches PX_DEVICE_JOIN_MIN_H2D_MBPS
        — direct-attached deployments get the kernel without config, a
        ~24 MB/s tunneled runtime keeps the host match.
    The decision is cached; metrics gauges px_device_join_enabled /
    px_h2d_bandwidth_mbps are set as a side effect so the gate is
    observable (the executor also records it in stats["device"]).
    """
    global _gate_cache, _gate_epoch
    from pixie_tpu.engine import transfer as _transfer

    with _gate_lock:
        flag = flags.get("PX_DEVICE_JOIN")
        # forced settings are never cached (tests flip the flag; no probe
        # needed anyway) — only the measured auto decision is, and only
        # while the probe epoch it was derived from is still current
        if _gate_cache is not None and not refresh \
                and _gate_cache.get("flag") == flag \
                and _gate_epoch == _transfer.probe_epoch():
            return _gate_cache
        out = {"flag": flag, "path": join_path()}
        if flag == 0:
            out.update(enabled=False, reason="forced_off")
        elif flag == 1:
            out.update(enabled=True, reason="forced_on")
        elif _dispatch_backend() == "cpu":
            ok = native_join_available()
            out.update(enabled=ok,
                       reason="native_cpu" if ok else "no_native_kernel")
        else:
            from pixie_tpu.engine import transfer

            try:
                probe = transfer.h2d_bandwidth_probe()
                mbps = probe["mbps"]
                out["h2d_mbps"] = mbps
                thresh = flags.get("PX_DEVICE_JOIN_MIN_H2D_MBPS")
                out.update(enabled=mbps >= thresh,
                           reason=("h2d_direct_attached" if mbps >= thresh
                                   else "h2d_tunneled"))
            except Exception as e:  # pragma: no cover — probe must not kill
                out.update(enabled=False,
                           reason=f"h2d_probe_error:{type(e).__name__}")
        from pixie_tpu import metrics

        metrics.gauge_set("px_device_join_enabled", float(out["enabled"]),
                          help_="device-join auto-gate decision (1=device "
                                "kernel, 0=host match)")
        # px_h2d_bandwidth_mbps is set by the probe itself now
        # (transfer.h2d_bandwidth_probe memoizes per process and owns the
        # gauge), so the gate no longer re-measures or re-exports it
        if flag == -1:
            _gate_cache = out
            _gate_epoch = _transfer.probe_epoch()
        return out


def reset_gate_for_testing() -> None:
    global _gate_cache
    with _gate_lock:
        _gate_cache = None

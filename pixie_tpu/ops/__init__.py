"""Device kernel library — the TPU equivalent of Carnot's exec operators
(reference src/carnot/exec/).

Everything here is pure-functional JAX over fixed-shape tensors:

  * Batches are dicts of equal-length device arrays plus a validity `mask`
    (padding + filtered rows are masked, never compacted on device — dynamic
    shapes would defeat XLA).
  * Group-by uses dense group codes (dictionary codes, mixed-radix combined),
    lowered to `segment_*` reductions — no hash tables on device.
  * Aggregate state is a pytree whose leaves each declare a reduction op
    ("add"/"min"/"max"), so partial→final distributed aggregation is a direct
    psum/pmin/pmax over a mesh axis (replaces the reference's serialize-UDA-state
    → gRPC → Merge path, planpb/plan.proto:250-257).
"""
from pixie_tpu.ops.groupby import combine_codes, split_codes, masked_segment_sum
from pixie_tpu.ops.sketch import LogHistogram

__all__ = ["combine_codes", "split_codes", "masked_segment_sum", "LogHistogram"]

"""Group-by primitives: dense group codes + masked segment reductions.

Replaces the reference's hash group-by (AbslRowTupleHashMap over RowTuples,
src/carnot/exec/agg_node.h:55-140) with a TPU-native formulation: every group key
column is a dense int32 code (dictionary code for strings/UPIDs; query-time
dictionary for raw ints), multi-key groups are mixed-radix combined into a single
segment id, and aggregation is an XLA segment reduction — which lowers to sorted
scatter-adds that tile well, instead of pointer-chasing hash probes.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def next_pow2(n: int) -> int:
    return 1 << max(0, (int(n) - 1)).bit_length()


def combine_codes(codes: list[jax.Array], cards: list[int]) -> tuple[jax.Array, int]:
    """Mixed-radix combine k dense code columns into one group id.

    cards[i] is a static upper bound on codes[i] (dictionary-size snapshot,
    bucketed by the caller to stabilize compiled shapes). Returns (gid, num_groups)
    with num_groups = prod(cards); gid of a row with any out-of-range/negative code
    is clamped into range — callers must mask such rows out beforehand.
    """
    assert len(codes) == len(cards) and codes
    num_groups = 1
    for c in cards:
        num_groups *= int(c)
    gid = jnp.zeros_like(codes[0], dtype=jnp.int32)
    for code, card in zip(codes, cards):
        c = jnp.clip(code.astype(jnp.int32), 0, card - 1)
        gid = gid * card + c
    return gid, num_groups


def split_codes(gids: np.ndarray, cards: list[int]) -> list[np.ndarray]:
    """Host-side inverse of combine_codes: group id → per-key codes."""
    out = []
    rem = np.asarray(gids)
    for card in reversed(cards):
        out.append((rem % card).astype(np.int32))
        rem = rem // card
    return list(reversed(out))


#: Matmul-lowered segment sums are used on TPU up to this group count; the
#: one-hot chunk buffer is CHUNK_ROWS × groups × 4B (≤ 256 MB at the cap).
MATMUL_MAX_GROUPS = 1 << 10
#: Rows per scan chunk.  Chosen so an 8-bit limb chunk sum (≤ CHUNK_ROWS × 255)
#: stays below 2^24 and is therefore EXACT in float32 MXU accumulation.
CHUNK_ROWS = 1 << 16


def dispatch_backend() -> str:
    """The platform kernels traced right now will run on.

    `jax.default_backend()` ignores an active `jax.default_device(...)`
    override (the executor routes small queries to CPU that way), so consult
    the config var first.  Formulation choices (MXU one-hot vs scatter) must
    follow the DISPATCH platform or CPU-routed aggs would trace the matmul
    path — measured 3.6 s vs 8 ms for 1M rows on CPU.
    """
    d = jax.config.jax_default_device
    if d is not None:
        return d.platform
    return jax.default_backend()


def encode_against(lut: jax.Array, values: jax.Array) -> jax.Array:
    """value → sorted-LUT position (== jnp.searchsorted(lut, values, 'left')).

    Small LUTs use a broadcast compare-count: XLA CPU lowers searchsorted to
    a sequential scan (~17 ms for 1M rows × 5 entries, measured) while the
    [N, K] compare is vectorized (~1 ms); TPU fuses either form.
    """
    if lut.shape[0] <= 64 and dispatch_backend() != "tpu":
        return jnp.sum(lut[None, :] < values[:, None], axis=1).astype(jnp.int32)
    return jnp.searchsorted(lut, values).astype(jnp.int32)


def _use_matmul(n: int, num_groups: int) -> bool:
    return (
        dispatch_backend() == "tpu"
        and num_groups <= MATMUL_MAX_GROUPS
        and n >= 4096
        and (n % min(n, CHUNK_ROWS)) == 0
    )


def _chunked_onehot_sum(v32: jax.Array, gid: jax.Array, num_groups: int) -> jax.Array:
    """sum per group of float32 contributions via MXU: for each chunk,
    v[1,CH] @ one_hot[CH,G], accumulated across chunks in float64.

    Scatter-adds on TPU run orders of magnitude slower than this (measured:
    segment_sum over 16M rows ≈ 1.4 s f64 / 180 ms f32; one-hot matmul ≈ 30 ms),
    and chunking keeps the materialized one-hot bounded while making per-chunk
    f32 accumulation exact for bounded-magnitude contributions.
    """
    return _chunked_onehot_multi_sum(
        lambda vv: vv[None, :], v32, gid, num_groups)[0]


def _chunked_onehot_multi_sum(lanes_fn, v, gid: jax.Array,
                              num_groups: int) -> jax.Array:
    """[L, G] f64 per-group sums where lanes_fn(chunk) -> [L, CH] f32 lanes.

    The one-hot is the expensive part (CH x G f32 written/read from HBM per
    chunk); stacking all L lanes into ONE [L,CH] @ [CH,G] GEMM builds it
    once instead of L times — the 8-limb exact-int64 sum was measured
    HBM-bound on exactly this (8 one-hot rebuilds per column per chunk).
    """
    n = v.shape[0]
    ch = min(n, CHUNK_ROWS)
    c = n // ch
    if c == 1:
        oh = jax.nn.one_hot(gid, num_groups, dtype=jnp.float32)
        return (lanes_fn(v) @ oh).astype(jnp.float64)
    vc = v.reshape(c, ch)
    gc = gid.reshape(c, ch)
    L = lanes_fn(v[:ch]).shape[0]

    def body(carry, xs):
        vv, gg = xs
        oh = jax.nn.one_hot(gg, num_groups, dtype=jnp.float32)
        return carry + (lanes_fn(vv) @ oh).astype(jnp.float64), None

    out, _ = jax.lax.scan(
        body, jnp.zeros((L, num_groups), jnp.float64), (vc, gc))
    return out


def masked_segment_sum(values: jax.Array, gid: jax.Array, num_groups: int, mask: jax.Array):
    v = jnp.where(mask, values, jnp.zeros((), dtype=values.dtype))
    if not _use_matmul(v.shape[0], num_groups):
        return jax.ops.segment_sum(v, gid, num_segments=num_groups)
    gid = gid.astype(jnp.int32)
    d = jnp.dtype(v.dtype)
    if d == jnp.bool_:
        return _chunked_onehot_sum(v.astype(jnp.float32), gid, num_groups).astype(jnp.int64)
    if d in (jnp.dtype(jnp.int64), jnp.dtype(jnp.uint64), jnp.dtype(jnp.int32)):
        # EXACT 64-bit sums on the MXU: split the two's-complement bit pattern
        # into 8-bit limbs; each limb's chunk sum ≤ 2^24 is exact in f32, the
        # f64 cross-chunk accumulation is exact below 2^53, and the final
        # shifted int64 adds wrap mod 2^64 — i.e. true two's-complement sum.
        # All 8 limbs ride ONE GEMM per chunk (the one-hot dominates HBM).
        u = v.astype(jnp.uint64)
        shifts = jnp.arange(8, dtype=jnp.uint64) * jnp.uint64(8)

        def limbs(uu):
            return ((uu[None, :] >> shifts[:, None])
                    & jnp.uint64(0xFF)).astype(jnp.float32)

        s = _chunked_onehot_multi_sum(limbs, u, gid, num_groups)  # [8, G]
        total = jnp.zeros((num_groups,), dtype=jnp.uint64)
        for k in range(8):
            total = total + (s[k].astype(jnp.uint64) << (8 * k))
        return total.astype(v.dtype if d != jnp.dtype(jnp.int32) else jnp.int64)
    if d == jnp.dtype(jnp.float64):
        # hi/lo float32 split: v == hi + lo to ~2^-48 relative; residual error
        # is the per-chunk f32 accumulation of hi (~1e-6 relative, documented).
        def hilo(vv):
            hi = vv.astype(jnp.float32)
            lo = (vv - hi.astype(jnp.float64)).astype(jnp.float32)
            return jnp.stack([hi, lo])

        s = _chunked_onehot_multi_sum(hilo, v, gid, num_groups)
        return s[0] + s[1]
    if d in (jnp.dtype(jnp.float32), jnp.dtype(jnp.bfloat16)):
        return _chunked_onehot_sum(v.astype(jnp.float32), gid, num_groups).astype(d)
    return jax.ops.segment_sum(v, gid, num_segments=num_groups)


def masked_segment_count(gid: jax.Array, num_groups: int, mask: jax.Array) -> jax.Array:
    """Rows per group (int64, exact): f32 one-hot matmul of the mask on TPU
    (per-chunk counts ≤ CHUNK_ROWS are exact in f32), scatter elsewhere."""
    n = gid.shape[0]
    if _use_matmul(n, num_groups):
        c = _chunked_onehot_sum(mask.astype(jnp.float32), gid.astype(jnp.int32), num_groups)
        return c.astype(jnp.int64)
    ones = jnp.where(mask, 1, 0).astype(jnp.int64)
    return jax.ops.segment_sum(ones, gid, num_segments=num_groups)


def masked_segment_min(values: jax.Array, gid: jax.Array, num_groups: int, mask: jax.Array):
    big = _identity_for(values.dtype, "min")
    v = jnp.where(mask, values, big)
    return jax.ops.segment_min(v, gid, num_segments=num_groups)


def masked_segment_max(values: jax.Array, gid: jax.Array, num_groups: int, mask: jax.Array):
    small = _identity_for(values.dtype, "max")
    v = jnp.where(mask, values, small)
    return jax.ops.segment_max(v, gid, num_segments=num_groups)


def _identity_for(dtype, op: str):
    d = jnp.dtype(dtype)
    if d.kind == "f":
        return jnp.array(jnp.inf if op == "min" else -jnp.inf, dtype=d)
    if d.kind in "iu":
        info = jnp.iinfo(d)
        return jnp.array(info.max if op == "min" else info.min, dtype=d)
    if d.kind == "b":
        return jnp.array(op == "min", dtype=d)
    raise TypeError(f"no identity for dtype {d}")

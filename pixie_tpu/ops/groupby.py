"""Group-by primitives: dense group codes + masked segment reductions.

Replaces the reference's hash group-by (AbslRowTupleHashMap over RowTuples,
src/carnot/exec/agg_node.h:55-140) with a TPU-native formulation: every group key
column is a dense int32 code (dictionary code for strings/UPIDs; query-time
dictionary for raw ints), multi-key groups are mixed-radix combined into a single
segment id, and aggregation is an XLA segment reduction — which lowers to sorted
scatter-adds that tile well, instead of pointer-chasing hash probes.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def next_pow2(n: int) -> int:
    return 1 << max(0, (int(n) - 1)).bit_length()


def combine_codes(codes: list[jax.Array], cards: list[int]) -> tuple[jax.Array, int]:
    """Mixed-radix combine k dense code columns into one group id.

    cards[i] is a static upper bound on codes[i] (dictionary-size snapshot,
    bucketed by the caller to stabilize compiled shapes). Returns (gid, num_groups)
    with num_groups = prod(cards); gid of a row with any out-of-range/negative code
    is clamped into range — callers must mask such rows out beforehand.
    """
    assert len(codes) == len(cards) and codes
    num_groups = 1
    for c in cards:
        num_groups *= int(c)
    gid = jnp.zeros_like(codes[0], dtype=jnp.int32)
    for code, card in zip(codes, cards):
        c = jnp.clip(code.astype(jnp.int32), 0, card - 1)
        gid = gid * card + c
    return gid, num_groups


def split_codes(gids: np.ndarray, cards: list[int]) -> list[np.ndarray]:
    """Host-side inverse of combine_codes: group id → per-key codes."""
    out = []
    rem = np.asarray(gids)
    for card in reversed(cards):
        out.append((rem % card).astype(np.int32))
        rem = rem // card
    return list(reversed(out))


def masked_segment_sum(values: jax.Array, gid: jax.Array, num_groups: int, mask: jax.Array):
    v = jnp.where(mask, values, jnp.zeros((), dtype=values.dtype))
    return jax.ops.segment_sum(v, gid, num_segments=num_groups)


def masked_segment_min(values: jax.Array, gid: jax.Array, num_groups: int, mask: jax.Array):
    big = _identity_for(values.dtype, "min")
    v = jnp.where(mask, values, big)
    return jax.ops.segment_min(v, gid, num_segments=num_groups)


def masked_segment_max(values: jax.Array, gid: jax.Array, num_groups: int, mask: jax.Array):
    small = _identity_for(values.dtype, "max")
    v = jnp.where(mask, values, small)
    return jax.ops.segment_max(v, gid, num_segments=num_groups)


def _identity_for(dtype, op: str):
    d = jnp.dtype(dtype)
    if d.kind == "f":
        return jnp.array(jnp.inf if op == "min" else -jnp.inf, dtype=d)
    if d.kind in "iu":
        info = jnp.iinfo(d)
        return jnp.array(info.max if op == "min" else info.min, dtype=d)
    if d.kind == "b":
        return jnp.array(op == "min", dtype=d)
    raise TypeError(f"no identity for dtype {d}")

"""Mergeable quantile sketch as a dense tensor.

Replaces the reference's t-digest UDA (src/carnot/funcs/builtins/math_sketches.h:34-49)
whose pointer-based centroid structure cannot live on a TPU. We use a DDSketch-style
log-bucketed histogram: fixed relative accuracy, fixed memory, and — crucially —
merge is elementwise addition, so distributed merge of per-device partial sketches
is a single `psum` over the mesh axis.

Layout per group: float32[NBINS + 2] — bin 0 counts values <= 0 ("zero bin"),
bins 1..NBINS count positive values by ceil(log_gamma(v)); the last bin absorbs
overflow. With gamma = 1.02 and 1024 bins the dynamic range is ~1e8 at 2% relative
error, which covers latency-in-ns style telemetry after scaling.
"""
from __future__ import annotations

import dataclasses
import math

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class LogHistogram:
    nbins: int = 1024
    gamma: float = 1.02
    #: values below this are counted in the zero bin.
    min_value: float = 1e-9

    @property
    def width(self) -> int:
        return self.nbins + 2

    def _log_gamma(self):
        return math.log(self.gamma)

    def bin_index(self, v: jax.Array) -> jax.Array:
        """Bin index per value (device)."""
        lg = jnp.log(jnp.maximum(v.astype(jnp.float32), self.min_value)) / self._log_gamma()
        idx = jnp.ceil(lg).astype(jnp.int32) + 1  # +1: bin 0 is the zero bin
        idx = jnp.where(v <= self.min_value, 0, idx)
        return jnp.clip(idx, 0, self.width - 1)

    #: rows per chunk for the matmul path (one-hot bin buffer = chunk × width × 4B)
    CHUNK = 1 << 13

    def update(
        self,
        hist: jax.Array,  # [num_groups, width]
        gid: jax.Array,
        values: jax.Array,
        mask: jax.Array,
        num_groups: int,
    ) -> jax.Array:
        """Add values into per-group histograms.

        TPU path: hist += one_hot(gid).T @ one_hot(bin) per chunk — a pure MXU
        GEMM [G,CH]@[CH,B] instead of a flat scatter-add (scatters serialize on
        TPU; measured ~5x slower than the double-one-hot matmul at 16M rows).
        """
        n = gid.shape[0]
        bins = self.bin_index(values)
        ch = min(n, self.CHUNK)
        from pixie_tpu.ops.groupby import dispatch_backend

        if dispatch_backend() == "tpu" and num_groups <= 4096 and n >= 4096 and n % ch == 0:
            g32 = gid.astype(jnp.int32)
            m32 = jnp.where(mask, 1.0, 0.0).astype(jnp.float32)
            c = n // ch
            if c == 1:
                ohg = jax.nn.one_hot(g32, num_groups, dtype=jnp.float32) * m32[:, None]
                ohb = jax.nn.one_hot(bins, self.width, dtype=jnp.float32)
                return hist + (ohg.T @ ohb).astype(hist.dtype)

            def body(carry, xs):
                gg, bb, mm = xs
                ohg = jax.nn.one_hot(gg, num_groups, dtype=jnp.float32) * mm[:, None]
                ohb = jax.nn.one_hot(bb, self.width, dtype=jnp.float32)
                return carry + (ohg.T @ ohb).astype(carry.dtype), None

            add, _ = jax.lax.scan(
                body,
                jnp.zeros((num_groups, self.width), hist.dtype),
                (g32.reshape(c, ch), bins.reshape(c, ch), m32.reshape(c, ch)),
            )
            return hist + add
        flat_idx = gid.astype(jnp.int32) * self.width + bins
        ones = jnp.where(mask, 1.0, 0.0).astype(hist.dtype)
        add = jax.ops.segment_sum(ones, flat_idx, num_segments=num_groups * self.width)
        return hist + add.reshape(num_groups, self.width)

    def init(self, num_groups: int, dtype=jnp.float32) -> jax.Array:
        return jnp.zeros((num_groups, self.width), dtype=dtype)

    # merge == elementwise add (psum-compatible); no method needed.

    def bin_value(self, idx: np.ndarray) -> np.ndarray:
        """Representative value of a bin (host): geometric mean of bin bounds."""
        i = np.asarray(idx, dtype=np.float64) - 1.0
        val = np.power(self.gamma, i - 0.5)
        return np.where(np.asarray(idx) <= 0, 0.0, val)

    def quantile(self, hist: np.ndarray, qs: list[float]) -> np.ndarray:
        """Host-side finalize: quantiles per group. hist: [G, width] → [G, len(qs)]."""
        h = np.asarray(hist, dtype=np.float64)
        totals = h.sum(axis=-1, keepdims=True)
        cum = np.cumsum(h, axis=-1)
        out = np.empty((h.shape[0], len(qs)), dtype=np.float64)
        for j, q in enumerate(qs):
            target = np.clip(q, 0.0, 1.0) * totals[:, 0]
            # Per-row searchsorted: first bin where cum >= target.
            idx = (cum < target[:, None]).sum(axis=-1)
            idx = np.minimum(idx, h.shape[1] - 1)
            out[:, j] = self.bin_value(idx)
        out[totals[:, 0] == 0] = np.nan
        return out

"""Mergeable quantile sketch as a dense tensor.

Replaces the reference's t-digest UDA (src/carnot/funcs/builtins/math_sketches.h:34-49)
whose pointer-based centroid structure cannot live on a TPU. We use a DDSketch-style
log-bucketed histogram: fixed relative accuracy, fixed memory, and — crucially —
merge is elementwise addition, so distributed merge of per-device partial sketches
is a single `psum` over the mesh axis.

Layout per group: float32[NBINS + 2] — bin 0 counts values <= 0 ("zero bin"),
bins 1..NBINS count positive values by ceil(log_gamma(v)); the last bin absorbs
overflow. With gamma = 1.0404 and 512 bins the dynamic range is ~6.6e8 at ~2%
relative error, which covers latency-in-ns style telemetry after scaling.

Update formulations (the FLOP bulk of a quantile query is this histogram
scatter — rows × groups × width on the old full-width one-hot GEMM):

  * LIMB-FACTORED GEMM (TPU, low group count): the bin index factors into
    two limbs, ``bin = digit * 257 + lane`` — the lane stays one-hot and the
    digit rides the GEMM *value* as a base-4096 digit (the same trick
    ops/groupby.py uses to sum int64 via 8-bit f32 limbs).  One narrow
    [G,CH]@[CH,257] GEMM then unpacks into the two histogram halves with an
    exact divmod — HALF the MXU FLOPs of the 514-wide one-hot at bit-equal
    counts.  Exactness: per-chunk per-cell counts ≤ CHUNK (2048) occupy the
    low digit, 4096·count the high one; their sum stays < 2^23, exact in
    f32 MXU accumulation; 1.0 and 4096.0 are exact in bf16, so bf16
    operands with f32 accumulation stay exact at 2x the f32 MXU rate.
  * SORTED SEGMENT-COUNT (high group count, mirrors the agg's sorted
    fallback): sort the flat (gid, bin) key — values only, no payload — and
    diff a searchsorted over the static G·W cell edges.  Model cost is
    O(n log n) comparisons with NO group factor, vs rows × G × 257 GEMM
    MACs: the win grows linearly in G.  The crossover is picked by
    measurement (`measure_update_crossover`), default from the measured
    CPU crossover (sorted ties segment_sum at G=128, wins 2.3x at
    G=1024), override via PX_SKETCH_SORT_MIN_GROUPS.
  * segment_sum (CPU, low group count): XLA-CPU native scatter, unchanged.

All formulations produce identical histograms (tests/test_sketch_kernels.py
asserts bit-equality), and every one is an elementwise ADD into the state,
so the distributed merge stays a single psum by construction.
"""
from __future__ import annotations

import dataclasses
import math

import jax
import jax.numpy as jnp
import numpy as np

from pixie_tpu import flags

#: Sorted segment-count takes over from the dense formulations at this many
#: groups.  Measured on XLA-CPU (8M rows): sorted 1.27s vs segment_sum 1.31s
#: at G=128 (tie), 1.03s vs 2.40s at G=1024 (2.3x) — the sort has no group
#: factor, so the gap only widens.  Re-measure on new hardware with
#: `measure_update_crossover()`; override with this env flag.
SORT_MIN_GROUPS = flags.define_int(
    "PX_SKETCH_SORT_MIN_GROUPS", 0,
    "group count at which the sketch update switches from the dense "
    "(GEMM/segment_sum) formulation to the sorted segment-count kernel; "
    "0 = measured per-backend default (512 on CPU, 4097 on TPU)")


def _sort_min_groups(backend: str) -> int:
    """Effective sorted-kernel crossover for `backend` — the flag when set,
    else the measured default: 512 on CPU (sorted ties segment_sum at G=128
    and wins 2.3x at G=1024), 4097 on TPU (the narrow GEMM is MXU-bound and
    beats the bitonic sort up to its 4096-group cap; beyond the cap the old
    code fell back to the serialized scatter, which the sort replaces)."""
    v = flags.get("PX_SKETCH_SORT_MIN_GROUPS")
    if v > 0:
        return v
    from pixie_tpu.engine import autotune as _autotune

    if _autotune.enabled():
        # kernel-choice model: measure_update_crossover feeds both kernels'
        # measured costs per group count into the model; once it has a
        # fitted crossover for this backend the hand-measured default
        # retires.  Model-only (no per-query probe): the dispatch is baked
        # into compiled programs at trace time.
        fitted = _autotune.MODEL.sketch_threshold(backend)
        if fitted is not None:
            return fitted
    return 4097 if backend == "tpu" else 512


@dataclasses.dataclass(frozen=True)
class LogHistogram:
    nbins: int = 512
    gamma: float = 1.0404
    #: values below this are counted in the zero bin.
    min_value: float = 1e-9

    @property
    def width(self) -> int:
        return self.nbins + 2

    def _log_gamma(self):
        return math.log(self.gamma)

    def bin_index(self, v: jax.Array) -> jax.Array:
        """Bin index per value (device)."""
        lg = jnp.log(jnp.maximum(v.astype(jnp.float32), self.min_value)) / self._log_gamma()
        idx = jnp.ceil(lg).astype(jnp.int32) + 1  # +1: bin 0 is the zero bin
        idx = jnp.where(v <= self.min_value, 0, idx)
        return jnp.clip(idx, 0, self.width - 1)

    #: GEMM lanes: the 514 bins fold into width/2 lanes × 2 digits.
    LANES = 257
    #: base of the packed digit — per-chunk counts must stay below it so the
    #: two digits never carry into each other (CHUNK < DIGIT ⇒ exact).
    DIGIT = 4096
    #: rows per chunk for the limb-factored GEMM path.  Must be < DIGIT for
    #: exact digit separation; 2048 keeps the one-hot buffer small
    #: (chunk × 257 bf16) while the MXU contraction stays deep enough.
    CHUNK = 2048

    def update(
        self,
        hist: jax.Array,  # [num_groups, width]
        gid: jax.Array,
        values: jax.Array,
        mask: jax.Array,
        num_groups: int,
    ) -> jax.Array:
        """Add values into per-group histograms.

        Formulation dispatch (see module docstring): limb-factored GEMM on
        TPU at low group counts, sorted segment-count above the measured
        crossover, segment_sum otherwise.  All paths bit-equal.
        """
        n = gid.shape[0]
        bins = self.bin_index(values)
        from pixie_tpu.ops.groupby import dispatch_backend

        backend = dispatch_backend()
        if (num_groups >= _sort_min_groups(backend) and n >= (1 << 14)
                and num_groups * self.width <= 4 * n):
            # the cell-edge diff costs O(G·W): only worth it while the cell
            # space stays comparable to the row count
            return self._update_sorted(hist, gid, bins, mask, num_groups)
        if backend == "tpu" and num_groups <= 4096 and n >= 4096:
            return self._update_gemm(hist, gid, bins, mask, num_groups)
        return self._update_segment(hist, gid, bins, mask, num_groups)

    def _update_segment(self, hist, gid, bins, mask, num_groups):
        """Flat scatter-add (XLA-CPU native path)."""
        flat_idx = gid.astype(jnp.int32) * self.width + bins
        ones = jnp.where(mask, 1.0, 0.0).astype(hist.dtype)
        add = jax.ops.segment_sum(ones, flat_idx, num_segments=num_groups * self.width)
        return hist + add.reshape(num_groups, self.width)

    def _update_sorted(self, hist, gid, bins, mask, num_groups):
        """Sorted segment-count: values-only sort of the flat cell key, then
        per-cell counts from a searchsorted diff over the STATIC cell edges.

        No payload rides the sort and no G-wide one-hot is built, so the
        model cost is O(n log n) with no group factor — the high-group-count
        regime where the GEMM's rows × G × LANES term explodes.  Counts are
        computed as exact integers before the single f32 add into the state
        (the scatter formulations round progressively; this path can only be
        more exact, and is bit-equal at any count below 2^24).
        """
        ncell = num_groups * self.width
        flat = gid.astype(jnp.int32) * self.width + bins
        # masked rows get the one-past-the-end cell: they sort after every
        # real cell edge and fall out of the diff
        flat = jnp.where(mask, flat, ncell)
        s = jnp.sort(flat)
        edges = jnp.arange(ncell + 1, dtype=jnp.int32)
        bounds = jnp.searchsorted(s, edges, side="left")
        cnt = (bounds[1:] - bounds[:-1]).astype(hist.dtype)
        return hist + cnt.reshape(num_groups, self.width)

    def _update_gemm(self, hist, gid, bins, mask, num_groups):
        """Limb-factored one-hot GEMM (TPU): bin = digit·LANES + lane; the
        lane is one-hot, the digit is the VALUE (1 or DIGIT) — one narrow
        [G,CH]@[CH,LANES] MXU GEMM per chunk, then an exact divmod unpack
        into the histogram halves.  Half the MXU FLOPs of the full-width
        one-hot (LANES = width/2) at bit-equal counts."""
        n = gid.shape[0]
        ch = min(n, self.CHUNK)
        if n % ch:
            # pad to a whole number of chunks with masked-out rows — zero
            # contributions, so exactness and bit-equality are unaffected
            pad = ch - n % ch
            gid = jnp.concatenate([gid, jnp.zeros(pad, gid.dtype)])
            bins = jnp.concatenate([bins, jnp.zeros(pad, bins.dtype)])
            mask = jnp.concatenate([mask, jnp.zeros(pad, mask.dtype)])
            n += pad
        g32 = gid.astype(jnp.int32)
        c = n // ch
        digit = jnp.float32(self.DIGIT)

        def gemm(gg, bb, mm):
            # group side: exact {0,1} bf16 one-hot, masked
            ohg = jax.nn.one_hot(gg, num_groups,
                                 dtype=jnp.bfloat16) * mm[:, None]
            lane = bb % self.LANES
            hi = (bb // self.LANES).astype(jnp.bfloat16)
            # lane side: one-hot scaled by the digit base when the bin sits
            # in the upper half — 1.0 and 4096.0 are both exact in bf16
            val = jnp.float32(1.0) + hi.astype(jnp.float32) * (digit - 1.0)
            ohb = jax.nn.one_hot(lane, self.LANES,
                                 dtype=jnp.bfloat16) * val.astype(
                                     jnp.bfloat16)[:, None]
            packed = jax.lax.dot_general(
                ohg, ohb, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)  # [G, LANES]
            # exact unpack: packed = c_lo + DIGIT * c_hi with
            # c_lo, c_hi <= CHUNK < DIGIT and packed < 2^23
            c_hi = jnp.floor(packed / digit)
            c_lo = packed - c_hi * digit
            return jnp.concatenate([c_lo, c_hi], axis=1)[:, :self.width]

        mb = jnp.where(mask, 1.0, 0.0).astype(jnp.bfloat16)
        if c == 1:
            return hist + gemm(g32, bins, mb).astype(hist.dtype)

        def body(carry, xs):
            gg, bb, mm = xs
            return carry + gemm(gg, bb, mm).astype(carry.dtype), None

        add, _ = jax.lax.scan(
            body,
            jnp.zeros((num_groups, self.width), hist.dtype),
            (g32.reshape(c, ch), bins.reshape(c, ch), mb.reshape(c, ch)),
        )
        return hist + add

    def init(self, num_groups: int, dtype=jnp.float32) -> jax.Array:
        return jnp.zeros((num_groups, self.width), dtype=dtype)

    # merge == elementwise add (psum-compatible); no method needed.

    def bin_value(self, idx: np.ndarray) -> np.ndarray:
        """Representative value of a bin (host): geometric mean of bin bounds."""
        i = np.asarray(idx, dtype=np.float64) - 1.0
        val = np.power(self.gamma, i - 0.5)
        return np.where(np.asarray(idx) <= 0, 0.0, val)

    def quantile(self, hist: np.ndarray, qs: list[float]) -> np.ndarray:
        """Host-side finalize: quantiles per group. hist: [G, width] → [G, len(qs)]."""
        h = np.asarray(hist, dtype=np.float64)
        totals = h.sum(axis=-1, keepdims=True)
        cum = np.cumsum(h, axis=-1)
        out = np.empty((h.shape[0], len(qs)), dtype=np.float64)
        for j, q in enumerate(qs):
            target = np.clip(q, 0.0, 1.0) * totals[:, 0]
            # Per-row searchsorted: first bin where cum >= target.
            idx = (cum < target[:, None]).sum(axis=-1)
            idx = np.minimum(idx, h.shape[1] - 1)
            out[:, j] = self.bin_value(idx)
        out[totals[:, 0] == 0] = np.nan
        return out

    def quantile_device(self, hist: jax.Array, qs: list[float]) -> jax.Array:
        """DEVICE finalize (same rank rule as `quantile`): [G, width] →
        [G, len(qs)] f64.  Rationale: the histogram is the big part of an
        agg's state ([G, 514] f32 — ~2 MB at G≈1024, per sketch, per feed);
        pulling it over a tunneled runtime costs ~40 ms/MB while pulling
        the [G, nq] RESULT is a single cheap wave, so finalize belongs
        device-side.
        """
        # f32 for the [G, width] cumsum/compare (TPU f64 is software-emulated
        # and a f64 cumsum becomes a serialized scan — measured ~4x
        # whole-query regression).  The final power runs in f64 over the
        # tiny [G, nq] result, matching the host finalize (`quantile`)
        # exactly while group counts stay below 2^24 (above that, f32
        # cum/target rounding near a rank boundary can pick the adjacent
        # bin — a sub-bucket-width deviation).
        h = hist.astype(jnp.float32)
        totals = h.sum(axis=-1, keepdims=True)
        cum = jnp.cumsum(h, axis=-1)
        qv = jnp.asarray(qs, dtype=jnp.float32)
        target = jnp.clip(qv, 0.0, 1.0)[None, :] * totals  # [G, nq]
        idx = (cum[:, None, :] < target[:, :, None]).sum(axis=-1)
        idx = jnp.minimum(idx, h.shape[-1] - 1)
        val = jnp.power(jnp.float64(self.gamma),
                        idx.astype(jnp.float64) - 1.5)
        out = jnp.where(idx <= 0, 0.0, val)
        return jnp.where(totals > 0, out, jnp.nan)


def measure_update_crossover(n: int = 1 << 21, groups=(128, 256, 512, 1024),
                             repeats: int = 3) -> dict:
    """Measure the dense-vs-sorted sketch-update crossover ON THIS BACKEND.

    Times the dense formulation (GEMM on TPU dispatch, segment_sum on CPU)
    against the sorted segment-count kernel at each group count and returns
    {"backend", "points": {G: {"dense_ms", "sorted_ms"}}, "crossover":
    smallest measured G where sorted wins}.  The default
    PX_SKETCH_SORT_MIN_GROUPS was picked from exactly this measurement;
    re-run on new hardware and override the flag if the crossover moved.
    """
    import time

    from pixie_tpu.ops.groupby import dispatch_backend

    lh = LogHistogram()
    rng = np.random.default_rng(7)
    gidh = rng.integers(0, max(groups), n)
    vals = jax.device_put(rng.exponential(50.0, n))
    mask = jax.device_put(np.ones(n, dtype=bool))
    backend = dispatch_backend()
    bins = lh.bin_index(vals)
    points = {}
    crossover = None
    for g in sorted(groups):
        gid = jax.device_put((gidh % g).astype(np.int32))
        hist = lh.init(g)
        if backend == "tpu":
            dense = jax.jit(lambda h, i, b, m: lh._update_gemm(h, i, b, m, g))
        else:
            dense = jax.jit(lambda h, i, b, m: lh._update_segment(h, i, b, m, g))
        srt = jax.jit(lambda h, i, b, m: lh._update_sorted(h, i, b, m, g))
        out = {}
        for name, fn in (("dense_ms", dense), ("sorted_ms", srt)):
            jax.block_until_ready(fn(hist, gid, bins, mask))  # compile
            ts = []
            for _ in range(repeats):
                t0 = time.perf_counter()
                jax.block_until_ready(fn(hist, gid, bins, mask))
                ts.append(time.perf_counter() - t0)
            out[name] = round(sorted(ts)[len(ts) // 2] * 1000, 1)
        points[g] = out
        if crossover is None and out["sorted_ms"] < out["dense_ms"]:
            crossover = g
        from pixie_tpu.engine import autotune as _autotune

        if _autotune.enabled():
            # each measured point feeds the kernel-choice model: once every
            # probed group count is warm, _sort_min_groups serves the
            # fitted crossover instead of the hand-measured default
            for _ in range(int(flags.get("PX_AUTOTUNE_MIN_SAMPLES"))):
                _autotune.MODEL.observe_sketch(
                    backend, g, out["dense_ms"], out["sorted_ms"])
    return {"backend": backend, "rows": n, "points": points,
            "crossover": crossover}

"""Mergeable quantile sketch as a dense tensor.

Replaces the reference's t-digest UDA (src/carnot/funcs/builtins/math_sketches.h:34-49)
whose pointer-based centroid structure cannot live on a TPU. We use a DDSketch-style
log-bucketed histogram: fixed relative accuracy, fixed memory, and — crucially —
merge is elementwise addition, so distributed merge of per-device partial sketches
is a single `psum` over the mesh axis.

Layout per group: float32[NBINS + 2] — bin 0 counts values <= 0 ("zero bin"),
bins 1..NBINS count positive values by ceil(log_gamma(v)); the last bin absorbs
overflow. With gamma = 1.0404 and 512 bins the dynamic range is ~6.6e8 at ~2%
relative error, which covers latency-in-ns style telemetry after scaling.
(512 bins, not 1024 @ gamma 1.02: the per-row one-hot GEMM that updates the
histogram costs rows x groups x BINS MXU FLOPs — it dominates quantile-query
device time at 64M rows, and halving the bins halves it for one accuracy
notch, measured 1028->514 bins = -32% whole-GEMM wall on v5e.)
"""
from __future__ import annotations

import dataclasses
import math

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class LogHistogram:
    nbins: int = 512
    gamma: float = 1.0404
    #: values below this are counted in the zero bin.
    min_value: float = 1e-9

    @property
    def width(self) -> int:
        return self.nbins + 2

    def _log_gamma(self):
        return math.log(self.gamma)

    def bin_index(self, v: jax.Array) -> jax.Array:
        """Bin index per value (device)."""
        lg = jnp.log(jnp.maximum(v.astype(jnp.float32), self.min_value)) / self._log_gamma()
        idx = jnp.ceil(lg).astype(jnp.int32) + 1  # +1: bin 0 is the zero bin
        idx = jnp.where(v <= self.min_value, 0, idx)
        return jnp.clip(idx, 0, self.width - 1)

    #: rows per chunk for the matmul path (one-hot bin buffer = chunk × width × 4B)
    CHUNK = 1 << 13

    def update(
        self,
        hist: jax.Array,  # [num_groups, width]
        gid: jax.Array,
        values: jax.Array,
        mask: jax.Array,
        num_groups: int,
    ) -> jax.Array:
        """Add values into per-group histograms.

        TPU path: hist += one_hot(gid).T @ one_hot(bin) per chunk — a pure MXU
        GEMM [G,CH]@[CH,B] instead of a flat scatter-add (scatters serialize on
        TPU; measured ~5x slower than the double-one-hot matmul at 16M rows).
        """
        n = gid.shape[0]
        bins = self.bin_index(values)
        ch = min(n, self.CHUNK)
        from pixie_tpu.ops.groupby import dispatch_backend

        if dispatch_backend() == "tpu" and num_groups <= 4096 and n >= 4096 and n % ch == 0:
            # bf16 one-hot operands with f32 MXU accumulation: the inputs
            # are exact {0,1} in bf16 and the products accumulate in f32,
            # so counts stay exact while the GEMM runs at 2x the f32 rate —
            # this GEMM is the FLOP bulk of a quantile query (rows x G x
            # bins), measured MXU-bound at 64M rows.
            g32 = gid.astype(jnp.int32)
            mb = jnp.where(mask, 1.0, 0.0).astype(jnp.bfloat16)
            c = n // ch

            def gemm(gg, bb, mm):
                ohg = jax.nn.one_hot(gg, num_groups,
                                     dtype=jnp.bfloat16) * mm[:, None]
                ohb = jax.nn.one_hot(bb, self.width, dtype=jnp.bfloat16)
                return jax.lax.dot_general(
                    ohg, ohb, (((0,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)

            if c == 1:
                return hist + gemm(g32, bins, mb).astype(hist.dtype)

            def body(carry, xs):
                gg, bb, mm = xs
                return carry + gemm(gg, bb, mm).astype(carry.dtype), None

            add, _ = jax.lax.scan(
                body,
                jnp.zeros((num_groups, self.width), hist.dtype),
                (g32.reshape(c, ch), bins.reshape(c, ch), mb.reshape(c, ch)),
            )
            return hist + add
        flat_idx = gid.astype(jnp.int32) * self.width + bins
        ones = jnp.where(mask, 1.0, 0.0).astype(hist.dtype)
        add = jax.ops.segment_sum(ones, flat_idx, num_segments=num_groups * self.width)
        return hist + add.reshape(num_groups, self.width)

    def init(self, num_groups: int, dtype=jnp.float32) -> jax.Array:
        return jnp.zeros((num_groups, self.width), dtype=dtype)

    # merge == elementwise add (psum-compatible); no method needed.

    def bin_value(self, idx: np.ndarray) -> np.ndarray:
        """Representative value of a bin (host): geometric mean of bin bounds."""
        i = np.asarray(idx, dtype=np.float64) - 1.0
        val = np.power(self.gamma, i - 0.5)
        return np.where(np.asarray(idx) <= 0, 0.0, val)

    def quantile(self, hist: np.ndarray, qs: list[float]) -> np.ndarray:
        """Host-side finalize: quantiles per group. hist: [G, width] → [G, len(qs)]."""
        h = np.asarray(hist, dtype=np.float64)
        totals = h.sum(axis=-1, keepdims=True)
        cum = np.cumsum(h, axis=-1)
        out = np.empty((h.shape[0], len(qs)), dtype=np.float64)
        for j, q in enumerate(qs):
            target = np.clip(q, 0.0, 1.0) * totals[:, 0]
            # Per-row searchsorted: first bin where cum >= target.
            idx = (cum < target[:, None]).sum(axis=-1)
            idx = np.minimum(idx, h.shape[1] - 1)
            out[:, j] = self.bin_value(idx)
        out[totals[:, 0] == 0] = np.nan
        return out

    def quantile_device(self, hist: jax.Array, qs: list[float]) -> jax.Array:
        """DEVICE finalize (same rank rule as `quantile`): [G, width] →
        [G, len(qs)] f64.  Rationale: the histogram is the big part of an
        agg's state ([G, 514] f32 — ~2 MB at G≈1024, per sketch, per feed);
        pulling it over a tunneled runtime costs ~40 ms/MB while pulling
        the [G, nq] RESULT is a single cheap wave, so finalize belongs
        device-side.
        """
        # f32 for the [G, width] cumsum/compare (TPU f64 is software-emulated
        # and a f64 cumsum becomes a serialized scan — measured ~4x
        # whole-query regression).  The final power runs in f64 over the
        # tiny [G, nq] result, matching the host finalize (`quantile`)
        # exactly while group counts stay below 2^24 (above that, f32
        # cum/target rounding near a rank boundary can pick the adjacent
        # bin — a sub-bucket-width deviation).
        h = hist.astype(jnp.float32)
        totals = h.sum(axis=-1, keepdims=True)
        cum = jnp.cumsum(h, axis=-1)
        qv = jnp.asarray(qs, dtype=jnp.float32)
        target = jnp.clip(qv, 0.0, 1.0)[None, :] * totals  # [G, nq]
        idx = (cum[:, None, :] < target[:, :, None]).sum(axis=-1)
        idx = jnp.minimum(idx, h.shape[-1] - 1)
        val = jnp.power(jnp.float64(self.gamma),
                        idx.astype(jnp.float64) - 1.5)
        out = jnp.where(idx <= 0, 0.0, val)
        return jnp.where(totals > 0, out, jnp.nan)

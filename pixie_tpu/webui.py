"""Live web view: script editor + widget grid served over HTTP.

Reference: the Live View (src/ui/src/containers/live/) — per-script vis.json
drives a widget grid (tables, timeseries, bars, flamegraphs, graphs), script
source is editable and re-runnable in place, and entity names deep-link to
drill-down scripts (script_reference semantics).  The reference is a 66K-LoC
React app; this is the same user loop on the stdlib HTTP server with
server-rendered widgets (inline SVG), which keeps the framework dependency-
free and testable end-to-end.

Serving modes: a local TableStore (demo / single agent) or any callable with
the broker-runner signature — the CLI exposes `pixie ui`.
"""
from __future__ import annotations

import html
import json
import pathlib
import secrets
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional

from pixie_tpu.types import SemanticType as ST

from pixie_tpu.scripts import default_bundle

DEFAULT_SCRIPTS = default_bundle()

#: entity semantic types → drill-down script + arg name (the reference's
#: script_reference deep links, px/http_data/data.pxl add_source_dest_links)
_ENTITY_LINKS = {
    ST.ST_POD_NAME: ("pod", "pod"),
    ST.ST_SERVICE_NAME: ("service", "service"),
    ST.ST_NAMESPACE_NAME: ("namespace", "namespace"),
    ST.ST_NODE_NAME: ("node", "node"),
    ST.ST_IP_ADDRESS: ("ip", "ip"),
}

_PAGE = """<!doctype html>
<html><head><meta charset="utf-8"><title>{title} — pixie-tpu live</title>
<style>
body {{ font: 13px/1.45 system-ui, sans-serif; margin: 0; background: #101418; color: #e4e8ec; }}
header {{ padding: 10px 16px; background: #161c22; border-bottom: 1px solid #2a333c; }}
header a {{ color: #6cb6ff; text-decoration: none; margin-right: 14px; }}
main {{ padding: 14px 16px; }}
.vars label {{ margin-right: 14px; font-size: 12px; color: #9aa6b2; }}
.vars input {{ background: #0c1014; color: #e4e8ec; border: 1px solid #2a333c; padding: 3px 6px; border-radius: 3px; }}
button {{ background: #2563eb; color: #fff; border: 0; padding: 6px 16px; border-radius: 4px; cursor: pointer; }}
textarea {{ width: 100%; min-height: 180px; background: #0c1014; color: #d3e0ea; border: 1px solid #2a333c; font: 12px/1.4 ui-monospace, monospace; padding: 8px; box-sizing: border-box; }}
.grid {{ display: grid; grid-template-columns: repeat(auto-fit, minmax(430px, 1fr)); gap: 14px; margin-top: 14px; }}
.widget {{ background: #161c22; border: 1px solid #2a333c; border-radius: 6px; padding: 10px 12px; overflow: auto; }}
.widget h3 {{ margin: 0 0 8px; font-size: 13px; color: #9aa6b2; font-weight: 600; }}
table {{ border-collapse: collapse; font-size: 12px; width: 100%; }}
th, td {{ text-align: left; padding: 3px 8px; border-bottom: 1px solid #222a33; white-space: nowrap; }}
th {{ color: #9aa6b2; position: sticky; top: 0; background: #161c22; }}
td a {{ color: #6cb6ff; text-decoration: none; }}
.flame div {{ font: 10px/1.6 ui-monospace, monospace; white-space: nowrap; overflow: hidden; border-radius: 2px; margin-top: 1px; padding: 0 3px; color: #10141a; }}
.err {{ color: #ff7a7a; white-space: pre-wrap; }}
#status {{ color: #9aa6b2; font-size: 12px; margin-left: 10px; }}
svg text {{ fill: #9aa6b2; font-size: 10px; }}
</style></head>
<body>
<header><a href="/">pixie-tpu live</a><b>{title}</b></header>
<main>
<form class="vars" id="vars" onsubmit="run(); return false;">{var_inputs}
<button type="submit">Run</button>
<label>auto-refresh s <input id="refresh" value="{refresh_s}" size="3"
 onchange="schedule()"></label><span id="status"></span></form>
<details style="margin-top:10px"><summary style="cursor:pointer;color:#9aa6b2">script source (edit &amp; re-run)</summary>
<textarea id="source">{source}</textarea></details>
<div class="grid" id="grid"></div>
</main>
<script>
let gen = 0;        // drop out-of-order responses: only the newest renders
let inflight = false;
async function run() {{
  if (inflight) return;  // a slow query must not pile up overlapping runs
  inflight = true;
  const my = ++gen;
  const st = document.getElementById('status');
  st.textContent = 'running…';
  const vars = {{}};
  for (const el of document.querySelectorAll('.vars input')) vars[el.name] = el.value;
  const body = {{script: {script_json}, vars, source: document.getElementById('source').value}};
  const t0 = performance.now();
  try {{
    const r = await fetch('/api/run', {{method: 'POST',
      headers: {{'X-Pixie-Session': {session_token}}},
      body: JSON.stringify(body)}});
    const data = await r.json();
    if (my !== gen) return;  // a newer run superseded this response
    const grid = document.getElementById('grid');
    grid.innerHTML = '';
    if (data.error) {{ grid.innerHTML = '<div class="widget err">' + data.error + '</div>'; }}
    for (const w of (data.widgets || [])) {{
      const d = document.createElement('div');
      d.className = 'widget';
      d.innerHTML = '<h3>' + w.name + '</h3>' + w.html;
      grid.appendChild(d);
    }}
    st.textContent = ((performance.now() - t0) | 0) + ' ms';
  }} catch (e) {{ if (my === gen) st.textContent = 'error: ' + e; }}
  finally {{ inflight = false; }}
}}
let timer = null;
function schedule() {{
  // Dashboard poll loop: re-run the same script every N seconds.  Repeated
  // runs hit the engine's standing materialized views, so each poll costs
  // O(rows since last poll) server-side instead of a full rescan.  Ticks
  // landing while a run is in flight are skipped (run()'s inflight guard),
  // so a slow script degrades to back-to-back runs, never a pile-up.
  if (timer !== null) {{ clearInterval(timer); timer = null; }}
  const s = parseFloat(document.getElementById('refresh').value);
  if (s > 0) timer = setInterval(run, s * 1000);
}}
run();
schedule();
</script>
</body></html>"""

_INDEX = """<!doctype html>
<html><head><meta charset="utf-8"><title>pixie-tpu live</title>
<style>body { font: 14px system-ui; margin: 24px; background: #101418; color: #e4e8ec; }
a { color: #6cb6ff; text-decoration: none; display: inline-block; width: 240px; padding: 3px 0; }</style>
</head><body><h2>pixie-tpu live — scripts</h2>
<p><a href="/profiles">query profiles (flight recorder)</a></p>
%s</body></html>"""

#: the query-profile panels (GET /profiles): the flight recorder's and the
#: storage observatory's own tables rendered server-side, all read from
#: self_telemetry.* through the normal query path (pixie_tpu.observe,
#: pixie_tpu.table.heat).  Panels are (title, pxl-body) pairs NUMBERED AT
#: RENDER TIME — appending a pane never renumbers or retouches the others.
#: Each body ends in px.display(<unique var>, '{title}').
_PROFILE_PANELS: list = [
    ("recent query profiles", """\
df = px.DataFrame(table='self_telemetry.query_profiles')
df = df[['time_', 'query_id', 'tenant', 'service', 'status', 'wall_ns',
         'exec_ns', 'rows_scanned', 'plan_cache_hit', 'matview_hits',
         'matview_stale', 'batch_size', 'hedged', 'evictions']]
df = df.head(50)
px.display(df, '{title}')"""),
    ("per-tenant latency", """\
lat = px.DataFrame(table='self_telemetry.query_profiles')
lat = lat.groupby(['tenant', 'status']).agg(
    queries=('wall_ns', px.count),
    latency_p50=('wall_ns', px.p50),
    latency_p99=('wall_ns', px.p99),
)
px.display(lat, '{title}')"""),
    ("slo alert edges", """\
al = px.DataFrame(table='self_telemetry.alerts')
al = al.groupby(['slo', 'tenant', 'window', 'state']).agg(
    edges=('burn_rate', px.count),
    max_burn=('burn_rate', px.max),
)
px.display(al, '{title}')"""),
    ("autoscaler decisions", """\
sc = px.DataFrame(table='self_telemetry.scale_events')
sc = sc[['time_', 'action', 'agent', 'reason', 'pressure', 'agents']]
sc = sc.head(30)
px.display(sc, '{title}')"""),
    ("shard heat by tier", """\
hh = px.DataFrame(table='self_telemetry.shard_heat')
hh = hh.groupby(['table_name', 'shard', 'tier']).agg(
    heat=('heat', px.max),
    rows_scanned=('rows_scanned', px.max),
    skew=('skew', px.max),
)
px.display(hh, '{title}')"""),
    ("storage state", """\
st = px.DataFrame(table='self_telemetry.storage_state')
st = st.groupby(['agent', 'table_name']).agg(
    hot_rows=('hot_rows', px.max),
    sealed_batches=('sealed_batches', px.max),
    sealed_bytes=('sealed_bytes', px.max),
    cold_bytes=('cold_bytes', px.max),
    cold_segments=('cold_segments', px.max),
    journal_bytes=('journal_bytes', px.max),
    repl_lag=('repl_lag_batches', px.max),
)
px.display(st, '{title}')"""),
    ("adaptive gate decisions", """\
at = px.DataFrame(table='self_telemetry.autotune')
at = at.groupby(['gate', 'plan_class', 'size_bucket', 'arm', 'source']).agg(
    decisions=('observed_ms', px.count),
    observed_p50=('observed_ms', px.p50),
)
px.display(at, '{title}')"""),
]

_PROFILES_SCRIPT = "\n".join(
    body.format(title=f"{i} {title}")
    for i, (title, body) in enumerate(_PROFILE_PANELS, 1))

_PROFILES_PAGE = """<!doctype html>
<html><head><meta charset="utf-8"><title>query profiles — pixie-tpu</title>
<style>body { font: 14px system-ui; margin: 24px; background: #101418; color: #e4e8ec; }
table { border-collapse: collapse; margin: 8px 0 20px; }
td, th { border: 1px solid #2a3038; padding: 3px 8px; font: 12px ui-monospace, monospace; }
th { background: #1a2028; } a { color: #6cb6ff; }</style>
</head><body><h2>query profiles (flight recorder)</h2>
<p><a href="/">&larr; scripts</a></p>%s</body></html>"""


def _esc(v) -> str:
    return html.escape(str(v))


# -------------------------------------------------------- widget renderers
def _cell(val, fmt, link: Optional[tuple], extra_args: dict) -> str:
    s = _esc(fmt(val) if fmt else val)
    if link and val not in ("", "-", None):
        script, arg = link
        q = urllib.parse.urlencode({arg: val, **extra_args})
        return f'<a href="/script/{script}?{q}">{s}</a>'
    return s


def table_html(result, max_rows: int = 200, link_args: Optional[dict] = None
               ) -> str:
    from pixie_tpu.cli import _formatter

    names = result.relation.names()
    n = min(result.num_rows, max_rows)
    cols = {}
    fmts = {}
    links = {}
    for name in names:
        arr = result.columns[name][:n]
        d = result.dictionaries.get(name)
        cols[name] = d.decode(arr) if d is not None else arr.tolist()
        cs = result.relation.col(name)
        fmts[name] = _formatter(cs)
        links[name] = _ENTITY_LINKS.get(cs.semantic_type)
    head = "".join(f"<th>{_esc(c)}</th>" for c in names)
    rows = []
    for i in range(n):
        tds = "".join(
            f"<td>{_cell(cols[c][i], fmts[c], links[c], link_args or {})}</td>"
            for c in names
        )
        rows.append(f"<tr>{tds}</tr>")
    more = (f"<div style='color:#9aa6b2'>… {result.num_rows - n} more rows"
            f"</div>" if result.num_rows > n else "")
    return (f"<table><thead><tr>{head}</tr></thead>"
            f"<tbody>{''.join(rows)}</tbody></table>{more}")


_SERIES_COLORS = ["#6cb6ff", "#f6a343", "#51c995", "#e37fd2", "#a7a9fc",
                  "#ffd166"]


def timeseries_svg(result, display: dict, width: int = 420,
                   height: int = 150) -> str:
    """Inline-SVG line chart (reference TimeseriesChart widget)."""
    ts_specs = display.get("timeseries") or [{}]
    value_col = ts_specs[0].get("value")
    series_col = ts_specs[0].get("series")
    names = result.relation.names()
    time_col = "time_" if "time_" in names else names[0]
    if value_col is None:
        value_col = next(
            (c for c in names if c != time_col and c != series_col), None)
    if value_col is None or result.num_rows == 0:
        return "<div>(no data)</div>"
    t = [float(v) for v in result.columns[time_col]]
    y = [float(v) for v in result.columns[value_col]]
    groups: dict = {}
    if series_col and series_col in names:
        d = result.dictionaries.get(series_col)
        arr = result.columns[series_col]
        svals = d.decode(arr) if d is not None else [str(v) for v in arr]
        for tv, yv, sv in zip(t, y, svals):
            groups.setdefault(sv, []).append((tv, yv))
    else:
        groups[value_col] = list(zip(t, y))
    t0, t1 = min(t), max(t) or 1
    y0, y1 = min(y + [0.0]), max(y) or 1
    spant, spany = (t1 - t0) or 1, (y1 - y0) or 1
    polys = []
    legend = []
    for i, (name, pts) in enumerate(sorted(groups.items())[:6]):
        pts.sort()
        color = _SERIES_COLORS[i % len(_SERIES_COLORS)]
        path = " ".join(
            f"{(tv - t0) / spant * (width - 10) + 5:.1f},"
            f"{height - 18 - (yv - y0) / spany * (height - 30):.1f}"
            for tv, yv in pts
        )
        polys.append(f'<polyline fill="none" stroke="{color}" '
                     f'stroke-width="1.5" points="{path}"/>')
        legend.append(f'<tspan fill="{color}">● {_esc(name)}  </tspan>')
    return (f'<svg viewBox="0 0 {width} {height}" width="100%">'
            f"{''.join(polys)}"
            f'<text x="5" y="{height - 4}">{"".join(legend)}'
            f"</text></svg>")


def bars_svg(result, display: dict, width: int = 420) -> str:
    """Inline-SVG horizontal bar chart (reference BarChart widget)."""
    from pixie_tpu.cli import _formatter

    bar = display.get("bar", {})
    label_col = bar.get("label")
    value_col = bar.get("value")
    names = result.relation.names()
    if label_col is None:
        label_col = next((c for c in names
                          if c in result.dictionaries), names[0])
    if value_col is None:
        value_col = next((c for c in names if c != label_col), None)
    if value_col is None or result.num_rows == 0:
        return "<div>(no data)</div>"
    d = result.dictionaries.get(label_col)
    arr = result.columns[label_col]
    labels = d.decode(arr) if d is not None else [str(v) for v in arr]
    vals = [float(v) for v in result.columns[value_col]]
    pairs = sorted(zip(labels, vals), key=lambda kv: -kv[1])[:12]
    vmax = max((v for _l, v in pairs), default=1) or 1
    fmt = _formatter(result.relation.col(value_col)) or (lambda v: f"{v:g}")
    rows = []
    bh = 16
    for i, (label, v) in enumerate(pairs):
        w = max(v / vmax * (width - 180), 1)
        rows.append(
            f'<text x="0" y="{i * (bh + 4) + 12}">{_esc(label)[:22]}</text>'
            f'<rect x="150" y="{i * (bh + 4)}" width="{w:.1f}" height="{bh}"'
            f' fill="#6cb6ff"/>'
            f'<text x="{152 + w:.1f}" y="{i * (bh + 4) + 12}">'
            f"{_esc(fmt(v))}</text>"
        )
    h = len(pairs) * (bh + 4) + 4
    return f'<svg viewBox="0 0 {width} {h}" width="100%">{"".join(rows)}</svg>'


def flamegraph_html(result, display: dict, max_depth: int = 24) -> str:
    """Nested-div flamegraph (reference StackTraceFlameGraph widget)."""
    spec = display.get("stacktraceFlameGraph", display.get("flamegraph", {}))
    stack_col = spec.get("stacktraceColumn", "stack_trace")
    count_col = spec.get("countColumn", "count")
    names = result.relation.names()
    if stack_col not in names or count_col not in names:
        return table_html(result)
    d = result.dictionaries.get(stack_col)
    arr = result.columns[stack_col]
    stacks = d.decode(arr) if d is not None else [str(v) for v in arr]
    counts = [int(v) for v in result.columns[count_col]]
    root: dict = {"n": "all", "c": 0, "ch": {}}
    for s, c in zip(stacks, counts):
        root["c"] += c
        node = root
        for frame in s.split(";")[:max_depth]:
            node = node["ch"].setdefault(frame, {"n": frame, "c": 0, "ch": {}})
            node["c"] += c
    total = root["c"] or 1
    palette = ["#f6a343", "#e8863c", "#ffd166", "#f09d51"]
    out = []

    def walk(node, depth):
        if depth > max_depth:
            return
        kids = sorted(node["ch"].values(), key=lambda k: -k["c"])
        for k in kids:
            pct = k["c"] / total * 100
            if pct < 0.5:
                continue
            color = palette[depth % len(palette)]
            out.append(
                f'<div style="width:{pct:.1f}%;background:{color};'
                f'margin-left:{depth * 6}px" title="{_esc(k["n"])} '
                f'({k["c"]})">{_esc(k["n"])}</div>'
            )
            walk(k, depth + 1)

    walk(root, 0)
    return f'<div class="flame">{"".join(out)}</div>'


def render_widget_html(kind: str, display: dict, result,
                       link_args: Optional[dict] = None) -> str:
    if result.num_rows == 0:
        return "<div style='color:#9aa6b2'>(no rows)</div>"
    if kind == "TimeseriesChart":
        return timeseries_svg(result, display)
    if kind in ("BarChart", "HistogramChart"):
        return bars_svg(result, display)
    if kind == "StackTraceFlameGraph":
        return flamegraph_html(result, display)
    return table_html(result, link_args=link_args)


# --------------------------------------------------------------- the server
class LiveServer:
    """Serve the live view.

    runner(source, funcs) -> {sink_name: QueryResult} where funcs is
    [(prefix, func_name, args)] (fused execution) or None (module script).
    """

    def __init__(self, runner: Callable, scripts_dir=DEFAULT_SCRIPTS,
                 host: str = "127.0.0.1", port: int = 0,
                 refresh_s: float = 5.0):
        self.runner = runner
        self.scripts_dir = pathlib.Path(scripts_dir)
        #: default dashboard poll cadence (seconds; 0 disables). Each poll
        #: re-runs the same script, which the engine answers from standing
        #: matview state — the workload the subsystem exists for.
        self.refresh_s = refresh_s
        # Per-session token embedded in served pages; POST /api/run requires
        # it, so a drive-by cross-origin page (which cannot read our HTML)
        # cannot trigger script execution or tracepoint mutations.  The
        # reference UI sits behind cloud auth (src/ui auth flow).
        self.session_token = secrets.token_urlsafe(16)
        self._host = host
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _host_ok(self) -> bool:
                # DNS-rebinding defense: a page at evil.com rebound to
                # 127.0.0.1 reaches us with Host: evil.com — reject any
                # Host that is not our own address (localhost variants ok).
                # Only meaningful for loopback binds: rebinding targets the
                # attacker-unreachable localhost; an operator who binds a
                # routable address has exposed the service deliberately and
                # clients will present that address (or any of the host's
                # names) as Host.
                if outer._host not in ("127.0.0.1", "localhost", "::1"):
                    return True
                hdr = self.headers.get("Host", "")
                hostname = hdr.rsplit(":", 1)[0] if ":" in hdr else hdr
                return hostname in ("127.0.0.1", "localhost", "::1", "[::1]")

            def _send(self, body: str, ctype="text/html", code=200):
                data = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", f"{ctype}; charset=utf-8")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                if not self._host_ok():
                    return self._send("forbidden host", code=403)
                parsed = urllib.parse.urlparse(self.path)
                if parsed.path in ("", "/"):
                    return self._send(outer.index_page())
                if parsed.path == "/profiles":
                    return self._send(outer.profiles_page())
                if parsed.path.startswith("/script/"):
                    name = parsed.path[len("/script/"):]
                    qs = dict(urllib.parse.parse_qsl(parsed.query))
                    try:
                        return self._send(outer.script_page(name, qs))
                    except FileNotFoundError:
                        return self._send("not found", code=404)
                return self._send("not found", code=404)

            def do_POST(self):
                if not self._host_ok():
                    return self._send("forbidden host", code=403)
                if self.path != "/api/run":
                    return self._send("not found", code=404)
                token = self.headers.get("X-Pixie-Session", "")
                if not secrets.compare_digest(token, outer.session_token):
                    return self._send(
                        json.dumps({"error": "missing/invalid session token"}),
                        ctype="application/json", code=403)
                origin = self.headers.get("Origin")
                if origin:
                    ohost = urllib.parse.urlparse(origin).netloc
                    if ohost != self.headers.get("Host", ""):
                        return self._send(
                            json.dumps({"error": "cross-origin rejected"}),
                            ctype="application/json", code=403)
                ln = int(self.headers.get("Content-Length", 0))
                try:
                    req = json.loads(self.rfile.read(ln) or b"{}")
                    out = outer.run_api(req)
                except Exception as e:  # surface to the page, not the socket
                    out = {"error": f"{type(e).__name__}: {e}"}
                return self._send(json.dumps(out), ctype="application/json")

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self._httpd.server_port
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------- lifecycle
    def start(self) -> "LiveServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="pixie-webui")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()

    # ----------------------------------------------------------------- pages
    def _script_names(self) -> list[str]:
        from pixie_tpu.scripts import bundle_map

        return sorted(bundle_map(self.scripts_dir))

    def index_page(self) -> str:
        links = "".join(
            f'<a href="/script/{n}">{_esc(n)}</a>' for n in self._script_names()
        )
        return _INDEX % links

    def profiles_page(self) -> str:
        """Server-rendered query-profile panel over the flight recorder's
        self_telemetry tables (empty tables render as a note, not a 500 —
        a fresh deployment has no profiles yet)."""
        try:
            results, _ = self.runner(_PROFILES_SCRIPT, None)
            body = "".join(
                f"<h3>{_esc(name)}</h3>" + table_html(res, max_rows=50)
                for name, res in sorted(results.items()))
        except Exception as e:
            body = ("<p>no profiles yet — run a query with tracing on "
                    f"(PL_TRACING_ENABLED) first. ({_esc(type(e).__name__)}: "
                    f"{_esc(e)})</p>")
        return _PROFILES_PAGE % body

    def _load(self, name: str):
        # script names are single bundle-dir components; anything with path
        # separators or leading dots could traverse out of the bundles —
        # rejected BEFORE resolution (bundle_map only holds dir basenames,
        # so lookup never joins an attacker-controlled path)
        if not name or "/" in name or "\\" in name or name.startswith("."):
            raise FileNotFoundError(name)
        from pixie_tpu.scripts import bundle_map

        d = bundle_map(self.scripts_dir).get(name)
        if d is None:
            raise FileNotFoundError(name)
        pxls = sorted(d.glob("*.pxl"))
        if not pxls:
            raise FileNotFoundError(name)
        source = pxls[0].read_text()
        from pixie_tpu.vis import parse_vis

        vis_path = d / "vis.json"
        vis = parse_vis(json.loads(vis_path.read_text())) \
            if vis_path.exists() else parse_vis({})
        return source, vis

    def script_page(self, name: str, overrides: dict) -> str:
        source, vis = self._load(name)
        values = vis.variable_values(overrides)
        var_inputs = "".join(
            f'<label>{_esc(v.name)} <input name="{_esc(v.name)}" '
            f'value="{_esc(values.get(v.name, ""))}"></label>'
            for v in vis.variables
        )
        return _PAGE.format(
            title=_esc(name), var_inputs=var_inputs,
            source=_esc(source), script_json=json.dumps(name),
            session_token=json.dumps(self.session_token),
            refresh_s=_esc(f"{self.refresh_s:g}"),
        )

    # ------------------------------------------------------------------- api
    def run_api(self, req: dict) -> dict:
        name = req.get("script", "")
        overrides = req.get("vars") or {}
        source, vis = self._load(name)
        if req.get("source"):
            source = req["source"]
        runs = vis.executions(overrides)
        displays = vis.widget_displays()
        link_args = {
            k: v for k, v in vis.variable_values(overrides).items()
            if k in ("start_time",)
        }
        widgets = []
        if runs:
            funcs = [(out_name, fn, args) for out_name, fn, args in runs]
            results, sink_map = self.runner(source, funcs)
            for out_name, _fn, _args in runs:
                w = displays.get(out_name)
                kind = w.kind if w else "table"
                display = w.display if w else {}
                for _orig, fused_name in sink_map.get(out_name, {}).items():
                    res = results.get(fused_name)
                    if res is None:
                        continue
                    widgets.append({
                        "name": out_name, "kind": kind,
                        "html": render_widget_html(kind, display, res,
                                                   link_args),
                    })
        else:
            results, _ = self.runner(source, None)
            for sink, res in results.items():
                widgets.append({
                    "name": sink, "kind": "table",
                    "html": table_html(res, link_args=link_args),
                })
        return {"widgets": widgets}


# ---------------------------------------------------------------- runners
def local_runner(store, registry=None, now=None):
    """Runner over an in-process TableStore (fused multi-widget execution)."""
    from pixie_tpu.compiler import compile_pxl, compile_pxl_funcs
    from pixie_tpu.engine import execute_plan

    def run(source, funcs):
        from pixie_tpu.collect.schemas import all_schemas

        schemas = dict(all_schemas())
        schemas.update(store.schemas())
        if funcs:
            q, sink_map = compile_pxl_funcs(source, schemas, funcs,
                                            registry=registry, now=now)
            return execute_plan(q.plan, store), sink_map
        q = compile_pxl(source, schemas, registry=registry, now=now)
        results = execute_plan(q.plan, store)
        return results, {s: {s: s} for s in results}

    return run


def broker_runner(client):
    """Runner over a broker Client (fused distributed execution)."""

    def run(source, funcs):
        if funcs:
            results = client.execute_script(source, funcs=funcs)
            stats = next(iter(results.values())).exec_stats \
                if results else {}
            sink_map = stats.get("sink_map") or {}
            return results, sink_map
        results = client.execute_script(source)
        return results, {s: {s: s} for s in results}

    return run

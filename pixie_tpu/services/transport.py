"""Length-prefixed framed messaging over TCP sockets.

The reference runs a dual fabric — NATS for control, gRPC streaming for data
(SURVEY.md §5).  Here both ride one framed-TCP transport: each message is
`u32 length | wire frame` (services.wire), and a lightweight envelope in the
frame's JSON meta carries routing (`msg`, `req_id`).  Connections are
full-duplex: either side sends at any time; a reader thread per connection
dispatches by handler.
"""
from __future__ import annotations

import socket
import struct
import threading
import time
from typing import Callable, Optional

from pixie_tpu.services import faultinject
from pixie_tpu.status import Internal

_LEN = struct.Struct("<I")
MAX_FRAME = 1 << 30


def send_frame(sock: socket.socket, frame: bytes) -> None:
    if len(frame) > MAX_FRAME:
        raise Internal(f"frame too large ({len(frame)} bytes)")
    sock.sendall(_LEN.pack(len(frame)) + frame)


def recv_frame(sock: socket.socket) -> Optional[bytes]:
    """One frame, or None on clean EOF."""
    hdr = _recv_exact(sock, _LEN.size)
    if hdr is None:
        return None
    (n,) = _LEN.unpack(hdr)
    if n > MAX_FRAME:
        raise Internal(f"peer announced oversized frame ({n} bytes)")
    body = _recv_exact(sock, n)
    if body is None:
        return None
    return body


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    chunks = []
    got = 0
    while got < n:
        try:
            b = sock.recv(min(n - got, 1 << 20))
        except (ConnectionResetError, OSError):
            return None
        if not b:
            return None
        chunks.append(b)
        got += len(b)
    return b"".join(chunks)


class Connection:
    """One framed full-duplex connection with a background reader thread."""

    def __init__(self, sock: socket.socket, on_frame: Callable[["Connection", bytes], None],
                 on_close: Optional[Callable[["Connection"], None]] = None,
                 name: str = "?"):
        self.sock = sock
        self.name = name
        #: fault-injection target key (services/faultinject.py): endpoints
        #: that want to be addressable by a chaos plan set a logical label
        #: (agents: "agent:<name>", clients: "client"); defaults to the
        #: peer-addr name so unlabeled conns still match wildcard rules
        self.label = name
        self._on_frame = on_frame
        self._on_close = on_close
        self._wlock = threading.Lock()
        self._closed = threading.Event()
        self._thread = threading.Thread(
            target=self._read_loop, name=f"pixie-conn-{name}", daemon=True
        )
        #: arbitrary per-connection state for the owning service
        self.state: dict = {}

    def start(self):
        self._thread.start()

    def _apply_fault(self, direction: str) -> str:
        """Consult the installed fault injector (if any) for one frame.
        Returns "proceed", "drop" (swallow the frame), or "closed" (the
        injector killed this connection)."""
        inj = faultinject.active()
        if inj is None:
            return "proceed"
        d = inj.on_frame(id(self), self.label, direction)
        if d is None:
            return "proceed"
        if d.action == "delay":
            time.sleep(d.delay_s)
            return "proceed"
        if d.action == "drop":
            return "drop"
        if d.action == "reset":
            self.abort()
            return "closed"
        if d.action == "kill":
            # true pod loss: the label's registered handler drops the
            # owner's in-memory state FIRST, then the socket RSTs — the
            # peer observes exactly what a reaped pod leaves behind
            faultinject.fire_kill(self.label)
            self.abort()
            return "closed"
        self.close()  # crash: the peer sees a dead socket mid-stream
        return "closed"

    def abort(self) -> None:
        """Close with SO_LINGER 0 — the peer gets an RST, not a clean FIN
        (the injected-fault analog of a kernel reaping a crashed process)."""
        try:
            self.sock.setsockopt(
                socket.SOL_SOCKET, socket.SO_LINGER,
                struct.pack("ii", 1, 0))
        except OSError:
            pass
        self.close()

    def _read_loop(self):
        from pixie_tpu import metrics as _metrics

        while True:
            frame = recv_frame(self.sock)
            if frame is None:
                break
            fate = self._apply_fault("recv")
            if fate == "drop":
                continue
            if fate == "closed":
                break
            _metrics.counter_inc(
                "px_transport_frames_received_total",
                help_="frames received over framed-TCP connections")
            _metrics.counter_inc(
                "px_transport_bytes_received_total", float(len(frame)),
                help_="frame bytes received over framed-TCP connections")
            try:
                self._on_frame(self, frame)
            except Exception:
                # handler bugs must not kill the connection reader
                import traceback

                traceback.print_exc()
        self.close()

    def send(self, frame: bytes) -> bool:
        from pixie_tpu import metrics as _metrics

        fate = self._apply_fault("send")
        if fate == "drop":
            # the frame vanishes but the caller sees success — exactly what
            # a crashed peer's kernel buffer does to an un-acked write
            return True
        if fate == "closed":
            return False
        with self._wlock:
            try:
                send_frame(self.sock, frame)
            except OSError:
                return False
        _metrics.counter_inc(
            "px_transport_frames_sent_total",
            help_="frames sent over framed-TCP connections")
        _metrics.counter_inc(
            "px_transport_bytes_sent_total", float(len(frame)),
            help_="frame bytes sent over framed-TCP connections")
        return True

    def close(self):
        if self._closed.is_set():
            return
        self._closed.set()
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass
        if self._on_close is not None:
            cb, self._on_close = self._on_close, None
            cb(self)

    @property
    def closed(self) -> bool:
        return self._closed.is_set()


class Server:
    """Accept-loop TCP server handing Connections to a handler factory."""

    def __init__(self, host: str, port: int,
                 on_frame: Callable[[Connection, bytes], None],
                 on_close: Optional[Callable[[Connection], None]] = None):
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(64)
        self.port = self._sock.getsockname()[1]
        self._on_frame = on_frame
        self._on_close = on_close
        self._stop = threading.Event()
        self._conns: list[Connection] = []
        self._thread = threading.Thread(
            target=self._accept_loop, name="pixie-server", daemon=True
        )

    def start(self) -> "Server":
        self._thread.start()
        return self

    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                sock, addr = self._sock.accept()
            except OSError:
                return
            if self._stop.is_set():
                # a dial that completed in the backlog as stop() ran: close
                # it instead of servicing it — a STOPPED server answering
                # (e.g. "no live agents") wedges clients that would
                # otherwise redial the restarted instance on this port
                try:
                    sock.close()
                except OSError:
                    pass
                return
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)

            def on_close(c, _user=self._on_close):
                try:
                    self._conns.remove(c)
                except ValueError:
                    pass
                if _user is not None:
                    _user(c)

            conn = Connection(
                sock, self._on_frame, on_close, name=f"{addr[0]}:{addr[1]}"
            )
            self._conns.append(conn)
            conn.start()

    def stop(self):
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass
        for c in list(self._conns):
            c.close()


def dial(host: str, port: int,
         on_frame: Callable[[Connection, bytes], None],
         on_close: Optional[Callable[[Connection], None]] = None,
         timeout: float = 10.0) -> Connection:
    sock = socket.create_connection((host, port), timeout=timeout)
    sock.settimeout(None)
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    conn = Connection(sock, on_frame, on_close, name=f"{host}:{port}")
    conn.start()
    return conn

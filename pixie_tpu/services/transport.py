"""Length-prefixed framed messaging over TCP sockets.

The reference runs a dual fabric — NATS for control, gRPC streaming for data
(SURVEY.md §5).  Here both ride one framed-TCP transport: each message is
`u32 length | wire frame` (services.wire), and a lightweight envelope in the
frame's JSON meta carries routing (`msg`, `req_id`).  Connections are
full-duplex: either side sends at any time; a reader thread per connection
dispatches by handler.
"""
from __future__ import annotations

import socket
import struct
import threading
from typing import Callable, Optional

from pixie_tpu.status import Internal

_LEN = struct.Struct("<I")
MAX_FRAME = 1 << 30


def send_frame(sock: socket.socket, frame: bytes) -> None:
    if len(frame) > MAX_FRAME:
        raise Internal(f"frame too large ({len(frame)} bytes)")
    sock.sendall(_LEN.pack(len(frame)) + frame)


def recv_frame(sock: socket.socket) -> Optional[bytes]:
    """One frame, or None on clean EOF."""
    hdr = _recv_exact(sock, _LEN.size)
    if hdr is None:
        return None
    (n,) = _LEN.unpack(hdr)
    if n > MAX_FRAME:
        raise Internal(f"peer announced oversized frame ({n} bytes)")
    body = _recv_exact(sock, n)
    if body is None:
        return None
    return body


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    chunks = []
    got = 0
    while got < n:
        try:
            b = sock.recv(min(n - got, 1 << 20))
        except (ConnectionResetError, OSError):
            return None
        if not b:
            return None
        chunks.append(b)
        got += len(b)
    return b"".join(chunks)


class Connection:
    """One framed full-duplex connection with a background reader thread."""

    def __init__(self, sock: socket.socket, on_frame: Callable[["Connection", bytes], None],
                 on_close: Optional[Callable[["Connection"], None]] = None,
                 name: str = "?"):
        self.sock = sock
        self.name = name
        self._on_frame = on_frame
        self._on_close = on_close
        self._wlock = threading.Lock()
        self._closed = threading.Event()
        self._thread = threading.Thread(
            target=self._read_loop, name=f"pixie-conn-{name}", daemon=True
        )
        #: arbitrary per-connection state for the owning service
        self.state: dict = {}

    def start(self):
        self._thread.start()

    def _read_loop(self):
        from pixie_tpu import metrics as _metrics

        while True:
            frame = recv_frame(self.sock)
            if frame is None:
                break
            _metrics.counter_inc(
                "px_transport_frames_received_total",
                help_="frames received over framed-TCP connections")
            _metrics.counter_inc(
                "px_transport_bytes_received_total", float(len(frame)),
                help_="frame bytes received over framed-TCP connections")
            try:
                self._on_frame(self, frame)
            except Exception:
                # handler bugs must not kill the connection reader
                import traceback

                traceback.print_exc()
        self.close()

    def send(self, frame: bytes) -> bool:
        from pixie_tpu import metrics as _metrics

        with self._wlock:
            try:
                send_frame(self.sock, frame)
            except OSError:
                return False
        _metrics.counter_inc(
            "px_transport_frames_sent_total",
            help_="frames sent over framed-TCP connections")
        _metrics.counter_inc(
            "px_transport_bytes_sent_total", float(len(frame)),
            help_="frame bytes sent over framed-TCP connections")
        return True

    def close(self):
        if self._closed.is_set():
            return
        self._closed.set()
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass
        if self._on_close is not None:
            cb, self._on_close = self._on_close, None
            cb(self)

    @property
    def closed(self) -> bool:
        return self._closed.is_set()


class Server:
    """Accept-loop TCP server handing Connections to a handler factory."""

    def __init__(self, host: str, port: int,
                 on_frame: Callable[[Connection, bytes], None],
                 on_close: Optional[Callable[[Connection], None]] = None):
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(64)
        self.port = self._sock.getsockname()[1]
        self._on_frame = on_frame
        self._on_close = on_close
        self._stop = threading.Event()
        self._conns: list[Connection] = []
        self._thread = threading.Thread(
            target=self._accept_loop, name="pixie-server", daemon=True
        )

    def start(self) -> "Server":
        self._thread.start()
        return self

    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                sock, addr = self._sock.accept()
            except OSError:
                return
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)

            def on_close(c, _user=self._on_close):
                try:
                    self._conns.remove(c)
                except ValueError:
                    pass
                if _user is not None:
                    _user(c)

            conn = Connection(
                sock, self._on_frame, on_close, name=f"{addr[0]}:{addr[1]}"
            )
            self._conns.append(conn)
            conn.start()

    def stop(self):
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass
        for c in list(self._conns):
            c.close()


def dial(host: str, port: int,
         on_frame: Callable[[Connection, bytes], None],
         on_close: Optional[Callable[[Connection], None]] = None,
         timeout: float = 10.0) -> Connection:
    sock = socket.create_connection((host, port), timeout=timeout)
    sock.settimeout(None)
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    conn = Connection(sock, on_frame, on_close, name=f"{host}:{port}")
    conn.start()
    return conn

"""Cron scripts: persisted PxL scripts executed on an interval.

Reference: the query broker's ScriptRunner syncs + executes cron scripts
(script_runner/script_runner.go:47-54) backed by the cron-script store
(metadata controllers/cronscript + cloud cron_script svc).  Scripts typically
carry a px.export(...) OTel sink — that is the retention/plugin export path.
"""
from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable, Optional

from pixie_tpu.status import InvalidArgument, NotFound


@dataclasses.dataclass
class CronScript:
    name: str
    script: str
    interval_s: float
    func: Optional[str] = None
    func_args: Optional[dict] = None
    enabled: bool = True
    # runtime state (not persisted)
    last_run: float = 0.0
    last_error: str = ""
    run_count: int = 0
    error_count: int = 0


class Ticker:
    """Generic periodic maintenance job on a daemon thread — the cron-runner
    tick discipline without the script registry.  Services hang incremental
    maintainers off it (matview standing-view refresh, future compactors);
    a failing tick is counted, never raised (maintenance must not kill its
    host service)."""

    def __init__(self, name: str, interval_s: float, fn: Callable):
        if interval_s <= 0:
            raise InvalidArgument("ticker interval must be positive")
        self.name = name
        self.interval_s = float(interval_s)
        self._fn = fn
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.tick_count = 0
        self.error_count = 0

    def start(self) -> "Ticker":
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()

        def loop():
            while not self._stop.wait(timeout=self.interval_s):
                try:
                    self._fn()
                    self.tick_count += 1
                except Exception:
                    self.error_count += 1
                    from pixie_tpu import metrics as _metrics

                    _metrics.counter_inc(
                        "px_ticker_errors_total",
                        labels={"ticker": self.name},
                        help_="background ticker callbacks that raised "
                              "(the loop continues)")

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name=f"pixie-ticker-{self.name}")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None


class CronScriptRunner:
    """Background executor over a persisted script set."""

    def __init__(self, execute: Callable, kv=None,
                 on_result: Optional[Callable] = None):
        """execute(script, func, func_args) → results (broker.execute_script);
        on_result(name, results) optional hook (tests, custom retention)."""
        self._execute = execute
        self.kv = kv
        self.on_result = on_result
        self._scripts: dict[str, CronScript] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if kv is not None:
            import json

            for _k, raw in kv.scan("cronscript/"):
                d = json.loads(raw.decode())
                cs = CronScript(**{k: d[k] for k in
                                   ("name", "script", "interval_s", "func",
                                    "func_args", "enabled") if k in d})
                self._scripts[cs.name] = cs

    # ---------------------------------------------------------------- registry
    def upsert(self, name: str, script: str, interval_s: float,
               func: Optional[str] = None, func_args: Optional[dict] = None,
               enabled: bool = True) -> CronScript:
        if interval_s <= 0:
            raise InvalidArgument("cron interval must be positive")
        with self._lock:
            cs = CronScript(name, script, float(interval_s), func, func_args, enabled)
            prev = self._scripts.get(name)
            if prev is not None:
                cs.last_run = prev.last_run
                cs.run_count = prev.run_count
                cs.error_count = prev.error_count
            self._scripts[name] = cs
            self._persist(cs)
            return cs

    def delete(self, name: str) -> None:
        with self._lock:
            if name not in self._scripts:
                raise NotFound(f"no cron script {name!r}")
            del self._scripts[name]
            if self.kv is not None:
                self.kv.delete(f"cronscript/{name}")

    def list(self) -> list[CronScript]:  # noqa: A003
        with self._lock:
            return sorted(self._scripts.values(), key=lambda c: c.name)

    def _persist(self, cs: CronScript) -> None:
        if self.kv is not None:
            self.kv.set_json(f"cronscript/{cs.name}", {
                "name": cs.name, "script": cs.script,
                "interval_s": cs.interval_s, "func": cs.func,
                "func_args": cs.func_args, "enabled": cs.enabled,
            })

    # --------------------------------------------------------------- execution
    def run_due(self, now: Optional[float] = None) -> int:
        """Run every enabled script whose interval elapsed; returns #ran."""
        now = time.monotonic() if now is None else now
        with self._lock:
            due = []
            for cs in self._scripts.values():
                if cs.enabled and now - cs.last_run >= cs.interval_s:
                    cs.last_run = now  # claim under the lock
                    due.append(cs)
        ran = 0
        for cs in due:
            try:
                results = self._execute(cs.script, cs.func, cs.func_args)
                err = ""
                if self.on_result is not None:
                    self.on_result(cs.name, results)
            except Exception as e:
                err = str(e)
                from pixie_tpu import metrics as _metrics

                _metrics.counter_inc(
                    "px_cron_script_errors_total",
                    labels={"script": cs.name},
                    help_="cron script executions that raised (recorded on "
                          "the script's error_count/last_error)")
            # Record outcome on whatever object is CURRENTLY registered under
            # this name — an upsert mid-run replaces the object and would
            # otherwise lose the counters.
            with self._lock:
                target = self._scripts.get(cs.name, cs)
                if err:
                    target.error_count += 1
                    target.last_error = err
                else:
                    target.run_count += 1
                    target.last_error = ""
            ran += 1
        return ran

    def start(self, tick_s: float = 1.0) -> "CronScriptRunner":
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()

        def loop():
            while not self._stop.wait(timeout=tick_s):
                self.run_due()

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="pixie-cron-runner")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

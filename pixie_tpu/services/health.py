"""Healthz endpoint for services (broker/agent).

Reference: src/shared/services/ — every Go service exposes an HTTP
`/healthz` (and `/metrics`) used by k8s liveness/readiness probes.  The
framed-TCP data port stays auth-gated; health lives on its own HTTP
listener so probes need no protocol client or credentials.

GET /healthz  → 200 `{"ok": true, "checks": {...}}` when every registered
check passes, else 503 with the failing checks' errors (liveness).
GET /readyz   → same over checks + ready_checks (readiness — e.g. leader
election or serving-front overload: a healthy standby / a broker past its
queue-depth watermark is alive but must not receive traffic).
GET /metrics  → the Prometheus-style text rendering of pixie_tpu.metrics.

The liveness/readiness split matters operationally: a k8s liveness probe
restarts a failing pod, a readiness probe only pulls it from the service
endpoints — an overloaded broker that fails BOTH gets restarted in a loop
and sheds its queues, so overload may only ever flip /readyz.
"""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional


class HealthzServer:
    """checks: name -> callable returning truthy (healthy) or raising."""

    def __init__(self, checks: Optional[dict[str, Callable]] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 ready_checks: Optional[dict[str, Callable]] = None,
                 detail: Optional[dict[str, Callable]] = None):
        self.checks: dict[str, Callable] = dict(checks or {})
        #: extra checks for /readyz only (e.g. leadership): failing them
        #: means "alive but not serving", which must NOT fail liveness
        self.ready_checks: dict[str, Callable] = dict(ready_checks or {})
        #: informational payloads (name -> callable returning a JSON-able
        #: value) merged into the /healthz body under "detail" — never
        #: affect the verdict (journal disk usage, queue depths, ...)
        self.detail: dict[str, Callable] = dict(detail or {})
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _send(self, code: int, body: bytes, ctype: str):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path in ("/healthz", "/readyz"):
                    ok, results = outer.run_checks(
                        ready=self.path == "/readyz")
                    doc = {"ok": ok, "checks": results}
                    det = outer.run_detail()
                    if det:
                        doc["detail"] = det
                    body = json.dumps(doc).encode()
                    return self._send(200 if ok else 503, body,
                                      "application/json")
                if self.path == "/metrics":
                    from pixie_tpu import metrics as _metrics

                    return self._send(200, _metrics.render().encode(),
                                      "text/plain; version=0.0.4")
                return self._send(404, b"not found", "text/plain")

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self._httpd.server_port
        self._thread: Optional[threading.Thread] = None

    def run_detail(self) -> dict:
        """Evaluate the informational payloads; a failing provider reports
        its error in place rather than failing the probe."""
        out = {}
        for name, fn in self.detail.items():
            try:
                out[name] = fn()
            except Exception as e:
                out[name] = f"error: {e}"
        return out

    def add_ready_check(self, name: str, fn: Callable) -> None:
        """Register a READINESS-ONLY check: failing it flips /readyz while
        /healthz stays green (overload, leadership, warmup...)."""
        self.ready_checks[name] = fn

    def run_checks(self, ready: bool = False) -> tuple[bool, dict]:
        checks = dict(self.checks)
        if ready:
            checks.update(self.ready_checks)
        results = {}
        ok = True
        for name, fn in checks.items():
            try:
                good = bool(fn())
                results[name] = "ok" if good else "failed"
                ok = ok and good
            except Exception as e:
                results[name] = f"error: {e}"
                ok = False
        return ok, results

    def start(self) -> "HealthzServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="pixie-healthz")
        self._thread.start()
        return self

    def stop(self) -> None:
        # shutdown() blocks forever unless serve_forever() is running —
        # a stop() after a FAILED service start must not hang cleanup
        if self._thread is not None:
            self._httpd.shutdown()
        self._httpd.server_close()

"""Agent runtime: a PEM analog — local TableStore (+ collectors) that dials
the broker, registers its schemas, heartbeats, and executes plan fragments.

Reference: src/vizier/services/agent/ Manager (registration handshake +
heartbeats every 5s, manager/manager.h:100-266, heartbeat.h:79) and
ExecuteQueryMessageHandler running plans on a threadpool (manager/exec.cc:38-98).
PEM wiring of collector→store mirrors pem/pem_manager.cc:47.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Optional

import numpy as np

from pixie_tpu import flags, observe, trace
from pixie_tpu.engine.executor import HostBatch, PlanExecutor
from pixie_tpu.matview import MatViewManager
from pixie_tpu.parallel.partial import PartialAggBatch
from pixie_tpu.plan.plan import Plan
from pixie_tpu.services import replication as _replication
from pixie_tpu.services import wire
from pixie_tpu.services.transport import Connection, dial
from pixie_tpu.table import heat as _heat
from pixie_tpu.table import journal as _journal
from pixie_tpu.table.table import TableStore

DEFAULT_HEARTBEAT_S = 5.0  # reference manager/heartbeat.h:79

flags.define_int(
    "PL_STREAM_WINDOW", 4,
    "max unacked in-flight result chunk frames per query (the agent blocks "
    "further chunk sends until the broker acks; 0 = unbounded)")
flags.define_int(
    "PL_STREAM_AGG_CHUNK_GROUPS", 65536,
    "split an agg_state channel payload into chunks of at most this many "
    "groups so the broker's incremental fold starts early; 0 = one chunk")
#: give up waiting for chunk acks after this long and degrade to unbounded
#: streaming — a slow broker must throttle us, a broken one must not hang us
ACK_STALL_S = 10.0


def _chunk_view_state(channel: str, pb: PartialAggBatch, agg_chunk_groups: int):
    """Yield a standing view's state as the same chunk stream shape the
    executor produces, honoring the agg-chunk split so the broker's
    incremental fold and ack window behave identically on view answers."""
    from pixie_tpu.parallel.partial import slice_partial

    n = pb.num_groups
    if agg_chunk_groups > 0 and n > agg_chunk_groups:
        for a in range(0, n, agg_chunk_groups):
            idx = np.arange(a, min(a + agg_chunk_groups, n))
            yield channel, slice_partial(pb, idx)
    else:
        yield channel, pb


class Agent:
    def __init__(
        self,
        name: str,
        broker_host: str,
        broker_port: int,
        store: Optional[TableStore] = None,
        collector=None,
        registry=None,
        heartbeat_s: float = DEFAULT_HEARTBEAT_S,
        n_devices: Optional[int] = None,
        auth_token: Optional[str] = None,
        healthz_port: Optional[int] = None,
        healthz_host: str = "127.0.0.1",
    ):
        self.auth_token = auth_token
        self.healthz = None
        if healthz_port is not None:
            from pixie_tpu.services.health import HealthzServer

            self.healthz = HealthzServer(checks={
                "broker_conn": lambda: (self.conn is not None
                                        and not self.conn.closed),
                "registered": lambda: self._registered.is_set(),
            }, host=healthz_host, port=healthz_port,
                detail={"journal": self._journal_detail})
        self.name = name
        self.broker = (broker_host, broker_port)
        self.store = store or (collector.store if collector else TableStore())
        #: shard identity for the heat model (table/heat.py): executor feeds
        #: over this store account as this agent's shard
        self.store.node_name = name
        self.collector = collector
        self.registry = registry
        self.heartbeat_s = heartbeat_s
        self.n_devices = n_devices
        self.conn: Optional[Connection] = None
        self.asid: Optional[int] = None
        self._registered = threading.Event()
        self._stop = threading.Event()
        self._hb_thread: Optional[threading.Thread] = None
        from pixie_tpu.services.tracepoints import TracepointManager

        #: dynamic tracepoints deployed to this agent (pem TracepointManager
        #: analog, pem/tracepoint_manager.h:48)
        self.tracepoints = TracepointManager(self.store)
        #: self-telemetry: this agent's exec spans + broker-shipped spans
        #: land in the local spans table, created BEFORE registration so the
        #: broker's registry knows the schema from the first handshake
        self.tracer = trace.Tracer(name)
        trace.ensure_table(self.store)
        #: flight-recorder tables (query profiles, op stats, metrics,
        #: alerts) exist before registration too: the broker ships its
        #: per-query rows here and PxL dashboards scan them like any table
        observe.ensure_self_tables(self.store)
        self._self_metrics = None
        #: standing materialized views over this agent's store: repeated
        #: scan→filter→map→partial-agg plans answer from incrementally
        #: refreshed state instead of rescanning (pixie_tpu.matview)
        self.matviews = MatViewManager(self.store, registry)
        #: req_id → in-flight window semaphore; chunk_ack frames release it
        self._windows: dict[str, threading.Semaphore] = {}
        self._windows_lock = threading.Lock()
        #: durable data plane (PL_DATA_DIR / PL_REPLICATION): set in start()
        self.replication = None
        self.rehydrate_stats: dict = {}
        self._owns_journal = False
        self.pod_killed = threading.Event()
        #: broker RPC slots (get_peers): req_id -> [Event, reply]
        self._replies: dict[str, list] = {}
        self._replies_lock = threading.Lock()

    # ---------------------------------------------------------------- lifecycle
    def start(self, timeout: float = 10.0) -> "Agent":
        trace.register_gauges()
        if self.collector is not None:
            self.collector.start()
        self.conn = dial(*self.broker, on_frame=self._on_frame)
        # fault-injection target (services/faultinject.py): chaos plans
        # address this agent's broker link as "agent:<name>"; kill rules
        # (true pod loss) route back into _pod_kill through the handler
        # registry so the store drops with the connection
        self.conn.label = f"agent:{self.name}"
        from pixie_tpu.services import faultinject as _faultinject

        _faultinject.register_kill_handler(self.conn.label, self._pod_kill)
        if self.auth_token is not None:
            self.conn.send(wire.encode_json(
                {"msg": "auth", "token": self.auth_token}))
        self._rehydrate(timeout)
        self._register()
        if not self._registered.wait(timeout=timeout):
            raise TimeoutError(f"agent {self.name}: broker did not ack registration")
        self._hb_thread = threading.Thread(
            target=self._hb_loop, daemon=True, name=f"pixie-agent-hb-{self.name}"
        )
        self._hb_thread.start()
        if self.healthz is not None:
            self.healthz.start()
        self.matviews.start_refresher()  # no-op unless PL_MATVIEW_REFRESH_S>0
        period = float(flags.get("PL_SELF_METRICS_S"))
        if period > 0:
            from pixie_tpu.services.cron import Ticker

            # metrics-as-data on the agent side: this process's registry
            # folds into the LOCAL store (no hop — the agent IS the data
            # plane), stamped with the agent's own service name
            self._self_metrics = Ticker(
                f"self_metrics_{self.name}", period,
                self._fold_self_metrics).start()
        return self

    def _journal_detail(self) -> dict:
        """Per-table journal disk usage for the /healthz detail payload:
        PL_JOURNAL_MAX_MB pruning pressure, visible before it bites."""
        from pixie_tpu.table.table import Table

        tables = {}
        total = 0
        for name in self.store.names():
            t = self.store._tables.get(name)
            j = getattr(t, "journal", None) if isinstance(t, Table) else None
            if j is None:
                continue
            nbytes, nsegs = j.disk_usage()
            tables[name] = {"bytes": nbytes, "segments": nsegs}
            total += nbytes
        return {"tables": tables, "total_bytes": total,
                "budget_mb": int(flags.get("PL_JOURNAL_MAX_MB"))}

    def _fold_self_metrics(self) -> None:
        """PL_SELF_METRICS_S cron body: the metrics registry plus the
        storage observatory (decayed shard heat + per-table storage state,
        table/heat.py) fold into the local store."""
        observe.write_rows(self.store, observe.METRICS_TABLE,
                           observe.sample_metrics_rows(self.name))
        _heat.fold_into(self.store, self.name, matviews=self.matviews,
                        replication=self.replication)
        from pixie_tpu.engine import autotune as _autotune

        if _autotune.enabled():
            # adaptive-gate events raised in THIS process (fallback trips,
            # fitted-threshold changes) land in the local store's slice of
            # the autotune table on the same cadence as the metrics fold
            rows = _autotune.MODEL.drain_rows()
            if rows:
                observe.write_rows(self.store, observe.AUTOTUNE_TABLE, rows)

    def stop(self):
        self._stop.set()
        if self._self_metrics is not None:
            self._self_metrics.stop()
            self._self_metrics = None
        self.matviews.stop_refresher()
        if self.healthz is not None:
            self.healthz.stop()
        if self.collector is not None:
            self.collector.stop()
        from pixie_tpu.services import faultinject as _faultinject

        # only OUR handler: a restarted successor owns the label now
        _faultinject.unregister_kill_handler(f"agent:{self.name}",
                                             fn=self._pod_kill)
        if self.replication is not None:
            self.replication.stop()
            self.replication = None
        if self._owns_journal:
            _journal.detach_store(self.store)
            self._owns_journal = False
        if self.conn is not None:
            self.conn.close()

    # ------------------------------------------------------------- durability
    def _rehydrate(self, timeout: float) -> None:
        """Restore durable state BEFORE registration, so the broker never
        dispatches to a store that is still catching up: (1) journal replay
        into the local store (acked rows survive restart), (2) peer fetch
        of sealed batches the journal no longer covers (pod loss), (3) the
        matview snapshot dir arms so standing state resumes at O(delta).
        A no-op with PL_DATA_DIR unset and PL_REPLICATION=1."""
        ndir = _journal.node_dir(self.name)
        if ndir is not None:
            self.rehydrate_stats["journal"] = _journal.attach_store(
                self.store, ndir)
            self._owns_journal = True
            import os as _os

            self.matviews.set_snapshot_dir(_os.path.join(ndir, "matview"))
        if not _replication.enabled():
            return
        self.replication = _replication.ReplicationManager(
            self.name, self.store).start()
        try:
            reply = self._rpc({"msg": "get_peers", "agent": self.name},
                              timeout=timeout)
        except TimeoutError:
            return  # an old broker: replicate-only mode, no topology yet
        shard_map = reply.get("shard_map") or {}
        peers = reply.get("peers") or {}
        self.replication.on_shard_map(shard_map, peers)
        holders = [h for h in (shard_map.get(self.name) or []) if h in peers]
        if holders:
            self.rehydrate_stats["fetch"] = self.replication.fetch_missing(
                self.store, holders)

    def _pod_kill(self) -> None:
        """True pod loss (faultinject `kill:` rule): drop every in-memory
        table — recovery must come from the journal and the replica peers,
        never from preserved process state."""
        self.pod_killed.set()
        self._stop.set()
        if self._owns_journal:
            _journal.detach_store(self.store)
            self._owns_journal = False
        if self.replication is not None:
            self.replication.stop()
            self.replication = None
        for n in list(self.store.names()):
            self.store.drop(n)

    def _rpc(self, meta: dict, timeout: float = 10.0) -> dict:
        import uuid as _uuid

        rid = meta.setdefault("req_id", _uuid.uuid4().hex)
        slot = [threading.Event(), None]
        with self._replies_lock:
            self._replies[rid] = slot
        try:
            self.conn.send(wire.encode_json(meta))
            if not slot[0].wait(timeout):
                raise TimeoutError(f"broker did not answer {meta.get('msg')}")
            return slot[1]
        finally:
            with self._replies_lock:
                self._replies.pop(rid, None)

    def _register(self):
        self.conn.send(wire.encode_json({
            "msg": "register",
            "agent": self.name,
            "schemas": {t: r.to_dict() for t, r in self.store.schemas().items()},
            "n_devices": self.n_devices,
            "repl_addr": (list(self.replication.addr())
                          if self.replication is not None else None),
        }))

    def _hb_loop(self):
        while not self._stop.wait(timeout=self.heartbeat_s):
            if self.conn is None or self.conn.closed:
                return
            self.conn.send(wire.encode_json({"msg": "heartbeat", "agent": self.name}))

    # ------------------------------------------------------------------- frames
    def _on_frame(self, conn: Connection, frame: bytes):
        kind, payload = wire.decode_frame(frame)
        if kind != "json":
            return
        msg = payload.get("msg")
        if msg == "registered":
            self.asid = payload.get("asid")
            self._registered.set()
        elif msg == "chunk_ack":
            # broker consumed (folded) one of our chunk frames: open the
            # in-flight window by one.  MUST stay on the read loop — it's a
            # lone semaphore release, and a thread per ack would cost more
            # than the fold it acknowledges.  Keyed per (req_id, attempt,
            # source agent): a hedged duplicate dispatch runs concurrently
            # with its twin, and a failover replica may stream its OWN
            # fragment beside a takeover fragment of the same query —
            # neither must drain the other's window.
            key = (f"{payload.get('req_id', '')}"
                   f"#{int(payload.get('attempt') or 0)}"
                   f"#{payload.get('agent') or self.name}")
            with self._windows_lock:
                sem = self._windows.get(key)
            if sem is not None:
                sem.release()
        elif msg == "reregister":
            self._register()
        elif msg == "retire_query":
            # scale-down drain audit (broker.retire_agent) — OFF the read
            # loop: wait_synced may block up to its budget, and stalling
            # this loop would freeze chunk_ack/execute/shard_map handling
            # for every in-flight query on a still-serving retire candidate
            threading.Thread(
                target=self._answer_retire_query,
                args=(payload.get("req_id"),), daemon=True,
                name=f"pixie-agent-retire-{self.name}",
            ).start()
        elif msg == "peers":
            # reply to a get_peers RPC (rehydration topology fetch)
            with self._replies_lock:
                slot = self._replies.get(payload.get("req_id"))
            if slot is not None:
                slot[1] = payload
                slot[0].set()
        elif msg == "shard_map":
            # broker push on topology change: retarget replication and drop
            # takeover materializations for primaries this node left
            if self.replication is not None:
                self.replication.on_shard_map(payload.get("map") or {},
                                              payload.get("peers") or {})
        elif msg == "execute":
            threading.Thread(
                target=self._execute, args=(payload,), daemon=True,
                name=f"pixie-agent-exec-{self.name}",
            ).start()
        elif msg == "spans":
            # broker-shipped spans (the merger holds no scanned store):
            # append into the local spans table so one distributed scan
            # returns the full trace.  Off the read loop — a table write
            # must not queue execute/heartbeat frames behind telemetry.
            threading.Thread(
                target=self._write_shipped_spans,
                args=(payload.get("spans") or [],), daemon=True,
                name=f"pixie-agent-spans-{self.name}",
            ).start()
        elif msg == "telemetry_rows":
            # broker-shipped flight-recorder rows (query profiles, op
            # stats, sampled metrics, SLO alerts): same contract as spans
            threading.Thread(
                target=self._write_telemetry_rows,
                args=(payload.get("table"), payload.get("rows") or []),
                daemon=True,
                name=f"pixie-agent-telemetry-{self.name}",
            ).start()
        elif msg == "rehome_prepare":
            # donor-side shard re-homing prep (broker.rehome_agent) — OFF
            # the read loop: force-sealing takes table locks and the
            # replication drain blocks up to its budget
            threading.Thread(
                target=self._answer_rehome_prepare,
                args=(payload.get("req_id"),), daemon=True,
                name=f"pixie-agent-rehome-{self.name}",
            ).start()
        elif msg == "rehome_audit":
            # target-side coverage audit: report the replica manifest this
            # node holds FOR the donor so the broker can verify the move
            threading.Thread(
                target=self._answer_rehome_audit,
                args=(payload.get("req_id"), payload.get("donor")),
                daemon=True,
                name=f"pixie-agent-rehome-audit-{self.name}",
            ).start()
        elif msg == "storage_report":
            # on-demand storage observatory read (broker heat_map RPC):
            # current decayed heat + storage state, NOT a fold — nothing is
            # written.  Off the read loop: the state walk takes table locks.
            threading.Thread(
                target=self._answer_storage_report,
                args=(payload.get("req_id"),), daemon=True,
                name=f"pixie-agent-storage-{self.name}",
            ).start()
        elif msg == "deploy_tracepoint":
            try:
                self.tracepoints.apply([payload["spec"]])
                # schemas changed: re-register BEFORE acking so the broker's
                # registry sees the new table when the ack lands
                self._register()
                self.conn.send(wire.encode_json({
                    "msg": "tracepoint_ready", "req_id": payload.get("req_id"),
                    "qtoken": payload.get("qtoken"),
                    "agent": self.name,
                }))
            except Exception as e:
                self.conn.send(wire.encode_json({
                    "msg": "tracepoint_error", "req_id": payload.get("req_id"),
                    "qtoken": payload.get("qtoken"),
                    "agent": self.name, "error": str(e),
                }))

    def _answer_retire_query(self, req_id) -> None:
        """Report the rows this agent holds outside the self-telemetry
        tables (the data a retire would lose) and whether the replication
        stream has synced them onto the peers — the broker's loss-safety
        input (broker.retire_agent)."""
        rows = 0
        for n in self.store.names():
            if n.startswith("self_telemetry."):
                continue
            try:
                rows += int(self.store.table(n).stats()
                            .get("rows_written", 0))
            except Exception:
                rows = -1  # unauditable: the broker refuses the retire
                break
        synced = (self.replication is not None
                  and self.replication.wait_synced(0.5))
        # per-peer watermark detail: the drain audit used to infer "synced"
        # as a bare bool — now the sent/acked/lag numbers behind the verdict
        # travel with it
        peer_sync = (self.replication.sync_state()
                     if self.replication is not None else {})
        self.conn.send(wire.encode_json({
            "msg": "retire_info", "req_id": req_id,
            "agent": self.name, "rows": rows, "repl_synced": synced,
            "peer_sync": peer_sync}))

    def _answer_rehome_prepare(self, req_id) -> None:
        """Donor half of a shard move (broker.rehome_agent): force-seal
        every hot remainder into replicable sealed form, drain the
        replication stream (the staged target is already in our shard map,
        so the seals ship to it), and report per-table row frontiers — the
        coverage the broker audits against the target's replica manifest."""
        from pixie_tpu.table.table import Table

        tables: dict = {}
        err = ""
        synced = False
        try:
            skip = _journal.non_durable_tables()
            for n in self.store.names():
                if n.startswith("self_telemetry.") or n in skip:
                    continue
                t = self.store._tables.get(n)
                if not isinstance(t, Table):
                    continue
                t.seal_hot()
                tables[n] = {"first": int(t.first_row_id()),
                             "last": int(t.last_row_id())}
            synced = (self.replication is not None
                      and self.replication.wait_synced(10.0))
        except Exception as e:
            err = str(e)
        self.conn.send(wire.encode_json({
            "msg": "rehome_info", "req_id": req_id, "agent": self.name,
            "phase": "prepare", "tables": tables,
            "repl_synced": bool(synced),
            "peer_sync": (self.replication.sync_state()
                          if self.replication is not None else {}),
            "error": err}))

    def _answer_rehome_audit(self, req_id, donor) -> None:
        """Target half of a shard move: the replica manifest this node
        holds FOR the donor ({table: {ranges: [[start, n]...]}}), which the
        broker diffs against the donor's reported frontiers to decide
        whether the flip is safe to commit."""
        man: dict = {}
        err = ""
        try:
            if self.replication is not None:
                man = self.replication.replicas.manifest(str(donor or ""))
        except Exception as e:
            err = str(e)
        self.conn.send(wire.encode_json({
            "msg": "rehome_info", "req_id": req_id, "agent": self.name,
            "phase": "audit", "donor": donor,
            "tables": {n: {"ranges": m.get("ranges") or []}
                       for n, m in man.items()},
            "error": err}))

    def _answer_storage_report(self, req_id) -> None:
        """One storage_report RPC answer: this agent's decayed shard-heat
        snapshot + storage-state rows (table/heat.py), as JSON."""
        try:
            report = {
                "shard_heat": _heat.snapshot_rows(),
                "storage_state": _heat.storage_state_rows(
                    self.store, self.name, matviews=self.matviews,
                    replication=self.replication),
            }
        except Exception as e:
            report = {"error": str(e)}
        self.conn.send(wire.encode_json({
            "msg": "storage_report", "req_id": req_id,
            "agent": self.name, **report}))

    def _execute(self, meta: dict):
        import contextlib

        req_id = meta.get("req_id", "")
        # echoed on every result frame; the broker drops frames whose token
        # doesn't match the live dispatch (per-dispatch result-stream auth,
        # reference carnotpb/carnot.proto:30-96).  `attempt` distinguishes
        # re-dispatches and hedged duplicates of the same query.
        qtoken = meta.get("qtoken")
        attempt = int(meta.get("attempt") or 0)
        # failover takeover: the broker dispatched a DEAD primary's fragment
        # here — execute it over the store materialized from that primary's
        # replicated sealed batches, and answer AS the primary (src/token
        # bookkeeping at the broker is keyed by the planned agent name)
        serve_for = meta.get("serve_for")
        src_name = str(serve_for) if serve_for else self.name
        # the window key carries the SOURCE name: a replica can run its own
        # fragment AND a takeover fragment of the same (req, attempt) — two
        # streams, two windows; a shared key would starve one of its acks
        wkey = f"{req_id}#{attempt}#{src_name}"
        # cross-process trace context: parent this agent's exec spans under
        # the broker's dispatch span for the same query
        tctx = meta.get("trace")
        cm = (trace.root(self.tracer, "exec", ctx=tctx, agent=self.name,
                         req_id=req_id)
              if tctx else contextlib.nullcontext())
        # a degraded broker narrows the in-flight chunk window per query
        # (serving-front backpressure: admitted queries throttle harder
        # instead of queueing frames at a merge that can't keep up)
        window = int(meta.get("stream_window")
                     or flags.get("PL_STREAM_WINDOW"))
        sem = threading.Semaphore(window) if window > 0 else None
        if sem is not None:
            with self._windows_lock:
                self._windows[wkey] = sem
        try:
            with cm:
                plan = Plan.from_dict(meta["plan"])
                exec_store = self.store
                if serve_for:
                    if self.replication is None:
                        raise RuntimeError(
                            f"takeover dispatch for {serve_for} without "
                            "replication enabled")
                    exec_store = self.replication.takeover_store(
                        str(serve_for))
                # Standing-view fast path: an eligible repeated plan answers
                # from incrementally refreshed partial-agg state (first sight
                # only registers and runs the normal path below).  analyze
                # runs bypass views — they exist to measure the real scan.
                # Takeover serves bypass them too: standing state is bound to
                # THIS node's store, not the materialized primary shard.
                served = None
                if not meta.get("analyze") and not serve_for:
                    served = self.matviews.serve(
                        plan, route_scale=int(meta.get("route_scale", 1)),
                        tenant=str(meta.get("tenant") or ""),
                        stale_ok=bool(meta.get("stale_ok")))
                if served is not None:
                    cid, pb, mv_info = served
                    ex = None
                    stream = _chunk_view_state(cid, pb, int(
                        flags.get("PL_STREAM_AGG_CHUNK_GROUPS")))
                else:
                    mv_info = None
                    ex = PlanExecutor(
                        plan, exec_store, self.registry,
                        analyze=bool(meta.get("analyze", False)),
                        route_scale=int(meta.get("route_scale", 1)),
                    )
                    stream = ex.run_agent_stream(
                        agg_chunk_groups=int(
                            flags.get("PL_STREAM_AGG_CHUNK_GROUPS")))
                t0 = time.perf_counter()
                # Chunk stream: each wave/slice ships as its own frame the
                # moment the executor yields it, so the broker's incremental
                # fold (and the NEXT wave's D2H) overlap this agent's compute
                # instead of queueing behind a terminal result frame.
                counts: dict[str, int] = {}
                stalled = False
                for channel, payload in stream:
                    if not stalled:
                        stalled = not self._await_window(sem)
                    seq = counts.get(channel, 0)
                    counts[channel] = seq + 1
                    extra = {"msg": "chunk", "req_id": req_id,
                             "channel": channel, "seq": seq,
                             "agent": src_name, "qtoken": qtoken,
                             "attempt": attempt}
                    if isinstance(payload, PartialAggBatch):
                        frame = wire.encode_partial_agg(payload, extra)
                    elif isinstance(payload, HostBatch):
                        frame = wire.encode_host_batch(payload, extra)
                    else:
                        raise TypeError(f"unexpected payload {type(payload)}")
                    self.conn.send(frame)
                stats = dict(ex.stats) if ex is not None else {}
                if mv_info is not None:
                    stats["matview"] = mv_info
                if serve_for:
                    # completeness accounting: the broker folds this into
                    # stats["fault"]["failover"] so a degraded (replica-
                    # served) answer is auditable per query
                    stats["takeover"] = {"primary": src_name,
                                         "replica": self.name}
                stats["exec_s"] = time.perf_counter() - t0
            # spans persist BEFORE the ack: when exec_done lands at the
            # broker, this query's spans are already scannable
            self._flush_trace()
            from pixie_tpu.services.broker import _jsonable

            self.conn.send(wire.encode_json({
                "msg": "exec_done", "req_id": req_id, "agent": src_name,
                "qtoken": qtoken, "attempt": attempt,
                "stats": _jsonable(stats),
                # per-channel chunk counts: the broker verifies its folds saw
                # every frame (a dropped chunk must fail loudly, not merge a
                # silently-partial answer)
                "chunks": counts,
            }))
        except Exception as e:
            self._flush_trace()
            self.conn.send(wire.encode_json({
                "msg": "exec_error", "req_id": req_id, "agent": src_name,
                "qtoken": qtoken, "attempt": attempt, "error": str(e),
            }))
        finally:
            if sem is not None:
                with self._windows_lock:
                    self._windows.pop(wkey, None)

    def _await_window(self, sem: Optional[threading.Semaphore]) -> bool:
        """Block until the in-flight chunk window opens; False on stall.
        After one stall the caller stops waiting for the rest of the query
        (degraded to unbounded, counted): TCP still backpressures a
        slow-but-alive broker, and a broker that stopped acking — typically
        because this query already died there — must not wedge this
        executor thread for stall-budget × remaining-chunks."""
        if sem is None:
            return True
        deadline = time.monotonic() + ACK_STALL_S
        while not self._stop.is_set():
            if sem.acquire(timeout=0.2):
                return True
            if self.conn is None or self.conn.closed:
                return False
            if time.monotonic() >= deadline:
                from pixie_tpu import metrics as _metrics

                _metrics.counter_inc(
                    "px_agent_chunk_ack_stalls_total",
                    help_="chunk sends that proceeded without an ack "
                          "(broker stopped acking within the stall budget)")
                return False
        return False

    def _write_shipped_spans(self, rows: list) -> None:
        try:
            trace.write_spans(self.store, rows)
        except Exception:
            from pixie_tpu import metrics as _metrics

            _metrics.counter_inc(
                "px_agent_span_write_errors_total",
                help_="spans that failed to persist to the local store")

    def _write_telemetry_rows(self, table, rows: list) -> None:
        try:
            if table in observe.SELF_TABLES:
                observe.write_rows(self.store, str(table), rows)
        except Exception:
            from pixie_tpu import metrics as _metrics

            _metrics.counter_inc(
                "px_agent_telemetry_write_errors_total",
                help_="flight-recorder rows that failed to persist to the "
                      "local store")

    def _flush_trace(self) -> None:
        """Persist buffered spans; never let telemetry failure block the
        exec_done/exec_error ack (an unacked query stalls the broker for
        the full query timeout)."""
        try:
            self.tracer.flush(store=self.store)
        except Exception:
            from pixie_tpu import metrics as _metrics

            _metrics.counter_inc(
                "px_agent_span_write_errors_total",
                help_="spans that failed to persist to the local store")


def main(argv=None):
    """`python -m pixie_tpu.services.agent --name pem1 --broker host:port
    [--connector seq_gen]` — standalone agent process (the pem_main analog)."""
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--name", required=True)
    ap.add_argument("--broker", required=True, help="host:port")
    ap.add_argument("--connector", action="append", default=[],
                    help="seq_gen | proc_stats | perf_profiler | "
                         "access_log:/path/to/log (repeatable)")
    ap.add_argument("--heartbeat-s", type=float, default=DEFAULT_HEARTBEAT_S)
    ap.add_argument("--auth-token", default=None,
                    help="shared secret; required if the broker enables auth")
    ap.add_argument("--healthz-port", type=int, default=None,
                    help="serve HTTP /healthz + /metrics on this port")
    ap.add_argument("--healthz-host", default="127.0.0.1",
                    help="bind address for the healthz listener (use the "
                         "pod IP / 0.0.0.0 for remote k8s probes)")
    ap.add_argument("--proc-scan-s", type=float, default=0.0,
                    help="scan /proc every N seconds, binding live PIDs to "
                         "UPIDs (+pods via cgroup) in the metadata state "
                         "(reference pids.cc); 0 disables")
    ap.add_argument("--watch-feed", default=None,
                    help="JSONL file of ResourceUpdates to tail into the "
                         "metadata state (the k8s watch fanout analog)")
    args = ap.parse_args(argv)
    host, port = args.broker.rsplit(":", 1)

    from pixie_tpu.collect.core import Collector

    collector = Collector()
    for cname in args.connector:
        if cname == "seq_gen":
            from pixie_tpu.collect.seq_gen import SeqGenConnector

            collector.register(SeqGenConnector())
        elif cname == "proc_stats":
            from pixie_tpu.collect.proc_stats import ProcStatsConnector

            collector.register(ProcStatsConnector())
        elif cname == "perf_profiler":
            from pixie_tpu.collect.perf_profiler import PerfProfilerConnector

            collector.register(PerfProfilerConnector())
        elif cname.startswith("access_log:"):
            from pixie_tpu.collect.access_log import AccessLogConnector

            collector.register(AccessLogConnector(cname.split(":", 1)[1]))
        elif cname.startswith("capture:"):
            # Replay a socket-event capture through the protocol parsers
            # (socket_tracer): capture:/path/to/capture.jsonl
            from pixie_tpu.collect.tracer import (
                CaptureFileSource,
                SocketTraceConnector,
            )

            path = cname.split(":", 1)[1]
            collector.register(SocketTraceConnector(
                CaptureFileSource(path), name=f"socket_tracer:{path}"))
        elif cname.startswith("tap:"):
            # Live tap proxy: tap:<listen_port>:<upstream_host>:<upstream_port>
            # — proxies traffic and traces every connection through it.
            from pixie_tpu.collect.tap import TapProxy
            from pixie_tpu.collect.tracer import SocketTraceConnector

            lport, uhost, uport = cname.split(":", 1)[1].split(":")
            tap = TapProxy(uhost, int(uport), listen_port=int(lport),
                           pid=os.getpid()).start()
            collector.register(SocketTraceConnector(
                tap.source, name=f"socket_tracer:tap:{tap.port}"))
        else:
            raise SystemExit(f"unknown connector {cname!r}")
    md_jobs = []
    if args.proc_scan_s > 0 or args.watch_feed:
        from pixie_tpu.metadata.state import global_manager

        mgr = global_manager()
        if args.proc_scan_s > 0:
            from pixie_tpu.metadata.proc_scanner import ProcScanner

            md_jobs.append((args.proc_scan_s,
                            ProcScanner(asid=mgr.current().asid).scan_into,
                            mgr))
        if args.watch_feed:
            from pixie_tpu.metadata.watch import ResourceUpdateFeed

            feed = ResourceUpdateFeed(mgr, args.watch_feed)
            md_jobs.append((1.0, lambda _m, feed=feed: feed.poll(), mgr))

    def _md_loop(period, fn, mgr):
        while True:
            try:
                fn(mgr)
            except Exception:
                pass  # metadata refresh must never kill the agent
            time.sleep(period)

    for period, fn, mgr in md_jobs:
        threading.Thread(target=_md_loop, args=(period, fn, mgr),
                         daemon=True).start()

    agent = Agent(args.name, host, int(port), collector=collector,
                  heartbeat_s=args.heartbeat_s, auth_token=args.auth_token,
                  healthz_port=args.healthz_port,
                  healthz_host=args.healthz_host)
    agent.start()
    try:
        while True:
            time.sleep(1.0)
            if agent.conn is None or agent.conn.closed:
                raise SystemExit("broker connection lost")
    except KeyboardInterrupt:
        pass
    finally:
        agent.stop()


if __name__ == "__main__":
    main()

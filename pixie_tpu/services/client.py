"""Python client for the broker's ExecuteScript API.

Reference: src/api/python/pxapi/client.py:100-262 (Conn/ScriptExecutor) — a
streaming client that connects, runs a script, and receives per-table row
batches + exec stats.
"""
from __future__ import annotations

import threading
from typing import Optional

from pixie_tpu.engine.result import QueryResult
from pixie_tpu.services import wire
from pixie_tpu.services.transport import Connection, dial
from pixie_tpu.status import PxError, Unavailable
from pixie_tpu.types import ColumnSchema, Relation


class QueryError(PxError):
    """Query failed at the broker.  `retry_after_s` is non-None when the
    failure was an admission-control shed (back off and retry); None means
    a real error (compile/exec/timeout) that retrying won't fix."""

    def __init__(self, msg: str, retry_after_s: Optional[float] = None):
        super().__init__(msg)
        self.retry_after_s = retry_after_s


class _Pending:
    def __init__(self):
        self.chunks: list = []
        self.stats: dict = {}
        self.schemas: Optional[dict] = None
        self.error: Optional[str] = None
        self.retry_after_s: Optional[float] = None
        self.done = threading.Event()


class Client:
    """Blocking client (the pxapi Conn analog).

    `tenant` identifies this client to the broker's admission controller
    (quotas, fair-share scheduling, per-tenant cache namespaces); it rides
    every execute_script frame and can be overridden per call.
    """

    def __init__(self, host: str, port: int, timeout_s: float = 120.0,
                 auth_token: Optional[str] = None,
                 tenant: Optional[str] = None):
        self.timeout_s = timeout_s
        self.tenant = tenant
        self._pending: dict[str, _Pending] = {}
        self._lock = threading.Lock()
        self._req = 0
        self.conn: Connection = dial(host, port, on_frame=self._on_frame,
                                     on_close=self._on_close)
        if auth_token is not None:
            self.conn.send(wire.encode_json(
                {"msg": "auth", "token": auth_token}))

    def close(self):
        self.conn.close()

    # ------------------------------------------------------------------ frames
    def _on_frame(self, conn: Connection, frame: bytes):
        kind, payload = wire.decode_frame(frame)
        meta = payload if kind == "json" else payload.wire_meta
        p = self._pending.get(meta.get("req_id", ""))
        if p is None:
            return
        msg = meta.get("msg")
        if kind == "host_batch" and msg == "result_chunk":
            p.chunks.append((meta["table"], payload))
        elif msg == "done":
            p.stats = meta.get("stats", {})
            p.done.set()
        elif msg == "schemas":
            p.schemas = meta["schemas"]
            p.done.set()
        elif msg == "error":
            p.error = meta.get("error", "unknown error")
            ra = meta.get("retry_after_s")
            p.retry_after_s = float(ra) if ra is not None else None
            p.done.set()

    def _on_close(self, conn: Connection):
        with self._lock:
            for p in self._pending.values():
                if not p.done.is_set():
                    p.error = "connection to broker lost"
                    p.done.set()

    def _new_pending(self) -> tuple[str, _Pending]:
        with self._lock:
            self._req += 1
            rid = f"c{self._req}"
            p = _Pending()
            self._pending[rid] = p
            return rid, p

    # --------------------------------------------------------------------- api
    def execute_script(
        self, script: str, func=None, func_args=None, now=None,
        default_limit=None, analyze: bool = False, funcs=None,
        tenant: Optional[str] = None,
    ) -> dict[str, QueryResult]:
        """funcs=[(prefix, func_name, func_args)] runs a multi-widget
        request as ONE fused broker query; results key by fused sink name,
        with exec_stats['sink_map'] mapping widget → sinks."""
        rid, p = self._new_pending()
        try:
            ok = self.conn.send(wire.encode_json({
                "msg": "execute_script", "req_id": rid, "script": script,
                "func": func, "func_args": func_args, "now": now,
                "default_limit": default_limit, "analyze": analyze,
                "funcs": [list(f) for f in funcs] if funcs else None,
                "tenant": tenant if tenant is not None else self.tenant,
            }))
            if not ok:
                raise Unavailable("broker connection closed")
            if not p.done.wait(timeout=self.timeout_s):
                raise Unavailable(f"query timed out after {self.timeout_s}s")
            if p.error:
                raise QueryError(p.error, retry_after_s=p.retry_after_s)
            out: dict[str, QueryResult] = {}
            for table, hb in p.chunks:
                meta_rel = getattr(hb, "wire_meta", {}).get("relation")
                rel = (Relation.from_dict(meta_rel) if meta_rel else
                       Relation([ColumnSchema(n, hb.dtypes[n])
                                 for n in hb.cols]))
                out[table] = QueryResult(
                    name=table, relation=rel, columns=hb.cols,
                    dictionaries=hb.dicts, exec_stats=dict(p.stats),
                )
            return out
        finally:
            with self._lock:
                self._pending.pop(rid, None)

    def schemas(self) -> dict[str, Relation]:
        rid, p = self._new_pending()
        try:
            if not self.conn.send(
                wire.encode_json({"msg": "list_schemas", "req_id": rid})
            ):
                raise Unavailable("broker connection closed")
            if not p.done.wait(timeout=self.timeout_s):
                raise Unavailable("schema request timed out")
            if p.error:
                raise QueryError(p.error)
            return {t: Relation.from_dict(r) for t, r in (p.schemas or {}).items()}
        finally:
            with self._lock:
                self._pending.pop(rid, None)

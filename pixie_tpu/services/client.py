"""Python client for the broker's ExecuteScript API.

Reference: src/api/python/pxapi/client.py:100-262 (Conn/ScriptExecutor) — a
streaming client that connects, runs a script, and receives per-table row
batches + exec stats.

Fault tolerance: idempotent (non-mutation) scripts auto-retry with jittered
backoff (`PL_CLIENT_RETRIES`) when the broker sheds them (retry-after), marks
an infrastructure failure retryable (agent eviction past the broker's own
retry budget), or the broker connection itself drops — the client redials
with backoff instead of dying on the stale socket, so a broker restart is a
latency blip, not an error.  Mutation scripts (tracepoint deploys) are NEVER
auto-retried: a re-issued mutation is not idempotent.
"""
from __future__ import annotations

import random
import threading
import time
from typing import Optional

from pixie_tpu import flags
from pixie_tpu.engine.result import QueryResult
from pixie_tpu.services import wire
from pixie_tpu.services.transport import Connection, dial
from pixie_tpu.status import PxError, Unavailable
from pixie_tpu.types import ColumnSchema, Relation

flags.define_int(
    "PL_CLIENT_RETRIES", 3,
    "client-side auto-retries for idempotent (non-mutation) scripts on "
    "shed (retry-after), retryable infrastructure errors, or a lost broker "
    "connection (redialed with backoff); 0 disables")

#: base/cap for the client's jittered exponential backoff (seconds)
RETRY_BACKOFF_BASE_S = 0.1
RETRY_BACKOFF_MAX_S = 5.0

#: tokens whose presence marks a script as a MUTATION — never auto-retried
#: (the broker's error envelope is authoritative when one arrives; this
#: lexical check covers the conn-lost path where no envelope exists)
_MUTATION_TOKENS = ("UpsertTracepoint", "DeleteTracepoint")


class QueryError(PxError):
    """Query failed at the broker.  `retry_after_s` is non-None when the
    failure was an admission-control shed (back off and retry); `retryable`
    marks an infrastructure failure of an idempotent query (agent eviction
    past the broker's retry budget, no live agents) that is safe to
    re-issue.  Both None/False means a real error (compile/exec) that
    retrying won't fix."""

    def __init__(self, msg: str, retry_after_s: Optional[float] = None,
                 retryable: bool = False):
        super().__init__(msg)
        self.retry_after_s = retry_after_s
        self.retryable = retryable


def _is_mutation(script: str) -> bool:
    return any(tok in script for tok in _MUTATION_TOKENS)


class _Pending:
    def __init__(self):
        self.chunks: list = []
        self.stats: dict = {}
        self.schemas: Optional[dict] = None
        self.reply: Optional[dict] = None
        self.error: Optional[str] = None
        self.retry_after_s: Optional[float] = None
        self.retryable: bool = False
        self.done = threading.Event()


class Client:
    """Blocking client (the pxapi Conn analog).

    `tenant` identifies this client to the broker's admission controller
    (quotas, fair-share scheduling, per-tenant cache namespaces); it rides
    every execute_script frame and can be overridden per call.
    """

    def __init__(self, host: str, port: int, timeout_s: float = 120.0,
                 auth_token: Optional[str] = None,
                 tenant: Optional[str] = None):
        self.timeout_s = timeout_s
        self.tenant = tenant
        self._addr = (host, port)
        self._auth_token = auth_token
        self._pending: dict[str, _Pending] = {}
        self._lock = threading.Lock()
        self._req = 0
        #: retries the LAST execute_script paid (the CLI surfaces
        #: "retried N×" from this instead of a stack trace)
        self.last_retries = 0
        self.conn: Connection = self._dial()

    def _dial(self) -> Connection:
        conn = dial(*self._addr, on_frame=self._on_frame,
                    on_close=self._on_close)
        conn.label = "client"  # fault-injection target (faultinject.py)
        if self._auth_token is not None:
            conn.send(wire.encode_json(
                {"msg": "auth", "token": self._auth_token}))
        return conn

    def _ensure_conn(self) -> None:
        """Redial a dead broker connection (one attempt; the retry loop
        provides the backoff).  A broker restart invalidates the old
        socket — dying on it would turn every restart into client errors."""
        if self.conn is not None and not self.conn.closed:
            return
        self.conn = self._dial()

    def close(self):
        self.conn.close()

    # ------------------------------------------------------------------ frames
    def _on_frame(self, conn: Connection, frame: bytes):
        kind, payload = wire.decode_frame(frame)
        meta = payload if kind == "json" else payload.wire_meta
        p = self._pending.get(meta.get("req_id", ""))
        if p is None:
            return
        msg = meta.get("msg")
        if kind == "host_batch" and msg == "result_chunk":
            p.chunks.append((meta["table"], payload))
        elif msg == "done":
            p.stats = meta.get("stats", {})
            p.done.set()
        elif msg == "schemas":
            p.schemas = meta["schemas"]
            p.done.set()
        elif msg in ("quota_ok", "quotas", "heat_map", "rehome_result"):
            p.reply = meta
            p.done.set()
        elif msg == "error":
            p.error = meta.get("error", "unknown error")
            ra = meta.get("retry_after_s")
            p.retry_after_s = float(ra) if ra is not None else None
            p.retryable = bool(meta.get("retryable", False))
            p.done.set()

    def _on_close(self, conn: Connection):
        with self._lock:
            for p in self._pending.values():
                if not p.done.is_set():
                    p.error = "connection to broker lost"
                    p.retryable = True  # the redial path owns this
                    p.done.set()

    def _new_pending(self) -> tuple[str, _Pending]:
        with self._lock:
            self._req += 1
            rid = f"c{self._req}"
            p = _Pending()
            self._pending[rid] = p
            return rid, p

    # --------------------------------------------------------------------- api
    def execute_script(
        self, script: str, func=None, func_args=None, now=None,
        default_limit=None, analyze: bool = False, funcs=None,
        tenant: Optional[str] = None, explain: bool = False,
    ) -> dict[str, QueryResult]:
        """funcs=[(prefix, func_name, func_args)] runs a multi-widget
        request as ONE fused broker query; results key by fused sink name,
        with exec_stats['sink_map'] mapping widget → sinks.

        Idempotent scripts transparently retry/reconnect (see module doc);
        the retry count lands in every result's exec_stats["client_retries"]
        and in `self.last_retries`."""
        budget = int(flags.get("PL_CLIENT_RETRIES"))
        mutation = _is_mutation(script)
        rng = random.Random()
        attempt = 0
        self.last_retries = 0
        while True:
            try:
                out = self._execute_once(
                    script, func=func, func_args=func_args, now=now,
                    default_limit=default_limit, analyze=analyze,
                    funcs=funcs, tenant=tenant, explain=explain)
                self.last_retries = attempt
                if attempt:
                    from pixie_tpu import metrics as _metrics

                    _metrics.counter_inc(
                        "px_client_retries_total", float(attempt),
                        help_="client-side query retries that led to a "
                              "successful answer")
                for r in out.values():
                    r.exec_stats["client_retries"] = attempt
                return out
            except QueryError as e:
                retriable = (e.retry_after_s is not None or e.retryable)
                if mutation or not retriable or attempt >= budget:
                    raise
                delay = (e.retry_after_s if e.retry_after_s is not None
                         else min(RETRY_BACKOFF_BASE_S * (2 ** attempt),
                                  RETRY_BACKOFF_MAX_S))
                time.sleep(delay * (0.5 + rng.random()))
            except Unavailable as e:
                # reconnect-and-retry ONLY when the request never reached a
                # live broker (stale socket / dial refused after a restart);
                # a response timeout is NOT auto-retried — the query may
                # still be executing and retries would double the load
                if (mutation or attempt >= budget
                        or not getattr(e, "reconnect", False)):
                    raise
                time.sleep(min(RETRY_BACKOFF_BASE_S * (2 ** attempt),
                               RETRY_BACKOFF_MAX_S) * (0.5 + rng.random()))
            attempt += 1
            # kept current even when the budget ends in a raise: the CLI
            # reports "query failed (retried Nx)" from this
            self.last_retries = attempt

    def _execute_once(
        self, script: str, func=None, func_args=None, now=None,
        default_limit=None, analyze: bool = False, funcs=None,
        tenant: Optional[str] = None, explain: bool = False,
    ) -> dict[str, QueryResult]:
        rid, p = self._new_pending()
        try:
            try:
                self._ensure_conn()
            except OSError as e:
                # broker still down (restart in progress): retryable
                ua = Unavailable(f"broker unreachable: {e}")
                ua.reconnect = True
                raise ua from e
            ok = self.conn.send(wire.encode_json({
                "msg": "execute_script", "req_id": rid, "script": script,
                "func": func, "func_args": func_args, "now": now,
                "default_limit": default_limit, "analyze": analyze,
                "explain": explain,
                "funcs": [list(f) for f in funcs] if funcs else None,
                "tenant": tenant if tenant is not None else self.tenant,
            }))
            if not ok:
                ua = Unavailable("broker connection closed")
                ua.reconnect = True
                raise ua
            if not p.done.wait(timeout=self.timeout_s):
                raise Unavailable(f"query timed out after {self.timeout_s}s")
            if p.error:
                raise QueryError(p.error, retry_after_s=p.retry_after_s,
                                 retryable=p.retryable)
            out: dict[str, QueryResult] = {}
            for table, hb in p.chunks:
                meta_rel = getattr(hb, "wire_meta", {}).get("relation")
                rel = (Relation.from_dict(meta_rel) if meta_rel else
                       Relation([ColumnSchema(n, hb.dtypes[n])
                                 for n in hb.cols]))
                out[table] = QueryResult(
                    name=table, relation=rel, columns=hb.cols,
                    dictionaries=hb.dicts, exec_stats=dict(p.stats),
                )
            return out
        finally:
            with self._lock:
                self._pending.pop(rid, None)

    # ------------------------------------------------------------ control plane
    def set_quota(self, tenant: str, qps=None, concurrency=None,
                  weight=None) -> dict:
        """Write one tenant's LIVE quota record (broker control plane):
        fields left None keep the PL_TENANT_* env-spec default for that
        field; qps/concurrency 0 = explicitly unlimited.  The broker
        validates (malformed specs raise QueryError), applies it to the
        scheduler in place, and persists it in its KV — the record
        survives broker restart.  Returns the tenant's effective quotas."""
        reply = self._control_rpc({
            "msg": "set_quota", "tenant": tenant, "qps": qps,
            "concurrency": concurrency, "weight": weight})
        return reply.get("effective") or {}

    def clear_quota(self, tenant: str) -> dict:
        """Drop a tenant's live quota record (back to env-spec defaults)."""
        reply = self._control_rpc({"msg": "set_quota", "tenant": tenant})
        return reply.get("effective") or {}

    def get_quotas(self) -> dict:
        """{tenants: {tenant: effective quota}, rate_model: snapshot} —
        the control plane's read side."""
        reply = self._control_rpc({"msg": "get_quotas"})
        return {"tenants": reply.get("quotas") or {},
                "rate_model": reply.get("rate_model") or {}}

    def heat_map(self) -> dict:
        """The cluster storage observatory ("df for the data plane"):
        {agents: {name: {shard_heat, storage_state}}, tables: {name:
        {shards, skew, rows_scanned, bytes}}} aggregated by the broker from
        live agents' storage_report RPCs."""
        reply = self._control_rpc({"msg": "heat_map"})
        return {"agents": reply.get("agents") or {},
                "tables": reply.get("tables") or {}}

    def rehome(self, agent: str, target: Optional[str] = None,
               reason: str = "manual") -> dict:
        """Operator shard re-homing: move `agent`'s sealed shard data onto
        `target` (broker picks one when None) over the replication channel
        and flip the shard map — the drain half of a decommission.
        Returns the broker's {ok, donor, target, tables, reason} verdict;
        a not-ok reply means ownership stayed with the donor."""
        return self._control_rpc({"msg": "rehome_agent", "agent": agent,
                                  "target": target, "reason": reason})

    def _control_rpc(self, meta: dict) -> dict:
        rid, p = self._new_pending()
        try:
            self._ensure_conn()
            if not self.conn.send(wire.encode_json(dict(meta, req_id=rid))):
                raise Unavailable("broker connection closed")
            if not p.done.wait(timeout=self.timeout_s):
                raise Unavailable(
                    f"{meta.get('msg')} timed out after {self.timeout_s}s")
            if p.error:
                raise QueryError(p.error)
            return p.reply or {}
        finally:
            with self._lock:
                self._pending.pop(rid, None)

    def schemas(self) -> dict[str, Relation]:
        rid, p = self._new_pending()
        try:
            if not self.conn.send(
                wire.encode_json({"msg": "list_schemas", "req_id": rid})
            ):
                raise Unavailable("broker connection closed")
            if not p.done.wait(timeout=self.timeout_s):
                raise Unavailable("schema request timed out")
            if p.error:
                raise QueryError(p.error)
            return {t: Relation.from_dict(r) for t, r in (p.schemas or {}).items()}
        finally:
            with self._lock:
                self._pending.pop(rid, None)

"""Tracepoint lifecycle: registry + deployment of dynamic-trace tables.

Reference: the metadata service's tracepoint controller persists and
reconciles tracepoints (src/vizier/services/metadata/controllers/tracepoint/),
agents' TracepointManager deploys them into Stirling
(pem/tracepoint_manager.h:48) which compiles the program and materializes a
new table (source_connectors/dynamic_tracer/).

Here deployment = create the probe's output table in the agent's store and
track state/TTL; the probe ATTACHMENT is pluggable via `probe_driver` (a
callable receiving the spec + table) because kernel eBPF is host-specific —
without a driver the table simply stays empty until a producer writes it,
which is also the reference's observable behavior pre-attach.
"""
from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable, Optional

from pixie_tpu.status import NotFound
from pixie_tpu.types import Relation


@dataclasses.dataclass
class TracepointInfo:
    name: str
    table_name: str
    program: str
    probe: str
    ttl_ns: int
    created_ns: int
    state: str = "running"  # pending | running | terminated | failed
    status: str = ""

    def expires_ns(self) -> int:
        return self.created_ns + self.ttl_ns


class TracepointManager:
    """Deployed-tracepoint registry for one store (agent or library use)."""

    def __init__(self, store, kv=None,
                 probe_driver: Optional[Callable] = None):
        self.store = store
        self.kv = kv
        self.probe_driver = probe_driver
        self._tps: dict[str, TracepointInfo] = {}
        self._lock = threading.Lock()
        if kv is not None:
            import json

            for _k, raw in kv.scan("tracepoint/"):
                d = json.loads(raw.decode())
                self._tps[d["name"]] = TracepointInfo(**d)

    # ------------------------------------------------------------- lifecycle
    def upsert(self, spec: dict, now_ns: Optional[int] = None) -> TracepointInfo:
        """Deploy (or refresh) a tracepoint: create its output table and mark
        it running (reference UpsertTracepoint semantics: same-name upsert
        refreshes the TTL)."""
        now = now_ns if now_ns is not None else time.time_ns()
        rel = Relation.from_dict(spec["schema"])
        with self._lock:
            tp = self._tps.get(spec["name"])
            # Ownership guard: a script-supplied table_name that already exists
            # in the store must be owned by THIS tracepoint — never a core
            # telemetry table (http_events, ...) and never another
            # tracepoint's output table.  The reference confines dynamic
            # trace output to its own new tables.
            owner = next((t.name for t in self._tps.values()
                          if t.table_name == spec["table_name"]), None)
            # Another tracepoint owning the name rejects even when the store
            # lacks the table (kv-restored registry + fresh store after a
            # broker restart must not let names be stolen).
            if ((owner is not None and owner != spec["name"])
                    or (owner is None and self.store.has(spec["table_name"]))):
                from pixie_tpu.status import InvalidArgument
                whose = (f"tracepoint {owner!r}" if owner is not None
                         else "a non-tracepoint table")
                raise InvalidArgument(
                    f"tracepoint table {spec['table_name']!r} collides with "
                    f"{whose}; choose a new table name")
            if tp is None:
                tp = TracepointInfo(
                    name=spec["name"], table_name=spec["table_name"],
                    program=spec["program"], probe=spec.get("probe", "kprobe"),
                    ttl_ns=int(spec["ttl_ns"]), created_ns=now,
                )
                self._tps[tp.name] = tp
            else:
                tp.created_ns = now  # TTL refresh
                tp.ttl_ns = int(spec["ttl_ns"])
                tp.state = "running"
            if self.store.has(tp.table_name):
                # Redeploy with a CHANGED program/schema replaces the table —
                # the compiling side already sees the new relation, so keeping
                # the old one would desync schema and data (in-memory
                # telemetry is droppable by design).
                if self.store.table(tp.table_name).relation != rel:
                    self.store.drop(tp.table_name)
                    self.store.create(tp.table_name, rel)
            else:
                self.store.create(tp.table_name, rel)
            if self.probe_driver is not None:
                try:
                    self.probe_driver(spec, self.store.table(tp.table_name))
                except Exception as e:
                    tp.state = "failed"
                    tp.status = str(e)
            self._persist(tp)
            return tp

    def delete(self, name: str) -> None:
        with self._lock:
            tp = self._tps.get(name)
            if tp is None:
                raise NotFound(f"no tracepoint {name!r}")
            tp.state = "terminated"
            self._persist(tp)

    def expire(self, now_ns: Optional[int] = None) -> list[str]:
        """TTL sweep: running tracepoints past their TTL terminate (the
        reference's reconciliation loop)."""
        now = now_ns if now_ns is not None else time.time_ns()
        out = []
        with self._lock:
            for tp in self._tps.values():
                if tp.state == "running" and now >= tp.expires_ns():
                    tp.state = "terminated"
                    tp.status = "ttl expired"
                    self._persist(tp)
                    out.append(tp.name)
        return out

    def apply(self, mutations: list[dict]) -> list[TracepointInfo]:
        """Apply a CompiledQuery.mutations list.  Deleting an unknown
        tracepoint is a no-op (agents may never have seen it)."""
        out = []
        for m in mutations:
            if m.get("kind") == "tracepoint":
                out.append(self.upsert(m))
            elif m.get("kind") == "delete_tracepoint":
                try:
                    self.delete(m["name"])
                except NotFound:
                    pass
        return out

    # ----------------------------------------------------------------- views
    def list(self) -> list[TracepointInfo]:  # noqa: A003
        self.expire()
        with self._lock:
            return sorted(self._tps.values(), key=lambda t: t.name)

    def _persist(self, tp: TracepointInfo) -> None:
        if self.kv is not None:
            self.kv.set_json(f"tracepoint/{tp.name}", dataclasses.asdict(tp))

"""Versioned binary wire format for control + data messages.

Replaces the reference's protobuf RowBatchData / TransferResultChunk
(src/carnot/carnotpb/carnot.proto:30-96, vizierpb RowBatchData) with a
self-describing frame:

    MAGIC "PXW1" | u32 header_len | header JSON (utf-8) | buffer bytes...

The header carries the message kind, JSON-safe metadata, and a buffer table
(name, numpy dtype str, length); numeric column data travels as raw
little-endian buffers, NEVER as pickled objects — a malicious peer can at
worst produce wrong values, not code execution (the round-1 advisor flagged
pickle here; this is the replacement).

String payloads (dictionary value lists, object-array string keys) ship as
length-prefixed raw UTF-8: one `|u1` bytes buffer plus an `<i8` offsets
buffer (n+1 entries), NOT as JSON lists — JSON escaping dominated frame
encode time for large string dictionaries.  Non-string values (UINT128
tuples, None) fall back to the JSON `jsonvals` path.

Optional payload compaction (`PL_WIRE_COMPRESS`): when set, the buffer
section of a frame whose raw size exceeds the threshold is compressed as one
blob and announced in the header (`comp`).  Accepted values: `zlib`,
`zlib:<threshold_bytes>`, `lz4[:<threshold>]` (falls back to zlib when the
lz4 module is absent), empty/`0`/`off` = disabled.  The decoder honors
whatever the header announces regardless of the local setting, with a
MAX_FRAME guard on the announced raw size (no zip bombs).

Kinds:
  json         — control messages ({} metadata only)
  host_batch   — HostBatch: dtypes, dictionaries, columns
  partial_agg  — PartialAggBatch: key values + flattened UDA state leaves
"""
from __future__ import annotations

import json
import struct
import zlib

import numpy as np

from pixie_tpu import flags as _flags
from pixie_tpu.status import InvalidArgument
from pixie_tpu.table.dictionary import Dictionary
from pixie_tpu.types import STORAGE_DTYPE, DataType as DT

_flags.define_str(
    "PL_WIRE_COMPRESS", "",
    "wire payload compaction: zlib[:<threshold>] | lz4[:<threshold>] | "
    "off.  Live: re-read per frame so tests/operators can toggle "
    "per-process", live=True)

MAGIC = b"PXW1"
_HDR = struct.Struct("<4sI")

#: frames larger than this are rejected on decode (also bounds the announced
#: decompressed size of a compressed payload)
MAX_WIRE_BYTES = 1 << 30

#: numpy dtype allowlist for wire buffers (validated on decode).
_ALLOWED_DTYPES = {
    "<i4", "<i8", "<u4", "<u8", "<f4", "<f8", "|b1", "<i2", "<u2", "|i1", "|u1"
}

#: default compression threshold: small frames gain nothing and pay latency
DEFAULT_COMPRESS_THRESHOLD = 1 << 16


def _norm_dtype(d: np.dtype) -> str:
    s = np.dtype(d).str
    if s == "=i8":
        s = "<i8"
    return s


# --------------------------------------------------------------- compression


def _compress_cfg() -> tuple[str, int] | None:
    """(codec, threshold) from PL_WIRE_COMPRESS, or None when disabled.

    A LIVE flag: re-read on every frame (not latched at import) — tests
    and operators toggle it per-process, and the parse is nanoseconds.
    """
    raw = str(_flags.get("PL_WIRE_COMPRESS")).strip().lower()
    if not raw or raw in ("0", "off", "false", "no"):
        return None
    codec, _, thr = raw.partition(":")
    if codec in ("1", "true", "yes", "on"):
        codec = "zlib"
    try:
        threshold = int(thr) if thr else DEFAULT_COMPRESS_THRESHOLD
    except ValueError:
        threshold = DEFAULT_COMPRESS_THRESHOLD
    if codec == "lz4" and _lz4() is None:
        codec = "zlib"
    if codec not in ("zlib", "lz4"):
        codec = "zlib"
    return codec, threshold


def _lz4():
    try:
        import lz4.frame as lz4f  # optional; the container may not ship it

        return lz4f
    except Exception:
        return None


def _compress(codec: str, raw: bytes) -> bytes:
    if codec == "lz4":
        lz4f = _lz4()
        if lz4f is not None:
            return lz4f.compress(raw)
    return zlib.compress(raw, 1)  # level 1: this is a transport, not an archive


def _decompress(codec: str, blob, raw_len: int) -> bytes:
    # Allocation is bounded BEFORE expansion, not checked after: the
    # announced size gates the limit, and the codecs run with max_length so
    # a bomb announcing a small `raw` stops at raw_len+1 produced bytes
    # instead of materializing its full expansion first.
    # raw_len <= 0 is never produced by the encoder (empty buffer sections
    # don't compress) and max_length=0 means UNLIMITED to zlib — rejecting
    # it here is what keeps the bound real.
    if raw_len <= 0 or raw_len > MAX_WIRE_BYTES:
        raise InvalidArgument(
            f"wire: announced decompressed size {raw_len} out of bounds")
    if codec == "zlib":
        d = zlib.decompressobj()
        out = d.decompress(blob, raw_len)
        if len(out) != raw_len or (
                d.unconsumed_tail and d.decompress(d.unconsumed_tail, 1)):
            raise InvalidArgument("wire: decompressed size mismatch")
    elif codec == "lz4":
        lz4f = _lz4()
        if lz4f is None:
            raise InvalidArgument("wire: lz4 frame received but lz4 unavailable")
        d = lz4f.LZ4FrameDecompressor()
        out = d.decompress(bytes(blob), max_length=raw_len)
        if len(out) != raw_len or d.decompress(b"", 1):
            raise InvalidArgument("wire: decompressed size mismatch")
    else:
        raise InvalidArgument(f"wire: unknown compression codec {codec!r}")
    return out


# ------------------------------------------------------------------- encoding


def _frame(kind: str, meta: dict, bufs: list[tuple[str, np.ndarray]]) -> bytes:
    table = []
    chunks = []
    total = 0
    for name, arr in bufs:
        arr = np.ascontiguousarray(arr)
        s = _norm_dtype(arr.dtype)
        if s not in _ALLOWED_DTYPES:
            raise InvalidArgument(f"wire: dtype {s} of buffer {name!r} not allowed")
        # Zero-copy column handoff: a read-only memoryview over the array's
        # own bytes (tobytes() would materialize an intermediate copy of
        # every result column per query); the single copy happens once, in
        # the final join that builds the frame.  Empty arrays can't cast
        # (zeros in shape/strides) — their tobytes() is free anyway.
        raw = memoryview(arr).cast("B") if arr.size else arr.tobytes()
        table.append({"name": name, "dtype": s, "shape": list(arr.shape),
                      "nbytes": len(raw)})
        chunks.append(raw)
        total += len(raw)
    hdr: dict = {"kind": kind, "meta": meta, "bufs": table}
    cfg = _compress_cfg()
    if cfg is not None and total >= cfg[1] and chunks:
        codec, _thr = cfg
        raw = b"".join(chunks)
        blob = _compress(codec, raw)
        if len(blob) < len(raw):  # incompressible payloads ship raw
            hdr["comp"] = {"codec": codec, "raw": len(raw)}
            chunks = [blob]
            from pixie_tpu import metrics as _metrics

            _metrics.counter_inc(
                "px_wire_compressed_frames_total",
                help_="wire frames whose buffer section was compressed")
            _metrics.counter_inc(
                "px_wire_compressed_bytes_saved_total",
                float(len(raw) - len(blob)),
                help_="buffer bytes saved by wire compression")
    header = json.dumps(hdr).encode()
    return b"".join([_HDR.pack(MAGIC, len(header)), header, *chunks])


def encode_json(meta: dict) -> bytes:
    return _frame("json", meta, [])


def encode_error(req_id, error, retry_after_s=None, retryable=None) -> bytes:
    """The error envelope, optionally carrying an admission-control
    retry-after hint (seconds) and/or a retryable marker.  Clients surface
    `retry_after_s` so a shed query backs off instead of hammering a
    saturated broker; `retryable=True` marks an INFRASTRUCTURE failure of
    an idempotent (non-mutation) query — agent eviction with the retry
    budget exhausted, no live agents — that a client may transparently
    re-issue.  Compile/exec errors never set it: retrying won't fix them."""
    meta = {"msg": "error", "req_id": req_id, "error": str(error)}
    if retry_after_s is not None:
        meta["retry_after_s"] = round(float(retry_after_s), 3)
    if retryable is not None:
        meta["retryable"] = bool(retryable)
    return _frame("json", meta, [])


def encode_json_raw(meta: dict, raw_fields: dict[str, str]) -> bytes:
    """encode_json with PRE-SERIALIZED JSON values spliced in as extra
    top-level meta keys.

    The broker's warm-query dispatch caches each agent plan's JSON once per
    compiled split; re-running json.dumps over the whole plan dict on every
    query was measurable interactive latency.  The decoder is unchanged —
    the spliced frame is byte-for-byte a normal json frame.
    """
    for k in raw_fields:
        if k in meta:
            raise InvalidArgument(f"wire: raw field {k!r} collides with meta")
    meta_json = json.dumps(meta)
    items = ",".join(f"{json.dumps(k)}:{v}" for k, v in raw_fields.items())
    if items:
        merged = (f"{{{items}}}" if meta_json == "{}"
                  else f"{meta_json[:-1]},{items}}}")
    else:
        merged = meta_json
    header = (f'{{"kind":"json","meta":{merged},"bufs":[]}}').encode()
    return b"".join([_HDR.pack(MAGIC, len(header)), header])


def _u128_jsonable(v):
    from pixie_tpu.types import UInt128

    if v is None:
        return None
    if isinstance(v, UInt128):
        return [v.high, v.low]
    return list(v)


def _strbuf_encode(vals: list) -> tuple[np.ndarray, np.ndarray] | None:
    """Length-prefixed UTF-8 packing of a pure-string list: (bytes |u1,
    offsets <i8 of n+1 entries).  None when any value is not a str (the
    caller falls back to jsonvals)."""
    enc = []
    for v in vals:
        if type(v) is not str:
            return None
        enc.append(v.encode())
    offs = np.zeros(len(enc) + 1, dtype=np.int64)
    if enc:
        np.cumsum([len(b) for b in enc], out=offs[1:])
    data = np.frombuffer(b"".join(enc), dtype=np.uint8)
    return data, offs


def _strbuf_decode(data: np.ndarray, offs: np.ndarray) -> list:
    if offs.ndim != 1 or len(offs) == 0:
        raise InvalidArgument("wire: bad string offsets buffer")
    blob = data.tobytes()
    ends = offs.tolist()
    if ends[0] != 0 or ends[-1] != len(blob) or any(
            a > b for a, b in zip(ends, ends[1:])):
        raise InvalidArgument("wire: string offsets out of bounds")
    return [blob[a:b].decode() for a, b in zip(ends, ends[1:])]


def _dict_values_jsonable(d: Dictionary, dt: DT) -> list:
    if dt == DT.UINT128:
        return [_u128_jsonable(v) for v in d.values()]
    return d.values()


def _dict_values_restore(vals: list, dt: DT) -> list:
    if dt == DT.UINT128:
        from pixie_tpu.types import UInt128

        # canonical in-memory form is UInt128 (metadata UDFs read .high/.pid)
        return [UInt128(*v) if v is not None else None for v in vals]
    return vals


def encode_host_batch(hb, extra_meta: dict | None = None) -> bytes:
    """HostBatch → frame (reference: RowBatchData on the result stream)."""
    dicts_meta: dict = {}
    bufs: list[tuple[str, np.ndarray]] = []
    for n, d in hb.dicts.items():
        packed = (_strbuf_encode(d.values())
                  if hb.dtypes[n] == DT.STRING else None)
        if packed is not None:
            data, offs = packed
            dicts_meta[n] = {"strbuf": True}
            bufs.append((f"d:{n}", data))
            bufs.append((f"do:{n}", offs))
        else:
            dicts_meta[n] = {"jsonvals": _dict_values_jsonable(d, hb.dtypes[n])}
    meta = {
        "dtypes": {n: int(t) for n, t in hb.dtypes.items()},
        "dicts": dicts_meta,
        "order": list(hb.cols),
    }
    if extra_meta:
        meta.update(extra_meta)
    return _frame("host_batch", meta, bufs + [(n, hb.cols[n]) for n in hb.cols])


def encode_partial_agg(pb, extra_meta: dict | None = None) -> bytes:
    """PartialAggBatch → frame (reference: serialized-UDA partial rows,
    planpb/plan.proto:250-257)."""
    key_meta = {}
    bufs: list[tuple[str, np.ndarray]] = []
    for name, vals in pb.key_cols.items():
        dt = pb.key_dtypes[name]
        arr = np.asarray(vals)
        if arr.dtype == object:
            if dt == DT.UINT128:
                key_meta[name] = {
                    "jsonvals": [_u128_jsonable(v) for v in arr.tolist()]
                }
            else:
                packed = _strbuf_encode(arr.tolist())
                if packed is not None:
                    data, offs = packed
                    key_meta[name] = {"strbuf": True}
                    bufs.append((f"kd:{name}", data))
                    bufs.append((f"ko:{name}", offs))
                else:
                    key_meta[name] = {"jsonvals": arr.tolist()}
        else:
            key_meta[name] = {"buf": f"k:{name}"}
            bufs.append((f"k:{name}", arr))
    states_meta = {}
    for out_name, tree in pb.states.items():
        paths = []
        for path, leaf in _flatten(tree):
            bname = f"s:{out_name}:{path}"
            bufs.append((bname, np.asarray(leaf)))
            paths.append(path)
        states_meta[out_name] = paths
    meta = {
        "key_dtypes": {k: int(v) for k, v in pb.key_dtypes.items()},
        "in_types": {k: (int(v) if v is not None else None) for k, v in pb.in_types.items()},
        "keys": key_meta,
        "states": states_meta,
        "key_order": list(pb.key_cols),
    }
    if extra_meta:
        meta.update(extra_meta)
    return _frame("partial_agg", meta, bufs)


def _flatten(tree, prefix="") -> list[tuple[str, np.ndarray]]:
    if isinstance(tree, dict):
        out = []
        for k in sorted(tree):
            if not isinstance(k, str) or "/" in k:
                raise InvalidArgument(f"wire: bad state key {k!r}")
            p = f"{prefix}/{k}" if prefix else k
            out.extend(_flatten(tree[k], p))
        return out
    return [(prefix, tree)]


def _unflatten(paths: dict[str, np.ndarray]):
    if list(paths) == [""]:
        return paths[""]
    root: dict = {}
    for path, leaf in paths.items():
        parts = path.split("/")
        d = root
        for p in parts[:-1]:
            d = d.setdefault(p, {})
        d[parts[-1]] = leaf
    return root


# ------------------------------------------------------------------- decoding


def _strbuf_lookup(bufs: dict, data_name: str, offs_name: str) -> list:
    if data_name not in bufs or offs_name not in bufs:
        raise InvalidArgument(f"wire: missing string buffers for {data_name!r}")
    data, offs = bufs[data_name], bufs[offs_name]
    if _norm_dtype(data.dtype) != "|u1" or _norm_dtype(offs.dtype) != "<i8":
        raise InvalidArgument("wire: bad string buffer dtypes")
    return _strbuf_decode(data.reshape(-1), offs.reshape(-1))


def decode_frame(data: bytes):
    """bytes → (kind, payload).

    json        → (kind, meta dict)
    host_batch  → (kind, HostBatch-with-meta)
    partial_agg → (kind, PartialAggBatch-with-meta)
    The original meta dict is attached as `.wire_meta` on decoded objects.
    """
    if len(data) < _HDR.size:
        raise InvalidArgument("wire: truncated frame")
    magic, hlen = _HDR.unpack_from(data)
    if magic != MAGIC:
        raise InvalidArgument(f"wire: bad magic {magic!r}")
    if _HDR.size + hlen > len(data):
        raise InvalidArgument("wire: truncated header")
    header = json.loads(data[_HDR.size : _HDR.size + hlen].decode())
    kind = header["kind"]
    meta = header["meta"]
    # memoryview: the buffer section of a large result frame must not be
    # copied wholesale just to re-slice it per column
    body = memoryview(data)[_HDR.size + hlen:]
    comp = header.get("comp")
    if comp:
        body = _decompress(str(comp.get("codec")), body, int(comp.get("raw", -1)))
    bufs: dict[str, np.ndarray] = {}
    off = 0
    for b in header["bufs"]:
        s = b["dtype"]
        if s not in _ALLOWED_DTYPES:
            raise InvalidArgument(f"wire: dtype {s} not allowed")
        nb = int(b["nbytes"])
        if off + nb > len(body):
            raise InvalidArgument("wire: truncated buffer")
        arr = np.frombuffer(body[off : off + nb], dtype=np.dtype(s))
        # Checked-Python-int product: np.prod would wrap in int64 on an
        # adversarial shape like [2**40, 2**40] and falsely pass.
        import math

        shape = tuple(int(x) for x in b["shape"])
        if any(d < 0 for d in shape) or math.prod(shape) * arr.itemsize != nb:
            raise InvalidArgument("wire: buffer shape/nbytes mismatch")
        bufs[b["name"]] = arr.reshape(shape).copy()  # writable, owned
        off += nb

    if kind == "json":
        return kind, meta
    if kind == "host_batch":
        from pixie_tpu.engine.executor import HostBatch

        dtypes = {n: DT(v) for n, v in meta["dtypes"].items()}
        dicts = {}
        for n, spec in meta["dicts"].items():
            if isinstance(spec, dict) and spec.get("strbuf"):
                dicts[n] = Dictionary(_strbuf_lookup(bufs, f"d:{n}", f"do:{n}"))
            else:
                vals = spec["jsonvals"] if isinstance(spec, dict) else spec
                dicts[n] = Dictionary(_dict_values_restore(vals, dtypes[n]))
        cols = {}
        for n in meta["order"]:
            if n not in bufs:
                raise InvalidArgument(f"wire: missing column buffer {n!r}")
            want = STORAGE_DTYPE[dtypes[n]]
            cols[n] = bufs[n].astype(want, copy=False)
        hb = HostBatch(dtypes, dicts, cols)
        hb.wire_meta = meta  # type: ignore[attr-defined]
        return kind, hb
    if kind == "partial_agg":
        from pixie_tpu.parallel.partial import PartialAggBatch

        key_dtypes = {k: DT(v) for k, v in meta["key_dtypes"].items()}
        key_cols = {}
        for name in meta["key_order"]:
            spec = meta["keys"][name]
            if "strbuf" in spec:
                key_cols[name] = np.asarray(
                    _strbuf_lookup(bufs, f"kd:{name}", f"ko:{name}"),
                    dtype=object,
                )
            elif "jsonvals" in spec:
                key_cols[name] = np.asarray(
                    _dict_values_restore(spec["jsonvals"], key_dtypes[name]),
                    dtype=object,
                )
            else:
                if spec["buf"] not in bufs:
                    raise InvalidArgument(f"wire: missing key buffer {spec['buf']!r}")
                key_cols[name] = bufs[spec["buf"]]
        states = {}
        for out_name, paths in meta["states"].items():
            leaves = {}
            for p in paths:
                bname = f"s:{out_name}:{p}"
                if bname not in bufs:
                    raise InvalidArgument(f"wire: missing state buffer {bname!r}")
                leaves[p] = bufs[bname]
            states[out_name] = _unflatten(leaves)
        pb = PartialAggBatch(
            key_cols=key_cols,
            key_dtypes=key_dtypes,
            states=states,
            in_types={
                k: (DT(v) if v is not None else None)
                for k, v in meta["in_types"].items()
            },
        )
        pb.wire_meta = meta  # type: ignore[attr-defined]
        return kind, pb
    raise InvalidArgument(f"wire: unknown kind {kind!r}")

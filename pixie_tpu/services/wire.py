"""Versioned binary wire format for control + data messages.

Replaces the reference's protobuf RowBatchData / TransferResultChunk
(src/carnot/carnotpb/carnot.proto:30-96, vizierpb RowBatchData) with a
self-describing frame:

    MAGIC "PXW1" | u32 header_len | header JSON (utf-8) | buffer bytes...

The header carries the message kind, JSON-safe metadata, and a buffer table
(name, numpy dtype str, length); numeric column data travels as raw
little-endian buffers, NEVER as pickled objects — a malicious peer can at
worst produce wrong values, not code execution (the round-1 advisor flagged
pickle here; this is the replacement).

Kinds:
  json         — control messages ({} metadata only)
  host_batch   — HostBatch: dtypes, dictionaries (JSON value lists), columns
  partial_agg  — PartialAggBatch: key values + flattened UDA state leaves
"""
from __future__ import annotations

import json
import struct

import numpy as np

from pixie_tpu.status import InvalidArgument
from pixie_tpu.table.dictionary import Dictionary
from pixie_tpu.types import STORAGE_DTYPE, DataType as DT

MAGIC = b"PXW1"
_HDR = struct.Struct("<4sI")

#: numpy dtype allowlist for wire buffers (validated on decode).
_ALLOWED_DTYPES = {
    "<i4", "<i8", "<u4", "<u8", "<f4", "<f8", "|b1", "<i2", "<u2", "|i1", "|u1"
}


def _norm_dtype(d: np.dtype) -> str:
    s = np.dtype(d).str
    if s == "=i8":
        s = "<i8"
    return s


# ------------------------------------------------------------------- encoding


def _frame(kind: str, meta: dict, bufs: list[tuple[str, np.ndarray]]) -> bytes:
    table = []
    chunks = []
    for name, arr in bufs:
        arr = np.ascontiguousarray(arr)
        s = _norm_dtype(arr.dtype)
        if s not in _ALLOWED_DTYPES:
            raise InvalidArgument(f"wire: dtype {s} of buffer {name!r} not allowed")
        raw = arr.tobytes()
        table.append({"name": name, "dtype": s, "shape": list(arr.shape),
                      "nbytes": len(raw)})
        chunks.append(raw)
    header = json.dumps({"kind": kind, "meta": meta, "bufs": table}).encode()
    return b"".join([_HDR.pack(MAGIC, len(header)), header, *chunks])


def encode_json(meta: dict) -> bytes:
    return _frame("json", meta, [])


def _u128_jsonable(v):
    from pixie_tpu.types import UInt128

    if v is None:
        return None
    if isinstance(v, UInt128):
        return [v.high, v.low]
    return list(v)


def _dict_values_jsonable(d: Dictionary, dt: DT) -> list:
    if dt == DT.UINT128:
        return [_u128_jsonable(v) for v in d.values()]
    return d.values()


def _dict_values_restore(vals: list, dt: DT) -> list:
    if dt == DT.UINT128:
        from pixie_tpu.types import UInt128

        # canonical in-memory form is UInt128 (metadata UDFs read .high/.pid)
        return [UInt128(*v) if v is not None else None for v in vals]
    return vals


def encode_host_batch(hb, extra_meta: dict | None = None) -> bytes:
    """HostBatch → frame (reference: RowBatchData on the result stream)."""
    meta = {
        "dtypes": {n: int(t) for n, t in hb.dtypes.items()},
        "dicts": {
            n: _dict_values_jsonable(d, hb.dtypes[n]) for n, d in hb.dicts.items()
        },
        "order": list(hb.cols),
    }
    if extra_meta:
        meta.update(extra_meta)
    return _frame("host_batch", meta, [(n, hb.cols[n]) for n in hb.cols])


def encode_partial_agg(pb, extra_meta: dict | None = None) -> bytes:
    """PartialAggBatch → frame (reference: serialized-UDA partial rows,
    planpb/plan.proto:250-257)."""
    key_meta = {}
    bufs: list[tuple[str, np.ndarray]] = []
    for name, vals in pb.key_cols.items():
        dt = pb.key_dtypes[name]
        arr = np.asarray(vals)
        if arr.dtype == object:
            if dt == DT.UINT128:
                key_meta[name] = {
                    "jsonvals": [_u128_jsonable(v) for v in arr.tolist()]
                }
            else:
                key_meta[name] = {"jsonvals": arr.tolist()}
        else:
            key_meta[name] = {"buf": f"k:{name}"}
            bufs.append((f"k:{name}", arr))
    states_meta = {}
    for out_name, tree in pb.states.items():
        paths = []
        for path, leaf in _flatten(tree):
            bname = f"s:{out_name}:{path}"
            bufs.append((bname, np.asarray(leaf)))
            paths.append(path)
        states_meta[out_name] = paths
    meta = {
        "key_dtypes": {k: int(v) for k, v in pb.key_dtypes.items()},
        "in_types": {k: (int(v) if v is not None else None) for k, v in pb.in_types.items()},
        "keys": key_meta,
        "states": states_meta,
        "key_order": list(pb.key_cols),
    }
    if extra_meta:
        meta.update(extra_meta)
    return _frame("partial_agg", meta, bufs)


def _flatten(tree, prefix="") -> list[tuple[str, np.ndarray]]:
    if isinstance(tree, dict):
        out = []
        for k in sorted(tree):
            if not isinstance(k, str) or "/" in k:
                raise InvalidArgument(f"wire: bad state key {k!r}")
            p = f"{prefix}/{k}" if prefix else k
            out.extend(_flatten(tree[k], p))
        return out
    return [(prefix, tree)]


def _unflatten(paths: dict[str, np.ndarray]):
    if list(paths) == [""]:
        return paths[""]
    root: dict = {}
    for path, leaf in paths.items():
        parts = path.split("/")
        d = root
        for p in parts[:-1]:
            d = d.setdefault(p, {})
        d[parts[-1]] = leaf
    return root


# ------------------------------------------------------------------- decoding


def decode_frame(data: bytes):
    """bytes → (kind, payload).

    json        → (kind, meta dict)
    host_batch  → (kind, HostBatch-with-meta)
    partial_agg → (kind, PartialAggBatch-with-meta)
    The original meta dict is attached as `.wire_meta` on decoded objects.
    """
    if len(data) < _HDR.size:
        raise InvalidArgument("wire: truncated frame")
    magic, hlen = _HDR.unpack_from(data)
    if magic != MAGIC:
        raise InvalidArgument(f"wire: bad magic {magic!r}")
    if _HDR.size + hlen > len(data):
        raise InvalidArgument("wire: truncated header")
    header = json.loads(data[_HDR.size : _HDR.size + hlen].decode())
    kind = header["kind"]
    meta = header["meta"]
    bufs: dict[str, np.ndarray] = {}
    off = _HDR.size + hlen
    for b in header["bufs"]:
        s = b["dtype"]
        if s not in _ALLOWED_DTYPES:
            raise InvalidArgument(f"wire: dtype {s} not allowed")
        nb = int(b["nbytes"])
        if off + nb > len(data):
            raise InvalidArgument("wire: truncated buffer")
        arr = np.frombuffer(data[off : off + nb], dtype=np.dtype(s))
        # Checked-Python-int product: np.prod would wrap in int64 on an
        # adversarial shape like [2**40, 2**40] and falsely pass.
        import math

        shape = tuple(int(x) for x in b["shape"])
        if any(d < 0 for d in shape) or math.prod(shape) * arr.itemsize != nb:
            raise InvalidArgument("wire: buffer shape/nbytes mismatch")
        bufs[b["name"]] = arr.reshape(shape).copy()  # writable, owned
        off += nb

    if kind == "json":
        return kind, meta
    if kind == "host_batch":
        from pixie_tpu.engine.executor import HostBatch

        dtypes = {n: DT(v) for n, v in meta["dtypes"].items()}
        dicts = {
            n: Dictionary(_dict_values_restore(vals, dtypes[n]))
            for n, vals in meta["dicts"].items()
        }
        cols = {}
        for n in meta["order"]:
            if n not in bufs:
                raise InvalidArgument(f"wire: missing column buffer {n!r}")
            want = STORAGE_DTYPE[dtypes[n]]
            cols[n] = bufs[n].astype(want, copy=False)
        hb = HostBatch(dtypes, dicts, cols)
        hb.wire_meta = meta  # type: ignore[attr-defined]
        return kind, hb
    if kind == "partial_agg":
        from pixie_tpu.parallel.partial import PartialAggBatch

        key_dtypes = {k: DT(v) for k, v in meta["key_dtypes"].items()}
        key_cols = {}
        for name in meta["key_order"]:
            spec = meta["keys"][name]
            if "jsonvals" in spec:
                key_cols[name] = np.asarray(
                    _dict_values_restore(spec["jsonvals"], key_dtypes[name]),
                    dtype=object,
                )
            else:
                if spec["buf"] not in bufs:
                    raise InvalidArgument(f"wire: missing key buffer {spec['buf']!r}")
                key_cols[name] = bufs[spec["buf"]]
        states = {}
        for out_name, paths in meta["states"].items():
            leaves = {}
            for p in paths:
                bname = f"s:{out_name}:{p}"
                if bname not in bufs:
                    raise InvalidArgument(f"wire: missing state buffer {bname!r}")
                leaves[p] = bufs[bname]
            states[out_name] = _unflatten(leaves)
        pb = PartialAggBatch(
            key_cols=key_cols,
            key_dtypes=key_dtypes,
            states=states,
            in_types={
                k: (DT(v) if v is not None else None)
                for k, v in meta["in_types"].items()
            },
        )
        pb.wire_meta = meta  # type: ignore[attr-defined]
        return kind, pb
    raise InvalidArgument(f"wire: unknown kind {kind!r}")

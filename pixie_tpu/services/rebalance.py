"""RebalanceController: skew-driven shard re-homing (data lifecycle).

The storage observatory (table/heat.py, PR 17) measures per-shard decayed
heat; this module ACTS on it.  One tick per ``PL_REBALANCE_S``:

  * fan out ``storage_report`` RPCs to every live agent (the same probe
    ``Broker._answer_heat_map`` aggregates) and fold per-shard heat;
  * skew = hottest / mean over LIVE shards (cold empty capacity counts
    as zero heat — that is exactly the imbalance worth fixing);
  * skew past ``PL_REBALANCE_SKEW`` with the cooldown lapsed → move the
    hottest shard: ``Broker.rehome_agent(donor, coldest)`` ships the
    donor's sealed data to the coldest peer over the replication channel
    (two-phase, crash-safe — ownership stays with the donor until the
    target's replica manifest verifiably covers the donor's frontier),
    then ``Broker.retire_agent(donor)`` hands the shard off so failover
    serves it from the moved copy, and the optional ``stop_agent``
    callable stops the donor process.

Every decision lands in ``self_telemetry.scale_events`` through the
broker's normal path (``rehome`` rows from the move itself, ``rebalance``
rows from this loop).  ``PL_REBALANCE_S=0`` (the default) never starts
the loop — the data plane is bit-identical to the fixed-placement engine.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from pixie_tpu import flags, metrics

flags.define_float(
    "PL_REBALANCE_S", 0.0,
    "shard re-homing control loop tick period (services/rebalance.py): "
    "measure per-shard heat skew and move the hottest shard onto the "
    "coldest live peer when it exceeds PL_REBALANCE_SKEW; 0 disables "
    "(fixed placement, the seed behavior)")
flags.define_float(
    "PL_REBALANCE_SKEW", 1.3,
    "hottest/mean shard-heat ratio at or above which one re-homing move "
    "triggers per cooldown")
flags.define_float(
    "PL_REBALANCE_COOLDOWN_S", 5.0,
    "minimum seconds between re-homing moves — a move changes the heat "
    "surface it was decided on, so the next decision waits for fresh "
    "measurements")
flags.define_float(
    "PL_REBALANCE_MIN_HEAT", 1000.0,
    "decayed-heat floor (rows) the hottest shard must exceed before any "
    "move: skew ratios over a near-idle fleet are noise, not imbalance")

#: pxlint lock-discipline: controller counters are owned by its one mutex
_pxlint_locks_ = {
    "_note_move_locked": "self._lock",
}


class RebalanceController:
    """The broker's shard-placement control loop (see module docstring).

    Constructed by harnesses/benches with the broker and an optional
    ``stop_agent(name)`` callable that stops the donor process after a
    successful hand-off (a ThreadLauncher/ProcLauncher stop, or a k8s
    pod delete in a real deployment)."""

    def __init__(self, broker, stop_agent: Optional[Callable] = None,
                 min_agents: int = 2):
        self.broker = broker
        self.stop_agent = stop_agent
        self.min_agents = max(int(min_agents), 2)
        self._lock = threading.Lock()
        self._last_move = 0.0
        self.moves = 0
        self.skips = 0
        self.last_skew = 1.0
        self.last_outlier = 1.0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._gauges = False

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "RebalanceController":
        if self._thread is not None:
            return self
        self._stop.clear()
        if not self._gauges:
            self._gauges = True
            metrics.register_gauge_fn(
                "px_rebalance_skew",
                lambda: {(): float(self.last_skew)},
                "hottest/mean shard-heat ratio at the last rebalance tick")
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="pixie-rebalance")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        th, self._thread = self._thread, None
        if th is not None:
            th.join(timeout=5.0)
        if self._gauges:
            self._gauges = False
            metrics.unregister_gauge_fn("px_rebalance_skew")

    def _loop(self) -> None:
        while not self._stop.wait(
                timeout=max(float(flags.get("PL_REBALANCE_S")), 0.05)):
            try:
                self.tick()
            except Exception:
                metrics.counter_inc(
                    "px_rebalance_tick_errors_total",
                    help_="rebalance ticks that raised (the loop survives; "
                          "the decision is skipped)")

    # ------------------------------------------------------------- decision
    def shard_heat(self) -> dict[str, float]:
        """{live agent → summed decayed shard heat}.  Agents whose probe
        fails (or that report nothing) count as zero — missing evidence
        must read as cold, never as hot enough to move.  Heat a live
        agent accrues serving a DEAD primary's shard through takeover
        rides under that primary's shard name (replication.takeover_store)
        and is deliberately invisible here: it belongs to the moved shard,
        not the host's own, and folding it in would make every move target
        read hottest (takeover serving full-scans — no matviews) and
        cascade the fleet."""
        heat: dict[str, float] = {
            r.name: 0.0 for r in self.broker.registry.live_agents()}
        # per-(shard, table) fold takes the MAX across reports: in-process
        # harnesses share one heat registry, so every agent's report sees
        # every shard's rows — summing them would multiply heat by fleet
        # size and trip the skew gate on a perfectly balanced cluster
        seen: dict[tuple, float] = {}
        for name in list(heat):
            try:
                rep = self.broker._agent_rpc(
                    name, {"msg": "storage_report"}, timeout=5.0)
            except Exception:
                continue
            for r in rep.get("shard_heat") or []:
                key = (str(r.get("shard")), str(r.get("table_name")))
                seen[key] = max(seen.get(key, 0.0),
                                float(r.get("heat") or 0.0))
        for (shard, _table), h in seen.items():
            if shard in heat:
                heat[shard] += h
        return heat

    @staticmethod
    def skew_of(heat: dict[str, float]) -> float:
        """max/mean — the observatory's standard skew statistic."""
        vals = list(heat.values())
        mean = sum(vals) / max(len(vals), 1)
        return (max(vals) / mean) if mean > 0 else 1.0

    @staticmethod
    def outlier_of(heat: dict[str, float]) -> float:
        """max/median — the MOVE gate.  max/mean alone would cascade: the
        moment a move lands, the target has served for zero half-lives and
        reads cold, dragging the mean down and re-arming the trigger until
        the fleet consolidates onto one node.  Against the median, a
        cluster whose only imbalance is an idle spare (or a just-moved-to
        node still warming) reads 1.0 — only a genuinely hot outlier
        shard justifies a move."""
        vals = sorted(heat.values())
        if not vals:
            return 1.0
        n = len(vals)
        med = (vals[n // 2] if n % 2
               else (vals[n // 2 - 1] + vals[n // 2]) / 2.0)
        return (vals[-1] / med) if med > 0 else 1.0

    def _note_move_locked(self, now: float) -> None:
        self._last_move = now
        self.moves += 1

    def tick(self, now: Optional[float] = None) -> Optional[dict]:
        """One placement decision (public so tests and benches drive it
        deterministically).  Returns the move result dict when a move was
        attempted, None otherwise."""
        now = time.monotonic() if now is None else now
        heat = self.shard_heat()
        self.last_skew = self.skew_of(heat)
        self.last_outlier = self.outlier_of(heat)
        if len(heat) < self.min_agents:
            return None
        threshold = float(flags.get("PL_REBALANCE_SKEW"))
        cooldown = float(flags.get("PL_REBALANCE_COOLDOWN_S"))
        with self._lock:
            cooling = now - self._last_move < cooldown
        # BOTH gates must trip: mean-skew says the fleet is imbalanced,
        # median-outlier says the hottest shard (not an idle spare or a
        # still-warming move target) is what's causing it — and the
        # hottest shard must carry real heat, not decayed noise
        if self.last_skew < threshold or self.last_outlier < threshold \
                or cooling \
                or max(heat.values()) < float(
                    flags.get("PL_REBALANCE_MIN_HEAT")):
            return None
        donor = max(heat, key=lambda a: (heat[a], a))
        target = min((a for a in heat if a != donor),
                     key=lambda a: (heat[a], a))
        reason = f"skew {self.last_skew:.2f} >= {threshold:.2f}"
        moved = self.broker.rehome_agent(donor, target=target,
                                         reason=reason)
        if not moved.get("ok"):
            self.skips += 1
            metrics.counter_inc(
                "px_rebalance_move_refused_total",
                help_="skew-triggered re-homing moves that the two-phase "
                      "protocol refused or aborted")
            return moved
        with self._lock:
            self._note_move_locked(now)
        metrics.counter_inc(
            "px_rebalance_moves_total",
            help_="skew-triggered shard moves committed by the rebalance "
                  "control loop")
        # hand off serving: the donor's shard now answers through failover
        # from the moved copy; a refused retire (e.g. audit timeout) leaves
        # the donor serving with an extra replica staged — safe, retried
        # next tick once the cooldown lapses
        retired = self.broker.retire_agent(donor)
        if retired.get("ok") and self.stop_agent is not None:
            try:
                self.stop_agent(donor)
            except Exception:
                metrics.counter_inc(
                    "px_rebalance_stop_errors_total",
                    help_="donor stop callbacks that raised after a "
                          "successful hand-off")
        self.broker.record_scale_event(
            "rebalance", donor, reason, self.last_skew,
            len(self.broker.registry.live_agents()))
        return {**moved, "retired": retired}


def maybe_start(broker, stop_agent: Optional[Callable] = None):
    """Arm the controller when PL_REBALANCE_S > 0 (cli/bench hook);
    returns the started controller or None."""
    if float(flags.get("PL_REBALANCE_S")) <= 0:
        return None
    return RebalanceController(broker, stop_agent=stop_agent).start()

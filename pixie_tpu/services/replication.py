"""Sealed-batch replication: each shard's sealed batches live on peers too.

The journal (table/journal.py) makes acked rows survive a RESTART; this
module makes them survive losing the pod's disk entirely.  Every agent runs
a small framed-TCP peer server; when a table seals a batch, the primary
ships it (values, not dictionary codes — deterministic re-encode on the far
side) to the `PL_REPLICATION - 1` replica peers the shard map assigns.  The
map itself lives in the control KV and is maintained by the registry on
join/evict (services/registry.py); the broker pushes map + peer addresses
to agents on every topology change.

Replicas keep the batches in memory, serving three consumers:

  * failover — the broker re-plans a dead primary's fragments onto a live
    replica (`serve_for` dispatch); the replica materializes a takeover
    TableStore from the primary's batches and executes the fragment over it.
  * rehydration — a restarting primary fetches the sealed batches its
    journal no longer covers (wiped/pruned segments) before registering.
  * audit — manifests expose per-primary coverage for completeness checks.

Peer protocol (wire frames on the peer port):

  repl_batch    host_batch frame, meta {msg, primary, table, relation,
                batch_rows, max_bytes, row_id_start, n, seq} → repl_ack
  repl_manifest json {primary} → repl_manifest_ack {tables: {name:
                {relation, batch_rows, max_bytes, ranges: [[start, n]...]}}}
  repl_get      json {primary, table, row_id_start} → one repl_batch reply

`PL_REPLICATION=1` (the default) disables everything — no peer server, no
hooks, bit-identical to the seed behavior.
"""
from __future__ import annotations

import queue
import threading
import time
import uuid
from typing import Optional

from pixie_tpu import flags, metrics
from pixie_tpu.services import wire
from pixie_tpu.services.transport import Connection, Server, dial
from pixie_tpu.status import Unavailable
from pixie_tpu.table.journal import decode_columns, encode_columns
from pixie_tpu.types import Relation

flags.define_int(
    "PL_REPLICATION", 1,
    "copies of every sealed batch across the agent set (including the "
    "primary); 1 disables replication entirely — the seed single-copy "
    "behavior, bit-identical")


def enabled() -> bool:
    return int(flags.get("PL_REPLICATION")) > 1


#: live managers in this process (tests run several), for the per-peer lag
#: gauge — registered once, reads every manager's sent/acked watermarks
_MANAGERS: list = []
_MANAGERS_LOCK = threading.Lock()


def _lag_gauges() -> dict:
    with _MANAGERS_LOCK:
        mgrs = list(_MANAGERS)
    out: dict = {}
    for m in mgrs:
        for peer, lag in m.lag().items():
            key = (("peer", metrics.capped_label("repl_peer", peer)),)
            out[key] = max(out.get(key, 0.0), float(lag))
    return out


metrics.register_gauge_fn(
    "px_repl_lag_batches", _lag_gauges,
    help_="sealed-vs-acked replication watermark delta per peer (batches "
          "enqueued to the peer that it has not acked yet)")


def encode_sealed(table, batch, row_id_start: int, primary: str,
                  seq: int) -> bytes:
    """One sealed RowBatch → a repl_batch frame.  Dictionary codes decode
    to values here; the receiver re-encodes into its own code space."""
    nv = batch.num_valid
    data = {}
    for c in table.relation:
        arr = batch.columns[c.name][:nv]
        if c.name in table.dictionaries:
            data[c.name] = table.dictionaries[c.name].decode(arr)
        else:
            data[c.name] = arr
    return encode_columns(table.relation, data, {
        "msg": "repl_batch", "primary": primary, "table": table.name,
        "relation": table.relation.to_dict(), "batch_rows": table.batch_rows,
        "max_bytes": table.max_bytes, "row_id_start": int(row_id_start),
        "n": int(nv), "seq": int(seq),
    })


class ReplicaStore:
    """Sealed batches held FOR other primaries, keyed (primary, table,
    row_id_start); materializes takeover TableStores on demand."""

    def __init__(self, node_name: str = ""):
        #: the REPLICA's own name (kept for diagnostics; takeover
        #: materializations are attributed to the PRIMARY's shard name —
        #: see takeover_store)
        self.node_name = node_name
        self._lock = threading.Lock()
        #: primary -> table -> {"relation","batch_rows","max_bytes",
        #:                      "batches": {row_id_start: (n, {col: vals})}}
        self._data: dict[str, dict[str, dict]] = {}
        self._version: dict[str, int] = {}
        #: primary -> (version, TableStore) takeover materialization cache
        self._stores: dict[str, tuple[int, object]] = {}

    def put(self, meta: dict, data: dict) -> None:
        primary = str(meta["primary"])
        with self._lock:
            tabs = self._data.setdefault(primary, {})
            t = tabs.get(meta["table"])
            if t is None:
                t = tabs[meta["table"]] = {
                    "relation": meta["relation"],
                    "batch_rows": int(meta["batch_rows"]),
                    "max_bytes": int(meta["max_bytes"]),
                    "batches": {},
                }
            t["batches"][int(meta["row_id_start"])] = (int(meta["n"]), data)
            self._version[primary] = self._version.get(primary, 0) + 1
            stale = self._stores.pop(primary, None)
        self._drop_resident(stale)
        metrics.counter_inc(
            "px_repl_batches_received_total",
            help_="sealed batches accepted from primary peers")

    @staticmethod
    def _drop_resident(stale) -> None:
        """A dropped takeover store's tables may have device-pinned resident
        entries; free them now (pinned-tier invalidation on shard-map /
        replica-content change)."""
        if stale is None:
            return
        try:
            from pixie_tpu.engine import resident

            for name in stale[1].names():
                resident.drop_table(stale[1].table(name).uid)
        except Exception:  # engine layer absent must not break replication
            pass

    def primaries(self) -> list[str]:
        with self._lock:
            return sorted(self._data)

    def manifest(self, primary: str) -> dict:
        with self._lock:
            tabs = self._data.get(primary, {})
            return {
                name: {
                    "relation": t["relation"],
                    "batch_rows": t["batch_rows"],
                    "max_bytes": t["max_bytes"],
                    "ranges": sorted(
                        [s, n] for s, (n, _) in t["batches"].items()),
                }
                for name, t in tabs.items()
            }

    def get_batch(self, primary: str, table: str, row_id_start: int):
        with self._lock:
            t = self._data.get(primary, {}).get(table)
            if t is None:
                return None
            hit = t["batches"].get(int(row_id_start))
            if hit is None:
                return None
            n, data = hit
            return {"relation": t["relation"], "batch_rows": t["batch_rows"],
                    "max_bytes": t["max_bytes"], "n": n, "data": data}

    def drop_primaries(self, keep: set) -> None:
        """Shard-map change: free replica state for primaries this node no
        longer backs (and their takeover materializations)."""
        with self._lock:
            gone = [p for p in self._data if p not in keep]
            stale = []
            for p in gone:
                self._data.pop(p, None)
                self._version.pop(p, None)
                s = self._stores.pop(p, None)
                if s is not None:
                    stale.append(s)
        for s in stale:
            self._drop_resident(s)

    def takeover_store(self, primary: str):
        """A TableStore materialized from the primary's replicated sealed
        batches (values re-encoded locally; batch_rows preserved, so sealing
        reproduces the primary's batch layout).  Cached per content version."""
        from pixie_tpu.table.table import TableStore

        with self._lock:
            ver = self._version.get(primary, 0)
            hit = self._stores.get(primary)
            if hit is not None and hit[0] == ver:
                return hit[1]
            tabs = {
                name: (t["relation"], t["batch_rows"], t["max_bytes"],
                       sorted(t["batches"].items()))
                for name, t in self._data.get(primary, {}).items()
            }
        store = TableStore()
        # heat attribution: takeover scans account under the PRIMARY's
        # shard name, not the serving node — shard heat follows the shard
        # across failover and re-homing (the observatory keeps one stable
        # identity per shard), and the rebalance controller, which folds
        # heat per LIVE agent's own shard, never mistakes the full-scan
        # cost of takeover serving (no matviews on a takeover store) for
        # the host's own shard running hot — that misread is a move
        # cascade: every move target immediately looks hottest
        store.node_name = str(primary)
        # the engine-owned self-telemetry tables (spans, query profiles,
        # op stats, metrics, alerts) exist on every agent by construction,
        # so the dead primary's registered schema advertises them; their
        # sealed batches rarely replicate (telemetry churns below the seal
        # threshold).  Create them EMPTY so a distributed scan of
        # self_telemetry.* stays answerable through failover — the replica
        # serves an empty shard for the dead primary instead of erroring
        # the whole query.
        from pixie_tpu import observe, trace

        if trace.SPANS_TABLE not in tabs:
            trace.ensure_table(store)
        for tname in observe.SELF_TABLES:
            if tname not in tabs:
                observe.ensure_table(store, tname)
        for name, (rel, batch_rows, max_bytes, batches) in tabs.items():
            tb = store.create(name, Relation.from_dict(rel),
                              batch_rows=batch_rows, max_bytes=max_bytes)
            expected = batches[0][0] if batches else 0
            for start, (n, data) in batches:
                if start != expected:
                    # a HOLE (a replication send that never arrived):
                    # writing past it would place later rows at wrong row
                    # ids — serve the contiguous prefix and count the gap
                    # loudly instead of answering with mis-positioned rows
                    metrics.counter_inc(
                        "px_repl_takeover_holes_total",
                        help_="takeover materializations truncated at a "
                              "missing replicated batch")
                    break
                tb.write(data)
                expected = start + n
        with self._lock:
            # keep whichever materialization is newest; a racing put()
            # already invalidated ours if the content moved on
            if self._version.get(primary, 0) == ver:
                self._stores[primary] = (ver, store)
        return store


class PeerClient:
    """One request/reply client connection to a peer's replication port."""

    def __init__(self, host: str, port: int, timeout_s: float = 10.0):
        self.timeout_s = timeout_s
        self._lock = threading.Lock()
        self._pending: dict[str, list] = {}
        self.conn = dial(host, port, on_frame=self._on_frame)
        self.conn.label = "repl-client"

    def _on_frame(self, conn: Connection, frame: bytes) -> None:
        kind, payload = wire.decode_frame(frame)
        meta = payload if kind == "json" else payload.wire_meta
        rid = meta.get("req_id")
        with self._lock:
            slot = self._pending.get(rid)
        if slot is not None:
            slot[1] = (kind, payload)
            slot[0].set()

    def request(self, meta: dict):
        rid = meta.setdefault("req_id", uuid.uuid4().hex)
        slot = [threading.Event(), None]
        with self._lock:
            self._pending[rid] = slot
        try:
            if not self.conn.send(wire.encode_json(meta)):
                raise Unavailable("replication peer not reachable")
            if not slot[0].wait(self.timeout_s):
                raise Unavailable(f"replication peer timed out on "
                                  f"{meta.get('msg')}")
            return slot[1]
        finally:
            with self._lock:
                self._pending.pop(rid, None)

    def close(self) -> None:
        self.conn.close()


class ReplicationManager:
    """Per-agent replication runtime: peer server + sealed-batch fan-out."""

    def __init__(self, name: str, store):
        self.name = name
        self.store = store
        self.replicas = ReplicaStore(name)
        self._server = Server("127.0.0.1", 0, self._on_peer_frame)
        self._q: "queue.Queue" = queue.Queue()
        self._lock = threading.Lock()
        self._peers: dict[str, tuple[str, int]] = {}
        self._targets: list[str] = []
        self._conns: dict[str, Connection] = {}
        self._seq = 0
        #: target -> highest repl_ack seq seen (wait_synced blocks on these)
        self._acked: dict[str, int] = {}
        self._sent: dict[str, int] = {}
        self._synced = threading.Condition(self._lock)
        self._stop = threading.Event()
        self._sender = threading.Thread(target=self._send_loop, daemon=True,
                                        name=f"pixie-repl-{name}")

    # --------------------------------------------------------------- lifecycle
    def start(self) -> "ReplicationManager":
        self._server.start()
        self._sender.start()
        self._attach(self.store)
        with _MANAGERS_LOCK:
            _MANAGERS.append(self)
        return self

    @property
    def port(self) -> int:
        return self._server.port

    def addr(self) -> tuple[str, int]:
        return ("127.0.0.1", self.port)

    def stop(self) -> None:
        with _MANAGERS_LOCK:
            if self in _MANAGERS:
                _MANAGERS.remove(self)
        self._stop.set()
        self._q.put(None)
        self._server.stop()
        with self._lock:
            conns = list(self._conns.values())
            self._conns.clear()
        for c in conns:
            c.close()
        self._detach(self.store)

    def _attach(self, store) -> None:
        from pixie_tpu.table.journal import non_durable_tables
        from pixie_tpu.table.table import Table

        def hook(table):
            if (isinstance(table, Table)
                    and table.name not in non_durable_tables()):
                table.on_seal = self._on_seal

        for name in store.names():
            hook(store._tables.get(name))
        store.add_observer(hook)

    def _detach(self, store) -> None:
        from pixie_tpu.table.table import Table

        for name in store.names():
            t = store._tables.get(name)
            if isinstance(t, Table) and t.on_seal == self._on_seal:
                t.on_seal = None

    # ---------------------------------------------------------------- topology
    def on_shard_map(self, shard_map: dict, peers: dict) -> None:
        """Broker-pushed topology: who this node replicates TO, where every
        peer's replication port lives, and which primaries it still backs.
        A NEW replica target gets a full backfill of already-sealed batches
        — batches sealed before it joined must reach it too, or its
        takeover coverage silently starts at its join time."""
        backs = {p for p, reps in shard_map.items()
                 if self.name in (reps or []) and p != self.name}
        with self._lock:
            old = set(self._targets)
            self._targets = [r for r in shard_map.get(self.name, [])
                             if r != self.name]
            added = [r for r in self._targets if r not in old]
            self._peers = {n: (str(h), int(p))
                           for n, (h, p) in (peers or {}).items()
                           if n != self.name}
        self.replicas.drop_primaries(backs)
        for target in added:
            self._backfill(target)

    def _backfill(self, target: str) -> None:
        """Enqueue every already-sealed batch for one new replica target.
        Receivers keyed by (primary, table, row_id_start) make duplicate
        delivery (backfill racing a live seal) idempotent."""
        from pixie_tpu.table.journal import non_durable_tables
        from pixie_tpu.table.table import Table

        for name in self.store.names():
            table = self.store._tables.get(name)
            if not isinstance(table, Table) or name in non_durable_tables():
                continue
            for rb, rid, gen in table.cursor(include_hot=False):
                if gen is None:
                    continue
                self._enqueue(table, rb, rid, [target])

    def peer_addr(self, name: str) -> Optional[tuple[str, int]]:
        with self._lock:
            return self._peers.get(name)

    # ---------------------------------------------------------------- outbound
    def _on_seal(self, table, sealed: list) -> None:
        with self._lock:
            targets = list(self._targets)
        if not targets:
            return
        for sb in sealed:
            self._enqueue(table, sb.batch, sb.row_id_start, targets)

    def _enqueue(self, table, batch, row_id_start: int,
                 targets: list) -> None:
        with self._lock:
            self._seq += 1
            seq = self._seq
        frame = encode_sealed(table, batch, row_id_start, self.name, seq)
        for t in targets:
            with self._lock:
                self._sent[t] = max(self._sent.get(t, 0), seq)
            self._q.put((t, seq, frame, 0))

    #: re-dial + re-send attempts per batch before a send failure becomes a
    #: hole (holes are survivable — takeover serves the contiguous prefix
    #: and a rehydrating primary falls back to its journal — but cheap to
    #: avoid for the common transient-dial case)
    SEND_RETRIES = 3

    def _send_loop(self) -> None:
        while not self._stop.is_set():
            item = self._q.get()
            if item is None:
                return
            target, seq, frame, tries = item
            conn = self._peer_conn(target)
            if conn is not None and conn.send(frame):
                continue
            # the cached conn may be a dead socket: drop it so the retry
            # redials, and requeue with a bounded budget
            with self._lock:
                stale = self._conns.pop(target, None)
            if stale is not None:
                stale.close()
            if tries < self.SEND_RETRIES and not self._stop.is_set():
                metrics.counter_inc(
                    "px_repl_send_retries_total",
                    help_="sealed-batch replication sends re-attempted "
                          "after a dead connection or failed dial")
                time.sleep(0.05 * (tries + 1))
                self._q.put((target, seq, frame, tries + 1))
                continue
            metrics.counter_inc(
                "px_repl_send_failures_total",
                help_="sealed-batch replication sends that failed after "
                      "retries (the replica holds a hole until backfill)")
            with self._synced:
                self._acked[target] = max(self._acked.get(target, 0), seq)
                self._synced.notify_all()

    def _peer_conn(self, name: str) -> Optional[Connection]:
        with self._lock:
            conn = self._conns.get(name)
            addr = self._peers.get(name)
        if conn is not None and not conn.closed:
            return conn
        if addr is None:
            return None
        try:
            conn = dial(addr[0], addr[1], on_frame=self._on_ack_frame)
            conn.label = f"repl:{self.name}"
        except OSError:
            return None
        with self._lock:
            self._conns[name] = conn
        return conn

    def _on_ack_frame(self, conn: Connection, frame: bytes) -> None:
        kind, payload = wire.decode_frame(frame)
        if kind != "json" or payload.get("msg") != "repl_ack":
            return
        sender = str(payload.get("replica") or "")
        with self._synced:
            self._acked[sender] = max(self._acked.get(sender, 0),
                                      int(payload.get("seq") or 0))
            self._synced.notify_all()

    def sync_state(self) -> dict:
        """Per-peer watermarks: {peer: {"sent", "acked", "lag"}} where lag
        is the sealed-vs-acked delta in batches — the drain audit
        (retire_info) and the storage-state fold both read this."""
        with self._lock:
            return {t: {"sent": int(s),
                        "acked": int(self._acked.get(t, 0)),
                        "lag": max(int(s) - int(self._acked.get(t, 0)), 0)}
                    for t, s in self._sent.items()}

    def lag(self) -> dict[str, int]:
        """{peer: unacked batches} (0 = fully synced)."""
        return {t: st["lag"] for t, st in self.sync_state().items()}

    def wait_synced(self, timeout_s: float = 10.0) -> bool:
        """Block until every target acked every enqueued batch (benches and
        tests use this to bound the replication race before injecting
        faults; production sends stay fire-and-forget)."""
        import time

        deadline = time.monotonic() + timeout_s
        with self._synced:
            while any(self._acked.get(t, 0) < s
                      for t, s in self._sent.items()):
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                self._synced.wait(timeout=min(left, 0.2))
        return True

    # ----------------------------------------------------------------- inbound
    def _on_peer_frame(self, conn: Connection, frame: bytes) -> None:
        kind, payload = wire.decode_frame(frame)
        if kind == "host_batch":
            meta = payload.wire_meta
            if meta.get("msg") != "repl_batch":
                return
            self.replicas.put(meta, decode_columns(payload))
            conn.send(wire.encode_json({
                "msg": "repl_ack", "seq": int(meta.get("seq") or 0),
                "replica": self.name}))
            return
        if kind != "json":
            return
        msg = payload.get("msg")
        if msg == "repl_manifest":
            conn.send(wire.encode_json({
                "msg": "repl_manifest_ack", "req_id": payload.get("req_id"),
                "tables": self.replicas.manifest(str(payload.get("primary"))),
            }))
        elif msg == "repl_get":
            hit = self.replicas.get_batch(
                str(payload.get("primary")), str(payload.get("table")),
                int(payload.get("row_id_start") or 0))
            if hit is None:
                conn.send(wire.encode_json({
                    "msg": "error", "req_id": payload.get("req_id"),
                    "error": "replica batch not found"}))
                return
            rel = Relation.from_dict(hit["relation"])
            conn.send(encode_columns(rel, hit["data"], {
                "msg": "repl_batch", "req_id": payload.get("req_id"),
                "primary": payload.get("primary"),
                "table": payload.get("table"), "relation": hit["relation"],
                "batch_rows": hit["batch_rows"],
                "max_bytes": hit["max_bytes"],
                "row_id_start": int(payload.get("row_id_start") or 0),
                "n": hit["n"], "seq": 0,
            }))

    # ------------------------------------------------------------- rehydration
    def takeover_store(self, primary: str):
        metrics.counter_inc(
            "px_failover_serves_total",
            help_="fragments served from replicated batches for a dead "
                  "primary (takeover dispatch)")
        return self.replicas.takeover_store(primary)

    def fetch_missing(self, store, holders: list[str],
                      timeout_s: float = 10.0) -> dict:
        """Pull this node's OWN missing sealed batches from the peers that
        back it (`holders` = the shard map's replica list for this node).
        Journal replay runs first; this covers journal segments lost with
        the pod.  Batches overlapping the local row watermark are sliced so
        the store stays contiguous and seals reproduce the primary layout."""
        from pixie_tpu.table.table import Table

        stats = {"batches": 0, "rows": 0, "tables": 0, "holes": 0}
        for holder in holders:
            addr = self.peer_addr(holder)
            if addr is None:
                continue
            try:
                client = PeerClient(*addr, timeout_s=timeout_s)
            except OSError:
                continue
            try:
                kind, reply = client.request(
                    {"msg": "repl_manifest", "primary": self.name})
                tables = (reply.get("tables") or {}) if kind == "json" else {}
                for tname, m in sorted(tables.items()):
                    if not store.has(tname):
                        store.create(tname, Relation.from_dict(m["relation"]),
                                     batch_rows=int(m["batch_rows"]),
                                     max_bytes=int(m["max_bytes"]))
                        stats["tables"] += 1
                    table = store._tables.get(tname)
                    if not isinstance(table, Table):
                        continue
                    for start, n in m.get("ranges") or []:
                        have = table.last_row_id()
                        if start + n <= have:
                            continue  # journal replay already covers it
                        if start > have:
                            stats["holes"] += 1
                            break  # applying past a hole fabricates ids
                        k2, batch = client.request({
                            "msg": "repl_get", "primary": self.name,
                            "table": tname, "row_id_start": int(start)})
                        if k2 != "host_batch":
                            break
                        data = decode_columns(batch)
                        off = have - int(start)
                        if off:
                            data = {c: v[off:] for c, v in data.items()}
                        table.write(data)
                        stats["batches"] += 1
                        stats["rows"] += int(n) - off
            except Unavailable:
                continue
            finally:
                client.close()
        if stats["rows"]:
            metrics.counter_inc(
                "px_repl_rehydrated_rows_total", float(stats["rows"]),
                help_="rows restored from replica peers during rehydration")
        return stats

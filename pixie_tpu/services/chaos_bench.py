"""Chaos-recovery harness (the `chaos_recovery` bench config).

Replays a fixed query set against a REAL broker + agent deployment twice:
once fault-free (the baseline: canonical result bytes + latencies), then
under an injected kill-and-restart schedule — every `kill_every` queries an
agent's socket is RST mid-flight and the same agent (same name, same store)
restarts after `restart_delay_s`.  The whole fault-tolerance stack is in the
loop: broker-side eviction → re-plan → re-dispatch under fresh tokens
(`PL_QUERY_RETRIES`), straggler hedging, registry incarnation fencing of the
dead socket, and client-side auto-retry/reconnect (`PL_CLIENT_RETRIES`).

Acceptance (held absolutely by `bench.py --check-regressions`):

  * recovery_rate == 1.0 — every retryable query returns an answer; zero
    client-visible errors.
  * bit_equal_frac == 1.0 — each answer is BIT-equal to the fault-free
    baseline (canonical row order; float bit patterns compared, not
    approximations).  Kill-and-restart preserves each agent's store, and
    per-source folds merge in deterministic sorted-source order, so
    recovery must not perturb a single bit.
  * added_p99_ms bounded — recovery costs bounded latency (backoff +
    re-execution), never an unbounded stall.

Everything is measured from the run — no modeled numbers.
"""
from __future__ import annotations

import threading
import time

import numpy as np

#: the replayed query mix — retryable (non-mutation) shapes only: a partial
#: agg channel, a multi-key agg with float state (mean/p50 exercise float
#: fold determinism), and a rows channel with a filter
SCRIPTS = [
    """
df = px.DataFrame(table='http_events')
df = df.groupby('service').agg(cnt=('latency', px.count),
                               mx=('latency', px.max))
px.display(df, 'out')
""",
    """
df = px.DataFrame(table='http_events')
df = df.groupby(['service', 'status']).agg(
    cnt=('latency', px.count), m=('latency', px.mean),
    p50=('latency', px.p50))
px.display(df, 'out')
""",
    """
df = px.DataFrame(table='http_events')
df = df[df.status == 500]
df = df.groupby('service').agg(cnt=('latency', px.count),
                               s=('latency', px.sum))
px.display(df, 'out')
""",
]


def _mkstore(seed: int, rows: int):
    from pixie_tpu.table import TableStore
    from pixie_tpu.types import DataType as DT, Relation

    rng = np.random.default_rng(seed)
    ts = TableStore()
    rel = Relation.of(
        ("time_", DT.TIME64NS), ("service", DT.STRING),
        ("latency", DT.FLOAT64), ("status", DT.INT64),
    )
    t = ts.create("http_events", rel, batch_rows=1 << 13, max_bytes=1 << 32)
    svc = np.array([f"svc-{i}" for i in range(6)])
    t.write({
        "time_": np.arange(rows, dtype=np.int64) * 1000,
        "service": svc[rng.integers(0, len(svc), rows)],
        "latency": rng.exponential(20.0, rows),
        "status": rng.choice([200, 404, 500], rows, p=[0.9, 0.05, 0.05]),
    })
    return ts


def canonical_bytes(results: dict) -> bytes:
    """Order-independent BIT-exact fingerprint of a query answer: per table,
    rows sort lexicographically by every column's VALUE (dictionary codes
    decoded — code spaces differ across merges by construction) and the
    sorted columns' raw bytes concatenate.  Float columns contribute their
    bit patterns: a recovered query that differs in one ulp fails."""
    out = []
    for name in sorted(results):
        qr = results[name]
        cols = {}
        for cname in sorted(qr.columns):
            arr = qr.columns[cname]
            if cname in qr.dictionaries:
                vals = qr.dictionaries[cname].decode(arr)
                cols[cname] = np.asarray(
                    [v if v is not None else "" for v in vals], dtype=object)
            else:
                cols[cname] = np.asarray(arr)
        if cols:
            order = np.lexsort([cols[c] if cols[c].dtype != object
                                else np.asarray(cols[c], dtype="U64")
                                for c in sorted(cols)])
        for cname in sorted(cols):
            arr = cols[cname][order] if cols else cols[cname]
            out.append(cname.encode())
            if arr.dtype == object:
                out.append("\x00".join(str(v) for v in arr).encode())
            else:
                out.append(arr.tobytes())  # bit patterns, not repr
    return b"\x01".join(out)


def _pct(xs: list, q: float) -> float:
    if not xs:
        return 0.0
    xs = sorted(xs)
    return xs[min(len(xs) - 1, int(q * len(xs)))]


def run_chaos(queries: int = 80, rows: int = 200_000, n_agents: int = 3,
              kill_every: int = 7, restart_delay_s: float = 0.35,
              retries: int = 6, client_retries: int = 6,
              backoff_ms: int = 120) -> dict:
    """Drive the kill-and-restart replay; returns the chaos_recovery dict."""
    from pixie_tpu import flags, metrics
    from pixie_tpu.services.agent import Agent
    from pixie_tpu.services.broker import Broker
    from pixie_tpu.services.client import Client

    saved = {name: flags.get(name) for name in (
        "PL_QUERY_RETRIES", "PL_RETRY_BACKOFF_MS", "PL_CLIENT_RETRIES")}
    flags.set_for_testing("PL_QUERY_RETRIES", retries)
    flags.set_for_testing("PL_RETRY_BACKOFF_MS", backoff_ms)
    flags.set_for_testing("PL_CLIENT_RETRIES", client_retries)

    broker = Broker(hb_expiry_s=5.0, query_timeout_s=60.0).start()
    stores = {f"pem{i}": _mkstore(i + 1, rows) for i in range(n_agents)}
    agents = {n: Agent(n, "127.0.0.1", broker.port, store=st,
                       heartbeat_s=0.5).start() for n, st in stores.items()}
    client = Client("127.0.0.1", broker.port, timeout_s=90.0)

    def counters():
        return {
            "retries": metrics.counter_value("px_query_retries_total"),
            "evictions": metrics.counter_value("px_agent_evictions_total"),
            "hedged": metrics.counter_value("px_hedged_dispatches_total"),
            "discarded": metrics.counter_value("px_chunks_discarded_total"),
            "client_retries": metrics.counter_value(
                "px_client_retries_total"),
        }

    restarters: list[threading.Thread] = []

    def kill_and_restart(victim: str):
        """RST the victim's broker socket mid-flight (process-crash analog),
        then bring the SAME agent (name + store) back after the delay —
        the k8s pod restart the reference's churn assumptions model."""
        old = agents[victim]
        old.conn.abort()
        old.stop()

        def restart():
            time.sleep(restart_delay_s)
            agents[victim] = Agent(victim, "127.0.0.1", broker.port,
                                   store=stores[victim],
                                   heartbeat_s=0.5).start()

        th = threading.Thread(target=restart, daemon=True)
        th.start()
        restarters.append(th)

    try:
        # ---- fault-free baseline: canonical bytes + latencies ------------
        baseline: list[bytes] = []
        base_lat: list[float] = []
        for i in range(queries):
            t0 = time.perf_counter()
            res = client.execute_script(SCRIPTS[i % len(SCRIPTS)])
            base_lat.append(time.perf_counter() - t0)
            baseline.append(canonical_bytes(res))
        c0 = counters()

        # ---- chaos replay under the kill-and-restart schedule ------------
        chaos_lat: list[float] = []
        ok = 0
        bit_equal = 0
        errors = 0
        kills = 0
        victims = sorted(stores)
        for i in range(queries):
            if kill_every > 0 and i % kill_every == kill_every - 1:
                # the kill lands while query i is in flight: issue it on a
                # short fuse so some kills hit mid-stream, some mid-dispatch
                victim = victims[kills % len(victims)]
                kills += 1
                threading.Timer(0.01, kill_and_restart, (victim,)).start()
            t0 = time.perf_counter()
            try:
                res = client.execute_script(SCRIPTS[i % len(SCRIPTS)])
                chaos_lat.append(time.perf_counter() - t0)
                ok += 1
                if canonical_bytes(res) == baseline[i]:
                    bit_equal += 1
            except Exception:
                errors += 1
        for th in restarters:
            th.join(timeout=10.0)
        c1 = counters()
    finally:
        client.close()
        for a in agents.values():
            try:
                a.stop()
            except Exception:
                pass
        broker.stop()
        for name, v in saved.items():
            flags.set_for_testing(name, v)

    base_p99 = _pct(base_lat, 0.99) * 1000
    chaos_p99 = _pct(chaos_lat, 0.99) * 1000
    return {
        # `rows` = replayed query count: the SHAPE key --check-regressions
        # matches on, so a --smoke run never diffs against a full run
        "rows": queries,
        "queries": queries,
        "n_agents": n_agents,
        "kills": kills,
        "recovery_rate": round(ok / max(queries, 1), 4),
        "bit_equal_frac": round(bit_equal / max(queries, 1), 4),
        "client_errors": errors,
        "baseline_p99_ms": round(base_p99, 1),
        "chaos_p99_ms": round(chaos_p99, 1),
        "added_p99_ms": round(max(chaos_p99 - base_p99, 0.0), 1),
        "baseline_p50_ms": round(_pct(base_lat, 0.5) * 1000, 1),
        "chaos_p50_ms": round(_pct(chaos_lat, 0.5) * 1000, 1),
        "broker_retries": round(c1["retries"] - c0["retries"], 1),
        "evictions": round(c1["evictions"] - c0["evictions"], 1),
        "hedged": round(c1["hedged"] - c0["hedged"], 1),
        "chunks_discarded": round(c1["discarded"] - c0["discarded"], 1),
        "client_retries": round(c1["client_retries"] - c0["client_retries"],
                                1),
    }


def main(argv=None):  # pragma: no cover — exercised via bench.py
    import argparse
    import json

    ap = argparse.ArgumentParser()
    ap.add_argument("--queries", type=int, default=80)
    ap.add_argument("--rows", type=int, default=200_000)
    ap.add_argument("--agents", type=int, default=3)
    ap.add_argument("--kill-every", type=int, default=7)
    args = ap.parse_args(argv)
    print(json.dumps(run_chaos(queries=args.queries, rows=args.rows,
                               n_agents=args.agents,
                               kill_every=args.kill_every),
                     separators=(",", ":")))


if __name__ == "__main__":  # pragma: no cover
    main()

"""Chaos-recovery harness (the `chaos_recovery` bench config).

Replays a fixed query set against a REAL broker + agent deployment twice:
once fault-free (the baseline: canonical result bytes + latencies), then
under an injected kill-and-restart schedule — every `kill_every` queries an
agent's socket is RST mid-flight and the same agent (same name, same store)
restarts after `restart_delay_s`.  The whole fault-tolerance stack is in the
loop: broker-side eviction → re-plan → re-dispatch under fresh tokens
(`PL_QUERY_RETRIES`), straggler hedging, registry incarnation fencing of the
dead socket, and client-side auto-retry/reconnect (`PL_CLIENT_RETRIES`).

Acceptance (held absolutely by `bench.py --check-regressions`):

  * recovery_rate == 1.0 — every retryable query returns an answer; zero
    client-visible errors.
  * bit_equal_frac == 1.0 — each answer is BIT-equal to the fault-free
    baseline (canonical row order; float bit patterns compared, not
    approximations).  Kill-and-restart preserves each agent's store, and
    per-source folds merge in deterministic sorted-source order, so
    recovery must not perturb a single bit.
  * added_p99_ms bounded — recovery costs bounded latency (backoff +
    re-execution), never an unbounded stall.

Everything is measured from the run — no modeled numbers.
"""
from __future__ import annotations

import threading
import time

import numpy as np

#: the replayed query mix — retryable (non-mutation) shapes only: a partial
#: agg channel, a multi-key agg with float state (mean/p50 exercise float
#: fold determinism), and a rows channel with a filter
SCRIPTS = [
    """
df = px.DataFrame(table='http_events')
df = df.groupby('service').agg(cnt=('latency', px.count),
                               mx=('latency', px.max))
px.display(df, 'out')
""",
    """
df = px.DataFrame(table='http_events')
df = df.groupby(['service', 'status']).agg(
    cnt=('latency', px.count), m=('latency', px.mean),
    p50=('latency', px.p50))
px.display(df, 'out')
""",
    """
df = px.DataFrame(table='http_events')
df = df[df.status == 500]
df = df.groupby('service').agg(cnt=('latency', px.count),
                               s=('latency', px.sum))
px.display(df, 'out')
""",
]


def _mkdata(seed: int, rows: int) -> dict:
    rng = np.random.default_rng(seed)
    svc = np.array([f"svc-{i}" for i in range(6)])
    return {
        "time_": np.arange(rows, dtype=np.int64) * 1000,
        "service": svc[rng.integers(0, len(svc), rows)],
        "latency": rng.exponential(20.0, rows),
        "status": rng.choice([200, 404, 500], rows, p=[0.9, 0.05, 0.05]),
    }


def _mkstore(seed: int, rows: int, batch_rows: int = 1 << 13):
    from pixie_tpu.table import TableStore
    from pixie_tpu.types import DataType as DT, Relation

    ts = TableStore()
    rel = Relation.of(
        ("time_", DT.TIME64NS), ("service", DT.STRING),
        ("latency", DT.FLOAT64), ("status", DT.INT64),
    )
    t = ts.create("http_events", rel, batch_rows=batch_rows,
                  max_bytes=1 << 32)
    if rows:
        t.write(_mkdata(seed, rows))
    return ts


def canonical_bytes(results: dict) -> bytes:
    """Order-independent BIT-exact fingerprint of a query answer: per table,
    rows sort lexicographically by every column's VALUE (dictionary codes
    decoded — code spaces differ across merges by construction) and the
    sorted columns' raw bytes concatenate.  Float columns contribute their
    bit patterns: a recovered query that differs in one ulp fails."""
    out = []
    for name in sorted(results):
        qr = results[name]
        cols = {}
        for cname in sorted(qr.columns):
            arr = qr.columns[cname]
            if cname in qr.dictionaries:
                vals = qr.dictionaries[cname].decode(arr)
                cols[cname] = np.asarray(
                    [v if v is not None else "" for v in vals], dtype=object)
            else:
                cols[cname] = np.asarray(arr)
        if cols:
            order = np.lexsort([cols[c] if cols[c].dtype != object
                                else np.asarray(cols[c], dtype="U64")
                                for c in sorted(cols)])
        for cname in sorted(cols):
            arr = cols[cname][order] if cols else cols[cname]
            out.append(cname.encode())
            if arr.dtype == object:
                out.append("\x00".join(str(v) for v in arr).encode())
            else:
                out.append(arr.tobytes())  # bit patterns, not repr
    return b"\x01".join(out)


def _pct(xs: list, q: float) -> float:
    if not xs:
        return 0.0
    xs = sorted(xs)
    return xs[min(len(xs) - 1, int(q * len(xs)))]


def run_chaos(queries: int = 80, rows: int = 200_000, n_agents: int = 3,
              kill_every: int = 7, restart_delay_s: float = 0.35,
              retries: int = 6, client_retries: int = 6,
              backoff_ms: int = 120) -> dict:
    """Drive the kill-and-restart replay; returns the chaos_recovery dict."""
    from pixie_tpu import flags, metrics
    from pixie_tpu.services.agent import Agent
    from pixie_tpu.services.broker import Broker
    from pixie_tpu.services.client import Client

    saved = {name: flags.get(name) for name in (
        "PL_QUERY_RETRIES", "PL_RETRY_BACKOFF_MS", "PL_CLIENT_RETRIES")}
    flags.set_for_testing("PL_QUERY_RETRIES", retries)
    flags.set_for_testing("PL_RETRY_BACKOFF_MS", backoff_ms)
    flags.set_for_testing("PL_CLIENT_RETRIES", client_retries)

    broker = Broker(hb_expiry_s=5.0, query_timeout_s=60.0).start()
    stores = {f"pem{i}": _mkstore(i + 1, rows) for i in range(n_agents)}
    agents = {n: Agent(n, "127.0.0.1", broker.port, store=st,
                       heartbeat_s=0.5).start() for n, st in stores.items()}
    client = Client("127.0.0.1", broker.port, timeout_s=90.0)

    def counters():
        return {
            "retries": metrics.counter_value("px_query_retries_total"),
            "evictions": metrics.counter_value("px_agent_evictions_total"),
            "hedged": metrics.counter_value("px_hedged_dispatches_total"),
            "discarded": metrics.counter_value("px_chunks_discarded_total"),
            "client_retries": metrics.counter_value(
                "px_client_retries_total"),
        }

    restarters: list[threading.Thread] = []

    def kill_and_restart(victim: str):
        """RST the victim's broker socket mid-flight (process-crash analog),
        then bring the SAME agent (name + store) back after the delay —
        the k8s pod restart the reference's churn assumptions model."""
        old = agents[victim]
        old.conn.abort()
        old.stop()

        def restart():
            time.sleep(restart_delay_s)
            agents[victim] = Agent(victim, "127.0.0.1", broker.port,
                                   store=stores[victim],
                                   heartbeat_s=0.5).start()

        th = threading.Thread(target=restart, daemon=True)
        th.start()
        restarters.append(th)

    try:
        # ---- fault-free baseline: canonical bytes + latencies ------------
        baseline: list[bytes] = []
        base_lat: list[float] = []
        for i in range(queries):
            t0 = time.perf_counter()
            res = client.execute_script(SCRIPTS[i % len(SCRIPTS)])
            base_lat.append(time.perf_counter() - t0)
            baseline.append(canonical_bytes(res))
        c0 = counters()

        # ---- chaos replay under the kill-and-restart schedule ------------
        chaos_lat: list[float] = []
        ok = 0
        bit_equal = 0
        errors = 0
        kills = 0
        victims = sorted(stores)
        for i in range(queries):
            if kill_every > 0 and i % kill_every == kill_every - 1:
                # the kill lands while query i is in flight: issue it on a
                # short fuse so some kills hit mid-stream, some mid-dispatch
                victim = victims[kills % len(victims)]
                kills += 1
                threading.Timer(0.01, kill_and_restart, (victim,)).start()
            t0 = time.perf_counter()
            try:
                res = client.execute_script(SCRIPTS[i % len(SCRIPTS)])
                chaos_lat.append(time.perf_counter() - t0)
                ok += 1
                if canonical_bytes(res) == baseline[i]:
                    bit_equal += 1
            except Exception:
                errors += 1
        for th in restarters:
            th.join(timeout=10.0)
        c1 = counters()
    finally:
        client.close()
        for a in agents.values():
            try:
                a.stop()
            except Exception:
                pass
        broker.stop()
        for name, v in saved.items():
            flags.set_for_testing(name, v)

    base_p99 = _pct(base_lat, 0.99) * 1000
    chaos_p99 = _pct(chaos_lat, 0.99) * 1000
    return {
        # `rows` = replayed query count: the SHAPE key --check-regressions
        # matches on, so a --smoke run never diffs against a full run
        "rows": queries,
        "queries": queries,
        "n_agents": n_agents,
        "kills": kills,
        "recovery_rate": round(ok / max(queries, 1), 4),
        "bit_equal_frac": round(bit_equal / max(queries, 1), 4),
        "client_errors": errors,
        "baseline_p99_ms": round(base_p99, 1),
        "chaos_p99_ms": round(chaos_p99, 1),
        "added_p99_ms": round(max(chaos_p99 - base_p99, 0.0), 1),
        "baseline_p50_ms": round(_pct(base_lat, 0.5) * 1000, 1),
        "chaos_p50_ms": round(_pct(chaos_lat, 0.5) * 1000, 1),
        "broker_retries": round(c1["retries"] - c0["retries"], 1),
        "evictions": round(c1["evictions"] - c0["evictions"], 1),
        "hedged": round(c1["hedged"] - c0["hedged"], 1),
        "chunks_discarded": round(c1["discarded"] - c0["discarded"], 1),
        "client_retries": round(c1["client_retries"] - c0["client_retries"],
                                1),
    }


#: hard-mode batch size: `rows` is rounded UP to a multiple of this so every
#: acked row seals (and therefore replicates) before the chaos phase — the
#: precondition for zero-loss recovery when the journal dies WITH the pod
HARD_BATCH_ROWS = 1 << 12


def run_chaos_hard(queries: int = 60, rows: int = 24_576, n_agents: int = 3,
                   kill_every: int = 7, restart_delay_s: float = 0.8,
                   retries: int = 6, client_retries: int = 6,
                   backoff_ms: int = 120, replication: int = 2,
                   rejoin_grace_s: float = 0.3) -> dict:
    """The durable-data-plane proof (`chaos_recovery_hard` bench config).

    Same replayed-query contract as `run_chaos`, but the kills are TRUE pod
    losses: the fault injector's `kill:` rule fires the victim agent's
    registered handler, which DROPS its in-memory store before the socket
    RSTs — nothing survives in process state.  Kills alternate between two
    recovery paths:

      * journal kill — the victim's `PL_DATA_DIR` tree survives (a pod
        restart on the same node): the restarted agent replays its ingest
        journal into a fresh store.
      * wipe kill — the victim's data dir is deleted too (node loss): the
        restarted agent rehydrates purely by peer fetch of the sealed
        batches its `PL_REPLICATION` replicas hold.

    While a victim is down past the rejoin grace, its fragments serve from
    a promoted replica (broker failover), so queries keep answering over
    the FULL data set — the restart delay deliberately EXCEEDS the grace so
    every kill exercises the failover path, not just the rejoin hold.
    Acceptance, held absolutely by `bench.py --check-regressions`:

      * row_loss == 0 — every acknowledged row is present in every agent's
        store after the final recovery (journal replay + peer fetch).
      * bit_equal_frac == 1.0 and client_errors == 0 — replayed answers
        stay bit-identical to the fault-free baseline throughout, whether
        served by the primary, a failover replica, or a rehydrated store.
      * recovery_s_max bounded — a restarted agent is registered and
        serving within the recovery budget.
    """
    import os
    import shutil
    import tempfile

    from pixie_tpu import flags, metrics
    from pixie_tpu.services import faultinject
    from pixie_tpu.services.agent import Agent
    from pixie_tpu.services.broker import Broker
    from pixie_tpu.services.client import Client
    from pixie_tpu.table import TableStore

    rows = -(-rows // HARD_BATCH_ROWS) * HARD_BATCH_ROWS
    data_dir = tempfile.mkdtemp(prefix="px-chaos-hard-")
    saved = {name: flags.get(name) for name in (
        "PL_QUERY_RETRIES", "PL_RETRY_BACKOFF_MS", "PL_CLIENT_RETRIES",
        "PL_DATA_DIR", "PL_REPLICATION", "PL_REJOIN_GRACE_S",
        "PL_JOURNAL_FSYNC")}
    flags.set_for_testing("PL_QUERY_RETRIES", retries)
    flags.set_for_testing("PL_RETRY_BACKOFF_MS", backoff_ms)
    flags.set_for_testing("PL_CLIENT_RETRIES", client_retries)
    flags.set_for_testing("PL_DATA_DIR", data_dir)
    flags.set_for_testing("PL_REPLICATION", replication)
    flags.set_for_testing("PL_REJOIN_GRACE_S", rejoin_grace_s)
    # batch policy: the in-process kill model loses process state, not the
    # page cache, so per-record fsync would only slow the bench down
    flags.set_for_testing("PL_JOURNAL_FSYNC", "batch")

    broker = Broker(hb_expiry_s=5.0, query_timeout_s=60.0).start()
    agents: dict[str, Agent] = {}
    expected_rows: dict[str, int] = {}
    for i in range(n_agents):
        name = f"pem{i}"
        ts = _mkstore(i + 1, 0, batch_rows=HARD_BATCH_ROWS)
        agents[name] = Agent(name, "127.0.0.1", broker.port, store=ts,
                             heartbeat_s=0.4).start()
    # ingest AFTER start: journal + replication hooks are attached, so every
    # written row is acked-durable; rows divide the batch size so the whole
    # data set seals (and replicates) before any fault fires
    for i, name in enumerate(sorted(agents)):
        agents[name].store.table("http_events").write(_mkdata(i + 1, rows))
        expected_rows[name] = rows
    for a in agents.values():
        if a.replication is not None and not a.replication.wait_synced(30.0):
            raise RuntimeError("replication did not sync before chaos phase")
    client = Client("127.0.0.1", broker.port, timeout_s=90.0)

    restarters: list[threading.Thread] = []
    recovery_s: list[float] = []
    decision_log: list[tuple] = []

    def kill_and_restart(victim: str, wipe: bool):
        """Arm a one-shot `kill:` rule for the victim's broker link — its
        next outbound frame drops the store and RSTs — then restart it
        with a FRESH store after the delay (journal replay + peer fetch
        do the recovery; nothing is preserved in process state)."""
        t_kill = time.monotonic()
        inj = faultinject.install(f"kill:agent:{victim}@send=1")

        def restart():
            old = agents[victim]
            if not old.pod_killed.wait(timeout=10.0):
                return  # the rule never fired (stopped bench)
            decision_log.extend(inj.log)
            if wipe:
                shutil.rmtree(os.path.join(data_dir, victim),
                              ignore_errors=True)
            time.sleep(restart_delay_s)
            agents[victim] = Agent(victim, "127.0.0.1", broker.port,
                                   store=TableStore(),
                                   heartbeat_s=0.4).start()
            recovery_s.append(time.monotonic() - t_kill)

        th = threading.Thread(target=restart, daemon=True)
        th.start()
        restarters.append(th)

    try:
        baseline: list[bytes] = []
        base_lat: list[float] = []
        for i in range(queries):
            t0 = time.perf_counter()
            res = client.execute_script(SCRIPTS[i % len(SCRIPTS)])
            base_lat.append(time.perf_counter() - t0)
            baseline.append(canonical_bytes(res))

        chaos_lat: list[float] = []
        ok = bit_equal = errors = kills = wipes = 0
        victims = sorted(agents)
        for i in range(queries):
            if kill_every > 0 and i % kill_every == kill_every - 1:
                # serialize recoveries: the next kill waits for the prior
                # victim to finish rehydrating (two simultaneous losses
                # would exceed PL_REPLICATION=2's tolerance by design)
                for th in restarters:
                    th.join(timeout=30.0)
                wipe = kills % 2 == 1
                wipes += int(wipe)
                kills += 1
                kill_and_restart(victims[kills % len(victims)], wipe)
            t0 = time.perf_counter()
            try:
                res = client.execute_script(SCRIPTS[i % len(SCRIPTS)])
                chaos_lat.append(time.perf_counter() - t0)
                ok += 1
                if canonical_bytes(res) == baseline[i]:
                    bit_equal += 1
            except Exception:
                errors += 1
        for th in restarters:
            th.join(timeout=30.0)
        # the zero-loss audit: after the last recovery every agent holds
        # every row it ever acked (journal replay and/or peer fetch)
        row_loss = 0
        for name, a in sorted(agents.items()):
            have = (a.store.table("http_events").stats()["rows_written"]
                    if a.store.has("http_events") else 0)
            row_loss += max(0, expected_rows[name] - have)
        repl_rows = metrics.counter_value("px_repl_rehydrated_rows_total")
        journal_rows = metrics.counter_value("px_journal_replayed_rows_total")
        failover_serves = metrics.counter_value("px_failover_serves_total")
    finally:
        faultinject.uninstall()
        client.close()
        for a in agents.values():
            try:
                a.stop()
            except Exception:
                pass
        broker.stop()
        for name, v in saved.items():
            flags.set_for_testing(name, v)
        shutil.rmtree(data_dir, ignore_errors=True)

    base_p99 = _pct(base_lat, 0.99) * 1000
    chaos_p99 = _pct(chaos_lat, 0.99) * 1000
    return {
        "rows": queries,  # the --check-regressions shape key
        "queries": queries,
        "ingest_rows": rows,
        "n_agents": n_agents,
        "replication": replication,
        "kills": kills,
        "wipe_kills": wipes,
        "row_loss": row_loss,
        "recovery_rate": round(ok / max(queries, 1), 4),
        "bit_equal_frac": round(bit_equal / max(queries, 1), 4),
        "client_errors": errors,
        "recovery_s_max": round(max(recovery_s, default=0.0), 2),
        "recovery_s_mean": round(sum(recovery_s)
                                 / max(len(recovery_s), 1), 2),
        "baseline_p99_ms": round(base_p99, 1),
        "chaos_p99_ms": round(chaos_p99, 1),
        "added_p99_ms": round(max(chaos_p99 - base_p99, 0.0), 1),
        "journal_replayed_rows": round(journal_rows, 1),
        "repl_rehydrated_rows": round(repl_rows, 1),
        "failover_serves": round(failover_serves, 1),
        "kill_decisions": len(decision_log),
    }


def main(argv=None):  # pragma: no cover — exercised via bench.py
    import argparse
    import json

    ap = argparse.ArgumentParser()
    ap.add_argument("--queries", type=int, default=80)
    ap.add_argument("--rows", type=int, default=200_000)
    ap.add_argument("--agents", type=int, default=3)
    ap.add_argument("--kill-every", type=int, default=7)
    ap.add_argument("--hard", action="store_true",
                    help="run the durable-data-plane variant (store+journal "
                         "destruction, replication failover, rehydration)")
    args = ap.parse_args(argv)
    fn = run_chaos_hard if args.hard else run_chaos
    print(json.dumps(fn(queries=args.queries, rows=args.rows,
                        n_agents=args.agents, kill_every=args.kill_every),
                     separators=(",", ":")))


if __name__ == "__main__":  # pragma: no cover
    main()

"""Query broker: the networked ExecuteScript front door.

Reference: src/vizier/services/query_broker — Server.ExecuteScript
(controllers/server.go:307) compiles the script, LaunchQuery ships per-agent
plans (launch_query.go:36-66), and QueryResultForwarder merges agent result
streams into the client stream with producer/consumer watchdogs
(query_result_forwarder.go:358-560).

This broker listens on one framed-TCP port for BOTH agents and clients
(the envelope's `msg` field routes).  Per query: compile against the live
registry's schemas, split with DistributedPlanner, push `execute` frames to
each agent's connection, collect channel payload frames, merge (partials via
combine/finalize, rows via dictionary-reconciled union), run the merger plan
locally, and stream result chunks back to the client.
"""
from __future__ import annotations

import json as _json
import threading
import traceback
from typing import Optional

from pixie_tpu import trace
from pixie_tpu.engine.executor import HostBatch, PlanExecutor
from pixie_tpu.engine.result import QueryResult
from pixie_tpu.parallel.distributed import DistributedPlanner
from pixie_tpu.serving import COST_COLD, COST_WARM, ServingFront, ShedError
from pixie_tpu.services import wire
from pixie_tpu.services.kvstore import KVStore
from pixie_tpu.services.registry import AgentRegistry
from pixie_tpu.services.transport import Connection, Server
from pixie_tpu.status import PxError
from pixie_tpu.table.table import TableStore
from pixie_tpu.types import Relation

DEFAULT_QUERY_TIMEOUT_S = 60.0

#: tenant id stamped on queries that arrive without one (older clients,
#: in-process callers like cron): they share one namespace and one quota
#: bucket rather than bypassing admission entirely
DEFAULT_TENANT = "default"

#: broker end-to-end query latency buckets (seconds)
QUERY_LATENCY_BOUNDS = (0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
                        10.0, 30.0, 60.0)

#: incremental_fold spans recorded per query (folds beyond the cap still
#: merge and count; only their span detail is dropped)
MAX_FOLD_EVENT_SPANS = 256


class _QueryCtx:
    def __init__(self, expected_agents: set[str], channels: set[str]):
        import secrets

        self.payloads: dict[str, list] = {c: [] for c in channels}
        self.pending_agents = set(expected_agents)
        self.agent_stats: dict[str, dict] = {}
        self.error: Optional[str] = None
        self.done = threading.Event()
        #: per-agent dispatch spans (trace.Span), opened at frame send and
        #: closed by the exec_done/exec_error handler threads
        self.dispatch_spans: dict[str, object] = {}
        #: per-query auth token: agents must echo it on every result chunk
        #: and completion frame, so a stale/confused/malicious producer
        #: cannot inject rows into another query's stream (reference: the
        #: broker injects a per-query auth token into GRPCSinks and the
        #: result-sink server validates it, carnotpb/carnot.proto:30-96)
        self.token = secrets.token_urlsafe(12)
        # ---- streaming incremental merge (set up by configure_folds) ----
        #: channel id → PartialAggFold | HostBatchUnion: chunk frames fold
        #: into these AS THEY ARRIVE (reader threads), so merge work hides
        #: under the slowest agent's compute; channels without a fold (join
        #: bucket channels) accumulate in `payloads` as before
        self.folds: dict[str, object] = {}
        #: per-channel locks: fold.add serializes across agent reader
        #: threads (the accumulators are not thread-safe), but folds on
        #: DISTINCT channels share no state — a heavy agg combine on one
        #: channel must not stall another channel's folds and acks
        self.fold_locks: dict[str, threading.Lock] = {}
        #: channel → chunks folded / expected (expected accumulates from the
        #: per-agent counts on exec_done frames)
        self.folded_chunks: dict[str, int] = {}
        self.expected_chunks: dict[str, int] = {}
        #: (start_unix_ns, duration_ns, channel, agent) per fold, emitted as
        #: incremental_fold spans at merge time (the reader threads hold no
        #: trace context); capped — first_fold_ns/last_terminal_ns carry the
        #: overlap evidence, span detail beyond the cap adds nothing
        self.fold_events: list[tuple] = []
        self.first_fold_ns: Optional[int] = None
        self.last_terminal_ns: Optional[int] = None

    def configure_folds(self, dp, registry) -> None:
        """Arm one incremental accumulator per merge-input channel.  Must run
        before the first `execute` frame is sent (chunks race the dispatch
        loop); join-stage bucket channels keep list accumulation — the stage
        runner consumes whole per-partition lists at merge time."""
        from pixie_tpu.parallel.cluster import HostBatchUnion
        from pixie_tpu.parallel.partial import PartialAggFold
        from pixie_tpu.parallel.repartition import bucket_channels

        consumed = bucket_channels(dp)
        for cid, ch in dp.channels.items():
            if cid in consumed:
                continue
            if ch.kind == "agg_state":
                self.folds[cid] = PartialAggFold(ch.agg, registry)
            else:
                self.folds[cid] = HostBatchUnion()
            self.fold_locks[cid] = threading.Lock()

    def fold_chunk(self, meta: dict, payload) -> None:
        """Fold one producer chunk frame; called from connection reader
        threads.  A malformed chunk fails the QUERY (error + done), never
        the reader thread."""
        import time as _time

        cid = meta["channel"]
        fold = self.folds.get(cid)
        if fold is None:
            self.payloads.setdefault(cid, []).append(payload)
            return
        from pixie_tpu.parallel.cluster import HostBatchUnion
        from pixie_tpu.parallel.partial import PartialAggBatch, PartialAggFold

        t0 = _time.time_ns()
        try:
            with self.fold_locks[cid]:
                if isinstance(fold, PartialAggFold):
                    if not isinstance(payload, PartialAggBatch):
                        raise TypeError(
                            f"channel {cid}: expected agg_state payloads")
                elif isinstance(fold, HostBatchUnion):
                    if not isinstance(payload, HostBatch):
                        raise TypeError(f"channel {cid}: expected row payloads")
                fold.add(payload)
                self.folded_chunks[cid] = self.folded_chunks.get(cid, 0) + 1
        except Exception as e:
            self.error = f"chunk fold failed on channel {cid}: {e}"
            self.done.set()
            return
        if self.first_fold_ns is None:
            self.first_fold_ns = t0
        if len(self.fold_events) < MAX_FOLD_EVENT_SPANS:
            self.fold_events.append(
                (t0, _time.time_ns() - t0, cid, meta.get("agent")))


class Broker:
    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        datastore_path: str = ":memory:",
        hb_expiry_s: float = 15.0,
        registry=None,
        query_timeout_s: float = DEFAULT_QUERY_TIMEOUT_S,
        auth_token: Optional[str] = None,
        healthz_port: Optional[int] = None,
        elector=None,
        election_id: Optional[str] = None,
    ):
        # resource-free validation FIRST: a raise here must not leak a
        # bound socket or an open KV handle
        if (election_id is not None and elector is None
                and datastore_path == ":memory:"):
            from pixie_tpu.status import InvalidArgument

            raise InvalidArgument(
                "leader election requires a shared --datastore file "
                "(an in-memory lease is private to this process)")
        #: shared-secret auth (reference fronts this port with JWT,
        #: src/shared/services/).  When set, every connection must present the
        #: token in an `auth` frame before any other message is honored.  The
        #: port must never be exposed beyond a trusted network regardless.
        self.auth_token = auth_token
        self.udf_registry = registry
        self.query_timeout_s = query_timeout_s
        self.merger_store = TableStore()
        #: whole-query plan cache (PL_QUERY_FASTPATH): warm dashboard
        #: queries skip re-trace/re-optimize/re-split/re-serialize — see
        #: engine/plancache.py for the soundness argument
        from pixie_tpu.engine.plancache import QueryPlanCache

        self.plan_cache = QueryPlanCache()
        #: multi-tenant serving front (pixie_tpu.serving): every
        #: ExecuteScript passes its admission gate (per-tenant token
        #: buckets, global in-flight cap, DRR fair-share dispatch) and
        #: returns its slot on completion.  PL_SERVING_ENABLED=0 makes it
        #: a pass-through.
        self.serving = ServingFront("broker")
        #: self-telemetry spans for the query path; shipped to an agent's
        #: spans table at query end (the broker holds no scanned store)
        self.tracer = trace.Tracer("broker")
        self._agent_conns: dict[str, Connection] = {}
        self._queries: dict[str, _QueryCtx] = {}
        self._qlock = threading.Lock()
        self._req_counter = 0
        self._stopped = threading.Event()
        self._expiry_thread = threading.Thread(
            target=self._expiry_loop, daemon=True, name="pixie-broker-expiry"
        )
        self.kv = KVStore(datastore_path)
        self.healthz: Optional[object] = None
        self._server = None
        try:
            self.registry = AgentRegistry(self.kv, expiry_s=hb_expiry_s)
            from pixie_tpu.services.tracepoints import TracepointManager

            #: cluster-level tracepoint registry (metadata-service analog:
            #: persisted in the control KV, surfaced by GetTracepointStatus)
            self.tracepoints = TracepointManager(self.merger_store, kv=self.kv)
            from pixie_tpu.services.cron import CronScriptRunner

            #: cron scripts (reference script_runner.go:47-54), persisted in kv
            self.cron = CronScriptRunner(
                lambda script, func, func_args: self.execute_script(
                    script, func=func, func_args=func_args
                )[0],
                kv=self.kv,
            )
            #: optional LeaderElector (services/election.py): when set, this
            #: broker only serves queries while holding the lease — a standby
            #: broker sharing the KV takes over when the leader dies
            #: (reference src/shared/services/election/).  `election_id`
            #: builds one over THIS broker's kv (one handle, one close path).
            if election_id is not None and elector is None:
                from pixie_tpu.services.election import LeaderElector

                elector = LeaderElector(self.kv, "broker", election_id)
            self.elector = elector
            #: optional HTTP healthz/metrics listener (reference
            #: src/shared/services/ healthz for k8s probes).  Leadership is
            #: a READINESS concern only: a healthy standby must pass
            #: /healthz (liveness) or a k8s liveness probe would restart it
            #: in a loop, defeating failover.
            if healthz_port is not None:
                from pixie_tpu.services.health import HealthzServer

                def _kv_alive() -> bool:
                    self.kv.get("__healthz")  # raises when the kv is unusable
                    return True

                self.healthz = HealthzServer(checks={
                    "kv": _kv_alive,
                    "server": lambda: not self._stopped.is_set(),
                }, ready_checks={
                    "leader": lambda: (self.elector is None
                                       or self.elector.is_leader()),
                }, host=host, port=healthz_port)
                # READINESS only: an overloaded broker (admission queue
                # past the shed watermark) must drop out of the serving
                # endpoints without a liveness restart wiping its queues
                self.healthz.add_ready_check("serving", self.serving.ready)
            self._server = Server(host, port, self._on_frame, self._on_close)
        except Exception:
            if self.healthz is not None:
                self.healthz.stop()
            self.kv.close()
            raise

    # ------------------------------------------------------------------ server
    @property
    def port(self) -> int:
        return self._server.port

    def start(self) -> "Broker":
        from pixie_tpu import metrics as _metrics

        _metrics.register_gauge_fn(
            "px_broker_live_agents",
            lambda: {(): float(len(self.registry.live_agents()))},
            "agents currently live in the registry",
        )
        trace.register_gauges()
        self.serving.attach_gauges()
        self._server.start()
        self._expiry_thread.start()
        self.cron.start()
        if self.elector is not None:
            self.elector.start()
        if self.healthz is not None:
            self.healthz.start()
        return self

    def stop(self):
        from pixie_tpu import metrics as _metrics

        self._stopped.set()
        self.cron.stop()
        if self.healthz is not None:
            self.healthz.stop()
        if self.elector is not None:
            self.elector.stop()
        self._server.stop()
        self.serving.detach_gauges()
        _metrics.unregister_gauge_fn("px_broker_live_agents")
        self.kv.close()

    def _expiry_loop(self):
        while not self._stopped.wait(timeout=max(self.registry.expiry_s / 3, 0.2)):
            self.registry.expire()
            # Reconcile connections against registry liveness — no matter
            # WHICH thread's expire() marked an agent dead (query paths call
            # live_agents() too), its connection gets closed here.  Dead
            # agents can't be revived by heartbeats (registry.heartbeat), so
            # this doesn't race a revival.
            live = {r.name for r in self.registry.live_agents()}
            for name, conn in list(self._agent_conns.items()):
                if name not in live:
                    self._agent_conns.pop(name, None)
                    conn.close()

    # ------------------------------------------------------------------ frames
    def _on_frame(self, conn: Connection, frame: bytes):
        if self.auth_token is not None and not conn.state.get("authed"):
            import hmac

            # Unauthenticated peers get NO decode work: the only acceptable
            # first frame is a small auth json.  Oversized or malformed
            # frames close the connection without allocating for them
            # (decode_frame would happily materialize a 1GB host_batch).
            if len(frame) > 4096:
                conn.close()
                return
            try:
                kind, payload = wire.decode_frame(frame)
            except Exception:
                conn.close()
                return
            # compare_digest over utf-8 bytes: str operands raise TypeError
            # on non-ASCII, which would skip the reject-and-close path.
            if (kind == "json" and payload.get("msg") == "auth"
                    and hmac.compare_digest(
                        str(payload.get("token", "")).encode(),
                        self.auth_token.encode())):
                conn.state["authed"] = True
                conn.send(wire.encode_json({"msg": "auth_ok"}))
            else:
                rid = payload.get("req_id") if kind == "json" else None
                conn.send(wire.encode_json(
                    {"msg": "error", "req_id": rid,
                     "error": "authentication required"}))
                conn.close()
            return
        kind, payload = wire.decode_frame(frame)
        if kind == "json":
            msg = payload.get("msg")
            if msg == "auth":
                conn.state["authed"] = True
                conn.send(wire.encode_json({"msg": "auth_ok"}))
            elif msg == "register":
                self._handle_register(conn, payload)
            elif msg == "heartbeat":
                if not self.registry.heartbeat(payload["agent"]):
                    conn.send(wire.encode_json({"msg": "reregister"}))
            elif msg == "tracepoint_ready":
                self._handle_exec_done({
                    "req_id": payload.get("req_id"),
                    "qtoken": payload.get("qtoken"),
                    "agent": payload.get("agent"), "stats": {},
                })
            elif msg == "tracepoint_error":
                self._handle_exec_error(payload)
            elif msg == "exec_done":
                self._handle_exec_done(payload)
            elif msg == "exec_error":
                self._handle_exec_error(payload)
            elif msg == "execute_script":
                threading.Thread(
                    target=self._run_query, args=(conn, payload), daemon=True
                ).start()
            elif msg == "metrics":
                from pixie_tpu import metrics as _metrics

                conn.send(wire.encode_json({
                    "msg": "metrics_text",
                    "req_id": payload.get("req_id"),
                    "text": _metrics.render(),
                }))
            elif msg == "flags":
                from pixie_tpu import flags as _flags

                conn.send(wire.encode_json({
                    "msg": "flags_dump",
                    "req_id": payload.get("req_id"),
                    "flags": _flags.dump(),
                }))
            elif msg == "cron_upsert":
                self._reply_ack(conn, payload, lambda: self.cron.upsert(
                    payload["name"], payload["script"],
                    payload.get("interval_s", 60.0),
                    func=payload.get("func"),
                    func_args=payload.get("func_args"),
                ))
            elif msg == "cron_delete":
                self._reply_ack(
                    conn, payload, lambda: self.cron.delete(payload["name"])
                )
            elif msg == "cron_list":
                conn.send(wire.encode_json({
                    "msg": "cron_scripts", "req_id": payload.get("req_id"),
                    "scripts": [
                        {"name": c.name, "interval_s": c.interval_s,
                         "enabled": c.enabled, "run_count": c.run_count,
                         "error_count": c.error_count,
                         "last_error": c.last_error}
                        for c in self.cron.list()
                    ],
                }))
            elif msg == "list_schemas":
                conn.send(wire.encode_json({
                    "msg": "schemas",
                    "req_id": payload.get("req_id"),
                    "schemas": {
                        t: r.to_dict()
                        for t, r in self.registry.combined_schemas().items()
                    },
                }))
            else:
                conn.send(wire.encode_json({"msg": "error", "error": f"unknown msg {msg!r}"}))
        else:
            # data chunk from an agent (host_batch | partial_agg)
            meta = payload.wire_meta
            self._handle_chunk(meta, payload)

    @staticmethod
    def _reply_ack(conn: Connection, payload: dict, fn) -> None:
        """Run a control action; reply {msg: ok} or the error envelope."""
        try:
            fn()
            conn.send(wire.encode_json({"msg": "ok", "req_id": payload.get("req_id")}))
        except Exception as e:
            conn.send(wire.encode_json({
                "msg": "error", "req_id": payload.get("req_id"), "error": str(e),
            }))

    def _on_close(self, conn: Connection):
        name = conn.state.get("agent")
        if name is not None:
            self.registry.mark_dead(name)
            self._agent_conns.pop(name, None)
            # fail this agent's pending queries (producer watchdog analog)
            with self._qlock:
                for ctx in self._queries.values():
                    if name in ctx.pending_agents:
                        ctx.error = f"agent {name} disconnected mid-query"
                        ctx.done.set()

    # ---------------------------------------------------------------- handlers
    def _handle_register(self, conn: Connection, meta: dict):
        name = meta["agent"]
        schemas = {t: Relation.from_dict(r) for t, r in meta["schemas"].items()}
        asid = self.registry.register(name, schemas, meta.get("n_devices"))
        conn.state["agent"] = name
        old = self._agent_conns.get(name)
        if old is not None and old is not conn:
            old.state.pop("agent", None)  # superseded; don't let its close kill the new one
            old.close()
        self._agent_conns[name] = conn
        conn.send(wire.encode_json({"msg": "registered", "asid": asid}))

    def _ctx(self, meta: dict) -> Optional[_QueryCtx]:
        """Resolve the query ctx for a producer frame, enforcing the
        per-query token.  Mismatched/missing tokens are dropped (and
        counted): a stale producer must not corrupt a newer query that
        reused context state."""
        import hmac

        with self._qlock:
            ctx = self._queries.get(meta.get("req_id", ""))
        if ctx is None:
            return None
        # utf-8 bytes operands: compare_digest raises TypeError on non-ASCII
        # str, which would skip the counted-drop path (same pitfall the auth
        # handler avoids)
        if not hmac.compare_digest(
                str(meta.get("qtoken", "")).encode(), ctx.token.encode()):
            from pixie_tpu import metrics as _metrics

            _metrics.counter_inc(
                "px_broker_stale_token_frames_total",
                help_="producer frames rejected for a bad per-query token")
            # surfaced loudly: an agent that never echoes the token (e.g. a
            # version mismatch) would otherwise present as an opaque query
            # timeout with only a metric to explain it
            _metrics.warn(
                "dropping producer frame with bad per-query token",
                req_id=meta.get("req_id"), agent=meta.get("agent"),
                has_token=bool(meta.get("qtoken")))
            return None
        return ctx

    def _handle_chunk(self, meta: dict, payload):
        ctx = self._ctx(meta)
        if ctx is not None:
            ctx.fold_chunk(meta, payload)
        # Open the producer's in-flight window (its backpressure gate): the
        # ack means this chunk's fold work is DONE, so a slow merge throttles
        # the agents instead of queueing unbounded frames.  Acked even when
        # the query is already dead (ctx None / stale token): acks are pure
        # flow control, and a producer still draining a doomed stream must
        # not stall on a window nobody will ever open.
        conn = self._agent_conns.get(meta.get("agent", ""))
        if conn is not None and not conn.closed:
            conn.send(wire.encode_json({
                "msg": "chunk_ack", "req_id": meta.get("req_id"),
                "channel": meta["channel"], "seq": meta.get("seq"),
            }))

    def _finish_dispatch_span(self, ctx: _QueryCtx, agent,
                              error: Optional[str] = None) -> None:
        sp = ctx.dispatch_spans.pop(agent, None)
        if sp is not None:
            if error:
                sp.attributes["error"] = error[:200]
            self.tracer.finish(sp)

    def _handle_exec_done(self, meta: dict):
        import time as _time

        ctx = self._ctx(meta)
        if ctx is None:
            return
        ctx.agent_stats[meta["agent"]] = meta.get("stats", {})
        ctx.last_terminal_ns = _time.time_ns()
        for cid, n in (meta.get("chunks") or {}).items():
            ctx.expected_chunks[cid] = ctx.expected_chunks.get(cid, 0) + int(n)
        self._finish_dispatch_span(ctx, meta["agent"])
        ctx.pending_agents.discard(meta["agent"])
        if not ctx.pending_agents:
            ctx.done.set()

    def _handle_exec_error(self, meta: dict):
        ctx = self._ctx(meta)
        if ctx is None:
            return
        ctx.error = f"agent {meta.get('agent')}: {meta.get('error')}"
        self._finish_dispatch_span(ctx, meta.get("agent"),
                                   error=str(meta.get("error")))
        ctx.done.set()

    # ------------------------------------------------------------------- query
    def _run_query(self, client: Connection, meta: dict):
        req_id = meta.get("req_id", "")
        tenant = str(meta.get("tenant") or DEFAULT_TENANT)
        try:
            with trace.root(self.tracer, "query", req_id=req_id,
                            tenant=tenant):
                results, stats = self.execute_script(
                    meta["script"],
                    func=meta.get("func"),
                    func_args=meta.get("func_args"),
                    now=meta.get("now"),
                    default_limit=meta.get("default_limit"),
                    analyze=bool(meta.get("analyze", False)),
                    funcs=[tuple(f) for f in meta.get("funcs") or []] or None,
                    tenant=tenant,
                )
                with trace.span("render"):
                    for name, qr in results.items():
                        hb = HostBatch(
                            dtypes={n: qr.relation.dtype(n)
                                    for n in qr.relation.names()},
                            dicts=qr.dictionaries,
                            cols=qr.columns,
                        )
                        client.send(wire.encode_host_batch(
                            hb, {"msg": "result_chunk", "req_id": req_id,
                                 "table": name,
                                 # semantic types ride the wire with the
                                 # relation
                                 "relation": qr.relation.to_dict()}
                        ))
                    client.send(wire.encode_json(
                        {"msg": "done", "req_id": req_id,
                         "stats": _jsonable(stats)}
                    ))
        except ShedError as e:
            # admission rejection: NOT a failure of the query itself — the
            # envelope carries the retry-after hint so clients back off
            client.send(wire.encode_error(req_id, e,
                                          retry_after_s=e.retry_after_s))
        except Exception as e:  # compile/plan/exec errors all surface to client
            if not isinstance(e, PxError):
                traceback.print_exc()
            client.send(wire.encode_error(req_id, e))
        finally:
            self._ship_spans()

    def _ship_spans(self) -> None:
        """Persist this broker's finished spans into the data plane: the rows
        go to one live agent's `self_telemetry.spans` table, so the normal
        distributed scan path (and any PxL script) sees the full trace —
        broker spans included — without the broker holding a scanned store.

        Runs in query finally-blocks: telemetry failure (agent churn racing
        the conn map, dead sockets) must never replace a query's outcome, so
        everything is counted instead of raised."""
        from pixie_tpu import metrics as _metrics

        try:
            if self.tracer.buffered == 0:
                return
            # snapshot: the expiry thread pops entries concurrently
            conns = dict(self._agent_conns)

            def send(rows):
                for name in sorted(conns):
                    c = conns[name]
                    if not c.closed and c.send(
                            wire.encode_json({"msg": "spans", "spans": rows})):
                        return
                _metrics.counter_inc(
                    "px_broker_trace_spans_unshipped_total", float(len(rows)),
                    help_="broker spans dropped: no agent accepted them")

            self.tracer.flush(send=send)
        except Exception:
            _metrics.counter_inc(
                "px_broker_trace_ship_errors_total",
                help_="unexpected failures shipping broker spans")

    def _deploy_mutations(self, mutations: list) -> None:
        from pixie_tpu.status import Unavailable

        specs = [
            m for m in mutations
            if m.get("kind") in ("tracepoint", "delete_tracepoint")
        ]
        targets = {
            name: conn for name, conn in self._agent_conns.items()
            if not conn.closed
        }
        if not specs or not targets:
            return
        # A fresh req_id + ctx per spec round: a straggler ack from round N
        # that lands after its timeout cannot corrupt round N+1's accounting.
        for spec in specs:
            with self._qlock:
                self._req_counter += 1
                rid = f"tp{self._req_counter}"
                ctx = _QueryCtx(set(targets), set())
                self._queries[rid] = ctx
            try:
                for conn in targets.values():
                    conn.send(wire.encode_json({
                        "msg": "deploy_tracepoint", "req_id": rid, "spec": spec,
                        "qtoken": ctx.token,
                    }))
                if not ctx.done.wait(timeout=self.query_timeout_s):
                    raise Unavailable(
                        f"tracepoint deploy timed out on {sorted(ctx.pending_agents)}"
                    )
                if ctx.error:
                    raise Unavailable(ctx.error)
            finally:
                with self._qlock:
                    self._queries.pop(rid, None)

    def _admit(self, script, func, func_args, default_limit, tenant):
        """Pass one query through the serving front's admission gate.

        Cost estimate: a plan-cache peek decides warm (dispatch+merge only)
        vs cold (full compile/split) — the same signal the DRR scheduler
        charges, so a tenant flooding cold compiles drains proportionally
        slower.  Raises ShedError (quota/queue-full/timeout/overload);
        returns the Ticket to release, or None when serving is disabled.
        """
        trace.set_attr(tenant=tenant)
        if not self.serving.enabled():
            return None
        from pixie_tpu.engine import plancache as _plancache

        if not _plancache.enabled():
            # PL_QUERY_FASTPATH=0: no warm/cold signal exists and every
            # query pays the same full compile — price uniformly WARM so
            # DRR stays fair by count and the overload shed (which drops
            # cost >= COST_COLD work) cannot turn degradation into a full
            # outage
            cost = COST_WARM
        else:
            key = self.plan_cache.key(script, func, func_args, default_limit,
                                      ("reg", self.registry.epoch),
                                      tenant=tenant)
            cost = COST_WARM if self.plan_cache.contains(key) else COST_COLD
        with trace.span("admission_wait", tenant=tenant, cost=cost):
            ticket = self.serving.admit(tenant, cost)
        if ticket.queued:
            # the scheduler's dispatch decision as its own span: start =
            # enqueue, duration = queue wait (ends at dispatch)
            trace.event_span("sched_dispatch", ticket.enqueue_ns,
                             ticket.wait_ns, tenant=tenant, cost=cost,
                             degraded=ticket.degraded)
        return ticket

    def execute_script(
        self, script: str, func=None, func_args=None, now=None,
        default_limit=None, analyze: bool = False, funcs=None,
        tenant: str = None,
    ) -> tuple[dict[str, QueryResult], dict]:
        """Compile + distribute + merge (the in-process core of ExecuteScript).

        `funcs=[(prefix, func_name, func_args)]` executes a MULTI-widget
        request as ONE fused distributed query (shared scans/filters/aggs
        run once — reference optimizer.h:39 MergeNodesRule); the returned
        stats carry `sink_map` so the caller splits results per widget.
        """
        import time as _time

        from pixie_tpu import metrics as _metrics

        tenant = str(tenant or DEFAULT_TENANT)
        _metrics.counter_inc("px_broker_queries_total",
                             help_="ExecuteScript requests received")
        # In-process callers (cron, tests) get their own trace root; under
        # the networked path _run_query's root is already active and this is
        # a no-op.  Shipping happens only when this frame owns the root.
        owns_root = trace.enabled() and trace.current() is None
        t0 = _time.perf_counter()
        shed = False
        try:
            with trace.maybe_root(self.tracer, "query"):
                ticket = self._admit(script, func, func_args, default_limit,
                                     tenant)
                ok = False
                try:
                    results, stats = self._execute_script_inner(
                        script, func, func_args, now, default_limit, analyze,
                        funcs, tenant=tenant, ticket=ticket,
                    )
                    ok = True
                    return results, stats
                finally:
                    self.serving.release(ticket, ok=ok)
        except ShedError:
            # admission rejections are flow control, not query failures —
            # they are counted under px_serving_shed_total instead
            shed = True
            raise
        except Exception:
            _metrics.counter_inc("px_broker_query_errors_total",
                                 help_="ExecuteScript requests that failed")
            raise
        finally:
            if not shed:
                # sheds stay out of the latency SLO histogram: a flood of
                # sub-ms rejections (or 30s queue-timeout sheds) during
                # overload would swamp the distribution of queries that
                # actually EXECUTED — exactly when the SLO signal matters
                _metrics.histogram_observe(
                    "px_broker_query_latency_seconds",
                    _time.perf_counter() - t0, QUERY_LATENCY_BOUNDS,
                    help_="broker end-to-end ExecuteScript latency "
                          "(executed queries; sheds excluded)")
            if owns_root:
                self._ship_spans()

    def _execute_script_inner(
        self, script, func, func_args, now, default_limit, analyze,
        funcs=None, tenant: str = DEFAULT_TENANT, ticket=None,
    ) -> tuple[dict[str, QueryResult], dict]:
        import time as _time

        from pixie_tpu import metrics as _metrics
        from pixie_tpu.compiler import compile_pxl, compile_pxl_funcs
        from pixie_tpu.status import Internal, Unavailable

        if self.elector is not None and not self.elector.is_leader():
            leader = self.elector.leader()
            raise Unavailable(
                f"this broker is not the leader (current leader: {leader})")
        # Epoch BEFORE cluster_spec: a registration landing between the two
        # reads must not let a split computed from the agent-less spec be
        # cached under the post-registration epoch (sticky wrong results).
        # The inverse race — cluster_spec's live_agents() expiring an agent
        # and bumping the epoch after our read — only caches the fresh split
        # under the stale epoch: one redundant miss, never a poisoned hit.
        topo_epoch = self.registry.epoch
        spec = self.registry.cluster_spec()
        if not any(a.has_data_store for a in spec.agents):
            raise Unavailable("no live data agents registered")
        sink_map = None
        entry = None
        plan_cache_hit = False
        if funcs:
            # multi-widget fusion stays on the slow path: its sink_map and
            # per-widget arg sets make the cache key explode for no warm win
            with trace.span("compile"):
                q, sink_map = compile_pxl_funcs(
                    script, self.registry.combined_schemas(),
                    [(p, f, a) for p, f, a in funcs],
                    registry=self.udf_registry, now=now,
                    default_limit=default_limit,
                )
        else:
            def _compile():
                with trace.span("compile"):
                    return compile_pxl(
                        script, self.registry.combined_schemas(), func=func,
                        func_args=func_args, registry=self.udf_registry,
                        now=now, default_limit=default_limit,
                    )

            key = self.plan_cache.key(script, func, func_args, default_limit,
                                      ("reg", topo_epoch), tenant=tenant)
            q, entry, plan_cache_hit = self.plan_cache.get_query(key, _compile)
        if q.mutations:
            # Deploy tracepoints to every live agent and wait for readiness
            # (reference MutationExecutor: register → agents deploy → poll
            # isSchemaReady, mutation_executor.go:84,272).
            with trace.span("deploy_mutations"):
                self.tracepoints.apply(q.mutations)
                self._deploy_mutations(q.mutations)
            topo_epoch = self.registry.epoch  # BEFORE cluster_spec (see above)
            spec = self.registry.cluster_spec()  # schemas refreshed by re-register

        def _split():
            with trace.span("plan_split"):
                dp = DistributedPlanner(spec).plan(q.plan)
                # pre-serialize the per-agent plan dicts: the dispatch loop
                # splices these cached JSON fragments into each execute
                # frame instead of re-walking + re-dumping the plan per query
                extras = {"plan_json": {
                    a: _json.dumps(p.to_dict())
                    for a, p in dp.agent_plans.items()
                }}
                return dp, extras

        from pixie_tpu.engine.plancache import QueryPlanCache as _QPC

        (dp, split_extras), split_hit = _QPC.get_split(
            entry, ("split", topo_epoch), _split)

        reg = self.udf_registry
        if reg is None:
            from pixie_tpu.udf import registry as reg
        # Broker-side view matcher: which agent fragments have a standing-
        # query shape?  The agents decide (and do) the actual serving — this
        # is the control-plane ledger that makes hit/miss observable per
        # query (stats["matview"], px_broker_matview_* counters, and a
        # matview_hit span when the whole query answered from views).
        # Disabled subsystem = no ledger: otherwise every query would pay
        # the canonicalize+hash and count as a "miss" for a feature that
        # is off.
        import pixie_tpu.matview  # noqa: F401 — defines the PL_MATVIEW_* flags

        from pixie_tpu import flags as _flags

        mv_keys = {}
        if _flags.get("PL_MATVIEW_ENABLED"):
            from pixie_tpu.matview.registry import plan_view_key

            mv_keys = {
                name: k for name, plan in dp.agent_plans.items()
                if (k := plan_view_key(plan, reg)) is not None
            }
        with self._qlock:
            self._req_counter += 1
            req_id = f"q{self._req_counter}"
            ctx = _QueryCtx(set(dp.agent_plans), set(dp.channels))
            ctx.configure_folds(dp, reg)
            self._queries[req_id] = ctx
        # Degradation hints ride each execute frame: past the shed
        # watermark, matview hits serve standing state WITHOUT folding
        # their delta (stale-while-revalidate) and the agents' chunk ack
        # window narrows so producers throttle at the source.  Read at
        # dispatch time (not admit time) so a queue that drained while
        # this query waited dispatches at full quality.
        degraded = self.serving.enabled() and self.serving.degraded()
        try:
            for agent_name, plan in dp.agent_plans.items():
                conn = self._agent_conns.get(agent_name)
                if conn is None or conn.closed:
                    raise Unavailable(f"agent {agent_name} not connected")
                # one dispatch span per agent: opened at send, closed by the
                # exec_done/exec_error handler; its id rides the wire so the
                # agent's exec spans parent under it across processes
                dsp = trace.start_child("dispatch", agent=agent_name)
                tctx = None
                if dsp is not None:
                    ctx.dispatch_spans[agent_name] = dsp
                    tctx = {"trace_id": dsp.trace_id, "span_id": dsp.span_id}
                meta = {
                    "msg": "execute", "req_id": req_id,
                    "qtoken": ctx.token,
                    "trace": tctx,
                    "analyze": analyze,
                    # tenant rides to the agents: matview state namespaces
                    # per tenant under PL_TENANT_ISOLATION
                    "tenant": tenant,
                    # distributed fan-out: agents route CPU/TPU by the
                    # query's total size, not their local shard's
                    "route_scale": len(dp.agent_plans),
                }
                if degraded:
                    meta["stale_ok"] = True
                    dw = int(_flags.get("PL_SERVING_DEGRADED_WINDOW"))
                    if dw > 0:
                        meta["stream_window"] = dw
                # splice the cached plan JSON (encoded once per plan/split,
                # not per query) instead of re-serializing the plan dict
                pj = split_extras["plan_json"].get(agent_name)
                if pj is not None:
                    conn.send(wire.encode_json_raw(meta, {"plan": pj}))
                else:  # pragma: no cover — split always covers its agents
                    meta["plan"] = plan.to_dict()
                    conn.send(wire.encode_json(meta))
            if dp.agent_plans and not ctx.done.wait(timeout=self.query_timeout_s):
                raise Unavailable(
                    f"query timed out after {self.query_timeout_s}s waiting for "
                    f"agents {sorted(ctx.pending_agents)}"
                )
            if ctx.error:
                raise Unavailable(ctx.error)

            with trace.span("merge"):
                from pixie_tpu.parallel.repartition import (
                    bucket_channels,
                    run_join_stages,
                    stage_output_inputs,
                )

                # chunk folds ran on the reader threads (no trace context
                # there): emit them as spans now, under this query's root —
                # their start times preceding last_terminal_ns is the direct
                # evidence that merge work overlapped agent compute
                for t0_ns, dur_ns, cid, agent in ctx.fold_events:
                    trace.event_span("incremental_fold", t0_ns, dur_ns,
                                     channel=cid, agent=agent)
                if dp.join_stages:
                    # repartitioned joins run partition-parallel on the merger
                    # (the Kelvin role); bucket channels are consumed here, with
                    # the same payload-shape contract as rows channels
                    run_join_stages(dp, ctx.payloads, reg,
                                    store=self.merger_store, analyze=analyze)
                consumed = bucket_channels(dp)
                inputs: dict[str, HostBatch] = {}
                for cid, ch in dp.channels.items():
                    if cid in consumed:
                        continue
                    fold = ctx.folds.get(cid)
                    if fold is None or fold.count == 0:
                        raise Internal(f"channel {cid} received no payloads")
                    # every chunk an agent SENT must have folded: a dropped
                    # frame means a silently-partial answer, so fail instead
                    if cid in ctx.expected_chunks and (
                            ctx.folded_chunks.get(cid, 0)
                            != ctx.expected_chunks[cid]):
                        raise Internal(
                            f"channel {cid}: folded "
                            f"{ctx.folded_chunks.get(cid, 0)} of "
                            f"{ctx.expected_chunks[cid]} chunk frames")
                    # the running fold already combined every chunk on
                    # arrival; finish() only finalizes (agg) or pays the one
                    # concatenation (rows)
                    with trace.span("merge_finish", channel=cid,
                                    kind=ch.kind, chunks=fold.count,
                                    incremental=True):
                        inputs[cid] = fold.finish()
                inputs.update(stage_output_inputs(dp, ctx.payloads))

                from pixie_tpu.udf.udtf import UDTFContext

                ex = PlanExecutor(
                    dp.merger_plan, self.merger_store, self.udf_registry,
                    inputs=inputs, analyze=analyze,
                    udtf_ctx=UDTFContext(
                        table_store=self.merger_store, registry=reg,
                        agent_registry=self.registry,
                        tracepoint_manager=self.tracepoints,
                    ),
                )
                results = ex.run()
                # The merger plan's sources are channels (no STs); the LOGICAL
                # plan + agent schemas determine them.
                from pixie_tpu.engine.semantics import SchemaStore, restamp_result

                sstore = SchemaStore(self.registry.combined_schemas())
                for r in results.values():
                    restamp_result(r, q.plan, sstore, reg)
                stats = {"agents": ctx.agent_stats, "merger": dict(ex.stats)}
                #: fast-path observability: did this query skip compile /
                #: split work?  (PL_QUERY_FASTPATH off ⇒ both always False)
                stats["fastpath"] = {"plan_cache_hit": plan_cache_hit,
                                     "split_cache_hit": split_hit}
                #: serving-front observability per query: its tenant, the
                #: queue wait it paid, and whether it dispatched degraded
                #: (stale matview serving + narrowed ack window)
                stats["serving"] = {
                    "tenant": tenant,
                    "queued_ms": (round(ticket.wait_ns / 1e6, 3)
                                  if ticket is not None and ticket.queued
                                  else 0.0),
                    "cost": ticket.cost if ticket is not None else None,
                    "degraded": degraded,
                }
                if mv_keys:
                    served = {
                        a: s["matview"] for a, s in ctx.agent_stats.items()
                        if isinstance(s, dict) and s.get("matview")
                    }
                    hits = sum(1 for i in served.values() if i.get("hit"))
                    stats["matview"] = {
                        "eligible_agents": len(mv_keys),
                        "agents_hit": hits,
                        "rows_folded": sum(
                            int(i.get("rows_folded", 0))
                            for i in served.values()),
                        "keys": sorted(set(mv_keys.values())),
                    }
                    if hits and hits == len(dp.agent_plans):
                        # the ENTIRE scan side answered from standing state:
                        # this query's cost was delta folds + one finalize
                        _metrics.counter_inc(
                            "px_broker_matview_hit_queries_total",
                            help_="queries fully answered from standing "
                                  "view state on every agent")
                        trace.event_span(
                            "matview_hit", _time.time_ns(), 0,
                            agents=hits,
                            rows_folded=stats["matview"]["rows_folded"])
                    else:
                        _metrics.counter_inc(
                            "px_broker_matview_miss_queries_total",
                            help_="view-eligible queries that rescanned on "
                                  "at least one agent")
                #: streaming-merge observability: merge_overlapped=True means
                #: the first chunk folded BEFORE the last agent's terminal
                #: frame — merge cost hid under the slowest agent's compute
                stats["stream"] = {
                    "chunks_folded": sum(ctx.folded_chunks.values()),
                    "first_fold_unix_ns": ctx.first_fold_ns,
                    "last_terminal_unix_ns": ctx.last_terminal_ns,
                    "merge_overlapped": bool(
                        ctx.first_fold_ns is not None
                        and ctx.last_terminal_ns is not None
                        and ctx.first_fold_ns < ctx.last_terminal_ns),
                }
                if sink_map is not None:
                    stats["sink_map"] = sink_map
                    stats["merger"]["operators"] = ex.op_stats
                for r in results.values():
                    r.exec_stats["agents"] = ctx.agent_stats
            return results, stats
        finally:
            # span hygiene: a timeout / disconnect / error leaves dispatch
            # spans without an exec_done to close them
            for agent_name in list(ctx.dispatch_spans):
                self._finish_dispatch_span(ctx, agent_name,
                                           error=ctx.error or "unresolved")
            with self._qlock:
                self._queries.pop(req_id, None)


def _jsonable(obj):
    import numpy as np

    if isinstance(obj, dict):
        return {k: _jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_jsonable(v) for v in obj]
    if isinstance(obj, np.generic):
        return obj.item()
    if isinstance(obj, np.ndarray):
        return obj.tolist()
    return obj

"""Query broker: the networked ExecuteScript front door.

Reference: src/vizier/services/query_broker — Server.ExecuteScript
(controllers/server.go:307) compiles the script, LaunchQuery ships per-agent
plans (launch_query.go:36-66), and QueryResultForwarder merges agent result
streams into the client stream with producer/consumer watchdogs
(query_result_forwarder.go:358-560).

This broker listens on one framed-TCP port for BOTH agents and clients
(the envelope's `msg` field routes).  Per query: compile against the live
registry's schemas, split with DistributedPlanner, push `execute` frames to
each agent's connection, collect channel payload frames, merge (partials via
combine/finalize, rows via dictionary-reconciled union), run the merger plan
locally, and stream result chunks back to the client.
"""
from __future__ import annotations

import json as _json
import threading
import time
import traceback
from typing import Optional

from pixie_tpu import flags as _flags
from pixie_tpu import trace
from pixie_tpu.engine import autotune as _autotune
from pixie_tpu.engine.executor import HostBatch, PlanExecutor
from pixie_tpu.engine.result import QueryResult
from pixie_tpu.parallel.distributed import DistributedPlanner
from pixie_tpu.serving import COST_COLD, COST_WARM, ServingFront, ShedError
from pixie_tpu.services import replication as _replication
from pixie_tpu.services import wire
from pixie_tpu.services.kvstore import KVStore
from pixie_tpu.services.registry import AgentRegistry
from pixie_tpu.services.transport import Connection, Server
from pixie_tpu.status import PxError
from pixie_tpu.table.table import TableStore
from pixie_tpu.types import Relation

DEFAULT_QUERY_TIMEOUT_S = 60.0

#: tenant id stamped on queries that arrive without one (older clients,
#: in-process callers like cron): they share one namespace and one quota
#: bucket rather than bypassing admission entirely
DEFAULT_TENANT = "default"

#: broker end-to-end query latency buckets (seconds)
QUERY_LATENCY_BOUNDS = (0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
                        10.0, 30.0, 60.0)

#: incremental_fold spans recorded per query (folds beyond the cap still
#: merge and count; only their span detail is dropped)
MAX_FOLD_EVENT_SPANS = 256

_flags.define_int(
    "PL_QUERY_RETRIES", 2,
    "broker-side re-dispatch rounds after an agent eviction (heartbeat "
    "expiry / mid-stream disconnect): surviving agents' folded results are "
    "kept, the lost fragments re-plan onto the live agent set and re-"
    "dispatch under fresh per-dispatch tokens; 0 restores fail-fast")
_flags.define_int(
    "PL_RETRY_BACKOFF_MS", 100,
    "base for the jittered exponential backoff between re-dispatch rounds "
    "(round i sleeps ~base*2^i, capped at 5s) — the window a killed-and-"
    "restarted agent gets to re-register before its fragments re-plan "
    "around it")
_flags.define_bool(
    "PL_HEDGE_ENABLED", True,
    "straggler hedging: a dispatch outliving its per-agent service-time "
    "deadline (EWMA/p99-derived) gets a duplicate dispatch; first answer "
    "wins, the loser's chunks are discarded idempotently")
_flags.define_int(
    "PL_HEDGE_MIN_MS", 500,
    "floor for the hedge deadline — never hedge a dispatch younger than "
    "this, however fast the agent's history says it should be")
_flags.define_float(
    "PL_HEDGE_FACTOR", 3.0,
    "hedge deadline = max(PL_HEDGE_MIN_MS, factor * p99_estimate) where "
    "p99_estimate = service-time EWMA + 4 * EWMA(|deviation|)")
_flags.define_float(
    "PL_REJOIN_GRACE_S", 2.0,
    "how long after an agent's death its shard counts as REJOINING: "
    "dispatch (and re-dispatch) holds for it instead of silently planning "
    "a reduced topology — a restarting pod re-registers within the grace; "
    "past it the cluster serves the surviving agents' data (the reference "
    "data-plane semantic).  Only active when PL_QUERY_RETRIES > 0")

#: service-time samples required before hedging arms for an agent — a cold
#: EWMA over one or two samples would hedge every slow compile
HEDGE_MIN_SAMPLES = 8

#: cap on the re-dispatch backoff and on the retry-after hint shipped with
#: a retry-budget-exhausted error
MAX_BACKOFF_MS = 5000.0

#: pxlint lock-discipline: _QueryCtx's *_locked members are owned by the
#: per-query ctx lock (checked by pixie_tpu.check.pxlint at CI time)
_pxlint_locks_ = {"_check_done_locked": ".lock"}


class _QueryCtx:
    """In-flight bookkeeping for one distributed query (or tracepoint
    deploy round).

    Fault-tolerant dispatch model: every `execute` frame is one DISPATCH,
    identified by ``src = f"{agent}#{attempt}"`` and authenticated by its
    OWN token (the per-query token of PR 1, narrowed per dispatch).  Chunk
    frames fold into per-src accumulators (`parallel.cluster.
    SourceKeyedFold`), so an evicted agent's partial stream — or the losing
    side of a hedged duplicate dispatch — is discarded at merge simply by
    never ACCEPTING its src; nothing is un-folded and late/duplicate chunks
    land in sub-folds nobody reads (idempotent discard).  The first
    exec_done per agent wins (`accepted[agent] = src`)."""

    def __init__(self, channels: set[str], retryable: bool = True):
        import secrets

        self.lock = threading.RLock()
        #: dead primary → live replica serving its shard this query
        #: (sealed-batch replication failover); {} without replication
        self.failover: dict[str, str] = {}
        #: failover routes actually dispatched (→ stats["fault"])
        self.failover_used: dict[str, str] = {}
        #: False for tracepoint-deploy rounds: agent loss fails the round
        #: immediately (mutations are never transparently re-dispatched)
        self.retryable = retryable
        self.error: Optional[str] = None
        self.done = threading.Event()
        #: nudges the query thread: completion, eviction, or error
        self.wake = threading.Event()
        #: base token — tracepoint deploy rounds dispatch under it directly
        self.token = secrets.token_urlsafe(12)
        #: agents whose answer the current plan requires
        self.needed_agents: set[str] = set()
        #: src → {agent, attempt, frag, deadline, hedged, t0}
        self.pending: dict[str, dict] = {}
        #: src → per-dispatch auth token (never pruned within a query: late
        #: frames from a losing/evicted src must validate so their discard
        #: is COUNTED as a discard, not mistaken for a stale-query frame)
        self.tokens: dict[str, str] = {}
        #: agent → winning src (first exec_done)
        self.accepted: dict[str, str] = {}
        #: src → the fragment JSON it was dispatched with (re-dispatch
        #: keeps an accepted result only when its fragment is unchanged
        #: under the re-planned split)
        self.frags: dict[str, Optional[str]] = {}
        self.next_attempt: dict[str, int] = {}
        #: (agent, reason) eviction events awaiting the query thread
        self.evictions: list[tuple] = []
        #: one hedge per agent per dispatch round
        self.hedged_agents: set[str] = set()
        #: per-src dispatch spans, opened at frame send and closed by the
        #: exec_done/exec_error handler threads (or eviction cleanup)
        self.dispatch_spans: dict[str, object] = {}
        self.agent_stats: dict[str, dict] = {}
        # ---- streaming incremental merge (set up by configure_folds) ----
        #: channel id → SourceKeyedFold: chunk frames fold into per-src
        #: sub-accumulators AS THEY ARRIVE (reader threads), so merge work
        #: hides under the slowest agent's compute AND a src is droppable
        self.folds: dict[str, object] = {}
        #: per-channel locks: fold.add serializes across agent reader
        #: threads, but folds on DISTINCT channels share no state
        self.fold_locks: dict[str, threading.Lock] = {}
        #: join-stage bucket channels accumulate whole payload lists per
        #: src; the stage runner consumes the accepted srcs' lists at merge
        self.bucket_payloads: dict[str, dict[str, list]] = {}
        #: (channel, src) → chunks the producer reported on exec_done
        self.expected_chunks: dict[tuple, int] = {}
        #: (start_unix_ns, duration_ns, channel, agent) per fold, emitted as
        #: incremental_fold spans at merge time (the reader threads hold no
        #: trace context); capped — first_fold_ns/last_terminal_ns carry the
        #: overlap evidence, span detail beyond the cap adds nothing
        self.fold_events: list[tuple] = []
        self.first_fold_ns: Optional[int] = None
        self.last_terminal_ns: Optional[int] = None

    def configure_folds(self, dp, registry) -> None:
        """Arm one source-keyed accumulator per merge-input channel.  Must
        run before the first `execute` frame is sent (chunks race the
        dispatch loop); join-stage bucket channels keep list accumulation —
        the stage runner consumes whole per-partition lists at merge time."""
        from pixie_tpu.parallel.cluster import SourceKeyedFold
        from pixie_tpu.parallel.repartition import bucket_channels

        consumed = bucket_channels(dp)
        for cid, ch in dp.channels.items():
            if cid in consumed:
                continue
            self.folds[cid] = SourceKeyedFold(ch.kind, agg=ch.agg,
                                              registry=registry)
            self.fold_locks[cid] = threading.Lock()

    # ------------------------------------------------------- dispatch state
    @staticmethod
    def src_of(meta: dict) -> str:
        return f"{meta.get('agent')}#{int(meta.get('attempt') or 0)}"

    def register_dispatch(self, agent: str, frag=None, deadline=None,
                          hedged: bool = False, token: Optional[str] = None,
                          via: Optional[str] = None):
        import secrets
        import time as _time

        with self.lock:
            attempt = self.next_attempt.get(agent, 0)
            self.next_attempt[agent] = attempt + 1
            src = f"{agent}#{attempt}"
            self.tokens[src] = token or secrets.token_urlsafe(12)
            self.frags[src] = frag
            self.pending[src] = {
                "agent": agent, "attempt": attempt, "frag": frag,
                "deadline": deadline, "hedged": hedged,
                # the agent whose CONNECTION carries this dispatch: the
                # planned agent itself, or its failover replica — eviction
                # of the carrier must drop the dispatch either way
                "via": via or agent,
                "t0": _time.monotonic(),
            }
            if hedged:
                self.hedged_agents.add(agent)
            return src, self.tokens[src], attempt

    def drop_dispatch(self, src: str) -> None:
        with self.lock:
            self.pending.pop(src, None)
            self.tokens.pop(src, None)

    def token_for(self, src: str) -> Optional[str]:
        with self.lock:
            return self.tokens.get(src)

    def frag_of(self, src: str) -> Optional[str]:
        return self.frags.get(src)

    def outstanding_agents(self) -> list[str]:
        with self.lock:
            return sorted(self.needed_agents - set(self.accepted))

    def uncovered_agents(self) -> list[str]:
        """Needed agents with neither an accepted result nor an in-flight
        dispatch — the set a re-dispatch round must cover."""
        with self.lock:
            covered = set(self.accepted)
            covered.update(i["agent"] for i in self.pending.values())
            return sorted(self.needed_agents - covered)

    def _check_done_locked(self) -> None:
        if self.error is not None or self.needed_agents <= set(self.accepted):
            self.done.set()
        self.wake.set()

    def fail(self, error: str) -> None:
        with self.lock:
            if self.error is None:
                self.error = error
            self._check_done_locked()

    # --------------------------------------- producer frames (reader threads)
    def on_exec_done(self, meta: dict):
        """Returns (agent, service_seconds) when this frame ACCEPTED the
        agent's result; None for stale or hedge-losing frames."""
        import time as _time

        src = self.src_of(meta)
        with self.lock:
            self.last_terminal_ns = _time.time_ns()
            info = self.pending.pop(src, None)
            if info is None:
                return None
            agent = info["agent"]
            if agent in self.accepted:
                # a hedge raced: first answer already won — this src's
                # chunks are discarded at merge (never accepted)
                self._check_done_locked()
                return None
            self.accepted[agent] = src
            self.agent_stats[agent] = meta.get("stats", {})
            for cid, n in (meta.get("chunks") or {}).items():
                self.expected_chunks[(cid, src)] = int(n)
            self._check_done_locked()
            return agent, _time.monotonic() - info["t0"]

    def on_exec_error(self, meta: dict) -> Optional[str]:
        """Returns the fatal error when no other live attempt can still
        answer for this agent; None when a hedge twin is outstanding or
        the frame is stale."""
        src = self.src_of(meta)
        with self.lock:
            info = self.pending.pop(src, None)
            if info is None:
                return None
            agent = info["agent"]
            if agent in self.accepted:
                return None
            if any(i["agent"] == agent for i in self.pending.values()):
                return None  # the hedged twin may still answer
            err = f"agent {meta.get('agent')}: {meta.get('error')}"
            if self.error is None:
                self.error = err
            self._check_done_locked()
            return err

    def on_agent_lost(self, agent: str, reason: str) -> list[str]:
        """Connection/liveness loss: drop the agent's in-flight dispatches
        and queue an eviction for the query thread (or fail outright for
        non-retryable rounds).  Returns dropped srcs for span cleanup.  An
        agent whose result was already accepted is a no-op — its data is
        folded and verified; its later death cannot poison this query."""
        with self.lock:
            srcs = [s for s, i in self.pending.items()
                    if i["agent"] == agent or i.get("via") == agent]
            for s in srcs:
                self.pending.pop(s, None)
            affected = bool(srcs) or (agent in self.needed_agents
                                      and agent not in self.accepted)
            if not affected:
                self.wake.set()
                return srcs
            if not self.retryable:
                if self.error is None:
                    self.error = f"agent {agent} disconnected mid-query"
                self._check_done_locked()
                return srcs
            self.evictions.append((agent, reason))
            self.wake.set()
            return srcs

    def take_evictions(self) -> list[tuple]:
        with self.lock:
            ev, self.evictions = self.evictions, []
            return ev

    def reset_for_restart(self, dp, registry) -> None:
        """Full re-dispatch: the re-planned channel topology changed (e.g.
        a repartition join lost its widest mesh), so every fold so far is
        unusable.  Fresh tokens mean frames from superseded dispatches are
        rejected (and counted) rather than folded."""
        with self.lock:
            self.pending.clear()
            self.tokens.clear()
            self.accepted.clear()
            self.frags = {}
            self.expected_chunks = {}
            self.agent_stats = {}
            self.folds = {}
            self.fold_locks = {}
            self.bucket_payloads = {}
            self.configure_folds(dp, registry)
            self.needed_agents = set(dp.agent_plans)
            self.hedged_agents = set()
            self.done.clear()

    # ------------------------------------------- chunk folds (reader threads)
    def fold_chunk(self, meta: dict, payload) -> None:
        """Fold one producer chunk frame; called from connection reader
        threads.  A malformed chunk fails the QUERY (error + done), never
        the reader thread."""
        import time as _time

        cid = meta["channel"]
        src = self.src_of(meta)
        fold = self.folds.get(cid)
        t0 = _time.time_ns()
        try:
            if fold is None:
                with self.lock:
                    self.bucket_payloads.setdefault(cid, {}).setdefault(
                        src, []).append(payload)
                return
            with self.fold_locks[cid]:
                fold.add(src, payload)
        except Exception as e:
            self.fail(f"chunk fold failed on channel {cid}: {e}")
            return
        if self.first_fold_ns is None:
            self.first_fold_ns = t0
        if len(self.fold_events) < MAX_FOLD_EVENT_SPANS:
            self.fold_events.append(
                (t0, _time.time_ns() - t0, cid, meta.get("agent")))


def _channels_compatible(dp, dp2) -> bool:
    """Whether a re-planned split can reuse the folds of the original: the
    channel set/kinds, join stages (incl. partition counts), and the merger
    plan must be identical — producer lists may differ (that is the point
    of re-planning around a dead agent)."""
    a, b = dp.to_dict(), dp2.to_dict()
    ak = {cid: (c["kind"], _json.dumps(c["agg"], sort_keys=True))
          for cid, c in a["channels"].items()}
    bk = {cid: (c["kind"], _json.dumps(c["agg"], sort_keys=True))
          for cid, c in b["channels"].items()}
    return (ak == bk and a["merger_plan"] == b["merger_plan"]
            and a["join_stages"] == b["join_stages"])


class Broker:
    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        datastore_path: str = ":memory:",
        hb_expiry_s: float = 15.0,
        registry=None,
        query_timeout_s: float = DEFAULT_QUERY_TIMEOUT_S,
        auth_token: Optional[str] = None,
        healthz_port: Optional[int] = None,
        elector=None,
        election_id: Optional[str] = None,
    ):
        # resource-free validation FIRST: a raise here must not leak a
        # bound socket or an open KV handle
        if (election_id is not None and elector is None
                and datastore_path == ":memory:"):
            from pixie_tpu.status import InvalidArgument

            raise InvalidArgument(
                "leader election requires a shared --datastore file "
                "(an in-memory lease is private to this process)")
        #: shared-secret auth (reference fronts this port with JWT,
        #: src/shared/services/).  When set, every connection must present the
        #: token in an `auth` frame before any other message is honored.  The
        #: port must never be exposed beyond a trusted network regardless.
        self.auth_token = auth_token
        self.udf_registry = registry
        self.query_timeout_s = query_timeout_s
        self.merger_store = TableStore()
        #: whole-query plan cache (PL_QUERY_FASTPATH): warm dashboard
        #: queries skip re-trace/re-optimize/re-split/re-serialize — see
        #: engine/plancache.py for the soundness argument
        from pixie_tpu.engine.plancache import QueryPlanCache

        self.plan_cache = QueryPlanCache()
        #: multi-tenant serving front (pixie_tpu.serving): every
        #: ExecuteScript passes its admission gate (per-tenant token
        #: buckets, global in-flight cap, DRR fair-share dispatch) and
        #: returns its slot on completion.  PL_SERVING_ENABLED=0 makes it
        #: a pass-through.
        self.serving = ServingFront("broker")
        #: measured per-(tenant, plan-class) service-rate model
        #: (serving/ratemodel.py): fed from every completion, it replaces
        #: the static warm/cold DRR costs and the heuristic retry-after
        #: with measured rates, and drives the autoscaler's demand signal
        from pixie_tpu.serving.ratemodel import ServiceRateModel

        self.ratemodel = ServiceRateModel()
        self.serving.rate_model = self.ratemodel
        #: broker-driven agent autoscaler (serving/elastic.py), armed in
        #: start() when PL_AUTOSCALE=1 (benches/tests may pre-assign one
        #: with their own launcher before start())
        self.supervisor = None
        #: self-telemetry spans for the query path; shipped to an agent's
        #: spans table at query end (the broker holds no scanned store)
        self.tracer = trace.Tracer("broker")
        #: query flight recorder: per-query profile/op-stat rows (and SLO
        #: alert + sampled-metric rows) buffered here, shipped to an agent
        #: store alongside the spans (pixie_tpu.observe)
        from pixie_tpu import observe as _observe

        self._telemetry = _observe.RowBuffer()
        self._self_metrics: Optional[object] = None
        #: concurrent-query batching rendezvous (PL_QUERY_BATCHING):
        #: groupable concurrent queries fuse into ONE distributed dispatch
        #: with a shared scan; results demux per member (serving/batching)
        from pixie_tpu.serving import batching as _batching

        self._batcher = _batching.BatchCollector()
        #: batch signature → BatchSlot (fused plan + sink map + split slot)
        from collections import OrderedDict as _OrderedDict

        self._batch_splits: "_OrderedDict" = _OrderedDict()
        self._agent_conns: dict[str, Connection] = {}
        self._queries: dict[str, _QueryCtx] = {}
        self._qlock = threading.Lock()
        #: broker→agent control RPC slots (retire drain audits):
        #: req_id -> [Event, reply payload]
        self._control_replies: dict[str, list] = {}
        #: per-agent service-time model for straggler hedging: EWMA of
        #: dispatch→exec_done seconds + EWMA of |deviation| (a cheap p99
        #: estimate: ewma + 4*dev); warmed by HEDGE_MIN_SAMPLES before a
        #: hedge deadline arms
        self._svc: dict[str, dict] = {}
        self._svc_lock = threading.Lock()
        self._req_counter = 0
        self._stopped = threading.Event()
        self._expiry_thread = threading.Thread(
            target=self._expiry_loop, daemon=True, name="pixie-broker-expiry"
        )
        self.kv = KVStore(datastore_path)
        self.healthz: Optional[object] = None
        self._server = None
        try:
            self.registry = AgentRegistry(self.kv, expiry_s=hb_expiry_s)
            from pixie_tpu.services.tracepoints import TracepointManager

            #: cluster-level tracepoint registry (metadata-service analog:
            #: persisted in the control KV, surfaced by GetTracepointStatus)
            self.tracepoints = TracepointManager(self.merger_store, kv=self.kv)
            from pixie_tpu.services.cron import CronScriptRunner

            #: cron scripts (reference script_runner.go:47-54), persisted in kv
            self.cron = CronScriptRunner(
                lambda script, func, func_args: self.execute_script(
                    script, func=func, func_args=func_args
                )[0],
                kv=self.kv,
            )
            # live tenant quotas persisted by the control plane: recall
            # them into the serving front so quota writes survive broker
            # restart (the PL_TENANT_* env specs stay the defaults)
            self._load_quotas()
            # recall the persisted adaptive-gate model (engine/autotune.py,
            # same KV pattern as quotas) so a restarted broker's gates
            # start warm — its first queries pay no cold exploration burst
            if _autotune.enabled():
                _autotune.MODEL.load_kv(self.kv)
            #: optional LeaderElector (services/election.py): when set, this
            #: broker only serves queries while holding the lease — a standby
            #: broker sharing the KV takes over when the leader dies
            #: (reference src/shared/services/election/).  `election_id`
            #: builds one over THIS broker's kv (one handle, one close path).
            if election_id is not None and elector is None:
                from pixie_tpu.services.election import LeaderElector

                elector = LeaderElector(self.kv, "broker", election_id)
            self.elector = elector
            #: optional HTTP healthz/metrics listener (reference
            #: src/shared/services/ healthz for k8s probes).  Leadership is
            #: a READINESS concern only: a healthy standby must pass
            #: /healthz (liveness) or a k8s liveness probe would restart it
            #: in a loop, defeating failover.
            if healthz_port is not None:
                from pixie_tpu.services.health import HealthzServer

                def _kv_alive() -> bool:
                    self.kv.get("__healthz")  # raises when the kv is unusable
                    return True

                self.healthz = HealthzServer(checks={
                    "kv": _kv_alive,
                    "server": lambda: not self._stopped.is_set(),
                }, ready_checks={
                    "leader": lambda: (self.elector is None
                                       or self.elector.is_leader()),
                }, host=host, port=healthz_port)
                # READINESS only: an overloaded broker (admission queue
                # past the shed watermark) must drop out of the serving
                # endpoints without a liveness restart wiping its queues
                self.healthz.add_ready_check("serving", self.serving.ready)
            self._server = Server(host, port, self._on_frame, self._on_close)
        except Exception:
            if self.healthz is not None:
                self.healthz.stop()
            self.kv.close()
            raise

    # ------------------------------------------------------------------ server
    @property
    def port(self) -> int:
        return self._server.port

    def start(self) -> "Broker":
        from pixie_tpu import metrics as _metrics

        _metrics.register_gauge_fn(
            "px_broker_live_agents",
            lambda: {(): float(len(self.registry.live_agents()))},
            "agents currently live in the registry",
        )
        trace.register_gauges()
        self.serving.attach_gauges()
        self.ratemodel.attach_gauges()
        # interrupted shard moves from a prior broker life abort BEFORE the
        # first shard-map push: ownership stays with the donor
        self._abort_stale_moves()
        self._server.start()
        self._expiry_thread.start()
        self.cron.start()
        from pixie_tpu.serving import elastic as _elastic  # PL_AUTOSCALE_*

        if _flags.get("PL_AUTOSCALE") and self.supervisor is None:
            # standalone broker (cli): the default launcher spawns real
            # agent subprocesses against this broker's port; harnesses
            # pre-assign a supervisor with their own launcher instead
            self.supervisor = _elastic.AgentSupervisor(
                self, _elastic.ProcLauncher("127.0.0.1", self.port))
        if self.supervisor is not None:
            self.supervisor.start()
        period = float(_flags.get("PL_SELF_METRICS_S"))
        if period > 0:
            from pixie_tpu.services.cron import Ticker

            #: metrics-as-data: fold the registry into
            #: self_telemetry.metrics (and evaluate SLO burn rates) on the
            #: same cadence dashboards poll at
            self._self_metrics = Ticker("self_metrics", period,
                                        self._sample_self_metrics).start()
        if self.elector is not None:
            self.elector.start()
        if self.healthz is not None:
            self.healthz.start()
        return self

    def stop(self):
        from pixie_tpu import metrics as _metrics

        self._stopped.set()
        if self.supervisor is not None:
            self.supervisor.stop()
        self.cron.stop()
        if self._self_metrics is not None:
            self._self_metrics.stop()
            self._self_metrics = None
        if self.healthz is not None:
            self.healthz.stop()
        if self.elector is not None:
            self.elector.stop()
        self._server.stop()
        self.serving.detach_gauges()
        self.ratemodel.detach_gauges()
        _metrics.unregister_gauge_fn("px_broker_live_agents")
        if _autotune.enabled():
            # final checkpoint: the next broker on this KV starts warm
            _autotune.MODEL.save_kv(self.kv)
        self.kv.close()

    def _expiry_loop(self):
        while not self._stopped.wait(timeout=max(self.registry.expiry_s / 3, 0.2)):
            self.registry.expire()
            # Reconcile connections against registry liveness — no matter
            # WHICH thread's expire() marked an agent dead (query paths call
            # live_agents() too), its connection gets closed here.  Dead
            # agents can't be revived by heartbeats (registry.heartbeat), so
            # this doesn't race a revival.
            live = {r.name for r in self.registry.live_agents()}
            for name, conn in list(self._agent_conns.items()):
                if name not in live:
                    self._agent_conns.pop(name, None)
                    conn.close()

    # ------------------------------------------------------------------ frames
    def _on_frame(self, conn: Connection, frame: bytes):
        if self.auth_token is not None and not conn.state.get("authed"):
            import hmac

            # Unauthenticated peers get NO decode work: the only acceptable
            # first frame is a small auth json.  Oversized or malformed
            # frames close the connection without allocating for them
            # (decode_frame would happily materialize a 1GB host_batch).
            if len(frame) > 4096:
                conn.close()
                return
            try:
                kind, payload = wire.decode_frame(frame)
            except Exception:
                conn.close()
                return
            # compare_digest over utf-8 bytes: str operands raise TypeError
            # on non-ASCII, which would skip the reject-and-close path.
            if (kind == "json" and payload.get("msg") == "auth"
                    and hmac.compare_digest(
                        str(payload.get("token", "")).encode(),
                        self.auth_token.encode())):
                conn.state["authed"] = True
                conn.send(wire.encode_json({"msg": "auth_ok"}))
            else:
                rid = payload.get("req_id") if kind == "json" else None
                conn.send(wire.encode_json(
                    {"msg": "error", "req_id": rid,
                     "error": "authentication required"}))
                conn.close()
            return
        kind, payload = wire.decode_frame(frame)
        if kind == "json":
            msg = payload.get("msg")
            if msg == "auth":
                conn.state["authed"] = True
                conn.send(wire.encode_json({"msg": "auth_ok"}))
            elif msg == "register":
                self._handle_register(conn, payload)
            elif msg == "heartbeat":
                if self._stale_incarnation(conn):
                    return  # a superseded socket's heartbeat must not keep
                    # the NEW incarnation's record warm
                if not self.registry.heartbeat(payload["agent"]):
                    conn.send(wire.encode_json({"msg": "reregister"}))
            elif msg == "tracepoint_ready":
                if self._stale_incarnation(conn):
                    return
                self._handle_exec_done({
                    "req_id": payload.get("req_id"),
                    "qtoken": payload.get("qtoken"),
                    "agent": payload.get("agent"), "stats": {},
                })
            elif msg == "tracepoint_error":
                if self._stale_incarnation(conn):
                    return
                self._handle_exec_error(payload)
            elif msg == "exec_done":
                if self._stale_incarnation(conn):
                    return
                self._handle_exec_done(payload)
            elif msg == "exec_error":
                if self._stale_incarnation(conn):
                    return
                self._handle_exec_error(payload)
            elif msg == "execute_script":
                threading.Thread(
                    target=self._run_query, args=(conn, payload), daemon=True
                ).start()
            elif msg == "metrics":
                from pixie_tpu import metrics as _metrics

                conn.send(wire.encode_json({
                    "msg": "metrics_text",
                    "req_id": payload.get("req_id"),
                    "text": _metrics.render(),
                }))
            elif msg == "flags":
                from pixie_tpu import flags as _flags

                conn.send(wire.encode_json({
                    "msg": "flags_dump",
                    "req_id": payload.get("req_id"),
                    "flags": _flags.dump(),
                }))
            elif msg == "cron_upsert":
                self._reply_ack(conn, payload, lambda: self.cron.upsert(
                    payload["name"], payload["script"],
                    payload.get("interval_s", 60.0),
                    func=payload.get("func"),
                    func_args=payload.get("func_args"),
                ))
            elif msg == "cron_delete":
                self._reply_ack(
                    conn, payload, lambda: self.cron.delete(payload["name"])
                )
            elif msg == "cron_list":
                conn.send(wire.encode_json({
                    "msg": "cron_scripts", "req_id": payload.get("req_id"),
                    "scripts": [
                        {"name": c.name, "interval_s": c.interval_s,
                         "enabled": c.enabled, "run_count": c.run_count,
                         "error_count": c.error_count,
                         "last_error": c.last_error}
                        for c in self.cron.list()
                    ],
                }))
            elif msg == "set_quota":
                self._handle_set_quota(conn, payload)
            elif msg == "get_quotas":
                conn.send(wire.encode_json({
                    "msg": "quotas", "req_id": payload.get("req_id"),
                    "quotas": self.serving.quotas(),
                    "rate_model": self.ratemodel.snapshot(),
                }))
            elif msg in ("retire_info", "storage_report", "rehome_info"):
                # reply to a broker→agent control RPC (retire drain audit /
                # heat_map storage fan-out / re-homing prepare+audit)
                with self._qlock:
                    slot = self._control_replies.get(payload.get("req_id"))
                if slot is not None:
                    slot[1] = payload
                    slot[0].set()
            elif msg == "heat_map":
                # cluster storage observatory read ("df for the data
                # plane") — off the read loop: it blocks on per-agent RPCs
                threading.Thread(
                    target=self._answer_heat_map, args=(conn, payload),
                    daemon=True, name="pixie-broker-heatmap",
                ).start()
            elif msg == "rehome_agent":
                # operator/controller shard move — off the read loop: the
                # prepare RPC + coverage audit block for seconds
                threading.Thread(
                    target=self._answer_rehome, args=(conn, payload),
                    daemon=True, name="pixie-broker-rehome",
                ).start()
            elif msg == "deregister_agent":
                # operator decommission: drop the durable record so the
                # shard map stops treating the retired node as a failover
                # primary (and catch-up degradation clears).  Refused when
                # the shard map says this agent is the LAST live holder of
                # any shard (its own, or a dead primary's it alone serves
                # failover for) — deregistering it would lose that shard
                # from every future plan; force=true overrides.
                name = str(payload.get("agent"))
                sole = ([] if payload.get("force")
                        else self._sole_holder_of(name))
                if sole:
                    conn.send(wire.encode_json({
                        "msg": "error", "req_id": payload.get("req_id"),
                        "error": f"agent {name} is the last live holder of "
                                 f"shard(s) {sole}; deregistering it would "
                                 "lose them (force=true overrides)"}))
                else:
                    ok = self.registry.deregister(name)
                    conn.send(wire.encode_json({
                        "msg": "ok" if ok else "error",
                        "req_id": payload.get("req_id"),
                        **({} if ok else {"error": "unknown agent"})}))
                    if ok:
                        self._push_shard_map()
            elif msg == "get_peers":
                # pre-registration topology fetch: a rehydrating agent asks
                # who backs its shard (and where their replication ports
                # live) BEFORE it registers, so peer fetch completes before
                # the broker ever dispatches to it
                conn.send(wire.encode_json({
                    "msg": "peers", "req_id": payload.get("req_id"),
                    "shard_map": self.registry.shard_map(),
                    "peers": self.registry.peer_addrs(),
                }))
            elif msg == "list_schemas":
                conn.send(wire.encode_json({
                    "msg": "schemas",
                    "req_id": payload.get("req_id"),
                    "schemas": {
                        t: r.to_dict()
                        for t, r in self.registry.combined_schemas().items()
                    },
                }))
            else:
                conn.send(wire.encode_json({"msg": "error", "error": f"unknown msg {msg!r}"}))
        else:
            # data chunk from an agent (host_batch | partial_agg)
            if self._stale_incarnation(conn):
                return
            meta = payload.wire_meta
            self._handle_chunk(conn, meta, payload)

    @staticmethod
    def _reply_ack(conn: Connection, payload: dict, fn) -> None:
        """Run a control action; reply {msg: ok} or the error envelope."""
        try:
            fn()
            conn.send(wire.encode_json({"msg": "ok", "req_id": payload.get("req_id")}))
        except Exception as e:
            conn.send(wire.encode_json({
                "msg": "error", "req_id": payload.get("req_id"), "error": str(e),
            }))

    def _on_close(self, conn: Connection):
        if conn.state.get("superseded"):
            # a newer incarnation already owns the name: marking it dead
            # here would kill the NEW agent's liveness (dead stays dead
            # until register), and its eviction already ran at supersede
            return
        name = conn.state.get("agent")
        if name is not None:
            self.registry.mark_dead(name)
            if self._agent_conns.get(name) is conn:
                self._agent_conns.pop(name, None)
            # producer watchdog analog: evict the agent from every pending
            # query — retryable queries re-plan + re-dispatch, tracepoint
            # deploy rounds (and PL_QUERY_RETRIES=0) fail fast
            self._evict_agent(name, "disconnected")

    def _stale_incarnation(self, conn: Connection) -> bool:
        """Incarnation fence: frames arriving on a connection registered
        under an OLDER incarnation of the agent name are dropped (counted).
        A restarted agent re-registering under the same name supersedes the
        old socket; whatever that socket still delivers — chunks, acks,
        heartbeats — must be rejected, not folded."""
        name = conn.state.get("agent")
        inc = conn.state.get("incarnation")
        if name is None or inc is None:
            return False
        if inc == self.registry.incarnation(name):
            return False
        from pixie_tpu import metrics as _metrics

        _metrics.counter_inc(
            "px_broker_stale_incarnation_frames_total",
            help_="frames dropped from superseded agent sockets (an agent "
                  "re-registered under the same name; the old incarnation "
                  "is fenced)")
        return True

    def _evict_agent(self, name: str, reason: str) -> None:
        from pixie_tpu import metrics as _metrics

        _metrics.counter_inc(
            "px_agent_evictions_total",
            help_="agent connections lost (disconnect, heartbeat expiry, "
                  "or supersede by a re-registration)")
        # per-agent series ride a CAPPED label: agent names arrive on the
        # wire, so an id flood must not mint immortal counter series past
        # the cap (same policy as the PR 8 tenant-label cap)
        _metrics.counter_inc(
            "px_agent_evictions_by_agent_total",
            labels={"agent": _metrics.capped_label("agent", name)},
            help_="agent evictions by (capped) agent name")
        with self._qlock:
            ctxs = list(self._queries.values())
        for ctx in ctxs:
            for src in ctx.on_agent_lost(name, reason):
                self._finish_dispatch_span(ctx, src,
                                           error=f"agent {name} {reason}")
        self._push_shard_map()

    # ------------------------------------------------------------ durability
    def _push_shard_map(self) -> None:
        """Broadcast the registry's primary→replicas map + peer addresses
        to every live agent connection, and flag catch-up on the serving
        front (dead primaries being served by failover replicas degrade
        dispatch until they rehydrate).  No-op with replication off."""
        if not _replication.enabled():
            return
        m = self.registry.shard_map()
        peers = self.registry.peer_addrs()
        self.serving.set_catchup(len(self._failover_map(m)))
        frame = wire.encode_json({"msg": "shard_map", "map": m,
                                  "peers": peers})
        for _name, conn in sorted(self._agent_conns.items()):
            if not conn.closed:
                conn.send(frame)

    def _failover_map(self, shard_map: Optional[dict] = None) -> dict:
        """{dead primary → live replica} for every known-dead agent whose
        shard map lists a replica with a live connection.  Empty unless
        replication is enabled."""
        if not _replication.enabled():
            return {}
        if shard_map is None:
            shard_map = self.registry.shard_map()
        live = {r.name for r in self.registry.live_agents()}
        out: dict[str, str] = {}
        for primary, reps in sorted(shard_map.items()):
            if primary in live:
                continue
            rec = self.registry.record(primary)
            if rec is None or not rec.schemas:
                continue
            for r in reps or []:
                conn = self._agent_conns.get(r)
                if r in live and conn is not None and not conn.closed:
                    out[primary] = r
                    break
        return out

    def _spec_with_failover(self, spec, failover: dict):
        """Planner topology with the failover map's dead primaries added
        back as virtual data agents (their durable schemas come from the
        registry records).  The merger stays last."""
        from pixie_tpu.parallel.topology import AgentInfo, ClusterSpec

        have = {a.name for a in spec.agents}
        extra = []
        for primary in sorted(failover):
            if primary in have:
                continue
            rec = self.registry.record(primary)
            if rec is None:
                continue
            extra.append(AgentInfo(
                name=primary, has_data_store=True, processes_data=True,
                accepts_remote_sources=False, schemas=rec.schemas,
                n_devices=rec.n_devices))
        if not extra:
            return spec
        return ClusterSpec(spec.agents[:-1] + extra + spec.agents[-1:])

    # ---------------------------------------------------------- quota control
    def _handle_set_quota(self, conn: Connection, payload: dict) -> None:
        """Live tenant quota write: validate (malformed specs are REJECTED
        with a clean error — this is an interactive API, not an env var),
        apply to the serving front in place, persist in the KV so the
        record survives broker restart."""
        from pixie_tpu.serving.admission import normalize_quota
        from pixie_tpu.status import InvalidArgument

        rid = payload.get("req_id")
        tenant = payload.get("tenant")
        try:
            rec = normalize_quota(tenant, payload.get("qps"),
                                  payload.get("concurrency"),
                                  payload.get("weight"))
        except InvalidArgument as e:
            conn.send(wire.encode_json(
                {"msg": "error", "req_id": rid, "error": str(e)}))
            return
        try:
            eff = self.serving.set_quota(tenant, rec)
        except PxError as e:  # e.g. the live-record cap: a clean reject
            conn.send(wire.encode_json(
                {"msg": "error", "req_id": rid, "error": str(e)}))
            return
        if all(v is None for v in rec.values()):
            self.kv.delete(f"quota/{tenant}")
        else:
            self.kv.set_json(f"quota/{tenant}", rec)
        conn.send(wire.encode_json({
            "msg": "quota_ok", "req_id": rid, "tenant": tenant,
            "effective": eff}))

    def _load_quotas(self) -> None:
        """Recall persisted quota records into the serving front (broker
        restart).  A corrupt record is skipped (counted), never fatal."""
        from pixie_tpu import metrics as _metrics
        from pixie_tpu.serving.admission import normalize_quota

        for key, raw in self.kv.scan("quota/"):
            tenant = key[len("quota/"):]
            try:
                d = _json.loads(raw.decode())
                rec = normalize_quota(tenant, d.get("qps"),
                                      d.get("concurrency"), d.get("weight"))
            except Exception:
                _metrics.counter_inc(
                    "px_broker_quota_recall_errors_total",
                    help_="persisted quota records skipped at broker "
                          "startup (corrupt or no longer valid)")
                continue
            self.serving.set_quota(tenant, rec)

    # ------------------------------------------------------------ agent retire
    def _sole_holder_of(self, name: str) -> list[str]:
        """Primaries whose ONLY live holder is `name` per the PR 12 shard
        map: the shard coverage retiring `name` would lose.  Empty with
        replication off (no map) — the retire path then relies on the
        drain audit (rows held) instead."""
        m = self.registry.shard_map()
        live = {r.name for r in self.registry.live_agents()}
        out = []
        for p, reps in m.items():
            holders = (({p} if p in live else set())
                       | {r for r in (reps or []) if r in live})
            if holders == {name}:
                out.append(p)
        return sorted(out)

    def _agent_rpc(self, name: str, meta: dict, timeout: float = 5.0) -> dict:
        """One broker→agent control round-trip on the agent's connection."""
        conn = self._agent_conns.get(name)
        if conn is None or conn.closed:
            raise TimeoutError(f"agent {name} not connected")
        with self._qlock:
            self._req_counter += 1
            rid = f"ctl{self._req_counter}"
            slot = [threading.Event(), None]
            self._control_replies[rid] = slot
        try:
            meta = dict(meta, req_id=rid)
            if not conn.send(wire.encode_json(meta)):
                raise TimeoutError(f"agent {name} not connected")
            if not slot[0].wait(timeout):
                raise TimeoutError(
                    f"agent {name} did not answer {meta.get('msg')}")
            return slot[1]
        finally:
            with self._qlock:
                self._control_replies.pop(rid, None)

    def _answer_heat_map(self, conn: Connection, payload: dict) -> None:
        """Aggregate every live agent's storage_report into the cluster
        heat map: per-agent raw reports plus a per-table rollup (shard →
        summed decayed heat, cluster skew = max/mean shard heat).  Consumed
        by `pixie_tpu.cli storage`; also refreshes the px_journal_bytes
        gauge family from the reports (the broker may be the only scraped
        process in a multi-process deployment)."""
        from pixie_tpu import metrics as _metrics

        agents: dict = {}
        for rec in self.registry.live_agents():
            try:
                rep = self._agent_rpc(rec.name, {"msg": "storage_report"},
                                      timeout=5.0)
            except TimeoutError as e:
                agents[rec.name] = {"error": str(e)}
                continue
            agents[rec.name] = {
                "shard_heat": rep.get("shard_heat") or [],
                "storage_state": rep.get("storage_state") or [],
                **({"error": rep["error"]} if rep.get("error") else {}),
            }
        tables: dict = {}
        for rep in agents.values():
            for r in rep.get("shard_heat") or []:
                t = tables.setdefault(str(r.get("table_name")), {
                    "shards": {}, "rows_scanned": 0, "bytes": 0})
                sh = str(r.get("shard"))
                t["shards"][sh] = (t["shards"].get(sh, 0.0)
                                   + float(r.get("heat") or 0.0))
                t["rows_scanned"] += int(r.get("rows_scanned") or 0)
                t["bytes"] += int(r.get("bytes") or 0)
        for t in tables.values():
            heats = list(t["shards"].values())
            mean = sum(heats) / max(len(heats), 1)
            t["skew"] = round(max(heats) / mean, 4) if mean > 0 else 1.0
        jbytes: dict = {}
        for name, rep in agents.items():
            for r in rep.get("storage_state") or []:
                jbytes[name] = (jbytes.get(name, 0)
                                + int(r.get("journal_bytes") or 0))
        for name, b in jbytes.items():
            _metrics.gauge_set(
                "px_journal_bytes", float(b),
                labels={"agent": _metrics.capped_label("heat_shard", name)},
                help_="journal bytes on disk per agent (PL_JOURNAL_MAX_MB "
                      "pruning pressure)")
        conn.send(wire.encode_json({
            "msg": "heat_map", "req_id": payload.get("req_id"),
            "agents": agents, "tables": tables}))

    # ---------------------------------------------------------- shard re-homing
    def _answer_rehome(self, conn: Connection, payload: dict) -> None:
        """Control-frame wrapper for rehome_agent (cli / tests)."""
        res = self.rehome_agent(str(payload.get("agent")),
                                target=(str(payload["target"])
                                        if payload.get("target") else None),
                                reason=str(payload.get("reason") or "manual"))
        conn.send(wire.encode_json({
            "msg": "rehome_result", "req_id": payload.get("req_id"), **res}))

    def _pick_rehome_target(self, donor: str) -> Optional[str]:
        """A live peer to re-home `donor`'s shard onto: prefer one that
        already replicates the donor (its copy is a backfill head start);
        otherwise the live agent backing the fewest shards (spread, not
        pile-up).  None when the donor is the only live agent."""
        live = sorted(r.name for r in self.registry.live_agents()
                      if r.name != donor)
        if not live:
            return None
        m = self.registry.shard_map()
        for r in m.get(donor) or []:
            if r in live:
                return r
        load = {a: 0 for a in live}
        for _p, reps in m.items():
            for r in reps or []:
                if r in load:
                    load[r] += 1
        return min(live, key=lambda a: (load[a], a))

    @staticmethod
    def _manifest_covers(ranges: list, first: int, last: int) -> bool:
        """True when the sorted [start, n] ranges contiguously cover
        [first, last) — the donor's sealed frontier.  An empty frontier
        (first == last) needs no batches."""
        if first >= last:
            return True
        if not ranges or int(ranges[0][0]) > first:
            return False
        end = int(ranges[0][0])
        for start, n in ranges:
            if int(start) > end:
                break  # hole
            end = max(end, int(start) + int(n))
        return end >= last

    def rehome_agent(self, donor: str, target: Optional[str] = None,
                     reason: str = "manual") -> dict:
        """Move the `donor` shard's sealed data onto `target` over the
        PR 12 replication channel — the heavy half of elastic rebalancing
        (hot shards migrate instead of refusing to retire).  Two-phase:

          prepare — durable `move/<donor>` KV record, then the target is
             staged as an extra shard-map replica (registry.add_replica):
             the donor's ReplicationManager backfills every sealed batch
             to it over the normal channel — no new transfer code.  A
             `rehome_prepare` RPC force-seals the donor's hot remainders
             (table.seal_hot) and drains the stream, so the frontier the
             donor reports is fully shipped.
          verify — a `rehome_audit` RPC asks the TARGET what it actually
             holds for the donor; the broker diffs the replica manifest
             against the donor's reported per-table frontiers.  Bounded
             retries (backfill is async); incarnation fences on BOTH ends
             abort the move if either process restarted mid-flight.
          commit — the move record is deleted; the staged replica STAYS
             in the map (durable under rehome/<donor>), so failover and
             the retire audit find the copy.  The registry epoch bump
             from staging already invalidated every plan cache.

        Crash-safety: ownership stays with the donor until commit — an
        interrupted move leaves only an EXTRA copy staged, and a
        restarted broker aborts the stale `move/` record (start()).
        Returns {ok, donor, target, tables, synced, reason}."""
        from pixie_tpu import metrics as _metrics

        def _abort(why: str, staged: bool = False) -> dict:
            if staged:
                self.registry.remove_replica(donor, target)
                self._push_shard_map()
            self.kv.delete(f"move/{donor}")
            _metrics.counter_inc(
                "px_rehome_aborts_total",
                help_="shard re-homing moves aborted before commit "
                      "(ownership stayed with the donor)")
            return {"ok": False, "donor": donor, "target": target,
                    "tables": {}, "synced": False, "reason": why}

        if not _replication.enabled():
            return {"ok": False, "donor": donor, "target": target,
                    "tables": {}, "synced": False,
                    "reason": "replication disabled (PL_REPLICATION<=1)"}
        rec = self.registry.record(donor)
        if rec is None or not rec.alive:
            return {"ok": False, "donor": donor, "target": target,
                    "tables": {}, "synced": False,
                    "reason": "donor not live"}
        if target is None:
            target = self._pick_rehome_target(donor)
        if target is None or target == donor:
            return {"ok": False, "donor": donor, "target": target,
                    "tables": {}, "synced": False,
                    "reason": "no live re-home target"}
        trec = self.registry.record(target)
        if trec is None or not trec.alive:
            return {"ok": False, "donor": donor, "target": target,
                    "tables": {}, "synced": False,
                    "reason": "target not live"}
        # incarnation fences: a donor or target that restarts mid-move
        # invalidates the coverage evidence gathered so far
        d_inc = self.registry.incarnation(donor)
        t_inc = self.registry.incarnation(target)
        self.kv.set_json(f"move/{donor}", {
            "target": target, "reason": reason, "phase": "prepare"})
        self.registry.add_replica(donor, target)
        self._push_shard_map()
        try:
            prep = self._agent_rpc(donor, {"msg": "rehome_prepare"},
                                   timeout=15.0)
        except TimeoutError as e:
            return _abort(f"prepare failed: {e}", staged=True)
        if prep.get("error"):
            return _abort(f"prepare failed: {prep['error']}", staged=True)
        frontiers = {n: (int(f.get("first") or 0), int(f.get("last") or 0))
                     for n, f in (prep.get("tables") or {}).items()}
        covered = False
        for _try in range(20):
            if (self.registry.incarnation(donor) != d_inc
                    or self.registry.incarnation(target) != t_inc):
                return _abort("incarnation changed mid-move", staged=True)
            try:
                audit = self._agent_rpc(
                    target, {"msg": "rehome_audit", "donor": donor},
                    timeout=5.0)
            except TimeoutError as e:
                return _abort(f"audit failed: {e}", staged=True)
            man = audit.get("tables") or {}
            covered = all(
                self._manifest_covers(
                    (man.get(n) or {}).get("ranges") or [], first, last)
                for n, (first, last) in frontiers.items())
            if covered:
                break
            time.sleep(0.25)
        if not covered:
            return _abort("target manifest never covered the donor "
                          "frontier", staged=True)
        # commit: the one-key delete IS the flip — a crash before it
        # replays as an abort (extra copy unstaged, donor keeps owning)
        self.kv.delete(f"move/{donor}")
        _metrics.counter_inc(
            "px_rehome_moves_total",
            help_="shard re-homing moves committed (donor sealed data "
                  "verified resident on the target)")
        _metrics.counter_inc(
            "px_rehome_moved_tables_total", float(len(frontiers)),
            help_="tables whose sealed frontier was re-homed")
        self.record_scale_event(
            "rehome", donor, f"{reason} -> {target}", 0.0,
            len(self.registry.live_agents()))
        return {"ok": True, "donor": donor, "target": target,
                "tables": {n: {"first": f, "last": l}
                           for n, (f, l) in frontiers.items()},
                "synced": bool(prep.get("repl_synced")), "reason": ""}

    def _abort_stale_moves(self) -> None:
        """Broker restart mid-move: every surviving `move/` record is a
        prepare that never committed — unstage its extra replica and
        delete it.  Ownership stays with the donor (the two-phase flip's
        crash guarantee); the staged copy was only ever additive."""
        from pixie_tpu import metrics as _metrics

        for key, raw in list(self.kv.scan("move/")):
            donor = key.split("/", 1)[1]
            try:
                d = _json.loads(raw.decode())
            except Exception:
                d = {}
            if d.get("target"):
                self.registry.remove_replica(donor, str(d["target"]))
            self.kv.delete(key)
            _metrics.counter_inc(
                "px_rehome_stale_aborts_total",
                help_="interrupted re-homing moves aborted at broker "
                      "startup (ownership left with the donor)")

    def retire_agent(self, name: str, force: bool = False) -> dict:
        """Scale-down decommission with loss safety (the autoscaler's
        retire path; serving/elastic.py).  Protocol:

          1. Shard-map check FIRST: an agent that is the last live holder
             of any shard (its own primary data, or a dead primary it
             alone serves failover for) is refused — deregistering it
             would lose rows from every future answer.
          2. Drain audit: the agent reports the rows it holds outside the
             self-telemetry tables (`retire_query` RPC) and whether its
             replication stream is synced.
          3. rows == 0 → deregister + shard-map push (a clean retire: the
             agent held nothing irreplaceable).
             rows > 0 with replication synced onto a live replica → the
             PR 12 hand-off: the agent stops but its durable record STAYS,
             so its shard keeps answering through broker failover from the
             replicated sealed batches.
             rows > 0 otherwise → REFUSED (retiring it would lose rows).

        Returns {ok, mode: deregister|handoff|None, rows, reason,
        peer_sync} — peer_sync is the agent's per-peer replication
        watermark detail ({peer: {sent, acked, lag}}), so the audit's
        "synced" verdict ships with the numbers behind it."""
        from pixie_tpu import metrics as _metrics

        rec = self.registry.record(name)
        if rec is None:
            return {"ok": False, "mode": None, "rows": None,
                    "reason": "unknown agent", "peer_sync": {}}
        sole = self._sole_holder_of(name)
        if sole and not force:
            # rehome-first: instead of refusing outright, try moving the
            # sole-held shard onto a live peer over the replication
            # channel, then re-check.  A failed move (no peers, audit
            # never covered, replication off) falls back to the old
            # refusal — force keeps the old semantics entirely.
            moved = self.rehome_agent(name, reason="retire")
            if moved.get("ok"):
                sole = self._sole_holder_of(name)
            if sole:
                _metrics.counter_inc(
                    "px_autoscale_retire_refused_total",
                    help_="scale-down retires refused by the loss-safety "
                          "audit (last live shard holder, unauditable "
                          "rows, or unsynced replication)")
                return {"ok": False, "mode": None, "rows": None,
                        "reason": f"last live holder of shard(s) {sole}"
                                  + (f"; rehome failed: {moved['reason']}"
                                     if moved.get("reason") else ""),
                        "peer_sync": {}}
        rows = None
        repl_synced = False
        peer_sync: dict = {}
        try:
            reply = self._agent_rpc(name, {"msg": "retire_query"},
                                    timeout=5.0)
            rows = int(reply.get("rows", -1))
            repl_synced = bool(reply.get("repl_synced"))
            peer_sync = dict(reply.get("peer_sync") or {})
        except TimeoutError:
            pass
        if rows is None or rows < 0:
            if not force:
                _metrics.counter_inc(
                    "px_autoscale_retire_refused_total",
                    help_="scale-down retires refused by the loss-safety "
                          "audit (last live shard holder, unauditable "
                          "rows, or unsynced replication)")
                return {"ok": False, "mode": None, "rows": rows,
                        "reason": "drain audit unanswered",
                        "peer_sync": peer_sync}
            rows = -1
        if rows > 0 and not force:
            reps = self.registry.shard_map().get(name) or []
            live = {r.name for r in self.registry.live_agents()}
            if not (_replication.enabled() and repl_synced
                    and any(r in live for r in reps)):
                # rehome-first here too: a failed drain audit (unsynced
                # stream, no live replica yet) is exactly what the move
                # protocol repairs — it force-seals, drains, and VERIFIES
                # the target's coverage before the hand-off proceeds
                moved = self.rehome_agent(name, reason="retire")
                if moved.get("ok"):
                    repl_synced = True
                    reps = self.registry.shard_map().get(name) or []
                    live = {r.name for r in self.registry.live_agents()}
            if not (_replication.enabled() and repl_synced
                    and any(r in live for r in reps)):
                _metrics.counter_inc(
                    "px_autoscale_retire_refused_total",
                    help_="scale-down retires refused by the loss-safety "
                          "audit (last live shard holder, unauditable "
                          "rows, or unsynced replication)")
                return {"ok": False, "mode": None, "rows": rows,
                        "reason": "holds rows with no synced live replica",
                        "peer_sync": peer_sync}
            # PR 12 hand-off: keep the durable record — the shard keeps
            # serving through failover from the replicated sealed batches
            # once the agent stops (the supervisor owns the stop)
            return {"ok": True, "mode": "handoff", "rows": rows,
                    "reason": "", "peer_sync": peer_sync}
        self.registry.deregister(name)
        self._push_shard_map()
        return {"ok": True, "mode": "deregister", "rows": rows,
                "reason": "", "peer_sync": peer_sync}

    def reap_dead_agent(self, name: str) -> bool:
        """Deregister a DEAD supervisor-owned agent (preemption cleanup) —
        refused when the shard map still needs it (it may hold the only
        replicated copy of a shard some peer will rehydrate from)."""
        rec = self.registry.record(name)
        if rec is None or rec.alive or self._sole_holder_of(name):
            return False
        self.registry.deregister(name)
        self._push_shard_map()
        return True

    def record_scale_event(self, action: str, agent: str, reason: str,
                           pressure: float, agents: int) -> None:
        """One autoscaler decision into self_telemetry.scale_events (the
        supervisor's journal, shipped with the normal telemetry path)."""
        import time as _time

        from pixie_tpu import observe as _observe

        self._telemetry.add(_observe.SCALE_EVENTS_TABLE, [{
            "time_": _time.time_ns(),
            "action": str(action),
            "agent": str(agent),
            "reason": str(reason or ""),
            "pressure": round(float(pressure), 4),
            "agents": int(agents),
        }])
        self._ship_spans()

    # ---------------------------------------------------------------- handlers
    def _handle_register(self, conn: Connection, meta: dict):
        name = meta["agent"]
        schemas = {t: Relation.from_dict(r) for t, r in meta["schemas"].items()}
        asid = self.registry.register(name, schemas, meta.get("n_devices"),
                                      repl_addr=meta.get("repl_addr"))
        conn.state["agent"] = name
        # the incarnation this socket speaks for — older sockets for the
        # same name are fenced from here on (_stale_incarnation)
        conn.state["incarnation"] = self.registry.incarnation(name)
        old = self._agent_conns.get(name)
        self._agent_conns[name] = conn
        if old is not None and old is not conn:
            # fence the old socket BEFORE acking the new registration:
            # once the agent sees "registered" the rejoin is observable,
            # so the supersede marker must already be set.  Keep
            # "agent"+"incarnation" on the old conn so frames its reader
            # already queued are FENCED (stale incarnation) rather than
            # processed; the superseded marker keeps its close from
            # killing the new registration
            old.state["superseded"] = True
            old.close()
        conn.send(wire.encode_json({"msg": "registered", "asid": asid}))
        if old is not None and old is not conn:
            # in-flight dispatches on the old socket are orphaned (the new
            # process never saw them): evict so they re-dispatch to the
            # fresh incarnation (after the ack, so any re-dispatch frame
            # follows "registered" on the new socket)
            self._evict_agent(name, "superseded")
        # topology changed: replicas retarget, rehydrated shards leave
        # catch-up, takeover materializations for this name invalidate
        self._push_shard_map()

    def _ctx(self, meta: dict) -> Optional[_QueryCtx]:
        """Resolve the query ctx for a producer frame, enforcing the
        per-dispatch token.  Mismatched/missing tokens are dropped (and
        counted): a stale producer must not corrupt a newer query — or a
        newer dispatch round — that reused context state."""
        import hmac

        with self._qlock:
            ctx = self._queries.get(meta.get("req_id", ""))
        if ctx is None:
            return None
        expect = ctx.token_for(_QueryCtx.src_of(meta))
        # utf-8 bytes operands: compare_digest raises TypeError on non-ASCII
        # str, which would skip the counted-drop path (same pitfall the auth
        # handler avoids)
        if expect is None or not hmac.compare_digest(
                str(meta.get("qtoken", "")).encode(), expect.encode()):
            from pixie_tpu import metrics as _metrics

            _metrics.counter_inc(
                "px_broker_stale_token_frames_total",
                help_="producer frames rejected for a bad per-dispatch token")
            # surfaced loudly: an agent that never echoes the token (e.g. a
            # version mismatch) would otherwise present as an opaque query
            # timeout with only a metric to explain it
            _metrics.warn(
                "dropping producer frame with bad per-dispatch token",
                req_id=meta.get("req_id"), agent=meta.get("agent"),
                has_token=bool(meta.get("qtoken")))
            return None
        return ctx

    def _handle_chunk(self, conn: Connection, meta: dict, payload):
        ctx = self._ctx(meta)
        if ctx is not None:
            ctx.fold_chunk(meta, payload)
        # Open the producer's in-flight window (its backpressure gate): the
        # ack means this chunk's fold work is DONE, so a slow merge throttles
        # the agents instead of queueing unbounded frames.  Acked even when
        # the query is already dead (ctx None / stale token): acks are pure
        # flow control, and a producer still draining a doomed stream must
        # not stall on a window nobody will ever open.  Replied on the SAME
        # connection the chunk arrived on — routing by agent name would ack
        # a restarted incarnation for its predecessor's frames.
        if not conn.closed:
            conn.send(wire.encode_json({
                "msg": "chunk_ack", "req_id": meta.get("req_id"),
                "channel": meta["channel"], "seq": meta.get("seq"),
                "attempt": meta.get("attempt"),
                # the SOURCE the chunk answered for (≠ the executing agent
                # on a failover takeover): the producer's ack-window key
                # includes it, so two streams on one socket stay distinct
                "agent": meta.get("agent"),
            }))

    def _finish_dispatch_span(self, ctx: _QueryCtx, src,
                              error: Optional[str] = None) -> None:
        sp = ctx.dispatch_spans.pop(src, None)
        if sp is not None:
            if error:
                sp.attributes["error"] = error[:200]
            self.tracer.finish(sp)

    #: distinct agents the service-time model tracks; like metric label
    #: series, the dict is keyed by wire-supplied names and would otherwise
    #: grow without bound — past the cap the least-recently-updated entry
    #: is evicted (a re-appearing agent just re-warms)
    MAX_SVC_AGENTS = 256

    def _record_service_time(self, agent: str, secs: float) -> None:
        """Fold one dispatch→exec_done sample into the agent's EWMA model
        (hedge deadlines derive from it)."""
        import time as _time

        if _autotune.enabled():
            # the same completion stream feeds the fleet-wide hedge-floor
            # model (engine/autotune.py): measured service p99 replaces
            # the fixed PL_HEDGE_MIN_MS once warm
            _autotune.MODEL.observe_service(secs)
        a = 0.2
        with self._svc_lock:
            s = self._svc.get(agent)
            if s is None:
                if len(self._svc) >= self.MAX_SVC_AGENTS:
                    lru = min(self._svc, key=lambda k: self._svc[k]["at"])
                    self._svc.pop(lru, None)
                self._svc[agent] = {"ewma": secs, "dev": secs / 2, "n": 1,
                                    "at": _time.monotonic()}
                return
            s["ewma"] += a * (secs - s["ewma"])
            s["dev"] += a * (abs(secs - s["ewma"]) - s["dev"])
            s["n"] += 1
            s["at"] = _time.monotonic()

    def _hedge_deadline_s(self, agent: str) -> Optional[float]:
        """Seconds a dispatch to `agent` may run before a hedged duplicate
        fires; None while the service-time model is cold (or hedging off)."""
        if not _flags.get("PL_HEDGE_ENABLED"):
            return None
        with self._svc_lock:
            s = self._svc.get(agent)
            if s is None or s["n"] < HEDGE_MIN_SAMPLES:
                return None
            p99 = s["ewma"] + 4.0 * s["dev"]
        floor = float(_flags.get("PL_HEDGE_MIN_MS")) / 1e3
        if _autotune.enabled():
            # adaptive floor: the measured fleet service p99 (with
            # headroom) replaces the fixed half-second constant once the
            # model is warm.  It only ever LOWERS the operator's floor —
            # a fast fleet hedges stragglers in tens of ms; the tail guard
            # snaps back to the static floor if the model drifts.
            floor, _dec = _autotune.MODEL.hedge_floor_s(floor)
        return max(floor, float(_flags.get("PL_HEDGE_FACTOR")) * p99)

    def _handle_exec_done(self, meta: dict):
        ctx = self._ctx(meta)
        if ctx is None:
            return
        src = _QueryCtx.src_of(meta)
        res = ctx.on_exec_done(meta)
        self._finish_dispatch_span(ctx, src)
        # non-retryable rounds are tracepoint deploys: their round-trip
        # measures apply+re-register, not query execution — folding them
        # into the hedge model would skew the straggler deadlines
        if res is not None and ctx.retryable:
            self._record_service_time(*res)

    def _handle_exec_error(self, meta: dict):
        ctx = self._ctx(meta)
        if ctx is None:
            return
        src = _QueryCtx.src_of(meta)
        ctx.on_exec_error(meta)
        self._finish_dispatch_span(ctx, src, error=str(meta.get("error")))

    # ------------------------------------------------------------------- query
    def _run_query(self, client: Connection, meta: dict):
        req_id = meta.get("req_id", "")
        tenant = str(meta.get("tenant") or DEFAULT_TENANT)
        try:
            with trace.root(self.tracer, "query", req_id=req_id,
                            tenant=tenant):
                results, stats = self.execute_script(
                    meta["script"],
                    func=meta.get("func"),
                    func_args=meta.get("func_args"),
                    now=meta.get("now"),
                    default_limit=meta.get("default_limit"),
                    analyze=bool(meta.get("analyze", False)),
                    funcs=[tuple(f) for f in meta.get("funcs") or []] or None,
                    tenant=tenant,
                    explain=bool(meta.get("explain", False)),
                )
                with trace.span("render"):
                    for name, qr in results.items():
                        hb = HostBatch(
                            dtypes={n: qr.relation.dtype(n)
                                    for n in qr.relation.names()},
                            dicts=qr.dictionaries,
                            cols=qr.columns,
                        )
                        client.send(wire.encode_host_batch(
                            hb, {"msg": "result_chunk", "req_id": req_id,
                                 "table": name,
                                 # semantic types ride the wire with the
                                 # relation
                                 "relation": qr.relation.to_dict()}
                        ))
                    client.send(wire.encode_json(
                        {"msg": "done", "req_id": req_id,
                         "stats": _jsonable(stats)}
                    ))
        except ShedError as e:
            # admission rejection: NOT a failure of the query itself — the
            # envelope carries the retry-after hint so clients back off
            client.send(wire.encode_error(req_id, e,
                                          retry_after_s=e.retry_after_s))
        except Exception as e:  # compile/plan/exec errors all surface to client
            if not isinstance(e, PxError):
                traceback.print_exc()
            # infrastructure failures on idempotent queries carry the
            # retryable marker (+ a retry-after hint) so clients auto-retry
            # instead of surfacing a one-off agent death to the user
            client.send(wire.encode_error(
                req_id, e,
                retry_after_s=getattr(e, "retry_after_s", None),
                retryable=getattr(e, "retryable", None)))
        finally:
            self._ship_spans()

    def _ship_spans(self) -> None:
        """Persist this broker's finished spans AND flight-recorder rows
        (query profiles, op stats, sampled metrics, SLO alerts) into the
        data plane: everything goes to one live agent's self_telemetry
        tables through the normal write path, so PxL scripts and standing
        matviews see it without the broker holding a scanned store.

        Runs in query finally-blocks: telemetry failure (agent churn racing
        the conn map, dead sockets) must never replace a query's outcome, so
        everything is counted instead of raised."""
        from pixie_tpu import metrics as _metrics

        try:
            if self.tracer.buffered == 0 and len(self._telemetry) == 0:
                return
            # snapshot: the expiry thread pops entries concurrently
            conns = dict(self._agent_conns)

            def send_to_agent(frame) -> bool:
                for name in sorted(conns):
                    c = conns[name]
                    if not c.closed and c.send(frame):
                        return True
                return False

            def send(rows):
                if not send_to_agent(wire.encode_json(
                        {"msg": "spans", "spans": rows})):
                    _metrics.counter_inc(
                        "px_broker_trace_spans_unshipped_total",
                        float(len(rows)),
                        help_="broker spans dropped: no agent accepted them")

            self.tracer.flush(send=send)
            for table, rows in self._telemetry.drain().items():
                if not send_to_agent(wire.encode_json(
                        {"msg": "telemetry_rows", "table": table,
                         "rows": rows})):
                    _metrics.counter_inc(
                        "px_broker_telemetry_rows_unshipped_total",
                        float(len(rows)),
                        help_="flight-recorder rows dropped: no agent "
                              "accepted them")
        except Exception:
            _metrics.counter_inc(
                "px_broker_trace_ship_errors_total",
                help_="unexpected failures shipping broker spans")

    def _sample_self_metrics(self) -> None:
        """PL_SELF_METRICS_S cron body: metrics registry → telemetry rows,
        SLO burn-rate evaluation → alert rows, one ship."""
        from pixie_tpu import observe as _observe
        from pixie_tpu.serving import slo as _slo

        self._telemetry.add(_observe.METRICS_TABLE,
                            _observe.sample_metrics_rows("broker"))
        if _slo.configured():
            mon = _slo.monitor()
            mon.evaluate()
            self._telemetry.add(_observe.ALERTS_TABLE, mon.drain_alerts())
        if _autotune.enabled():
            # fallback trips and fitted-threshold changes → the autotune
            # telemetry table; checkpoint the model so a crash between
            # crons loses at most one period of learning
            rows = _autotune.MODEL.drain_rows()
            if rows:
                self._telemetry.add(_observe.AUTOTUNE_TABLE, rows)
            _autotune.MODEL.save_kv(self.kv)
        self._ship_spans()

    def _deploy_mutations(self, mutations: list) -> None:
        from pixie_tpu.status import Unavailable

        specs = [
            m for m in mutations
            if m.get("kind") in ("tracepoint", "delete_tracepoint")
        ]
        targets = {
            name: conn for name, conn in self._agent_conns.items()
            if not conn.closed
        }
        if not specs or not targets:
            return
        # A fresh req_id + ctx per spec round: a straggler ack from round N
        # that lands after its timeout cannot corrupt round N+1's accounting.
        for spec in specs:
            with self._qlock:
                self._req_counter += 1
                rid = f"tp{self._req_counter}"
                # retryable=False: mutations are never transparently
                # re-dispatched — agent loss mid-deploy fails the round
                ctx = _QueryCtx(set(), retryable=False)
                ctx.needed_agents = set(targets)
                for name in targets:
                    # deploy acks ride the base token at attempt 0
                    ctx.register_dispatch(name, token=ctx.token)
                self._queries[rid] = ctx
            try:
                for conn in targets.values():
                    conn.send(wire.encode_json({
                        "msg": "deploy_tracepoint", "req_id": rid, "spec": spec,
                        "qtoken": ctx.token,
                    }))
                if not ctx.done.wait(timeout=self.query_timeout_s):
                    raise Unavailable(
                        f"tracepoint deploy timed out on "
                        f"{ctx.outstanding_agents()}"
                    )
                if ctx.error:
                    raise Unavailable(ctx.error)
            finally:
                with self._qlock:
                    self._queries.pop(rid, None)

    # ------------------------------------------------- fault-tolerant dispatch
    def _await_rejoin_grace(self) -> None:
        """Hold dispatch while a just-dead agent may still re-register: a
        query planned in the kill→restart window would otherwise silently
        answer from the surviving shards only.  Bounded by the grace window
        measured from each death (never the full query timeout); a no-op
        with retries disabled — PL_QUERY_RETRIES=0 keeps the legacy
        plan-with-whatever-is-live behavior bit-identically."""
        import time as _time

        if int(_flags.get("PL_QUERY_RETRIES")) <= 0:
            return
        grace = float(_flags.get("PL_REJOIN_GRACE_S"))
        if grace <= 0:
            return
        deadline = _time.monotonic() + min(grace, self.query_timeout_s)
        waited_for = None
        t0 = _time.time_ns()
        while _time.monotonic() < deadline:
            recent = self.registry.recently_dead(grace)
            if not recent:
                break
            waited_for = recent
            _time.sleep(0.05)
        if waited_for is not None:
            trace.event_span("rejoin_wait", t0, _time.time_ns() - t0,
                             agents=",".join(waited_for))

    def _send_execute(self, ctx: _QueryCtx, req_id: str, agent: str,
                      plan_json: str, base_meta: dict,
                      hedged: bool = False) -> str:
        """Send one execute dispatch (fragment `plan_json`) to `agent` under
        a fresh per-dispatch token.  Returns the src id; raises Unavailable
        when the agent has no live connection."""
        from pixie_tpu.status import Unavailable

        conn = self._agent_conns.get(agent)
        serve_for = None
        if conn is None or conn.closed:
            # failover: a dead primary's fragment dispatches to its live
            # replica, which serves it from the replicated sealed batches
            # (takeover store) and answers AS the primary
            replica = ctx.failover.get(agent)
            rconn = (self._agent_conns.get(replica)
                     if replica is not None else None)
            if rconn is None or rconn.closed:
                raise Unavailable(f"agent {agent} not connected")
            conn, serve_for = rconn, agent
            ctx.failover_used[agent] = replica
            from pixie_tpu import metrics as _metrics

            _metrics.counter_inc(
                "px_broker_failover_dispatches_total",
                help_="fragments dispatched to failover replicas for dead "
                      "primaries")
        deadline = None
        if not hedged:
            h = self._hedge_deadline_s(agent)
            if h is not None:
                import time as _time

                deadline = _time.monotonic() + h
        src, token, attempt = ctx.register_dispatch(
            agent, frag=plan_json, deadline=deadline, hedged=hedged,
            via=(ctx.failover.get(agent) if serve_for else None))
        # one dispatch span per src: opened at send, closed by the
        # exec_done/exec_error handler (or eviction cleanup); its id rides
        # the wire so the agent's exec spans parent under it cross-process
        dsp = trace.start_child("dispatch", agent=agent, attempt=attempt,
                                hedged=hedged)
        tctx = None
        if dsp is not None:
            ctx.dispatch_spans[src] = dsp
            tctx = {"trace_id": dsp.trace_id, "span_id": dsp.span_id}
        meta = dict(base_meta)
        meta.update({"req_id": req_id, "qtoken": token, "attempt": attempt,
                     "trace": tctx})
        if serve_for is not None:
            meta["serve_for"] = serve_for
        # splice the cached plan JSON (encoded once per plan/split, not per
        # query) instead of re-serializing the plan dict
        if not conn.send(wire.encode_json_raw(meta, {"plan": plan_json})):
            ctx.drop_dispatch(src)
            self._finish_dispatch_span(ctx, src, error="send failed")
            raise Unavailable(f"agent {agent} not connected")
        return src

    def _await_agents(self, ctx: _QueryCtx, req_id: str, entry, q, dp,
                      split_extras, base_meta: dict, reg, fault: dict,
                      retries: int, extra_verify=None):
        """Wait for every needed agent's answer, surviving evictions and
        stragglers: evicted fragments re-plan onto the live agent set and
        re-dispatch with jittered exponential backoff (bounded by
        PL_QUERY_RETRIES); dispatches outliving their service-time deadline
        get a hedged duplicate.  Returns the final (dp, split_extras) —
        re-dispatch may have re-planned them."""
        import random as _random
        import time as _time

        from pixie_tpu import metrics as _metrics
        from pixie_tpu.status import CompilerError, Unavailable

        backoff_ms = float(_flags.get("PL_RETRY_BACKOFF_MS"))
        rng = _random.Random()
        deadline = _time.monotonic() + self.query_timeout_s
        rounds = 0
        while True:
            if ctx.error:
                raise Unavailable(ctx.error)
            if ctx.done.is_set():
                return dp, split_extras
            evicted = ctx.take_evictions()
            fault["evictions"] += len(evicted)
            if evicted or ctx.uncovered_agents():
                names = (sorted({a for a, _ in evicted})
                         or ctx.uncovered_agents())
                if rounds >= retries:
                    err = Unavailable(
                        f"agent {names[0]} disconnected mid-query")
                    if not q.mutations:
                        # infrastructure loss, not a query bug: the client
                        # may retry once the agent re-registers.  The hint
                        # composes BOTH waits the retry faces: the backoff
                        # schedule covering the agent's rejoin window (the
                        # drain rate says nothing about when lost DATA
                        # comes back — a bare drain hint of 0.05s on an
                        # idle queue would burn every client retry inside
                        # the rejoin grace) and, when the rate model is
                        # warm, the measured time for the queued work
                        # ahead of the retry to drain.
                        err.retryable = True
                        hint = self.ratemodel.retry_after_s(
                            self.serving.total_queued,
                            int(_flags.get("PL_SERVING_MAX_INFLIGHT")))
                        err.retry_after_s = max(
                            min(backoff_ms * (2 ** rounds),
                                MAX_BACKOFF_MS) / 1e3,
                            hint or 0.0)
                    raise err
                rounds += 1
                fault["rounds"] = rounds
                _metrics.counter_inc(
                    "px_query_retries_total",
                    help_="query re-dispatch rounds after agent eviction")
                # jittered exponential backoff: the window a killed-and-
                # restarted agent gets to re-register before this round
                # re-plans around it
                delay = (backoff_ms * (2 ** (rounds - 1)) / 1e3
                         * (0.5 + rng.random()))
                delay = min(delay, MAX_BACKOFF_MS / 1e3,
                            max(deadline - _time.monotonic(), 0.0))
                if delay > 0:
                    _time.sleep(delay)
                t0 = _time.time_ns()
                try:
                    dp, split_extras = self._redispatch(
                        ctx, req_id, entry, q, dp, split_extras, base_meta,
                        reg, fault, extra_verify=extra_verify)
                except (Unavailable, CompilerError):
                    # the cluster cannot serve the query right now (e.g.
                    # the killed agent has not re-registered): burn the
                    # round and look again after the next backoff — the
                    # uncovered set keeps this loop re-entering here
                    continue
                trace.event_span("redispatch", t0, _time.time_ns() - t0,
                                 agents=",".join(names), round=rounds)
                continue
            nxt = self._maybe_hedge(ctx, req_id, base_meta, fault)
            remaining = deadline - _time.monotonic()
            if remaining <= 0:
                raise Unavailable(
                    f"query timed out after {self.query_timeout_s}s waiting "
                    f"for agents {ctx.outstanding_agents()}")
            wait_s = min(0.25, remaining)
            if nxt is not None:
                wait_s = min(wait_s, max(nxt, 0.01))
            ctx.wake.wait(timeout=wait_s)
            ctx.wake.clear()

    def _redispatch(self, ctx: _QueryCtx, req_id: str, entry, q, dp,
                    split_extras, base_meta: dict, reg, fault: dict,
                    extra_verify=None):
        """One re-plan + re-dispatch round: re-split over the LIVE agent
        set and dispatch every uncovered fragment under fresh tokens.
        Accepted results (and in-flight dispatches) whose fragments are
        unchanged are KEPT — only the lost work repeats.  Falls back to a
        full restart when the channel topology changed (e.g. a repartition
        join lost its widest mesh)."""
        from pixie_tpu.engine.plancache import QueryPlanCache as _QPC
        from pixie_tpu.parallel.distributed import DistributedPlanner
        from pixie_tpu.status import Unavailable

        topo_epoch = self.registry.epoch
        spec = self.registry.cluster_spec()
        if not any(a.has_data_store for a in spec.agents):
            raise Unavailable("no live data agents registered")
        # a needed agent that died within the rejoin grace is REJOINING,
        # not gone: re-planning around it now would silently answer from
        # the surviving shards — burn the round and wait for it instead
        grace = float(_flags.get("PL_REJOIN_GRACE_S"))
        live = {a.name for a in spec.agents}
        rejoining = [a for a in sorted(ctx.needed_agents)
                     if a not in live
                     and a in set(self.registry.recently_dead(grace))]
        if rejoining:
            raise Unavailable(
                f"agent {rejoining[0]} re-registration pending")
        # past the grace: dead primaries with live replicas re-plan as
        # failover (virtual) agents instead of dropping out of the answer
        failover = self._failover_map()
        ctx.failover = failover
        if failover:
            spec = self._spec_with_failover(spec, failover)

        def _split():
            with trace.span("plan_split", redispatch=True):
                dp2 = DistributedPlanner(spec).plan(q.plan)
                # the re-planned split dispatches too: same pre-dispatch
                # verification contract as the first round — INCLUDING the
                # fused-batch demux invariants for batched carriers (the
                # re-split is cached into the batch slot for warm repeats)
                from pixie_tpu.check import planverify

                planverify.maybe_verify(dp2, spec.combined_schemas(), reg)
                if extra_verify is not None:
                    extra_verify(dp2)
                extras = {"plan_json": {
                    a: _json.dumps(p.to_dict())
                    for a, p in dp2.agent_plans.items()
                }}
                return dp2, extras

        (dp2, extras2), _hit = _QPC.get_split(
            entry, ("split", topo_epoch), _split)
        base_meta["route_scale"] = len(dp2.agent_plans)
        if not _channels_compatible(dp, dp2):
            # topology-shaped plan state (join partition counts, channel
            # sets, the merger plan) changed: nothing folded so far is
            # usable — restart the whole dispatch under fresh tokens
            for src in list(ctx.dispatch_spans):
                self._finish_dispatch_span(ctx, src, error="redispatched")
            ctx.reset_for_restart(dp2, reg)
        else:
            with ctx.lock:
                ctx.needed_agents = set(dp2.agent_plans)
                # an accepted fragment that CHANGED under the new plan (or
                # an in-flight dispatch of one) cannot be kept — its chunks
                # answer a different question now
                for agent in list(ctx.accepted):
                    if (agent in dp2.agent_plans
                            and ctx.frag_of(ctx.accepted[agent])
                            != extras2["plan_json"][agent]):
                        ctx.accepted.pop(agent)
                for src, info in list(ctx.pending.items()):
                    agent = info["agent"]
                    if (agent not in dp2.agent_plans
                            or info.get("frag")
                            != extras2["plan_json"][agent]):
                        ctx.pending.pop(src, None)
                ctx.hedged_agents.clear()  # fresh round, fresh hedge budget
                ctx._check_done_locked()
        for agent in ctx.uncovered_agents():
            try:
                self._send_execute(ctx, req_id, agent,
                                   extras2["plan_json"][agent], base_meta)
            except Unavailable:
                # its conn raced away again — the uncovered set re-enters
                # the retry loop for it
                continue
            if agent not in fault["redispatched"]:
                fault["redispatched"].append(agent)
        return dp2, extras2

    def _maybe_hedge(self, ctx: _QueryCtx, req_id: str, base_meta: dict,
                     fault: dict):
        """Dispatch hedged duplicates for in-flight dispatches past their
        straggler deadline (first answer wins; the loser's chunks are
        discarded idempotently at merge).  Returns seconds until the next
        armed deadline, or None when nothing is armed."""
        if not _flags.get("PL_HEDGE_ENABLED"):
            return None
        import time as _time

        from pixie_tpu import metrics as _metrics
        from pixie_tpu.status import Unavailable

        now = _time.monotonic()
        soonest = None
        with ctx.lock:
            pend = [(s, dict(i)) for s, i in ctx.pending.items()]
        for src, info in pend:
            dl = info.get("deadline")
            if dl is None or info.get("hedged"):
                continue
            agent = info["agent"]
            with ctx.lock:
                if agent in ctx.hedged_agents or agent in ctx.accepted:
                    continue
            if now < dl:
                gap = dl - now
                soonest = gap if soonest is None else min(soonest, gap)
                continue
            try:
                self._send_execute(ctx, req_id, agent, info["frag"],
                                   base_meta, hedged=True)
            except Unavailable:
                continue  # conn gone: the eviction path owns this agent now
            fault["hedged"] += 1
            _metrics.counter_inc(
                "px_hedged_dispatches_total",
                help_="duplicate dispatches sent for straggling agents "
                      "(first answer wins)")
            _metrics.counter_inc(
                "px_hedged_dispatches_by_agent_total",
                labels={"agent": _metrics.capped_label("agent", agent)},
                help_="hedged dispatches by (capped) agent name")
        return soonest

    def _admit(self, script, func, func_args, default_limit, tenant):
        """Pass one query through the serving front's admission gate.

        Cost estimate: a plan-cache peek decides warm (dispatch+merge only)
        vs cold (full compile/split) — the same signal the DRR scheduler
        charges, so a tenant flooding cold compiles drains proportionally
        slower.  The cold price is the MEASURED cold/warm service-time
        ratio once the rate model has samples (PL_RATE_MODEL), the static
        COST_COLD until then.  Raises ShedError (quota/queue-full/timeout/
        overload); returns (ticket, plan_class) — ticket None when serving
        is disabled (the class still feeds the model)."""
        from pixie_tpu.serving import ratemodel as _rm

        trace.set_attr(tenant=tenant)
        from pixie_tpu.engine import plancache as _plancache

        # mutations classify apart (deploy round-trips must skew neither
        # service class) — the same lexical marker the client's no-retry
        # rule uses; everything else prices off the plan-cache peek
        mutation = ("UpsertTracepoint" in script
                    or "DeleteTracepoint" in script)
        if not _plancache.enabled():
            # PL_QUERY_FASTPATH=0: no warm/cold signal exists and every
            # query pays the same full compile — price uniformly WARM so
            # DRR stays fair by count and the overload shed (which drops
            # cost >= COST_COLD work) cannot turn degradation into a full
            # outage
            warm = True
        else:
            key = self.plan_cache.key(script, func, func_args, default_limit,
                                      ("reg", self.registry.epoch),
                                      tenant=tenant)
            warm = self.plan_cache.contains(key)
        cls = _rm.plan_class(warm, mutation=mutation)
        self.ratemodel.observe_arrival(tenant, cls)
        if not self.serving.enabled():
            return None, cls  # pass-through: no accounting, no queueing
        cost = (COST_WARM if warm
                else self.ratemodel.cost_of(False) if _rm.enabled()
                else COST_COLD)
        with trace.span("admission_wait", tenant=tenant, cost=cost):
            ticket = self.serving.admit(tenant, cost)
        if ticket.queued:
            # the scheduler's dispatch decision as its own span: start =
            # enqueue, duration = queue wait (ends at dispatch)
            trace.event_span("sched_dispatch", ticket.enqueue_ns,
                             ticket.wait_ns, tenant=tenant, cost=cost,
                             degraded=ticket.degraded)
        return ticket, cls

    def execute_script(
        self, script: str, func=None, func_args=None, now=None,
        default_limit=None, analyze: bool = False, funcs=None,
        tenant: str = None, explain: bool = False,
    ) -> tuple[dict[str, QueryResult], dict]:
        """Compile + distribute + merge (the in-process core of ExecuteScript).

        `funcs=[(prefix, func_name, func_args)]` executes a MULTI-widget
        request as ONE fused distributed query (shared scans/filters/aggs
        run once — reference optimizer.h:39 MergeNodesRule); the returned
        stats carry `sink_map` so the caller splits results per widget.

        `explain=True` (EXPLAIN ANALYZE) annotates whatever path ACTUALLY
        served the query — plan tree, measured phase breakdown, per-op ns,
        cache/matview/batch/failover provenance — into stats["explain"] +
        stats["profile"], without changing execution (a matview hit is
        explained AS a matview hit, not bypassed).
        """
        import time as _time

        from pixie_tpu import observe as _observe
        from pixie_tpu import metrics as _metrics
        from pixie_tpu.serving import slo as _slo

        tenant = str(tenant or DEFAULT_TENANT)
        _metrics.counter_inc("px_broker_queries_total",
                             help_="ExecuteScript requests received")
        # In-process callers (cron, tests) get their own trace root; under
        # the networked path _run_query's root is already active and this is
        # a no-op.  Shipping happens only when this frame owns the root.
        owns_root = trace.enabled() and trace.current() is None
        #: flight recorder: assemble a per-query profile when tracing is on
        #: (recorded into self_telemetry.*) or explain was requested (the
        #: per-query opt-in works with tracing off, without recording)
        prof_on = trace.enabled() or explain
        t0 = _time.perf_counter()
        t0_unix_ns = _time.time_ns()
        shed = False
        ok_query = False
        qid = None
        cls = None  # rate-model plan class, set once admission classifies
        wait_ns = 0
        try:
            with trace.maybe_root(self.tracer, "query"):
                # captured while the trace root is live: the except block
                # below runs AFTER the cm unwinds, and an error profile
                # must still join this query's spans on query_id==trace_id
                qid = self._query_trace_id() if prof_on else None
                ticket, cls = self._admit(script, func, func_args,
                                          default_limit, tenant)
                wait_ns = ticket.wait_ns if ticket is not None else 0
                ok = False
                try:
                    results, stats = self._execute_script_inner(
                        script, func, func_args, now, default_limit, analyze,
                        funcs, tenant=tenant, ticket=ticket, explain=explain,
                    )
                    ok = True
                    ok_query = True
                    if prof_on:
                        self._record_profile(
                            qid, stats, tenant, t0_unix_ns,
                            int((_time.perf_counter() - t0) * 1e9),
                            explain=explain)
                    return results, stats
                finally:
                    self.serving.release(ticket, ok=ok)
        except ShedError:
            # admission rejections are flow control, not query failures —
            # they are counted under px_serving_shed_total instead
            shed = True
            raise
        except Exception as e:
            _metrics.counter_inc("px_broker_query_errors_total",
                                 help_="ExecuteScript requests that failed")
            if trace.enabled():
                # failed queries are profile rows too (status=error): an
                # error budget burning down must be visible in the same
                # table the latency dashboards read
                prow, _ops = _observe.build_profile(
                    qid or self._query_trace_id(), tenant, "broker",
                    t0_unix_ns, int((_time.perf_counter() - t0) * 1e9), {},
                    status="error", error=str(e))
                self._telemetry.add(_observe.PROFILES_TABLE, [prow])
            raise
        finally:
            latency_s = _time.perf_counter() - t0
            if not shed:
                # sheds stay out of the latency SLO histogram: a flood of
                # sub-ms rejections (or 30s queue-timeout sheds) during
                # overload would swamp the distribution of queries that
                # actually EXECUTED — exactly when the SLO signal matters
                _metrics.histogram_observe(
                    "px_broker_query_latency_seconds",
                    latency_s, QUERY_LATENCY_BOUNDS,
                    help_="broker end-to-end ExecuteScript latency "
                          "(executed queries; sheds excluded)")
            # the serving front's SLO loop eats every outcome — completed,
            # failed, AND shed (a shed is a client-visible availability
            # failure; hiding it from the burn rate would defeat the alert)
            _slo.record_query(tenant, latency_s, ok_query)
            # the rate model eats SERVICE time only (queue wait excluded —
            # it measures how fast the engine serves, not the line length);
            # sheds never executed, so they feed arrival counts only
            if cls is not None and not shed:
                self.ratemodel.observe(tenant, cls,
                                       latency_s - wait_ns / 1e9, ok_query)
            if _slo.configured():
                mon = _slo.monitor()
                mon.maybe_evaluate()
                self._telemetry.add(_observe.ALERTS_TABLE,
                                    mon.drain_alerts())
            if owns_root:
                self._ship_spans()

    def _query_trace_id(self) -> str:
        """Query id for profile rows: the active trace root's trace_id (so
        profiles JOIN against self_telemetry.spans), else a fresh token."""
        c = trace.current()
        if c is not None:
            return c[1].trace_id
        import secrets as _secrets

        return _secrets.token_hex(16)

    def _record_profile(self, qid, stats: dict, tenant: str,
                        t0_unix_ns: int, wall_ns: int,
                        explain: bool) -> None:
        """Assemble this query's flight-recorder profile from its stats and
        attach it (stats["profile"], stats["explain"]); recording into the
        data plane only happens with tracing enabled."""
        from pixie_tpu import observe as _observe

        profile, op_rows = _observe.build_profile(
            qid or self._query_trace_id(), tenant, "broker", t0_unix_ns,
            wall_ns, stats)
        stats["profile"] = profile
        if explain:
            stats["explain"] = _observe.render_explain(
                profile, op_rows, plan_text=stats.pop("plan_explain", None))
        if trace.enabled():
            self._telemetry.add(_observe.PROFILES_TABLE, [profile])
            self._telemetry.add(_observe.OP_STATS_TABLE, op_rows)
            if _autotune.enabled():
                at_rows = _autotune.rows_from_stats(
                    stats, profile.get("query_id", ""))
                if at_rows:
                    self._telemetry.add(_observe.AUTOTUNE_TABLE, at_rows)

    def _execute_script_inner(
        self, script, func, func_args, now, default_limit, analyze,
        funcs=None, tenant: str = DEFAULT_TENANT, ticket=None,
        explain: bool = False,
    ) -> tuple[dict[str, QueryResult], dict]:
        import time as _time

        from pixie_tpu import metrics as _metrics
        from pixie_tpu.compiler import compile_pxl, compile_pxl_funcs
        from pixie_tpu.status import Internal, Unavailable

        if self.elector is not None and not self.elector.is_leader():
            leader = self.elector.leader()
            raise Unavailable(
                f"this broker is not the leader (current leader: {leader})")
        if _autotune.enabled():
            # arrival-rate signal for the batch-window controller
            _autotune.MODEL.observe_arrival()
        # Hold for shards whose agent died moments ago and may re-register
        # (kill-and-restart): planning through the gap would silently serve
        # a reduced topology
        self._await_rejoin_grace()
        # Epoch BEFORE cluster_spec: a registration landing between the two
        # reads must not let a split computed from the agent-less spec be
        # cached under the post-registration epoch (sticky wrong results).
        # The inverse race — cluster_spec's live_agents() expiring an agent
        # and bumping the epoch after our read — only caches the fresh split
        # under the stale epoch: one redundant miss, never a poisoned hit.
        topo_epoch = self.registry.epoch
        spec = self.registry.cluster_spec()
        # Failover: dead primaries with live replicas stay IN the plan as
        # virtual agents — their fragments dispatch to the replica's
        # connection (serve_for), so the answer keeps covering their shard
        # instead of silently shrinking to the survivors.
        failover = self._failover_map()
        if failover:
            spec = self._spec_with_failover(spec, failover)
        if not any(a.has_data_store for a in spec.agents):
            e = Unavailable("no live data agents registered")
            # nothing compiled, nothing executed: always safe to retry
            # once an agent (re-)registers
            e.retryable = True
            e.retry_after_s = 1.0
            raise e
        sink_map = None
        entry = None
        plan_cache_hit = False
        t_compile0 = _time.perf_counter_ns()
        if funcs:
            # multi-widget fusion stays on the slow path: its sink_map and
            # per-widget arg sets make the cache key explode for no warm win
            with trace.span("compile"):
                q, sink_map = compile_pxl_funcs(
                    script, self.registry.combined_schemas(),
                    [(p, f, a) for p, f, a in funcs],
                    registry=self.udf_registry, now=now,
                    default_limit=default_limit,
                )
        else:
            def _compile():
                with trace.span("compile"):
                    return compile_pxl(
                        script, self.registry.combined_schemas(), func=func,
                        func_args=func_args, registry=self.udf_registry,
                        now=now, default_limit=default_limit,
                    )

            key = self.plan_cache.key(script, func, func_args, default_limit,
                                      ("reg", topo_epoch), tenant=tenant)
            q, entry, plan_cache_hit = self.plan_cache.get_query(key, _compile)
        compile_ns = _time.perf_counter_ns() - t_compile0
        plan_text = None
        if explain:
            from pixie_tpu.plan.debug import explain as _plan_explain

            plan_text = _plan_explain(q.plan)
        if q.mutations:
            # Deploy tracepoints to every live agent and wait for readiness
            # (reference MutationExecutor: register → agents deploy → poll
            # isSchemaReady, mutation_executor.go:84,272).
            with trace.span("deploy_mutations"):
                self.tracepoints.apply(q.mutations)
                self._deploy_mutations(q.mutations)
            topo_epoch = self.registry.epoch  # BEFORE cluster_spec (see above)
            spec = self.registry.cluster_spec()  # schemas refreshed by re-register
        elif not analyze and funcs is None \
                and not getattr(q, "now_sensitive", True):
            # Concurrent-query batching (PL_QUERY_BATCHING): groupable
            # concurrent queries over the same (table, scan window,
            # topology epoch) rendezvous at the serving front's dispatch
            # seam and execute as ONE fused distributed query; results
            # demux back per member.  None = run the normal path.
            got = self._maybe_batched(q, key, spec, topo_epoch, failover,
                                      tenant, ticket)
            if got is not None:
                results, stats = got
                if plan_text is not None or trace.enabled():
                    # a batched member's profile carries ITS OWN compile
                    # time and logical plan (the fused plan is the
                    # leader's implementation detail) beside the fused
                    # run's measured phases + batch slot
                    stats = dict(stats)
                    stats["phases"] = dict(stats.get("phases") or {},
                                           compile_ns=compile_ns)
                    if plan_text is not None:
                        stats["plan_explain"] = plan_text
                return results, stats
        return self._run_distributed(
            q, entry, spec, topo_epoch, failover, analyze, tenant, ticket,
            plan_cache_hit, sink_map=sink_map, compile_ns=compile_ns,
            plan_text=plan_text)

    # ------------------------------------------------------ query batching
    def _maybe_batched(self, q, key, spec, topo_epoch, failover, tenant,
                       ticket):
        """Pass one compiled, cache-eligible query through the shared
        batching gate (serving/batching.gate).  Returns (results, stats)
        when the query was served through a fused batch, or None to run
        the normal path (batching off, non-groupable plan, matview-shaped
        member, solo leader)."""
        from pixie_tpu.serving import batching

        reg = self.udf_registry
        if reg is None:
            from pixie_tpu.udf import registry as reg
        window_s = float(_flags.get("PL_BATCH_WINDOW_MS")) / 1e3
        max_n = int(_flags.get("PL_BATCH_MAX_QUERIES"))
        at_dec = None
        if _autotune.enabled():
            # rendezvous window from measured wave RTT, member cap from the
            # measured arrival rate (engine/autotune.py batch controller);
            # both clamped to a 4x band around the operator's constants
            window_s, max_n, at_dec = _autotune.MODEL.batch_window(
                window_s, max_n)
        got = batching.gate(
            self._batcher, q.plan, key, topo_epoch, window_s, max_n,
            lambda members: self._execute_batch(members, spec, topo_epoch,
                                                failover, reg),
            wait_timeout_s=self.query_timeout_s + 30.0,
            tenant=tenant, ticket=ticket, registry=reg,
            # concurrent-traffic signal: other queries executing past
            # admission right now (members waiting in a batch hold their
            # slots, so sustained concurrency keeps this ≥ 2; a lone
            # sequential client sees only itself and never waits)
            concurrency=lambda: (self.serving.enabled()
                                 and self.serving.inflight >= 2))
        if got is None:
            return None
        results, stats = got
        if at_dec is not None and isinstance(stats, dict):
            # fresh list, not setdefault: fused-member stats share inner
            # structures across the batch — appending in place would leak
            # this member's decision into every sibling's stats
            stats = dict(stats)
            stats["autotune"] = list(stats.get("autotune") or []) + [at_dec]
        b = (stats or {}).get("batch") or {}
        if b.get("t0_unix_ns"):
            # ONE batch_exec span under every member's query root (leaders
            # and waiters alike): the cross-query group marker
            trace.event_span("batch_exec", b["t0_unix_ns"],
                             b.get("wall_ns", 0),
                             size=b.get("size"), slot=b.get("slot"))
        return results, stats

    def _execute_batch(self, members, spec, topo_epoch, failover, reg):
        """Batch-leader path: merge the member plans (shared scans, deduped
        chains, per-slot renamed sinks; identical members share ONE
        computed slot), split+verify once per batch signature riding the
        split cache, run ONE fault-tolerant distributed dispatch (an
        evicted agent's WHOLE fused fragment re-dispatches — the pinned
        mid-batch recovery semantic), and demux per-member
        (results, stats)."""
        import time as _time
        import types

        from pixie_tpu.check import planverify
        from pixie_tpu.serving import batching

        k = len(members)
        slot, plans, slot_of = batching.fused_slot(
            self._batch_splits, self._qlock, members,
            spec.combined_schemas())
        # DRR cost-accounting: each member was admitted at the full plan
        # cost estimate; the batch executes ~one dispatch, so charge the
        # amortized share (refunds queued members' deficit — batching must
        # not distort tenant fairness)
        for m in members:
            if m.ticket is not None:
                self.serving.rebate(m.ticket, m.ticket.cost / k)
        fused_q = types.SimpleNamespace(plan=slot.fused, mutations=[],
                                        now_sensitive=False)
        # the batch_exec span lands on EVERY member root (leader included)
        # via the event emission in _maybe_batched — no cm span here, or
        # the leader's root would carry it twice
        t0_ns = _time.time_ns()
        results, stats = self._run_distributed(
            fused_q, slot, spec, topo_epoch, failover, False,
            "__batch__", None, plan_cache_hit=False,
            extra_verify=lambda dp: planverify.maybe_verify_fused_batch(
                dp, slot.sink_map))
        wall_ns = _time.time_ns() - t0_ns
        if _autotune.enabled():
            # measured fused-wave wall → the batch-window controller
            _autotune.MODEL.observe_batch_wave(wall_ns / 1e9, k)
        batching.note_formed(k)
        out = []
        for i, m in enumerate(members):
            res = batching.demux_results(results, slot.sink_map,
                                         f"q{slot_of[i]}")
            st = dict(stats)
            st["batch"] = {"size": k, "slots": len(plans),
                           "slot": slot_of[i], "t0_unix_ns": t0_ns,
                           "wall_ns": wall_ns}
            st["serving"] = {
                "tenant": m.tenant,
                "queued_ms": (round(m.ticket.wait_ns / 1e6, 3)
                              if m.ticket is not None and m.ticket.queued
                              else 0.0),
                "cost": m.ticket.cost if m.ticket is not None else None,
                "degraded": stats.get("serving", {}).get("degraded", False),
            }
            for qr in res.values():
                qr.exec_stats["batch"] = st["batch"]
            out.append((res, st))
        return out

    def _run_distributed(
        self, q, entry, spec, topo_epoch, failover, analyze, tenant,
        ticket, plan_cache_hit, sink_map=None, extra_verify=None,
        compile_ns: int = 0, plan_text=None,
    ) -> tuple[dict[str, QueryResult], dict]:
        """Split (cached per topology epoch), dispatch to agents with the
        fault-tolerant machinery, fold/merge, run the merger plan, and
        assemble per-query stats — the shared back half of
        `_execute_script_inner` and the fused-batch leader path
        (`_execute_batch`, which passes the merged plan as `q` and the
        batch-signature slot as `entry` so warm batches ride the same
        split cache)."""
        import time as _time

        from pixie_tpu import metrics as _metrics
        from pixie_tpu.status import Internal, Unavailable

        def _split():
            with trace.span("plan_split"):
                dp = DistributedPlanner(spec).plan(q.plan)
                # pre-dispatch verification rides the split computation, so
                # a cached split IS a verified split: warm queries skip it
                # entirely (check/planverify.py, PX_PLAN_VERIFY)
                from pixie_tpu.check import planverify

                planverify.maybe_verify(dp, spec.combined_schemas(),
                                        self.udf_registry)
                if extra_verify is not None:
                    extra_verify(dp)
                # pre-serialize the per-agent plan dicts: the dispatch loop
                # splices these cached JSON fragments into each execute
                # frame instead of re-walking + re-dumping the plan per query
                extras = {"plan_json": {
                    a: _json.dumps(p.to_dict())
                    for a, p in dp.agent_plans.items()
                }}
                return dp, extras

        from pixie_tpu.engine.plancache import QueryPlanCache as _QPC

        #: flight-recorder phase anchors (observe.build_profile): one
        #: perf_counter read per phase boundary — cheap enough to measure
        #: unconditionally; the dict only ships when profiles are on
        t_split0 = _time.perf_counter_ns()
        (dp, split_extras), split_hit = _QPC.get_split(
            entry, ("split", topo_epoch), _split)
        split_ns = _time.perf_counter_ns() - t_split0

        reg = self.udf_registry
        if reg is None:
            from pixie_tpu.udf import registry as reg
        # Broker-side view matcher: which agent fragments have a standing-
        # query shape?  The agents decide (and do) the actual serving — this
        # is the control-plane ledger that makes hit/miss observable per
        # query (stats["matview"], px_broker_matview_* counters, and a
        # matview_hit span when the whole query answered from views).
        # Disabled subsystem = no ledger: otherwise every query would pay
        # the canonicalize+hash and count as a "miss" for a feature that
        # is off.
        import pixie_tpu.matview  # noqa: F401 — defines the PL_MATVIEW_* flags

        with self._qlock:
            self._req_counter += 1
            req_id = f"q{self._req_counter}"
            ctx = _QueryCtx(set(dp.channels))
            ctx.failover = failover
            ctx.needed_agents = set(dp.agent_plans)
            ctx.configure_folds(dp, reg)
            self._queries[req_id] = ctx
        # Degradation hints ride each execute frame: past the shed
        # watermark, matview hits serve standing state WITHOUT folding
        # their delta (stale-while-revalidate) and the agents' chunk ack
        # window narrows so producers throttle at the source.  Read at
        # dispatch time (not admit time) so a queue that drained while
        # this query waited dispatches at full quality.  Catch-up counts
        # as degradation too: while a dead shard is served by failover
        # replicas, views serve stale-while-revalidate and ack windows
        # narrow — quality sheds, not correctness, while the restarted
        # shard rehydrates.
        degraded = self.serving.enabled() and (self.serving.degraded()
                                               or self.serving.catching_up())
        base_meta = {
            "msg": "execute",
            "analyze": analyze,
            # tenant rides to the agents: matview state namespaces
            # per tenant under PL_TENANT_ISOLATION
            "tenant": tenant,
            # distributed fan-out: agents route CPU/TPU by the
            # query's total size, not their local shard's
            "route_scale": len(dp.agent_plans),
        }
        if degraded:
            base_meta["stale_ok"] = True
            dw = int(_flags.get("PL_SERVING_DEGRADED_WINDOW"))
            if dw > 0:
                base_meta["stream_window"] = dw
        #: per-query fault/recovery ledger → stats["fault"]
        fault = {"rounds": 0, "evictions": 0, "hedged": 0,
                 "chunks_discarded": 0, "redispatched": []}
        retries = int(_flags.get("PL_QUERY_RETRIES"))
        t_exec0 = _time.perf_counter_ns()
        try:
            for agent_name in dp.agent_plans:
                pj = (split_extras["plan_json"].get(agent_name)
                      or _json.dumps(dp.agent_plans[agent_name].to_dict()))
                try:
                    self._send_execute(ctx, req_id, agent_name, pj, base_meta)
                except Unavailable:
                    if retries <= 0 or q.mutations:
                        raise
                    # the retry loop below re-plans around (or waits out)
                    # the missing agent
                    with ctx.lock:
                        ctx.evictions.append((agent_name, "not connected"))
                        ctx.wake.set()
            if dp.agent_plans:
                dp, split_extras = self._await_agents(
                    ctx, req_id, entry, q, dp, split_extras, base_meta,
                    reg, fault, retries, extra_verify=extra_verify)
            if ctx.error:
                raise Unavailable(ctx.error)
            mv_keys = {}
            if _flags.get("PL_MATVIEW_ENABLED"):
                from pixie_tpu.matview.registry import plan_view_key

                mv_keys = {
                    name: k for name, plan in dp.agent_plans.items()
                    if (k := plan_view_key(plan, reg)) is not None
                }

            t_merge0 = _time.perf_counter_ns()
            with trace.span("merge"):
                from pixie_tpu.parallel.repartition import (
                    bucket_channels,
                    run_join_stages,
                    stage_output_inputs,
                )

                # chunk folds ran on the reader threads (no trace context
                # there): emit them as spans now, under this query's root —
                # their start times preceding last_terminal_ns is the direct
                # evidence that merge work overlapped agent compute
                for t0_ns, dur_ns, cid, agent in ctx.fold_events:
                    trace.event_span("incremental_fold", t0_ns, dur_ns,
                                     channel=cid, agent=agent)
                # only the ACCEPTED sources (first answer per agent) merge;
                # everything else — evicted agents' partial streams, losing
                # hedge attempts, late duplicates — is discarded here and
                # counted, never folded into the answer.  Losing/superseded
                # producers may STILL be streaming into ctx on their reader
                # threads, so every shared structure is read under its lock
                # (an unguarded dict iteration here would raise mid-merge
                # and fail a query that succeeded).
                with ctx.lock:
                    accepted_srcs = set(ctx.accepted.values())
                    buckets = {cid: {s: list(chunks)
                                     for s, chunks in by_src.items()}
                               for cid, by_src in ctx.bucket_payloads.items()}
                discarded = 0
                payloads: dict[str, list] = {cid: [] for cid in dp.channels}
                for cid, by_src in buckets.items():
                    for s, chunks in sorted(by_src.items()):
                        if s in accepted_srcs and cid in payloads:
                            payloads[cid].extend(chunks)
                        else:
                            discarded += len(chunks)
                if dp.join_stages:
                    # repartitioned joins run partition-parallel on the merger
                    # (the Kelvin role); bucket channels are consumed here, with
                    # the same payload-shape contract as rows channels
                    run_join_stages(dp, payloads, reg,
                                    store=self.merger_store, analyze=analyze)
                consumed = bucket_channels(dp)
                inputs: dict[str, HostBatch] = {}
                folded_total = 0
                for cid, ch in dp.channels.items():
                    if cid in consumed:
                        continue
                    fold = ctx.folds.get(cid)
                    flock = ctx.fold_locks.get(cid)
                    if fold is None or flock is None:
                        raise Internal(f"channel {cid} received no payloads")
                    # the channel's fold lock serializes against loser/
                    # superseded producers still folding on reader threads
                    with flock:
                        total = sum(fold.count_for(s)
                                    for s in accepted_srcs)
                        if total == 0:
                            raise Internal(
                                f"channel {cid} received no payloads")
                        # every chunk an accepted producer SENT must have
                        # folded: a dropped frame means a silently-partial
                        # answer, so fail instead
                        for s in sorted(accepted_srcs):
                            exp = ctx.expected_chunks.get((cid, s))
                            if exp is not None and fold.count_for(s) != exp:
                                raise Internal(
                                    f"channel {cid}: folded "
                                    f"{fold.count_for(s)} of "
                                    f"{exp} chunk frames")
                        folded_total += total
                        discarded += fold.discarded_chunks(accepted_srcs)
                        # the running per-src folds already combined every
                        # chunk on arrival; finish() pays one cross-source
                        # combine (deterministic sorted-source order) + the
                        # finalize
                        with trace.span("merge_finish", channel=cid,
                                        kind=ch.kind, chunks=total,
                                        incremental=True):
                            inputs[cid] = fold.finish(accepted_srcs)
                if discarded:
                    _metrics.counter_inc(
                        "px_chunks_discarded_total", float(discarded),
                        help_="producer chunks discarded at merge (evicted "
                              "agents' partial streams, losing hedge "
                              "attempts, late duplicates)")
                fault["chunks_discarded"] = discarded
                inputs.update(stage_output_inputs(dp, payloads))

                from pixie_tpu.udf.udtf import UDTFContext

                ex = PlanExecutor(
                    dp.merger_plan, self.merger_store, self.udf_registry,
                    inputs=inputs, analyze=analyze,
                    udtf_ctx=UDTFContext(
                        table_store=self.merger_store, registry=reg,
                        agent_registry=self.registry,
                        tracepoint_manager=self.tracepoints,
                    ),
                )
                results = ex.run()
                # The merger plan's sources are channels (no STs); the LOGICAL
                # plan + agent schemas determine them.
                from pixie_tpu.engine.semantics import SchemaStore, restamp_result

                sstore = SchemaStore(self.registry.combined_schemas())
                for r in results.values():
                    restamp_result(r, q.plan, sstore, reg)
                stats = {"agents": ctx.agent_stats, "merger": dict(ex.stats)}
                #: fast-path observability: did this query skip compile /
                #: split work?  (PL_QUERY_FASTPATH off ⇒ both always False)
                stats["fastpath"] = {"plan_cache_hit": plan_cache_hit,
                                     "split_cache_hit": split_hit}
                #: serving-front observability per query: its tenant, the
                #: queue wait it paid, and whether it dispatched degraded
                #: (stale matview serving + narrowed ack window)
                stats["serving"] = {
                    "tenant": tenant,
                    "queued_ms": (round(ticket.wait_ns / 1e6, 3)
                                  if ticket is not None and ticket.queued
                                  else 0.0),
                    "cost": ticket.cost if ticket is not None else None,
                    "degraded": degraded,
                }
                if mv_keys:
                    served = {
                        a: s["matview"] for a, s in ctx.agent_stats.items()
                        if isinstance(s, dict) and s.get("matview")
                    }
                    hits = sum(1 for i in served.values() if i.get("hit"))
                    stats["matview"] = {
                        "eligible_agents": len(mv_keys),
                        "agents_hit": hits,
                        "rows_folded": sum(
                            int(i.get("rows_folded", 0))
                            for i in served.values()),
                        "keys": sorted(set(mv_keys.values())),
                    }
                    if hits and hits == len(dp.agent_plans):
                        # the ENTIRE scan side answered from standing state:
                        # this query's cost was delta folds + one finalize
                        _metrics.counter_inc(
                            "px_broker_matview_hit_queries_total",
                            help_="queries fully answered from standing "
                                  "view state on every agent")
                        trace.event_span(
                            "matview_hit", _time.time_ns(), 0,
                            agents=hits,
                            rows_folded=stats["matview"]["rows_folded"])
                    else:
                        _metrics.counter_inc(
                            "px_broker_matview_miss_queries_total",
                            help_="view-eligible queries that rescanned on "
                                  "at least one agent")
                #: streaming-merge observability: merge_overlapped=True means
                #: the first chunk folded BEFORE the last agent's terminal
                #: frame — merge cost hid under the slowest agent's compute
                stats["stream"] = {
                    "chunks_folded": folded_total,
                    "first_fold_unix_ns": ctx.first_fold_ns,
                    "last_terminal_unix_ns": ctx.last_terminal_ns,
                    "merge_overlapped": bool(
                        ctx.first_fold_ns is not None
                        and ctx.last_terminal_ns is not None
                        and ctx.first_fold_ns < ctx.last_terminal_ns),
                }
                #: fault-recovery observability per query: re-dispatch
                #: rounds paid, agents evicted mid-query, hedged duplicate
                #: dispatches, and chunks discarded at merge — all zero on
                #: the fault-free path.  Row-completeness accounting:
                #: which primaries answered via a failover replica, and the
                #: rows each accepted source actually scanned (0 for
                #: standing-view serves) — the audit trail for "did this
                #: answer cover every shard".
                with ctx.lock:
                    fault["failover"] = dict(ctx.failover_used)
                fault["rows_scanned"] = {
                    a: int(s.get("rows_scanned", 0))
                    for a, s in ctx.agent_stats.items()
                    if isinstance(s, dict)
                }
                stats["fault"] = fault
                if sink_map is not None:
                    stats["sink_map"] = sink_map
                    stats["merger"]["operators"] = ex.op_stats
                for r in results.values():
                    r.exec_stats["agents"] = ctx.agent_stats
            if trace.enabled() or plan_text is not None:
                # where the time went, measured at the phase seams the
                # spans already mark — observe.build_profile sums these
                # into the per-query attribution row
                stats["phases"] = {
                    "compile_ns": int(compile_ns),
                    "plan_split_ns": int(split_ns),
                    "exec_ns": int(t_merge0 - t_exec0),
                    "merge_ns": int(_time.perf_counter_ns() - t_merge0),
                }
                if plan_text is not None:
                    stats["plan_explain"] = plan_text
            return results, stats
        finally:
            # span hygiene: a timeout / disconnect / error leaves dispatch
            # spans without an exec_done to close them
            for src in list(ctx.dispatch_spans):
                self._finish_dispatch_span(ctx, src,
                                           error=ctx.error or "unresolved")
            with self._qlock:
                self._queries.pop(req_id, None)


def _jsonable(obj):
    import numpy as np

    if isinstance(obj, dict):
        return {k: _jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_jsonable(v) for v in obj]
    if isinstance(obj, np.generic):
        return obj.item()
    if isinstance(obj, np.ndarray):
        return obj.tolist()
    return obj

"""Leader election over the control KV store.

Reference: src/shared/services/election/ — Go services elect a leader via a
k8s lease so exactly one broker instance serves mutations at a time.  Here
the lease lives in the shared control KVStore (sqlite): a compare-and-swap
on a single key with a TTL, renewed by the holder, stealable after expiry.
The KVStore's process-level lock serializes the read-modify-write (sqlite
single-writer semantics cover the cross-process case when the KV is a
shared file).

Usage (broker failover):
    elector = LeaderElector(kv, "broker", instance_id="broker-1").start()
    ... if elector.is_leader(): serve mutations ...
    elector.stop()   # resigns, letting a standby take over immediately
"""
from __future__ import annotations

import threading
import time
from typing import Optional

_KEY = "election/%s"

#: one lock per (kv identity, key): serializes the lease read-modify-write
#: among in-process electors of the SAME election without coupling
#: unrelated elections (or blocking is_leader() behind another elector's
#: sqlite I/O — the kv.cas itself is the cross-process guard)
_CAS_LOCKS: dict = {}
_CAS_LOCKS_GUARD = threading.Lock()


def _cas_lock(kv, key: str) -> threading.Lock:
    k = (id(kv), key)
    with _CAS_LOCKS_GUARD:
        return _CAS_LOCKS.setdefault(k, threading.Lock())


class LeaderElector:
    def __init__(self, kv, name: str, instance_id: str,
                 ttl_s: float = 5.0, renew_s: Optional[float] = None):
        self.kv = kv
        self.key = _KEY % name
        self.instance_id = instance_id
        self.ttl_s = float(ttl_s)
        self.renew_s = renew_s if renew_s is not None else self.ttl_s / 3
        self._leader = False
        self._lock = _cas_lock(kv, self.key)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------------ lease
    def try_acquire(self, now: Optional[float] = None) -> bool:
        """One CAS round: take the lease if free/expired/ours; else False.
        The read-modify-write runs as an atomic kv.cas (single sqlite
        transaction), so two processes racing for an expired lease cannot
        both win."""
        import json

        now = time.time() if now is None else now
        with self._lock:
            raw = self.kv.get(self.key)
            cur = None if raw is None else json.loads(raw.decode())
            if (cur is None or cur.get("expires", 0) <= now
                    or cur.get("holder") == self.instance_id):
                new = json.dumps({
                    "holder": self.instance_id,
                    "expires": now + self.ttl_s,
                }).encode()
                # CAS against the exact bytes we read; a concurrent winner
                # changes them and our take fails cleanly
                self._leader = self.kv.cas(self.key, raw, new)
            else:
                self._leader = False
            return self._leader

    def resign(self) -> None:
        import json

        with self._lock:
            raw = self.kv.get(self.key)
            cur = None if raw is None else json.loads(raw.decode())
            if cur is not None and cur.get("holder") == self.instance_id:
                # CAS to an expired lease rather than delete: if someone
                # stole the lease between read and write, the CAS fails and
                # we don't clobber THEIR lease
                self.kv.cas(self.key, raw, json.dumps(
                    {"holder": None, "expires": 0}).encode())
            self._leader = False

    def is_leader(self) -> bool:
        # plain bool read (atomic in CPython): must not block behind a
        # CAS in flight — the health/readiness probes and the per-query
        # leadership gate call this on hot paths
        return self._leader

    def leader(self) -> Optional[str]:
        """Current holder name (None when the lease is free/expired)."""
        cur = self.kv.get_json(self.key)
        if cur is None or cur.get("expires", 0) <= time.time():
            return None
        return cur.get("holder")

    # -------------------------------------------------------------- lifecycle
    def start(self) -> "LeaderElector":
        self.try_acquire()
        self._thread = threading.Thread(
            target=self._renew_loop, daemon=True,
            name=f"pixie-election-{self.instance_id}")
        self._thread.start()
        return self

    def _renew_loop(self):
        while not self._stop.wait(timeout=self.renew_s):
            try:
                self.try_acquire()
            except Exception:
                # a failed renewal (kv locked/closed/disk error) must DEMOTE,
                # not freeze a stale _leader=True while the thread dies
                with self._lock:
                    self._leader = False

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
        self.resign()

"""Service layer: broker, agents, registry, durable control store, wire format.

The networked counterpart of parallel.cluster.LocalCluster (reference
src/vizier/services/): query_broker (server.go:307 ExecuteScript), metadata
agent registry (agent.go:81-150), NATS/gRPC transports.  Control AND data ride
one framed-TCP transport here; the data plane payloads use a versioned binary
wire format (no pickle — untrusted bytes never reach an unpickler).
"""
from pixie_tpu.services.wire import (
    decode_frame,
    encode_host_batch,
    encode_json,
    encode_partial_agg,
)

__all__ = [
    "decode_frame",
    "encode_host_batch",
    "encode_json",
    "encode_partial_agg",
]

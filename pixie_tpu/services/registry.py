"""Agent registry with heartbeat expiry, persisted in the control KV store.

Reference: the metadata service's agent manager — register/heartbeat agents,
expire them when heartbeats stop, drop their schemas from planning
(src/vizier/services/metadata/controllers/agent/agent.go:81-150,221-470).
"""
from __future__ import annotations

import dataclasses
import threading
import time
from typing import Optional

from pixie_tpu.parallel.topology import AgentInfo, ClusterSpec
from pixie_tpu.services.kvstore import KVStore
from pixie_tpu.types import Relation


@dataclasses.dataclass
class AgentRecord:
    name: str
    asid: int
    schemas: dict  # table -> Relation
    n_devices: Optional[int]
    last_heartbeat: float
    alive: bool = True
    #: incarnation fence: bumped on EVERY register.  A restarted agent
    #: re-registering under the same name supersedes its old socket; frames
    #: still in flight from the dead incarnation (chunks, acks, heartbeats)
    #: carry — via their connection's recorded incarnation — a stale value
    #: and are rejected instead of folded (reference: ASIDs are never
    #: reused, agent.go expired agents handshake anew).
    incarnation: int = 0
    #: monotonic time the agent was last observed dying (disconnect or
    #: heartbeat expiry); 0 = never died (or recalled-from-KV cold record).
    #: The broker's rejoin grace window measures from this: a JUST-dead
    #: agent is likely a restarting pod, not a removed one.
    died_at: float = 0.0
    #: (host, port) of the agent's replication peer server (None when the
    #: agent runs without PL_REPLICATION); persisted so a rehydrating peer
    #: can find its replicas across broker restarts
    repl_addr: Optional[tuple] = None


class AgentRegistry:
    """Live agent set + durable record (the registry survives broker restarts;
    liveness does not — agents must re-register/heartbeat)."""

    def __init__(self, kv: Optional[KVStore] = None, expiry_s: float = 15.0):
        self.kv = kv or KVStore()
        self.expiry_s = expiry_s
        self._agents: dict[str, AgentRecord] = {}
        self._next_asid = 1
        self._lock = threading.Lock()
        #: topology/schema epoch: bumped on every liveness or schema change
        #: ((re-)register, death, expiry).  The broker's plan cache keys its
        #: compiled queries and distributed splits on this, so a changed
        #: cluster view can never serve a stale plan.
        self.epoch = 0
        #: re-homing overrides (broker rehome staging): primary → extra
        #: replica names merged into every recomputed shard map.  Persisted
        #: per primary under rehome/<name> so a broker restart mid-move
        #: keeps the staged target receiving the donor's batches — the
        #: two-phase flip's durable half.
        self._extra_replicas: dict[str, list] = {}
        for key, raw in self.kv.scan("rehome/"):
            import json

            try:
                self._extra_replicas[key.split("/", 1)[1]] = list(
                    json.loads(raw.decode()))
            except Exception:
                continue
        # Recall durable records (dead until they heartbeat again).
        for key, raw in self.kv.scan("agent/"):
            import json

            d = json.loads(raw.decode())
            rec = AgentRecord(
                name=d["name"],
                asid=d["asid"],
                schemas={t: Relation.from_dict(r) for t, r in d["schemas"].items()},
                n_devices=d.get("n_devices"),
                last_heartbeat=0.0,
                alive=False,
                repl_addr=(tuple(d["repl_addr"])
                           if d.get("repl_addr") else None),
            )
            self._agents[rec.name] = rec
            self._next_asid = max(self._next_asid, rec.asid + 1)

    # ---------------------------------------------------------------- mutation
    def register(self, name: str, schemas: dict, n_devices: Optional[int] = None,
                 repl_addr: Optional[tuple] = None) -> int:
        """(Re-)register an agent; returns its ASID."""
        now = time.monotonic()
        with self._lock:
            self.epoch += 1
            rec = self._agents.get(name)
            if rec is None:
                rec = AgentRecord(name, self._next_asid, schemas, n_devices, now)
                self._next_asid += 1
                self._agents[name] = rec
            else:
                rec.schemas = schemas
                rec.n_devices = n_devices
                rec.last_heartbeat = now
                rec.alive = True
            rec.incarnation += 1
            rec.repl_addr = tuple(repl_addr) if repl_addr else None
            self.kv.set_json(
                f"agent/{name}",
                {
                    "name": name,
                    "asid": rec.asid,
                    "schemas": {t: r.to_dict() for t, r in schemas.items()},
                    "n_devices": n_devices,
                    "repl_addr": list(rec.repl_addr) if rec.repl_addr else None,
                },
            )
            self._update_shard_map_locked()
            return rec.asid

    def heartbeat(self, name: str) -> bool:
        with self._lock:
            rec = self._agents.get(name)
            if rec is None or not rec.alive:
                # Unknown OR already expired: a heartbeat cannot revive a dead
                # agent — it must re-register (reference agent.go: expired
                # agents are deleted and handshake anew).  This also closes
                # the expire/heartbeat race: once dead, stays dead until
                # register().
                return False
            rec.last_heartbeat = time.monotonic()
            return True

    def mark_dead(self, name: str) -> None:
        with self._lock:
            rec = self._agents.get(name)
            if rec is not None:
                was_alive = rec.alive
                rec.alive = False
                if was_alive:
                    self.epoch += 1
                    rec.died_at = time.monotonic()
                    self._update_shard_map_locked()

    def expire(self) -> list[str]:
        """Mark agents whose heartbeats lapsed as dead; returns newly-dead."""
        now = time.monotonic()
        out = []
        with self._lock:
            for rec in self._agents.values():
                if rec.alive and now - rec.last_heartbeat > self.expiry_s:
                    rec.alive = False
                    rec.died_at = now
                    out.append(rec.name)
            if out:
                self.epoch += 1
                self._update_shard_map_locked()
        return out

    # --------------------------------------------------------------- shard map
    def _update_shard_map_locked(self) -> None:
        """Recompute + persist the primary→replicas shard map on every
        liveness change (join/evict).  Replicas are the next
        PL_REPLICATION-1 LIVE agents after the primary in sorted ring
        order; dead primaries KEEP an entry (their replicas are exactly
        what failover and rehydration need to find).  No-op with
        replication disabled — no KV writes, bit-identical legacy paths."""
        from pixie_tpu import flags as _flags

        try:
            k = int(_flags.get("PL_REPLICATION"))
        except Exception:  # services.replication not imported in this process
            return
        if k <= 1:
            return
        live = sorted(r.name for r in self._agents.values() if r.alive)
        live_set = set(live)
        out: dict[str, list] = {}
        import bisect

        for name in sorted(self._agents):
            ring = [a for a in live if a != name]
            reps: list = []
            if ring:
                pos = bisect.bisect_left(ring, name)
                reps = [ring[(pos + i) % len(ring)]
                        for i in range(min(k - 1, len(ring)))]
            # re-homing overrides ride ON TOP of the ring choice: the staged
            # target replicates the donor's shard regardless of ring position,
            # so the existing backfill machinery ships the data.  Prepended
            # (not appended) because failover serves from the FIRST live
            # replica: once the donor retires, the shard's queries must land
            # on the move target — landing on a ring peer instead would pile
            # the moved load onto an already-loaded node and re-trip the
            # rebalance trigger
            extras = [e for e in self._extra_replicas.get(name, ())
                      if e != name and e in live_set]
            out[name] = extras + [r for r in reps if r not in extras]
        self.kv.set_json("shardmap/current", {"k": k, "map": out})

    def shard_map(self) -> dict:
        """The persisted primary→replicas map ({} when replication is off)."""
        return (self.kv.get_json("shardmap/current") or {}).get("map", {})

    def add_replica(self, primary: str, replica: str) -> None:
        """Stage `replica` as an extra shard-map replica of `primary`
        (re-homing: the target starts receiving the donor's batches over
        the normal replication channel).  Durable across broker restarts;
        undone by remove_replica."""
        with self._lock:
            cur = self._extra_replicas.setdefault(primary, [])
            if replica not in cur:
                cur.append(replica)
            self.kv.set_json(f"rehome/{primary}", cur)
            self.epoch += 1
            self._update_shard_map_locked()

    def remove_replica(self, primary: str, replica: str) -> None:
        """Unstage a re-homing replica (move aborted or superseded)."""
        with self._lock:
            cur = self._extra_replicas.get(primary)
            if not cur or replica not in cur:
                return
            cur.remove(replica)
            if cur:
                self.kv.set_json(f"rehome/{primary}", cur)
            else:
                self._extra_replicas.pop(primary, None)
                self.kv.delete(f"rehome/{primary}")
            self.epoch += 1
            self._update_shard_map_locked()

    def extra_replicas(self, primary: str) -> list:
        with self._lock:
            return list(self._extra_replicas.get(primary, ()))

    def peer_addrs(self) -> dict[str, list]:
        """Replication peer addresses of LIVE agents (dead peers are not
        dialable; a rehydrating agent re-registers with a fresh port)."""
        with self._lock:
            return {r.name: list(r.repl_addr)
                    for r in self._agents.values()
                    if r.alive and r.repl_addr}

    def record(self, name: str) -> Optional[AgentRecord]:
        with self._lock:
            return self._agents.get(name)

    def deregister(self, name: str) -> bool:
        """Permanently remove an agent (operator decommission).  Without
        this a retired node's durable record keeps it in the shard map as
        a failover primary forever — every plan carries its virtual shard
        and the serving front never leaves catch-up.  Returns whether the
        record existed."""
        with self._lock:
            rec = self._agents.pop(name, None)
            if rec is None:
                return False
            self.epoch += 1
            self.kv.delete(f"agent/{name}")
            if self._extra_replicas.pop(name, None) is not None:
                self.kv.delete(f"rehome/{name}")
            self._update_shard_map_locked()
            return True

    # ------------------------------------------------------------------- views
    def incarnation(self, name: str) -> int:
        """Current incarnation of `name` (0 = never registered).  Frames
        from a connection recorded under an older incarnation are stale."""
        with self._lock:
            rec = self._agents.get(name)
            return rec.incarnation if rec is not None else 0

    def recently_dead(self, grace_s: float) -> list[str]:
        """Agents observed dying within the last `grace_s` seconds — the
        set the broker's dispatch holds for (a restarting pod re-registers
        within the grace; a removed one ages out of it)."""
        now = time.monotonic()
        with self._lock:
            return sorted(
                rec.name for rec in self._agents.values()
                if not rec.alive and rec.died_at > 0
                and now - rec.died_at < grace_s)

    def all_agents(self) -> list[AgentRecord]:
        """Every known agent, dead or alive (GetAgentStatus shows both)."""
        self.expire()
        with self._lock:
            return list(self._agents.values())

    def live_agents(self) -> list[AgentRecord]:
        self.expire()
        with self._lock:
            return [r for r in self._agents.values() if r.alive]

    def cluster_spec(self, merger_name: str = "broker") -> ClusterSpec:
        """Planner topology over LIVE agents only (dead agents are planned
        around — reference: expired agents drop out of DistributedState)."""
        agents = [
            AgentInfo(
                name=r.name,
                has_data_store=True,
                processes_data=True,
                accepts_remote_sources=False,
                schemas=r.schemas,
                n_devices=r.n_devices,
            )
            for r in self.live_agents()
        ]
        agents.append(
            AgentInfo(
                name=merger_name,
                has_data_store=False,
                processes_data=False,
                accepts_remote_sources=True,
                schemas={},
            )
        )
        return ClusterSpec(agents)

    def combined_schemas(self) -> dict[str, Relation]:
        out: dict[str, Relation] = {}
        for r in self.live_agents():
            for t, rel in r.schemas.items():
                out.setdefault(t, rel)
        return out

"""Agent registry with heartbeat expiry, persisted in the control KV store.

Reference: the metadata service's agent manager — register/heartbeat agents,
expire them when heartbeats stop, drop their schemas from planning
(src/vizier/services/metadata/controllers/agent/agent.go:81-150,221-470).
"""
from __future__ import annotations

import dataclasses
import threading
import time
from typing import Optional

from pixie_tpu.parallel.topology import AgentInfo, ClusterSpec
from pixie_tpu.services.kvstore import KVStore
from pixie_tpu.types import Relation


@dataclasses.dataclass
class AgentRecord:
    name: str
    asid: int
    schemas: dict  # table -> Relation
    n_devices: Optional[int]
    last_heartbeat: float
    alive: bool = True
    #: incarnation fence: bumped on EVERY register.  A restarted agent
    #: re-registering under the same name supersedes its old socket; frames
    #: still in flight from the dead incarnation (chunks, acks, heartbeats)
    #: carry — via their connection's recorded incarnation — a stale value
    #: and are rejected instead of folded (reference: ASIDs are never
    #: reused, agent.go expired agents handshake anew).
    incarnation: int = 0
    #: monotonic time the agent was last observed dying (disconnect or
    #: heartbeat expiry); 0 = never died (or recalled-from-KV cold record).
    #: The broker's rejoin grace window measures from this: a JUST-dead
    #: agent is likely a restarting pod, not a removed one.
    died_at: float = 0.0


class AgentRegistry:
    """Live agent set + durable record (the registry survives broker restarts;
    liveness does not — agents must re-register/heartbeat)."""

    def __init__(self, kv: Optional[KVStore] = None, expiry_s: float = 15.0):
        self.kv = kv or KVStore()
        self.expiry_s = expiry_s
        self._agents: dict[str, AgentRecord] = {}
        self._next_asid = 1
        self._lock = threading.Lock()
        #: topology/schema epoch: bumped on every liveness or schema change
        #: ((re-)register, death, expiry).  The broker's plan cache keys its
        #: compiled queries and distributed splits on this, so a changed
        #: cluster view can never serve a stale plan.
        self.epoch = 0
        # Recall durable records (dead until they heartbeat again).
        for key, raw in self.kv.scan("agent/"):
            import json

            d = json.loads(raw.decode())
            rec = AgentRecord(
                name=d["name"],
                asid=d["asid"],
                schemas={t: Relation.from_dict(r) for t, r in d["schemas"].items()},
                n_devices=d.get("n_devices"),
                last_heartbeat=0.0,
                alive=False,
            )
            self._agents[rec.name] = rec
            self._next_asid = max(self._next_asid, rec.asid + 1)

    # ---------------------------------------------------------------- mutation
    def register(self, name: str, schemas: dict, n_devices: Optional[int] = None) -> int:
        """(Re-)register an agent; returns its ASID."""
        now = time.monotonic()
        with self._lock:
            self.epoch += 1
            rec = self._agents.get(name)
            if rec is None:
                rec = AgentRecord(name, self._next_asid, schemas, n_devices, now)
                self._next_asid += 1
                self._agents[name] = rec
            else:
                rec.schemas = schemas
                rec.n_devices = n_devices
                rec.last_heartbeat = now
                rec.alive = True
            rec.incarnation += 1
            self.kv.set_json(
                f"agent/{name}",
                {
                    "name": name,
                    "asid": rec.asid,
                    "schemas": {t: r.to_dict() for t, r in schemas.items()},
                    "n_devices": n_devices,
                },
            )
            return rec.asid

    def heartbeat(self, name: str) -> bool:
        with self._lock:
            rec = self._agents.get(name)
            if rec is None or not rec.alive:
                # Unknown OR already expired: a heartbeat cannot revive a dead
                # agent — it must re-register (reference agent.go: expired
                # agents are deleted and handshake anew).  This also closes
                # the expire/heartbeat race: once dead, stays dead until
                # register().
                return False
            rec.last_heartbeat = time.monotonic()
            return True

    def mark_dead(self, name: str) -> None:
        with self._lock:
            rec = self._agents.get(name)
            if rec is not None:
                if rec.alive:
                    self.epoch += 1
                    rec.died_at = time.monotonic()
                rec.alive = False

    def expire(self) -> list[str]:
        """Mark agents whose heartbeats lapsed as dead; returns newly-dead."""
        now = time.monotonic()
        out = []
        with self._lock:
            for rec in self._agents.values():
                if rec.alive and now - rec.last_heartbeat > self.expiry_s:
                    rec.alive = False
                    rec.died_at = now
                    out.append(rec.name)
            if out:
                self.epoch += 1
        return out

    # ------------------------------------------------------------------- views
    def incarnation(self, name: str) -> int:
        """Current incarnation of `name` (0 = never registered).  Frames
        from a connection recorded under an older incarnation are stale."""
        with self._lock:
            rec = self._agents.get(name)
            return rec.incarnation if rec is not None else 0

    def recently_dead(self, grace_s: float) -> list[str]:
        """Agents observed dying within the last `grace_s` seconds — the
        set the broker's dispatch holds for (a restarting pod re-registers
        within the grace; a removed one ages out of it)."""
        now = time.monotonic()
        with self._lock:
            return sorted(
                rec.name for rec in self._agents.values()
                if not rec.alive and rec.died_at > 0
                and now - rec.died_at < grace_s)

    def all_agents(self) -> list[AgentRecord]:
        """Every known agent, dead or alive (GetAgentStatus shows both)."""
        self.expire()
        with self._lock:
            return list(self._agents.values())

    def live_agents(self) -> list[AgentRecord]:
        self.expire()
        with self._lock:
            return [r for r in self._agents.values() if r.alive]

    def cluster_spec(self, merger_name: str = "broker") -> ClusterSpec:
        """Planner topology over LIVE agents only (dead agents are planned
        around — reference: expired agents drop out of DistributedState)."""
        agents = [
            AgentInfo(
                name=r.name,
                has_data_store=True,
                processes_data=True,
                accepts_remote_sources=False,
                schemas=r.schemas,
                n_devices=r.n_devices,
            )
            for r in self.live_agents()
        ]
        agents.append(
            AgentInfo(
                name=merger_name,
                has_data_store=False,
                processes_data=False,
                accepts_remote_sources=True,
                schemas={},
            )
        )
        return ClusterSpec(agents)

    def combined_schemas(self) -> dict[str, Relation]:
        out: dict[str, Relation] = {}
        for r in self.live_agents():
            for t, rel in r.schemas.items():
                out.setdefault(t, rel)
        return out

"""Deterministic seeded fault injection for the framed-TCP transport.

The reference assumes constant agent churn (k8s nodes die mid-query; PEMs
heartbeat every 5s and the broker runs producer watchdogs).  Reproducing
those failures by actually killing processes makes tests timing-dependent;
this layer instead injects faults AT THE TRANSPORT SEAM, keyed to frame
COUNTS on labeled connections — the same failure surface (a socket that
dies mid-chunk-stream, a dropped ack, a slow producer) but deterministic:
the Nth frame on a connection is the Nth frame on every run.

Plan grammar (`PL_FAULT_PLAN`, rules separated by `;`):

    seed=42                              # jitter RNG seed (default 0)
    crash:agent:pem2@send=5              # close the conn hard before its
                                         #   5th outbound frame
    kill:agent:pem2@send=5               # TRUE pod loss: fire the label's
                                         #   registered kill handler (the
                                         #   agent DROPS its in-memory
                                         #   store) then RST the socket —
                                         #   recovery must come from the
                                         #   journal + replica peers, never
                                         #   from preserved process state
    reset:agent:pem2@recv=3              # RST (SO_LINGER 0) before the 3rd
                                         #   inbound frame is delivered
    drop:agent:pem1@send=2               # swallow one frame silently
    delay:agent:pem1@send=4:ms=250       # sleep before one frame
    slow:agent:*:ms=20:jitter=10         # every outbound frame on matching
                                         #   conns sleeps ms ± U(0,jitter)

Rule shape: `action:LABEL[@send=N|@recv=N|@frame=N][:k=v...]` — LABEL is an
fnmatch pattern over `Connection.label` (agents label their broker dial
`agent:<name>`, clients `client`; unlabeled conns keep their peer-addr
name).  `frame=` is an alias for `send=`.  Frame indices are 1-based and
count per (connection, direction); each frame-indexed rule fires ONCE
globally — it is an event ("crash agent X at frame N"), and a restarted
agent's fresh connection (same label, fresh counter) must not re-crash at
frame N forever.  To kill several connections, write several rules.

Determinism contract (tested): given the same plan string and the same
frame sequence per labeled connection, the injector makes the same
decisions — the slow-rule jitter stream is seeded per (seed, rule, label),
never from wall clock or a shared global RNG.
"""
from __future__ import annotations

import dataclasses
import fnmatch
import random
import threading
import zlib
from typing import Optional

from pixie_tpu import flags
from pixie_tpu.status import InvalidArgument

flags.define_str(
    "PL_FAULT_PLAN", "",
    "deterministic transport fault plan (services/faultinject.py grammar: "
    "crash/reset/drop/delay at frame N, slow with seeded jitter); empty "
    "disables injection entirely")

ACTIONS = ("crash", "reset", "drop", "delay", "slow", "kill")


@dataclasses.dataclass
class Rule:
    action: str  # crash | reset | drop | delay | slow
    label: str  # fnmatch pattern over Connection.label
    direction: str  # "send" | "recv"
    frame: Optional[int]  # 1-based; None = every frame (slow)
    ms: float = 0.0
    jitter_ms: float = 0.0


@dataclasses.dataclass
class Decision:
    """What the transport must do with one frame."""

    action: str  # "crash" | "reset" | "drop" | "delay"
    delay_s: float = 0.0


def parse_plan(spec: str) -> tuple[int, list[Rule]]:
    """`PL_FAULT_PLAN` string → (seed, rules).  Raises InvalidArgument on a
    malformed rule — a typo'd chaos plan must fail the run loudly, not
    silently inject nothing."""
    seed = 0
    rules: list[Rule] = []
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        if part.startswith("seed="):
            seed = int(part[5:])
            continue
        action, _, rest = part.partition(":")
        if action not in ACTIONS or not rest:
            raise InvalidArgument(f"fault plan: bad rule {part!r}")
        # trailing :k=v options; the label may itself contain ':'
        segs = rest.split(":")
        opts: dict[str, str] = {}
        while len(segs) > 1 and "=" in segs[-1]:
            k, _, v = segs[-1].partition("=")
            if not k.isidentifier():
                break
            opts[k] = v
            segs.pop()
        label = ":".join(segs)
        direction, frame = "send", None
        if "@" in label:
            label, _, at = label.partition("@")
            d, _, n = at.partition("=")
            if d == "frame":
                d = "send"
            if d not in ("send", "recv") or not n:
                raise InvalidArgument(f"fault plan: bad frame spec {at!r}")
            direction, frame = d, int(n)
        if action == "slow" and frame is not None:
            raise InvalidArgument("fault plan: slow rules apply to every "
                                  "frame (use delay for one frame)")
        if action in ("crash", "reset", "drop", "kill") and frame is None:
            raise InvalidArgument(f"fault plan: {action} needs @send=N/@recv=N")
        if action == "delay" and frame is None:
            raise InvalidArgument("fault plan: delay needs @send=N/@recv=N")
        rules.append(Rule(
            action=action, label=label, direction=direction, frame=frame,
            ms=float(opts.get("ms", 0.0)),
            jitter_ms=float(opts.get("jitter", 0.0)),
        ))
    return seed, rules


class FaultInjector:
    """Evaluates a parsed plan against per-(connection, direction) frame
    counters.  One injector is installed process-wide (`install`); the
    transport consults it per frame only when one is active."""

    def __init__(self, spec: str):
        self.spec = spec
        self.seed, self.rules = parse_plan(spec)
        self._lock = threading.Lock()
        #: (conn id, direction) -> frames seen (labels are not unique —
        #: several conns may share one, each with its own frame sequence)
        self._counts: dict[tuple, int] = {}
        #: rule idx -> fired.  Frame-indexed rules are one-shot EVENTS
        #: ("crash agent X at frame N" happens once): without this, a
        #: restarted agent's fresh connection — same label, fresh frame
        #: counter — would re-crash at frame N forever, turning one
        #: injected kill into a permanent outage
        self._fired: set[int] = set()
        #: (rule idx, label) -> Random for slow-jitter (seeded, not global)
        self._rngs: dict[tuple, random.Random] = {}
        #: decision log for determinism assertions:
        #: (label, direction, frame_idx, action)
        self.log: list[tuple] = []

    def _jitter(self, idx: int, rule: Rule, label: str) -> float:
        if rule.jitter_ms <= 0:
            return 0.0
        key = (idx, label)
        rng = self._rngs.get(key)
        if rng is None:
            # stable across processes: no PYTHONHASHSEED dependence
            rng = self._rngs[key] = random.Random(
                self.seed ^ zlib.crc32(f"{idx}|{label}".encode()))
        return rng.uniform(0, rule.jitter_ms)

    def on_frame(self, conn_id: int, label: str,
                 direction: str) -> Optional[Decision]:
        """Called by the transport before sending / delivering one frame.
        Returns the decision to apply, or None to proceed untouched."""
        with self._lock:
            key = (conn_id, direction)
            idx = self._counts.get(key, 0) + 1
            self._counts[key] = idx
            for i, r in enumerate(self.rules):
                if r.direction != direction or not fnmatch.fnmatchcase(
                        label, r.label):
                    continue
                if r.frame is None:  # slow: every frame pays the latency
                    delay = (r.ms + self._jitter(i, r, label)) / 1e3
                    self.log.append((label, direction, idx, "slow"))
                    return Decision("delay", delay_s=delay)
                if r.frame != idx or i in self._fired:
                    continue
                self._fired.add(i)
                self.log.append((label, direction, idx, r.action))
                if r.action == "delay":
                    return Decision(
                        "delay",
                        delay_s=(r.ms + self._jitter(i, r, label)) / 1e3)
                return Decision(r.action)
        return None


#: the process-wide injector; None (the overwhelmingly common case) keeps
#: the transport's per-frame cost to one attribute load
_active: Optional[FaultInjector] = None
_install_lock = threading.Lock()

#: label → pod-kill handler (agents register their broker-link label).
#: A `kill:` decision fires the handler BEFORE the RST so the store is
#: gone by the time the broker sees the eviction — exactly a pod death's
#: ordering.  Exact-label match: the handler registry is a service-side
#: contract, not a chaos-plan pattern (plans still match by fnmatch).
_kill_handlers: dict[str, object] = {}
_kill_lock = threading.Lock()


def register_kill_handler(label: str, fn) -> None:
    with _kill_lock:
        _kill_handlers[label] = fn


def unregister_kill_handler(label: str, fn=None) -> None:
    """Remove the label's handler.  Pass `fn` to remove ONLY if that exact
    handler is still registered — a stopped old Agent instance must not pop
    the handler its restarted successor registered under the same label."""
    with _kill_lock:
        if fn is None or _kill_handlers.get(label) == fn:
            _kill_handlers.pop(label, None)


def fire_kill(label: str) -> bool:
    """Invoke the kill handler for `label` (transport calls this on a
    `kill` decision).  Returns whether a handler ran; handler errors are
    swallowed — the connection dies regardless, as in a real pod loss."""
    with _kill_lock:
        fn = _kill_handlers.get(label)
    if fn is None:
        return False
    try:
        fn()
    except Exception:
        pass
    return True


def install(spec: Optional[str] = None) -> Optional[FaultInjector]:
    """Arm injection from `spec` (default: the PL_FAULT_PLAN flag).  An
    empty spec disarms.  Returns the active injector (or None)."""
    global _active
    if spec is None:
        spec = str(flags.get("PL_FAULT_PLAN"))
    with _install_lock:
        _active = FaultInjector(spec) if spec.strip() else None
        return _active


def uninstall() -> None:
    global _active
    with _install_lock:
        _active = None


def active() -> Optional[FaultInjector]:
    return _active


# arm from the environment at import: a process started with PL_FAULT_PLAN
# set (the chaos bench's subprocesses, an operator reproducing a failure)
# injects without any code calling install()
if str(flags.get("PL_FAULT_PLAN")).strip():  # pragma: no cover — env-driven
    install()

"""Durable control-state store.

The reference persists agent registry / schemas / tracepoints / cron scripts in
an embedded KV store (pebbledb default; src/vizier/utils/datastore/) — telemetry
data itself is deliberately NOT durable (SURVEY.md §5 checkpoint/resume).  This
is the same split: a small sqlite3-backed KV for control state only.
"""
from __future__ import annotations

import json
import sqlite3
import threading
from typing import Iterator, Optional


class KVStore:
    """Tiny durable KV (namespace via key prefixes, like the reference's
    datastore `SetWithPrefix/GetWithPrefix`)."""

    def __init__(self, path: str = ":memory:"):
        self.path = path
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._lock = threading.Lock()
        with self._lock:
            if path != ":memory:":
                # Crash safety: the KV now backs the replication shard map
                # and the agent registry, so a broker killed mid-write must
                # reopen to a consistent store.  WAL keeps readers unblocked
                # and makes commits an fsynced append; synchronous=FULL
                # makes every commit durable through power loss, not just
                # process death; busy_timeout bounds writer contention from
                # a standby broker sharing the file instead of failing cas.
                self._conn.execute("PRAGMA journal_mode=WAL")
                self._conn.execute("PRAGMA synchronous=FULL")
                self._conn.execute("PRAGMA busy_timeout=5000")
            self._conn.execute(
                "CREATE TABLE IF NOT EXISTS kv (k TEXT PRIMARY KEY, v BLOB)"
            )
            self._conn.commit()

    def set(self, key: str, value: bytes) -> None:
        with self._lock:
            self._conn.execute(
                "INSERT INTO kv(k, v) VALUES(?, ?) "
                "ON CONFLICT(k) DO UPDATE SET v=excluded.v",
                (key, value),
            )
            self._conn.commit()

    def get(self, key: str) -> Optional[bytes]:
        with self._lock:
            row = self._conn.execute("SELECT v FROM kv WHERE k=?", (key,)).fetchone()
        return None if row is None else bytes(row[0])

    def delete(self, key: str) -> None:
        with self._lock:
            self._conn.execute("DELETE FROM kv WHERE k=?", (key,))
            self._conn.commit()

    def scan(self, prefix: str) -> Iterator[tuple[str, bytes]]:
        with self._lock:
            rows = self._conn.execute(
                "SELECT k, v FROM kv WHERE k >= ? AND k < ? ORDER BY k",
                (prefix, prefix + "￿"),
            ).fetchall()
        for k, v in rows:
            yield k, bytes(v)

    def cas(self, key: str, old: Optional[bytes], new: bytes) -> bool:
        """Atomic compare-and-set in ONE sqlite transaction (BEGIN IMMEDIATE
        takes the write lock up front, so a concurrent process cannot
        interleave between the read and the write — the primitive leader
        election needs for a race-free lease take-over)."""
        with self._lock:
            try:
                self._conn.execute("BEGIN IMMEDIATE")
                row = self._conn.execute(
                    "SELECT v FROM kv WHERE k=?", (key,)).fetchone()
                cur = None if row is None else bytes(row[0])
                if cur != old:
                    self._conn.rollback()
                    return False
                self._conn.execute(
                    "INSERT INTO kv(k, v) VALUES(?, ?) "
                    "ON CONFLICT(k) DO UPDATE SET v=excluded.v",
                    (key, new),
                )
                self._conn.commit()
                return True
            except sqlite3.OperationalError:
                self._conn.rollback()
                return False

    # JSON conveniences (control state is JSON-safe by construction)
    def set_json(self, key: str, value) -> None:
        self.set(key, json.dumps(value).encode())

    def get_json(self, key: str, default=None):
        raw = self.get(key)
        return default if raw is None else json.loads(raw.decode())

    def close(self):
        with self._lock:
            self._conn.close()

"""Elastic-rebalance harness (the `elastic_rebalance` bench config).

The data-lifecycle proof (ROADMAP item 2): a real broker + agent cluster
with an UNEVEN data plane — three seed agents with equal base shards, one
of them also carrying a hot extra table, plus one empty spare — under a
3-cycle diurnal client curve, with the RebalanceController and the
compressed cold tier live.  Must hold, all measured from the run, all
guarded absolutely by ``bench.py --check-regressions``:

  * **the hot shard moves** — per-shard heat skew crosses
    ``PL_REBALANCE_SKEW`` during the first high phase; the controller
    re-homes the hottest agent onto the cold spare over the replication
    channel (two-phase, coverage-verified) and retires it (`moves` >= 1),
    after which the skew settles at or under the threshold (`skew_final`).
  * **zero loss, bit-equal throughout** — every query answered during the
    move is bit-equal to its fixed-placement baseline (`bit_equal_frac`),
    and the total row count after the ramp equals the count before it
    (`row_loss` == 0).
  * **the cold tier holds its ceiling** — `PL_COLD_MAX_HOT_MB` demotes
    sealed batches to compressed disk segments (`demotions` >= 1) and the
    in-RAM sealed footprint of any cold-managed table stays bounded
    (`hot_ram_peak_mb`), while those cold batches keep serving scans.

The spare joins schema-matched and EMPTY, so placement is the only thing
the move changes — not one result bit.
"""
from __future__ import annotations

import tempfile
import threading
import time

import numpy as np

from pixie_tpu.services.chaos_bench import _mkdata, canonical_bytes

#: flags the harness overrides and restores
_FLAGS = (
    "PL_DATA_DIR", "PL_REPLICATION", "PL_QUERY_RETRIES", "PL_CLIENT_RETRIES",
    "PL_RETRY_BACKOFF_MS", "PL_REJOIN_GRACE_S", "PL_JOURNAL_FSYNC",
    "PL_COLD_TIER", "PL_COLD_AFTER_S", "PL_COLD_MAX_HOT_MB",
    "PL_COLD_PROMOTE_READS", "PL_HEAT_HALF_LIFE_S",
    "PL_REBALANCE_S", "PL_REBALANCE_SKEW", "PL_REBALANCE_COOLDOWN_S",
)

#: base-shard agg + hot-table agg + count probe: the mix every client
#: rotates through (the count probe doubles as the row-loss audit)
SCRIPTS = [
    """
df = px.DataFrame(table='http_events')
df = df.groupby('service').agg(cnt=('latency', px.count),
                               mx=('latency', px.max))
px.display(df, 'out')
""",
    """
df = px.DataFrame(table='hot_events')
df = df.groupby('service').agg(cnt=('latency', px.count),
                               mn=('latency', px.min))
px.display(df, 'out')
""",
    """
df = px.DataFrame(table='http_events')
df = df.agg(cnt=('status', px.count))
px.display(df, 'out')
""",
]


def _mkstore(seed: int, rows: int, hot_rows: int = 0,
             batch_rows: int = 2048):
    """Base shard (+ optional hot_events extra table).  `hot_events` exists
    on the overloaded seed and (empty) on the spare, so the hot table's
    scans concentrate on one agent — the skew the controller must fix."""
    from pixie_tpu.table import TableStore
    from pixie_tpu.types import DataType as DT, Relation

    rel = Relation.of(
        ("time_", DT.TIME64NS), ("service", DT.STRING),
        ("latency", DT.FLOAT64), ("status", DT.INT64),
    )
    ts = TableStore()
    ts.create("http_events", rel, batch_rows=batch_rows, max_bytes=1 << 32)
    if hot_rows or not rows:
        # the overloaded seed AND the empty spare carry the hot schema
        ts.create("hot_events", rel, batch_rows=batch_rows,
                  max_bytes=1 << 32)
    return ts


def _count_rows(client, tables=("http_events", "hot_events")) -> int:
    from pixie_tpu.services.client import QueryError

    total = 0
    for t in tables:
        try:
            res = client.execute_script(
                f"df = px.DataFrame(table='{t}')\n"
                f"df = df.agg(cnt=('status', px.count))\n"
                f"px.display(df, 'rows')\n")
        except QueryError as e:
            if "not found" in str(e):
                # no live holder: every row of this table is lost from the
                # serving plane — count 0 so the loss lands in `row_loss`
                continue
            raise
        rec = next(iter(res.values()))
        total += int(np.sum(rec.columns["cnt"]))
    return total


def run_elastic_rebalance(clients_high: int = 12, clients_low: int = 3,
                          cycles: int = 3, phase_s: tuple = (1.5, 3.0),
                          rows: int = 60_000, settle_s: float = 2.5,
                          data_dir: str = None) -> dict:
    """Drive the 3-cycle diurnal ramp over the uneven cluster; returns the
    elastic_rebalance result dict."""
    import pixie_tpu.services.replication  # noqa: F401 — PL_REPLICATION
    import pixie_tpu.table.lifecycle  # noqa: F401 — PL_COLD_* flags
    import pixie_tpu.table.heat  # noqa: F401 — PL_HEAT_HALF_LIFE_S

    from pixie_tpu import flags, metrics
    from pixie_tpu.services.agent import Agent
    from pixie_tpu.services.broker import Broker
    from pixie_tpu.services.client import Client, QueryError
    from pixie_tpu.services.rebalance import RebalanceController
    from pixie_tpu.table.table import Table

    saved = {n: flags.get(n) for n in _FLAGS}
    tmp = data_dir or tempfile.mkdtemp(prefix="px-rebalance-")
    flags.set_for_testing("PL_DATA_DIR", tmp)
    flags.set_for_testing("PL_REPLICATION", 2)
    flags.set_for_testing("PL_QUERY_RETRIES", 6)
    flags.set_for_testing("PL_CLIENT_RETRIES", 6)
    flags.set_for_testing("PL_RETRY_BACKOFF_MS", 80)
    flags.set_for_testing("PL_REJOIN_GRACE_S", 0.3)
    flags.set_for_testing("PL_JOURNAL_FSYNC", "batch")
    # cold tier live with a deliberately TIGHT RAM ceiling: the base shards
    # (~1.6 MB sealed each at the default row count) must demote their tails
    # to compressed disk and keep serving the ramp's scans decode-on-read
    flags.set_for_testing("PL_COLD_TIER", 1)
    flags.set_for_testing("PL_COLD_AFTER_S", 0.0)  # ceiling-driven only
    flags.set_for_testing("PL_COLD_MAX_HOT_MB", 1)
    flags.set_for_testing("PL_COLD_PROMOTE_READS", 0)  # hold the ceiling
    # short heat half-life: the final skew reading reflects the settled
    # post-move placement, not the pre-move history
    flags.set_for_testing("PL_HEAT_HALF_LIFE_S", 4.0)
    flags.set_for_testing("PL_REBALANCE_S", 0.3)
    flags.set_for_testing("PL_REBALANCE_SKEW", 1.3)
    flags.set_for_testing("PL_REBALANCE_COOLDOWN_S", 5.0)

    n_seed = 3
    # the script mix is 2 http scans : 1 hot scan, so the donor's heat is
    # (2·rows + hot_rows) against 2·rows on its peers — 0.8 makes the donor
    # a 1.4× median outlier (trips the 1.3 gate with margin) and the
    # settled post-move fleet a 1.24 mean-skew (back under the gate)
    hot_rows = int(rows * 0.8)
    broker = Broker(hb_expiry_s=5.0, query_timeout_s=60.0).start()
    agents = {}
    for i in range(n_seed):
        agents[f"pem{i}"] = Agent(
            f"pem{i}", "127.0.0.1", broker.port,
            store=_mkstore(i + 1, rows, hot_rows=(hot_rows if i == 0 else 0)),
            heartbeat_s=0.5).start()
    agents["spare0"] = Agent("spare0", "127.0.0.1", broker.port,
                             store=_mkstore(0, 0), heartbeat_s=0.5).start()
    # demotion baseline BEFORE ingest: the ceiling-driven retention pass
    # demotes the sealed tail during the writes below, not during the ramp
    demote0 = metrics.counter_value("px_cold_demotions_total")
    # ingest AFTER start so the journal + cold tier are attached: the tight
    # RAM ceiling demotes the sealed tail as it lands
    for i in range(n_seed):
        st = agents[f"pem{i}"].store
        st.table("http_events").write(_mkdata(i + 1, rows))
        if i == 0:
            st.table("hot_events").write(_mkdata(17, hot_rows))
    deadline = time.monotonic() + 20.0
    for a in agents.values():
        assert a.replication.wait_synced(max(deadline - time.monotonic(),
                                             0.1))
    controller = RebalanceController(
        broker, stop_agent=lambda n: agents[n].stop())
    client = Client("127.0.0.1", broker.port, timeout_s=90.0)
    pool = [Client("127.0.0.1", broker.port, timeout_s=90.0)
            for _ in range(4)]

    stop = threading.Event()
    target = [clients_low]
    ok = [0]
    mismatches = [0]
    errors = [0]
    lat: list[float] = []
    count_lock = threading.Lock()
    ram_peak = [0.0]
    # (outlier, mean-skew): outlier = max/median shard heat is the guarded
    # statistic — after the hand-off the move target serves the donor's
    # shard via takeover (heat rides under the donor's shard name), so its
    # OWN shard reads cold and mean-skew stays high on an honest,
    # well-balanced fleet; the outlier reads 1.0 exactly when no live
    # shard is abnormally hot, which is the property the move must restore
    skew_live = [1.0, 1.0]

    def sample() -> None:
        """Peak in-RAM sealed footprint across cold-managed tables, and
        the live skew reading (taken while traffic still runs)."""
        peak = 0.0
        for a in agents.values():
            store = getattr(a, "store", None)
            if store is None or a.pod_killed.is_set():
                continue
            for n in list(store.names()):
                t = store._tables.get(n)
                if isinstance(t, Table) and t.cold is not None:
                    peak = max(peak, t._sealed_bytes / (1 << 20))
        ram_peak[0] = max(ram_peak[0], peak)

    try:
        baseline = [canonical_bytes(client.execute_script(s))
                    for s in SCRIPTS]
        rows_before = _count_rows(client)

        def client_loop(idx: int):
            conn = pool[idx % len(pool)]
            it = 0
            while not stop.is_set():
                if idx >= target[0]:
                    stop.wait(0.05)
                    continue
                si = (idx + it) % len(SCRIPTS)
                it += 1
                t0 = time.perf_counter()
                try:
                    got = conn.execute_script(SCRIPTS[si])
                    dt = time.perf_counter() - t0
                    with count_lock:
                        ok[0] += 1
                        lat.append(dt)
                        if canonical_bytes(got) != baseline[si]:
                            mismatches[0] += 1
                except QueryError as e:
                    if e.retry_after_s is not None:
                        stop.wait(min(e.retry_after_s, 1.0))
                    else:
                        with count_lock:
                            errors[0] += 1
                except Exception:
                    with count_lock:
                        errors[0] += 1

        threads = [threading.Thread(target=client_loop, args=(i,),
                                    daemon=True)
                   for i in range(clients_high)]
        for th in threads:
            th.start()
        controller.start()
        t_start = time.monotonic()
        # ---- the diurnal curve: cycles × (low → high), then settle ------
        phases = []
        for _c in range(cycles):
            phases.append((phase_s[0], clients_low))
            phases.append((phase_s[1], clients_high))
        phases.append((settle_s, clients_low))
        for dur, n in phases:
            target[0] = n
            end = time.monotonic() + dur
            while time.monotonic() < end:
                time.sleep(0.2)
                sample()
                skew_live[0] = controller.last_outlier
                skew_live[1] = controller.last_skew
        measured_s = time.monotonic() - t_start
        skew_final, skew_mean_final = skew_live
        stop.set()
        for th in threads:
            th.join(timeout=30.0)
        rows_after = _count_rows(client)
        demotions = (metrics.counter_value("px_cold_demotions_total")
                     - demote0)
        live_final = sorted(r.name for r in broker.registry.live_agents())
    finally:
        controller.stop()
        for c in pool:
            c.close()
        client.close()
        for a in agents.values():
            try:
                a.stop()
            except Exception:
                pass
        broker.stop()
        for name, v in saved.items():
            flags.set_for_testing(name, v)

    lat.sort()
    p99 = lat[int(0.99 * (len(lat) - 1))] if lat else 0.0
    return {
        # `rows` = high-phase client count: the --check-regressions shape
        # key, so a --smoke run never diffs against a full run
        "rows": clients_high,
        "clients_high": clients_high,
        "clients_low": clients_low,
        "cycles": cycles,
        "duration_s": round(measured_s, 2),
        "queries": ok[0],
        "goodput_qps": round(ok[0] / max(measured_s, 1e-9), 1),
        "p99_ms": round(p99 * 1000, 1),
        "client_errors": errors[0],
        "bit_equal_frac": round((ok[0] - mismatches[0]) / max(ok[0], 1), 4),
        "moves": controller.moves,
        "move_refusals": controller.skips,
        "skew_final": round(skew_final, 3),
        "skew_mean_final": round(skew_mean_final, 3),
        "row_loss": int(rows_before - rows_after),
        "rows_total": rows_before,
        "demotions": int(demotions),
        "hot_ram_peak_mb": round(ram_peak[0], 3),
        "agents_final": live_final,
    }


def main(argv=None):  # pragma: no cover — exercised via bench.py
    import argparse
    import json

    ap = argparse.ArgumentParser()
    ap.add_argument("--clients-high", type=int, default=12)
    ap.add_argument("--rows", type=int, default=60_000)
    args = ap.parse_args(argv)
    print(json.dumps(run_elastic_rebalance(clients_high=args.clients_high,
                                           rows=args.rows),
                     separators=(",", ":")))


if __name__ == "__main__":  # pragma: no cover
    main()

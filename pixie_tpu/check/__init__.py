"""Static analysis for pixie_tpu — two altitudes, one contract.

Runtime chaos tests (PR 9) prove the engine RECOVERS; nothing proved plans
were well-formed BEFORE dispatch.  Flare and Tailwind (PAPERS.md) both rest
on a verified lowering contract between the query plan and the native /
accelerator substrate; this package is that contract, enforced everywhere:

  * ``check.planverify`` — a typed dataflow pass over compiled Carnot plans
    the broker and LocalCluster run before every dispatch (PX_PLAN_VERIFY,
    default on).  Schema/dtype flow op-to-op, shard-axis consistency across
    shuffle boundaries, partial-agg mergeability (the PR 9 fold-correctness
    linchpin), matview prefix consistency, and limit/window sanity.
    Violations raise a structured :class:`PlanVerifyError` naming the op and
    the invariant.  Verified splits ride the whole-query plan cache, so warm
    queries pay zero re-verification.

  * ``check.pxlint`` — an AST linter over the repo itself
    (``python -m pixie_tpu.check.pxlint``): lock discipline via the
    ``*_locked`` naming convention, env reads outside the flags registry,
    metric/span hygiene, and host callbacks inside jitted code.  Findings
    are fixed or explicitly owned via ``# pxlint: disable=<rule> -- reason``
    — never silently ignored.
"""
from __future__ import annotations

from pixie_tpu.check.planverify import (  # noqa: F401
    PlanVerifyError,
    verify_distributed,
    verify_plan,
)

"""pxlint: AST-based repo linter for pixie_tpu's concurrency + hygiene
contracts (``python -m pixie_tpu.check.pxlint [paths] [--ratchet FILE]``).

The broker/agent layer is ~15 lock-guarded structures maintained by hand;
metrics, spans, and env-config each have ONE sanctioned surface.  These
conventions only hold if something checks them — this linter does, in CI
(tests/test_pxlint.py runs it over the whole package).

Rules:

  lock-discipline   a ``*_locked``-suffixed method/function/field (the
      repo's "caller must hold the owning lock" naming convention) touched
      outside a ``with <...lock...>:`` guard — unless the touching function
      is itself ``*_locked`` (the lock is held by contract up the stack).
      A module may pin WHICH lock owns a member via a module-level
      ``_pxlint_locks_ = {"<member>": "<expr suffix>"}`` mapping; the guard
      expression must then end with that suffix (e.g. ``"view.lock"``).
  env-read          ``os.environ``/``os.getenv`` of a ``PL_*``/``PX_*``/
      ``PIXIE_TPU_*`` name anywhere but flags.py — declared, typed flags
      (``flags.define_*``) are the one config surface; stray reads dodge
      dump()/introspection and silently fork defaults.
  metric-hygiene    metric names must be ``px_*`` string literals, and every
      written series must be REGISTERED (at least one call site passes
      ``help_=``) so /metrics never exposes undocumented names.
  span-hygiene      spans open only through the context-manager API
      (``trace.span``/``root``/``maybe_root`` as a ``with`` item, or the
      designated manual ``trace.start_child``); raw ``Tracer.start_span``
      outside trace.py leaks open spans past the hygiene ratchet.
  jit-host-callback no host callbacks (``print``, ``jax.debug.*``,
      ``pure_callback``/``io_callback``/``host_callback``) inside functions
      handed to ``jax.jit``/``shard_map`` — they silently synchronize the
      device stream (and deadlock under the XLA-CPU collective gate).
  bad-suppression   a suppression comment without a reason, or naming an
      unknown rule.
  pxl-columns       a bundled self-telemetry script
      (``pixie_tpu/scripts/px/self_*/*.pxl``) referencing a table or
      column that does not exist in the canonical relations
      (``collect/schemas.py`` ∪ the self-telemetry tables) — the schema
      registry and the shipped dashboards drift silently otherwise.
      Tracks frame shapes through ``px.DataFrame`` / filters /
      ``groupby(...).agg(...)`` assignments, so derived columns count.

Suppression: ``# pxlint: disable=<rule>[,<rule>] -- <reason>`` on (or one
line above) the flagged statement.  The reason is REQUIRED: findings are
fixed or explicitly owned, never silently ignored.

Ratchet: ``--ratchet FILE`` holds grandfathered ``path:rule: N`` counts.
New findings beyond an entry fail; an entry exceeding reality is STALE and
also fails (the ratchet only tightens).
"""
from __future__ import annotations

import argparse
import ast
import dataclasses
import io
import pathlib
import re
import sys
import tokenize
from typing import Optional

RULES = frozenset({
    "lock-discipline", "env-read", "metric-hygiene", "span-hygiene",
    "jit-host-callback", "bad-suppression", "pxl-columns",
})

_ENV_NAME = re.compile(r"^(PL_|PX_|PIXIE_TPU_)")
_SUPPRESS = re.compile(
    r"#\s*pxlint:\s*disable=([A-Za-z0-9_,-]+)\s*(?:--\s*(\S.*))?")

#: metrics-module write/read surfaces (name = first arg)
_METRIC_WRITES = frozenset({"counter_inc", "gauge_set", "histogram_observe",
                            "register_gauge_fn"})
_METRIC_READS = frozenset({"counter_value", "counter_series", "has_gauge_fn",
                           "unregister_gauge_fn"})
#: positional index of help_ per write fn (fallback when passed positionally)
_HELP_POS = {"counter_inc": 3, "gauge_set": 3, "histogram_observe": 4,
             "register_gauge_fn": 2}

_SPAN_CMS = frozenset({"span", "root", "maybe_root"})

_BANNED_IN_JIT = ("print", "jax.debug.print", "jax.debug.callback",
                  "jax.pure_callback", "pure_callback", "io_callback",
                  "jax.experimental.io_callback", "host_callback.call",
                  "host_callback.id_tap")


@dataclasses.dataclass(frozen=True)
class Finding:
    path: str
    line: int
    rule: str
    msg: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: {self.rule}: {self.msg}"


# ------------------------------------------------------------------ helpers


def _parents(tree: ast.AST) -> dict:
    par = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            par[child] = node
    return par


def _dotted(node: ast.AST) -> Optional[str]:
    """'a.b.c' for a Name/Attribute chain, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _subtree_mentions_lock(node: ast.AST) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Name) and "lock" in n.id.lower():
            return True
        if isinstance(n, ast.Attribute) and "lock" in n.attr.lower():
            return True
    return False


def _unparse(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:  # pragma: no cover - unparse is total on py>=3.9
        return "<?>"


class _FileCtx:
    """One parsed file: source, tree, parent links, suppressions."""

    def __init__(self, path: pathlib.Path, rel: str):
        self.path = path
        self.rel = rel
        self.src = path.read_text()
        self.tree = ast.parse(self.src, filename=str(path))
        self.par = _parents(self.tree)
        #: line -> set of suppressed rules
        self.suppress: dict[int, set] = {}
        self.findings: list[Finding] = []
        self._scan_comments()
        #: module-level owning-lock annotation
        self.lock_owners: dict[str, str] = {}
        for node in self.tree.body:
            tgt = None
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                tgt = node.targets[0]
            elif isinstance(node, ast.AnnAssign):
                tgt = node.target
            if isinstance(tgt, ast.Name) and tgt.id == "_pxlint_locks_" \
                    and isinstance(node.value, ast.Dict):
                for k, v in zip(node.value.keys, node.value.values):
                    if isinstance(k, ast.Constant) and isinstance(
                            v, ast.Constant):
                        self.lock_owners[str(k.value)] = str(v.value)

    def _scan_comments(self) -> None:
        try:
            toks = tokenize.generate_tokens(io.StringIO(self.src).readline)
            for tok in toks:
                if tok.type != tokenize.COMMENT:
                    continue
                m = _SUPPRESS.search(tok.string)
                if not m:
                    continue
                rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
                line = tok.start[0]
                unknown = rules - RULES
                if unknown:
                    self.findings.append(Finding(
                        self.rel, line, "bad-suppression",
                        f"unknown rule(s) {sorted(unknown)}"))
                    rules &= RULES
                if not m.group(2):
                    self.findings.append(Finding(
                        self.rel, line, "bad-suppression",
                        "suppression requires a reason: "
                        "# pxlint: disable=<rule> -- <why this is safe>"))
                    continue
                self.suppress.setdefault(line, set()).update(rules)
        except tokenize.TokenError:  # pragma: no cover
            pass

    def suppressed(self, node: ast.AST, rule: str) -> bool:
        lo = getattr(node, "lineno", 0)
        hi = getattr(node, "end_lineno", lo) or lo
        for line in range(lo - 1, hi + 1):
            if rule in self.suppress.get(line, ()):
                return True
        return False

    def add(self, node: ast.AST, rule: str, msg: str) -> None:
        if not self.suppressed(node, rule):
            self.findings.append(Finding(
                self.rel, getattr(node, "lineno", 0), rule, msg))

    def ancestors(self, node: ast.AST):
        cur = self.par.get(node)
        while cur is not None:
            yield cur
            cur = self.par.get(cur)


# ----------------------------------------------------------- lock discipline


def _check_lock_discipline(ctx: _FileCtx) -> None:
    for node in ast.walk(ctx.tree):
        member = None
        anchor = node
        if isinstance(node, ast.Call):
            member = (node.func.attr if isinstance(node.func, ast.Attribute)
                      else node.func.id if isinstance(node.func, ast.Name)
                      else None)
        elif isinstance(node, (ast.Attribute, ast.Name)):
            # bare loads (field reads / callback references); Call funcs are
            # handled above — skip the func child to avoid double reports
            parent = ctx.par.get(node)
            if isinstance(parent, ast.Call) and parent.func is node:
                continue
            if not isinstance(getattr(node, "ctx", None), ast.Load):
                continue
            member = node.attr if isinstance(node, ast.Attribute) else node.id
        if not member or not member.endswith("_locked"):
            continue
        guard_expr = None
        held = False
        for anc in ctx.ancestors(anchor):
            if isinstance(anc, ast.With):
                for item in anc.items:
                    if _subtree_mentions_lock(item.context_expr):
                        held = True
                        guard_expr = item.context_expr
                        break
            if held:
                break
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if anc.name.endswith("_locked"):
                    held = True  # caller holds the lock by contract
                break  # guards don't cross function boundaries
        if not held:
            ctx.add(node, "lock-discipline",
                    f"{member!r} touched outside a `with <lock>:` guard "
                    "(callers of *_locked members must hold the owning "
                    "lock)")
            continue
        owner = ctx.lock_owners.get(member)
        if owner and guard_expr is not None:
            text = _unparse(guard_expr)
            if not text.endswith(owner):
                ctx.add(node, "lock-discipline",
                        f"{member!r} guarded by {text!r} but its declared "
                        f"owning lock is {owner!r} (_pxlint_locks_)")


# ----------------------------------------------------------------- env read


def _env_name_of(node: ast.Call | ast.Subscript | ast.Compare
                 ) -> Optional[tuple]:
    """(env var name, how) when `node` reads the process environment."""
    if isinstance(node, ast.Call):
        d = _dotted(node.func)
        if d is None:
            return None
        leaf = d.split(".")[-1]
        if leaf == "getenv" and len(d.split(".")) >= 2:
            if node.args and isinstance(node.args[0], ast.Constant):
                return str(node.args[0].value), "os.getenv"
            return "<dynamic>", "os.getenv"
        if leaf in ("get", "setdefault") and ".environ." in d + ".":
            if node.args and isinstance(node.args[0], ast.Constant):
                return str(node.args[0].value), f"environ.{leaf}"
            return "<dynamic>", f"environ.{leaf}"
        return None
    if isinstance(node, ast.Subscript):
        d = _dotted(node.value)
        if d is not None and d.split(".")[-1] == "environ":
            sl = node.slice
            if isinstance(sl, ast.Constant):
                return str(sl.value), "environ[]"
            return "<dynamic>", "environ[]"
        return None
    if isinstance(node, ast.Compare) and len(node.ops) == 1 \
            and isinstance(node.ops[0], (ast.In, ast.NotIn)):
        d = _dotted(node.comparators[0])
        if d is not None and d.split(".")[-1] == "environ" \
                and isinstance(node.left, ast.Constant):
            return str(node.left.value), "in environ"
    return None


def _check_env_read(ctx: _FileCtx) -> None:
    if ctx.path.name == "flags.py":
        return  # the one sanctioned surface
    for node in ast.walk(ctx.tree):
        if not isinstance(node, (ast.Call, ast.Subscript, ast.Compare)):
            continue
        got = _env_name_of(node)
        if got is None:
            continue
        name, how = got
        if name != "<dynamic>" and not _ENV_NAME.match(name):
            continue  # PATH/HOME etc. are not engine flags
        ctx.add(node, "env-read",
                f"direct {how} of {name!r}: engine config must go through "
                "flags.define_* / flags.get (flags.py is the only "
                "sanctioned env surface)")


# ------------------------------------------------------------ metric hygiene


def _metric_call(node: ast.Call) -> Optional[tuple]:
    """(fn leaf, name node, registered: bool) for metrics-module calls."""
    d = _dotted(node.func)
    if d is None:
        return None
    parts = d.split(".")
    leaf = parts[-1]
    if leaf not in _METRIC_WRITES | _METRIC_READS:
        return None
    if len(parts) >= 2 and parts[-2] not in ("metrics", "_metrics"):
        return None
    if len(parts) == 1:
        return None  # local helpers sharing a name
    name_node = node.args[0] if node.args else None
    for kw in node.keywords:
        if kw.arg == "name":
            name_node = kw.value
    registered = any(kw.arg == "help_" for kw in node.keywords)
    hp = _HELP_POS.get(leaf)
    if hp is not None and len(node.args) > hp:
        registered = True
    return leaf, name_node, registered


def _check_metric_hygiene(ctx: _FileCtx, registry: dict) -> None:
    """First pass: per-file checks + collect (name -> registered anywhere,
    first write site) into `registry` for the cross-file pass."""
    if ctx.path.name == "metrics.py":
        return  # the registry's own internals
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        got = _metric_call(node)
        if got is None:
            continue
        leaf, name_node, registered = got
        if not (isinstance(name_node, ast.Constant)
                and isinstance(name_node.value, str)):
            ctx.add(node, "metric-hygiene",
                    f"{leaf}: metric name must be a px_* string literal "
                    "(dynamic names defeat static registration checks)")
            continue
        name = name_node.value
        if not name.startswith("px_"):
            ctx.add(node, "metric-hygiene",
                    f"metric {name!r} must be px_-prefixed")
        if leaf in _METRIC_WRITES:
            ent = registry.setdefault(
                name, {"registered": False, "site": (ctx.rel, node.lineno),
                       "node": node, "ctx": ctx})
            ent["registered"] = ent["registered"] or registered


def _finish_metric_hygiene(registry: dict) -> list[Finding]:
    out = []
    for name, ent in sorted(registry.items()):
        if not ent["registered"]:
            ctx, node = ent["ctx"], ent["node"]
            if not ctx.suppressed(node, "metric-hygiene"):
                rel, line = ent["site"]
                out.append(Finding(
                    rel, line, "metric-hygiene",
                    f"metric {name!r} is never registered: at least one "
                    "write site must pass help_= (the /metrics HELP text)"))
    return out


# -------------------------------------------------------------- span hygiene


def _check_span_hygiene(ctx: _FileCtx) -> None:
    if ctx.path.name == "trace.py":
        return
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr == "start_span":
            ctx.add(node, "span-hygiene",
                    "raw Tracer.start_span outside trace.py: open spans "
                    "via `with trace.span(...)` / trace.root / "
                    "trace.event_span / trace.start_child so the hygiene "
                    "ratchet (started == finished) holds")
            continue
        d = _dotted(node.func)
        if d is None:
            continue
        parts = d.split(".")
        if len(parts) != 2 or parts[0] not in ("trace", "_trace") \
                or parts[1] not in _SPAN_CMS:
            continue
        if _span_cm_ok(ctx, node):
            continue
        ctx.add(node, "span-hygiene",
                f"trace.{parts[1]}(...) must be entered as a context "
                "manager (`with` item, possibly via an assigned variable) "
                "— a span cm never entered is a silent no-op")


def _span_cm_ok(ctx: _FileCtx, node: ast.Call) -> bool:
    fn = None
    assigned: Optional[str] = None
    for anc in ctx.ancestors(node):
        if isinstance(anc, ast.withitem):
            return True
        if isinstance(anc, ast.Assign) and assigned is None:
            if len(anc.targets) == 1 and isinstance(anc.targets[0], ast.Name):
                assigned = anc.targets[0].id
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fn = anc
            break
    if assigned is None or fn is None:
        return False
    for n in ast.walk(fn):
        if isinstance(n, ast.With):
            for item in n.items:
                if isinstance(item.context_expr, ast.Name) \
                        and item.context_expr.id == assigned:
                    return True
    return False


# --------------------------------------------------------- jit host callback


def _jitted_functions(ctx: _FileCtx) -> list:
    """Function bodies (FunctionDef or Lambda) that are traced by
    jax.jit / shard_map, resolved lexically."""
    defs: dict[str, list] = {}
    for n in ast.walk(ctx.tree):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs.setdefault(n.name, []).append(n)
    out = []
    for n in ast.walk(ctx.tree):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in n.decorator_list:
                d = _dotted(dec.func if isinstance(dec, ast.Call) else dec)
                if d and d.split(".")[-1] in ("jit", "pjit"):
                    out.append(n)
        if not isinstance(n, ast.Call):
            continue
        d = _dotted(n.func)
        if d is None or d.split(".")[-1] not in ("jit", "pjit", "shard_map"):
            continue
        if not n.args:
            continue
        target = n.args[0]
        if isinstance(target, ast.Lambda):
            out.append(target)
        elif isinstance(target, ast.Name):
            out.extend(defs.get(target.id, ()))
    return out


def _check_jit_host_callback(ctx: _FileCtx) -> None:
    for fn in _jitted_functions(ctx):
        for n in ast.walk(fn):
            if not isinstance(n, ast.Call):
                continue
            d = _dotted(n.func)
            if d is None:
                continue
            if d == "print" or any(d == b or d.endswith("." + b)
                                   for b in _BANNED_IN_JIT if "." in b) \
                    or d.split(".")[-1] in ("pure_callback", "io_callback"):
                name = getattr(fn, "name", "<lambda>")
                ctx.add(n, "jit-host-callback",
                        f"host callback {d!r} inside jitted/shard_mapped "
                        f"function {name!r}: host calls inside a traced "
                        "program synchronize the device stream (and can "
                        "deadlock the XLA-CPU collective gate)")


# ----------------------------------------------------- pxl column references


def _chain_root(node: ast.AST) -> Optional[str]:
    """The base Name of a call/attribute chain: `df.groupby(..).agg(..)`
    → 'df'."""
    while True:
        if isinstance(node, ast.Call):
            node = node.func
        elif isinstance(node, ast.Attribute):
            node = node.value
        elif isinstance(node, ast.Subscript):
            node = node.value
        elif isinstance(node, ast.Name):
            return node.id
        else:
            return None


def _str_consts(node: ast.AST) -> list[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.List, ast.Tuple)):
        return [e.value for e in node.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, str)]
    return []


class _PxlChecker:
    """Column-reference lint for one bundled .pxl script: tracks the frame
    shape through `px.DataFrame` / filter / projection / groupby-agg
    assignments (sequentially, the shape the bundled scripts use) and flags
    any table or column reference the canonical relations don't carry."""

    def __init__(self, rel: str, schemas: dict[str, set]):
        self.rel = rel
        self.schemas = schemas
        self.findings: list[Finding] = []

    def check_module(self, tree: ast.Module) -> list[Finding]:
        for node in tree.body:
            if isinstance(node, ast.FunctionDef):
                self._check_fn(node)
        return self.findings

    def _add(self, node, msg: str) -> None:
        self.findings.append(Finding(
            self.rel, getattr(node, "lineno", 0), "pxl-columns", msg))

    # -------------------------------------------------------- frame shapes
    def _frame_of(self, expr: ast.AST, avail: dict) -> Optional[set]:
        """Resulting column set of an expression assigned to a variable,
        or None when it is not a tracked frame."""
        if isinstance(expr, ast.Call):
            d = _dotted(expr.func)
            if d == "px.DataFrame":
                for kw in expr.keywords:
                    if kw.arg == "table" and isinstance(kw.value, ast.Constant):
                        cols = self.schemas.get(str(kw.value.value))
                        return set(cols) if cols is not None else None
                if expr.args and isinstance(expr.args[0], ast.Constant):
                    cols = self.schemas.get(str(expr.args[0].value))
                    return set(cols) if cols is not None else None
                return None
            if isinstance(expr.func, ast.Attribute) and expr.func.attr == "agg":
                out = {kw.arg for kw in expr.keywords if kw.arg}
                base = expr.func.value
                if isinstance(base, ast.Call) and isinstance(
                        base.func, ast.Attribute) and base.func.attr == "groupby":
                    for a in base.args:
                        out.update(_str_consts(a))
                return out
            # other chained calls (head, drop-less shapes): propagate the
            # base frame's columns when the chain roots at a tracked frame
            root = _chain_root(expr)
            if root is not None and avail.get(root) is not None:
                return set(avail[root])
            return None
        if isinstance(expr, ast.Subscript):
            root = _chain_root(expr)
            if root is None or avail.get(root) is None:
                return None
            proj = _str_consts(expr.slice)
            if proj:  # df[['a', 'b']] projection narrows the shape
                return set(proj)
            return set(avail[root])  # boolean filter keeps it
        if isinstance(expr, ast.Name):
            got = avail.get(expr.id)
            return set(got) if got is not None else None
        return None

    # -------------------------------------------------------------- checks
    def _check_reads(self, stmt: ast.stmt, avail: dict) -> None:
        par = _parents(stmt)

        def cols_of(node) -> Optional[set]:
            root = _chain_root(node)
            return avail.get(root) if root is not None else None

        for node in ast.walk(stmt):
            if isinstance(node, ast.Call):
                d = _dotted(node.func)
                if d == "px.DataFrame":
                    table = None
                    for kw in node.keywords:
                        if kw.arg == "table" and isinstance(
                                kw.value, ast.Constant):
                            table = str(kw.value.value)
                    if table is not None and table not in self.schemas:
                        self._add(node, f"unknown table {table!r} (not in "
                                        "collect/schemas.py ∪ self-telemetry "
                                        "relations)")
                    continue
                if isinstance(node.func, ast.Attribute):
                    cols = cols_of(node.func.value)
                    if node.func.attr == "groupby" and cols is not None:
                        for a in node.args:
                            for c in _str_consts(a):
                                if c not in cols:
                                    self._add(node, f"groupby column {c!r} "
                                                    "not in the frame")
                    elif node.func.attr == "agg":
                        base = node.func.value
                        if isinstance(base, ast.Call) and isinstance(
                                base.func, ast.Attribute) \
                                and base.func.attr == "groupby":
                            base = base.func.value
                        bcols = cols_of(base)
                        if bcols is not None:
                            for kw in node.keywords:
                                if isinstance(kw.value, ast.Tuple) \
                                        and kw.value.elts:
                                    for c in _str_consts(kw.value.elts[0]):
                                        if c not in bcols:
                                            self._add(
                                                kw.value,
                                                f"agg input column {c!r} "
                                                "not in the frame")
            elif isinstance(node, ast.Attribute) \
                    and isinstance(getattr(node, "ctx", None), ast.Load):
                parent = par.get(node)
                if isinstance(parent, (ast.Call, ast.Attribute)) and (
                        getattr(parent, "func", None) is node
                        or getattr(parent, "value", None) is node):
                    continue  # method receiver / deeper chain link
                if isinstance(node.value, ast.Name):
                    cols = avail.get(node.value.id)
                    if cols is not None and node.attr not in cols:
                        self._add(node, f"column {node.attr!r} not in the "
                                        f"frame {node.value.id!r}")
            elif isinstance(node, ast.Subscript) \
                    and isinstance(getattr(node, "ctx", None), ast.Load) \
                    and isinstance(node.value, ast.Name):
                cols = avail.get(node.value.id)
                if cols is not None:
                    for c in _str_consts(node.slice):
                        if c not in cols:
                            self._add(node, f"column {c!r} not in the frame "
                                            f"{node.value.id!r}")

    def _check_fn(self, fn: ast.FunctionDef) -> None:
        avail: dict[str, Optional[set]] = {}
        for stmt in fn.body:
            self._check_reads(stmt, avail)
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
                tgt = stmt.targets[0]
                if isinstance(tgt, ast.Name):
                    avail[tgt.id] = self._frame_of(stmt.value, avail)
                elif isinstance(tgt, ast.Attribute) \
                        and isinstance(tgt.value, ast.Name):
                    cols = avail.get(tgt.value.id)
                    if cols is not None:  # df.newcol = expr adds a column
                        cols.add(tgt.attr)
                elif isinstance(tgt, ast.Subscript) \
                        and isinstance(tgt.value, ast.Name):
                    cols = avail.get(tgt.value.id)
                    if cols is not None:
                        for c in _str_consts(tgt.slice):
                            cols.add(c)


def _canonical_columns() -> dict[str, set]:
    from pixie_tpu.collect.schemas import all_schemas

    return {t: {c.name for c in rel} for t, rel in all_schemas().items()}


def lint_pxl_scripts(roots: Optional[list] = None) -> list[Finding]:
    """The pxl-columns rule over every bundled self-telemetry script
    (``self_*`` bundle dirs) under `roots` (default: the package's
    scripts/px bundle)."""
    roots = ([pathlib.Path(p) for p in roots] if roots
             else [_PKG / "scripts" / "px"])
    schemas = _canonical_columns()
    findings: list[Finding] = []
    for root in roots:
        if not root.is_dir():
            continue
        for f in sorted(root.rglob("*.pxl")):
            if not f.parent.name.startswith("self_"):
                continue
            try:
                rel = str(f.resolve().relative_to(_REPO))
            except ValueError:
                rel = str(f)
            try:
                tree = ast.parse(f.read_text(), filename=str(f))
            except SyntaxError as e:
                findings.append(Finding(rel, e.lineno or 0, "pxl-columns",
                                        f"script does not parse: {e.msg}"))
                continue
            findings.extend(_PxlChecker(rel, schemas).check_module(tree))
    return findings


# --------------------------------------------------------------------- main


#: package root (default lint scope)
_PKG = pathlib.Path(__file__).resolve().parent.parent
_REPO = _PKG.parent


def _iter_files(paths: list[pathlib.Path]):
    for p in paths:
        if p.is_dir():
            yield from sorted(p.rglob("*.py"))
        elif p.suffix == ".py":
            yield p


def lint_paths(paths: Optional[list] = None) -> list[Finding]:
    """Run every rule over `paths` (default: the pixie_tpu package).
    Returns unsuppressed findings sorted by (path, line)."""
    roots = [pathlib.Path(p) for p in paths] if paths else [_PKG]
    metric_registry: dict = {}
    findings: list[Finding] = []
    for f in _iter_files(roots):
        try:
            rel = str(f.resolve().relative_to(_REPO))
        except ValueError:
            rel = str(f)
        try:
            ctx = _FileCtx(f, rel)
        except SyntaxError as e:
            findings.append(Finding(rel, e.lineno or 0, "bad-suppression",
                                    f"file does not parse: {e.msg}"))
            continue
        _check_lock_discipline(ctx)
        _check_env_read(ctx)
        _check_metric_hygiene(ctx, metric_registry)
        _check_span_hygiene(ctx)
        _check_jit_host_callback(ctx)
        findings.extend(ctx.findings)
    findings.extend(_finish_metric_hygiene(metric_registry))
    # bundled self-telemetry scripts: schema-drift lint over the .pxl files
    # beneath the same roots (default: the package's scripts/px bundle;
    # explicit FILE paths lint .py only, matching the historical surface)
    if paths:
        dirs = [p for p in roots if p.is_dir()]
        if dirs:
            findings.extend(lint_pxl_scripts(dirs))
    else:
        findings.extend(lint_pxl_scripts(None))
    return sorted(findings, key=lambda f: (f.path, f.line, f.rule))


def load_ratchet(path) -> dict[tuple, int]:
    """{(path, rule): allowed count} from a ratchet file."""
    out: dict[tuple, int] = {}
    for raw in pathlib.Path(path).read_text().splitlines():
        line = raw.split("#", 1)[0].strip()
        if not line:
            continue
        m = re.match(r"^(.*?):([A-Za-z0-9-]+):\s*(\d+)$", line)
        if not m:
            raise ValueError(f"bad ratchet line: {raw!r}")
        out[(m.group(1), m.group(2))] = int(m.group(3))
    return out


def apply_ratchet(findings: list[Finding], allowed: dict[tuple, int]
                  ) -> tuple[list[Finding], list[str]]:
    """(net findings beyond the ratchet, stale-entry complaints)."""
    counts: dict[tuple, int] = {}
    for f in findings:
        counts[(f.path, f.rule)] = counts.get((f.path, f.rule), 0) + 1
    net = [f for f in findings
           if counts.get((f.path, f.rule), 0) > allowed.get(
               (f.path, f.rule), 0)]
    stale = [
        f"{p}:{r}: ratchet allows {n} but only {counts.get((p, r), 0)} "
        "remain — tighten the ratchet file"
        for (p, r), n in sorted(allowed.items())
        if counts.get((p, r), 0) < n
    ]
    return net, stale


def main(argv: Optional[list] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m pixie_tpu.check.pxlint",
        description="repo-wide concurrency & invariant lint")
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to lint (default: the pixie_tpu "
                         "package)")
    ap.add_argument("--ratchet", default=None,
                    help="grandfathered-findings file (path:rule: N lines)")
    args = ap.parse_args(argv)
    findings = lint_paths(args.paths or None)
    stale: list[str] = []
    if args.ratchet:
        findings, stale = apply_ratchet(findings, load_ratchet(args.ratchet))
    for f in findings:
        print(f)
    for s in stale:
        print(s)
    n = len(findings) + len(stale)
    if n:
        print(f"pxlint: {n} problem(s)")
        return 1
    print("pxlint: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())

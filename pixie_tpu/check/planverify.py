"""Compile-time plan verification: a typed dataflow pass over Carnot plans.

The broker and LocalCluster run this before EVERY dispatch (PX_PLAN_VERIFY,
default on).  A miscompiled fragment — mismatched dtypes across a shuffle,
a non-mergeable partial agg split across agents, a matview prefix that
silently diverges between producers, mismatched partition counts on a
repartitioned join — would otherwise surface only as a runtime bit-diff or
a hung wave.  Flare/Tailwind (PAPERS.md) lean on a verified lowering
contract between plan and substrate; this pass is that contract.

Checked invariants (each names itself in the raised PlanVerifyError):

  unknown-table / unknown-column / unknown-udf / unknown-uda
      every name a plan references resolves against the live schemas and
      the UDF registry, with the SAME overload resolution the executor uses
  filter-not-boolean       filter predicates type to BOOLEAN
  dict-input-agg           dictionary-encoded agg inputs only into dict_ok UDAs
  bad-limit                LimitOp.n is a non-negative int
  windowed-agg-no-time     windowed aggs carry a time-typed group key
  join-key-arity / join-key-dtype / join-how / join-output
      equijoin keys pair up with matching dtypes; outputs name real columns
  union-schema             union parents share one name→dtype relation
  agg-state-sink           agg_state sinks are fed by a partial AggOp
  not-mergeable            every partial agg has a combine path: each UDA's
      reduce_ops() tree is add/min/max leaves (what combine_partials and the
      in-mesh psum merge consume) and a finalize path exists — the PR 9
      fold-correctness linchpin
  partial-dict-agg         cross-agent partials never carry dictionary codes
      (each agent's code space is private; state must merge by VALUE)
  unknown-producer / unknown-channel / missing-bucket-channel
      channel topology is closed: producers exist, sinks ship to declared
      channels, every partition bucket channel exists
  shuffle-schema-mismatch  all producers of one channel ship ONE relation
      (names AND dtypes) — the dtype-flip-across-a-shuffle miscompile
  partition-count-mismatch all PartitionSinks of a join stage agree with the
      stage's n_parts (the shard-axis consistency contract)
  channel-agg-mismatch     an agg_state channel's declared agg (what the
      merger finalizes with) matches the partial agg its producers run
  matview-prefix-divergence all producers of an agg_state channel
      canonicalize to the SAME standing-view key (broker matcher and agent
      maintainers must agree on what the state is a function of)
  batch-slot-missing-sink / batch-slot-overlap
      fused multi-query (batched) splits: each member slot's renamed sinks
      exist exactly once in the merger plan and two slots never claim one
      fused sink (the per-member demux partition contract) — verified once
      per batch signature, riding the fused split cache

Cost model: one O(ops) walk per distributed split.  Both dispatch sites
cache splits in the whole-query plan cache keyed by (script, params,
topology epoch), and verification runs only when the split is freshly
computed — a warm query's verified signature IS its split-cache slot, so
warm queries pay zero re-verification.
"""
from __future__ import annotations

from typing import Optional

from pixie_tpu import flags as _flags
from pixie_tpu.plan.plan import (
    AggOp,
    Call,
    Column,
    FilterOp,
    JoinOp,
    LimitOp,
    Literal,
    MapOp,
    MemorySinkOp,
    MemorySourceOp,
    OTelExportSinkOp,
    PartitionSinkOp,
    Plan,
    RemoteSourceOp,
    ResultSinkOp,
    UDTFSourceOp,
    UnionOp,
)
from pixie_tpu.status import Code, PxError
from pixie_tpu.types import DICT_ENCODED, DataType as DT, Relation

_flags.define_bool(
    "PX_PLAN_VERIFY", True,
    "typed dataflow verification of every compiled plan before dispatch "
    "(broker and LocalCluster): schema/dtype flow, shuffle consistency, "
    "partial-agg mergeability, matview prefix agreement.  Violations raise "
    "PlanVerifyError naming the op and invariant; 0 disables (A/B only)")

_REDUCE_OPS = frozenset({"add", "min", "max"})
_JOIN_HOWS = frozenset({"inner", "left", "right", "outer"})
#: dtype pairs a join may legally mix (time is int64 nanoseconds on device)
_JOIN_COMPAT = frozenset({DT.INT64, DT.TIME64NS})


class PlanVerifyError(PxError):
    """A plan failed pre-dispatch verification.

    Structured: ``invariant`` is the rule id (stable, test-asserted),
    ``op_kind``/``op_id`` name the offending operator, ``where`` locates the
    fragment (logical plan, an agent's plan, a channel, a join stage)."""

    code = Code.INVALID_ARGUMENT

    def __init__(self, invariant: str, detail: str, op=None, where: str = ""):
        self.invariant = invariant
        self.op_kind = getattr(op, "kind", None) if op is not None else None
        self.op_id = getattr(op, "id", None) if op is not None else None
        self.where = where
        at = f" at {self.op_kind}#{self.op_id}" if op is not None else ""
        loc = f" [{where}]" if where else ""
        super().__init__(f"plan verify{loc}: {invariant}{at}: {detail}")


def enabled() -> bool:
    return bool(_flags.get("PX_PLAN_VERIFY"))


# ------------------------------------------------------------ expression flow


def _expr_dtype(expr, env: dict, registry, op, where: str):
    """Physical dtype of an expression under `env`, resolved with the same
    overload rules the executor applies.  Raises on unknown names."""
    if isinstance(expr, Column):
        dt = env.get(expr.name)
        if dt is None:
            raise PlanVerifyError(
                "unknown-column",
                f"column {expr.name!r} not in input relation "
                f"{sorted(env)}", op, where)
        return dt
    if isinstance(expr, Literal):
        return expr.dtype
    if isinstance(expr, Call):
        argdts = [_expr_dtype(a, env, registry, op, where) for a in expr.args]
        if any(d is None for d in argdts):
            return None
        # string-aware structural forms the evaluator lowers BEFORE registry
        # dispatch (engine.eval.ExprCompiler._compile_call)
        if expr.fn in ("equal", "not_equal") and argdts and all(
                d in DICT_ENCODED for d in argdts):
            return DT.BOOLEAN
        if expr.fn == "select" and len(argdts) == 3 \
                and argdts[1] == DT.STRING:
            return DT.STRING
        try:
            return registry.scalar(expr.fn, argdts).out_type
        except Exception as e:
            raise PlanVerifyError(
                "unknown-udf",
                f"no scalar overload {expr.fn!r} for "
                f"{tuple(getattr(d, 'name', d) for d in argdts)}: {e}",
                op, where) from None
    return None  # unknown expr kinds stay opaque rather than failing queries


# --------------------------------------------------------------- agg checks


def _check_reduce_tree(tree, ae, op, where: str) -> None:
    if isinstance(tree, dict):
        for v in tree.values():
            _check_reduce_tree(v, ae, op, where)
        return
    if tree not in _REDUCE_OPS:
        raise PlanVerifyError(
            "not-mergeable",
            f"agg {ae.out_name!r} ({ae.fn}): reduce op {tree!r} is not one "
            f"of {sorted(_REDUCE_OPS)}", op, where)


def _check_agg_mergeable(agg: AggOp, registry, op, where: str,
                         cross_agent: bool) -> None:
    """Every value of a PARTIAL agg must have a registered combine path:
    reduce_ops() drives combine_partials AND the in-mesh psum merge, so a
    UDA without a valid reduce tree has no way back to one answer.
    `cross_agent` additionally bans dictionary-coded state — each agent's
    code space is private, so cross-agent state must merge by VALUE."""
    from pixie_tpu.udf.udf import UDA

    for ae in agg.values:
        try:
            uda = registry.uda(ae.fn)
        except Exception as e:
            raise PlanVerifyError(
                "not-mergeable",
                f"agg {ae.out_name!r}: no combine_partials path — UDA "
                f"{ae.fn!r} is not registered ({e})", op, where) from None
        try:
            tree = uda.reduce_ops()
        except Exception as e:
            raise PlanVerifyError(
                "not-mergeable",
                f"agg {ae.out_name!r} ({ae.fn}): reduce_ops() failed: {e}",
                op, where) from None
        _check_reduce_tree(tree, ae, op, where)
        finalizable = (
            type(uda).finalize_host is not UDA.finalize_host
            or getattr(uda, "device_finalize", False)
            or getattr(uda, "needs_dict", False))
        if not finalizable:
            raise PlanVerifyError(
                "not-mergeable",
                f"agg {ae.out_name!r} ({ae.fn}): no finalize path "
                "(finalize_host/finalize_device/finalize_dict)", op, where)
        if cross_agent and (uda.dict_ok or getattr(uda, "needs_dict", False)):
            raise PlanVerifyError(
                "partial-dict-agg",
                f"agg {ae.out_name!r} ({ae.fn}): dictionary-coded state "
                "cannot merge across agents' private code spaces "
                "(the planner must ship rows for this aggregate)",
                op, where)


def _agg_sig(agg: AggOp) -> tuple:
    """Identity of an agg MODULO the partial/finalize split flags and op id
    — what must agree between a channel's declared agg and its producers'."""
    return (tuple(agg.groups),
            tuple((v.out_name, v.fn, v.arg) for v in agg.values),
            bool(agg.windowed))


# ------------------------------------------------------------------ op walk


def _source_env(op, schemas: dict, registry, channel_relations,
                where: str) -> Optional[dict]:
    if isinstance(op, MemorySourceOp):
        rel = schemas.get(op.table)
        if rel is None:
            raise PlanVerifyError(
                "unknown-table",
                f"table {op.table!r} not in live schemas "
                f"{sorted(schemas)[:20]}", op, where)
        cols = op.columns if op.columns is not None else rel.names()
        env = {}
        for c in cols:
            if c not in rel:
                raise PlanVerifyError(
                    "unknown-column",
                    f"table {op.table!r} has no column {c!r} "
                    f"(has {rel.names()})", op, where)
            env[c] = rel.dtype(c)
        return env
    if isinstance(op, UDTFSourceOp):
        rel = Relation.from_dict(op.schema) if op.schema is not None else None
        if rel is None:
            try:
                rel = registry.udtf(op.name).relation
            except Exception as e:
                raise PlanVerifyError(
                    "unknown-udf",
                    f"UDTF {op.name!r} is not registered and the plan "
                    f"carries no schema: {e}", op, where) from None
        return {c.name: c.data_type for c in rel}
    if isinstance(op, RemoteSourceOp):
        if channel_relations is not None and op.channel in channel_relations:
            return channel_relations[op.channel]
        if op.schema is not None:
            return {c.name: c.data_type
                    for c in Relation.from_dict(op.schema)}
        return None  # opaque: downstream checks skip rather than guess
    return None


def verify_plan(plan: Plan, schemas: dict, registry=None,
                channel_relations: Optional[dict] = None,
                where: str = "plan") -> dict:
    """Typed dataflow pass over one plan.  Returns {op id: output env}
    where an env is {column: DataType} (or None for opaque subgraphs fed by
    channels with no declared relation).  Raises PlanVerifyError on the
    first violation.

    `schemas` maps table name → Relation; `channel_relations` maps remote
    channel id → env for merger/fragment plans whose sources are channels.
    """
    if registry is None:
        from pixie_tpu.udf import registry as registry  # noqa: PLW0127
    envs: dict[int, Optional[dict]] = {}
    for op in plan.topo_sorted():
        parents = plan.parents(op)
        penvs = [envs[p.id] for p in parents]
        env: Optional[dict]
        if not parents:
            env = _source_env(op, schemas, registry, channel_relations, where)
        elif isinstance(op, MapOp):
            env = None
            if penvs[0] is not None:
                env = {}
                for name, expr in op.exprs:
                    env[name] = _expr_dtype(expr, penvs[0], registry, op,
                                            where)
        elif isinstance(op, FilterOp):
            env = penvs[0]
            if env is not None and op.expr is not None:
                dt = _expr_dtype(op.expr, env, registry, op, where)
                if dt is not None and dt != DT.BOOLEAN:
                    raise PlanVerifyError(
                        "filter-not-boolean",
                        f"predicate types to {getattr(dt, 'name', dt)}, "
                        "expected BOOLEAN", op, where)
        elif isinstance(op, LimitOp):
            if not isinstance(op.n, int) or isinstance(op.n, bool) \
                    or op.n < 0:
                raise PlanVerifyError(
                    "bad-limit", f"limit n={op.n!r} must be a non-negative "
                    "int", op, where)
            env = penvs[0]
        elif isinstance(op, AggOp):
            env = self_env = penvs[0]
            if self_env is not None:
                env = {}
                for g in op.groups:
                    if g not in self_env:
                        raise PlanVerifyError(
                            "unknown-column",
                            f"group key {g!r} not in input relation "
                            f"{sorted(self_env)}", op, where)
                    env[g] = self_env[g]
                if op.windowed and not any(
                        self_env.get(g) in (DT.TIME64NS, DT.INT64)
                        for g in op.groups):
                    raise PlanVerifyError(
                        "windowed-agg-no-time",
                        "windowed agg has no time-typed group key "
                        f"(groups {op.groups})", op, where)
                for ae in op.values:
                    try:
                        uda = registry.uda(ae.fn)
                    except Exception as e:
                        raise PlanVerifyError(
                            "unknown-uda", f"agg {ae.out_name!r}: {e}",
                            op, where) from None
                    in_dt = None
                    if not uda.nullary:
                        if ae.arg is None or ae.arg not in self_env:
                            raise PlanVerifyError(
                                "unknown-column",
                                f"agg {ae.out_name!r} ({ae.fn}) input "
                                f"{ae.arg!r} not in relation "
                                f"{sorted(self_env)}", op, where)
                        in_dt = self_env[ae.arg]
                        if in_dt in DICT_ENCODED and not uda.dict_ok:
                            raise PlanVerifyError(
                                "dict-input-agg",
                                f"agg {ae.out_name!r}: UDA {ae.fn!r} cannot "
                                f"consume dictionary-encoded "
                                f"{in_dt.name} column {ae.arg!r}", op, where)
                    try:
                        env[ae.out_name] = uda.out_type(in_dt)
                    except Exception:
                        env[ae.out_name] = None
                if op.partial:
                    # a partial agg's state must have a combine path even
                    # in-process (SPMD mesh merge uses the same reduce tree)
                    _check_agg_mergeable(op, registry, op, where,
                                         cross_agent=False)
        elif isinstance(op, JoinOp):
            if op.how not in _JOIN_HOWS:
                raise PlanVerifyError(
                    "join-how", f"unknown join how={op.how!r}", op, where)
            if len(op.left_on) != len(op.right_on):
                raise PlanVerifyError(
                    "join-key-arity",
                    f"left_on {op.left_on} and right_on {op.right_on} "
                    "differ in length", op, where)
            lenv, renv = (penvs + [None, None])[:2]
            if lenv is not None and renv is not None:
                for lk, rk in zip(op.left_on, op.right_on):
                    if lk not in lenv:
                        raise PlanVerifyError(
                            "unknown-column", f"left join key {lk!r} not in "
                            f"{sorted(lenv)}", op, where)
                    if rk not in renv:
                        raise PlanVerifyError(
                            "unknown-column", f"right join key {rk!r} not "
                            f"in {sorted(renv)}", op, where)
                    a, b = lenv[lk], renv[rk]
                    if a != b and not (a in _JOIN_COMPAT
                                       and b in _JOIN_COMPAT):
                        raise PlanVerifyError(
                            "join-key-dtype",
                            f"key {lk!r}:{a.name} vs {rk!r}:{b.name} — "
                            "join keys must share a physical dtype",
                            op, where)
                env = {}
                for side, col, out_name in op.output:
                    src = lenv if side == "left" else (
                        renv if side == "right" else None)
                    if src is None:
                        raise PlanVerifyError(
                            "join-output", f"output side {side!r} is not "
                            "left/right", op, where)
                    if col not in src:
                        raise PlanVerifyError(
                            "join-output",
                            f"output {out_name!r} references missing "
                            f"{side} column {col!r}", op, where)
                    env[out_name] = src[col]
                if not op.output:
                    env = {**renv, **lenv}
            else:
                env = None
        elif isinstance(op, UnionOp):
            known = [e for e in penvs if e is not None]
            for e in known[1:]:
                if e != known[0]:
                    raise PlanVerifyError(
                        "union-schema",
                        f"parents disagree: {sorted(known[0].items())} vs "
                        f"{sorted(e.items())}", op, where)
            env = known[0] if len(known) == len(penvs) and known else None
        elif isinstance(op, ResultSinkOp):
            if op.payload == "agg_state":
                if len(parents) != 1 or not isinstance(parents[0], AggOp) \
                        or not parents[0].partial:
                    raise PlanVerifyError(
                        "agg-state-sink",
                        "agg_state sink must be fed by AggOp(partial=True), "
                        f"got {parents[0].kind if parents else 'nothing'}",
                        op, where)
            env = penvs[0] if penvs else None
        elif isinstance(op, PartitionSinkOp):
            if op.n_parts < 1:
                raise PlanVerifyError(
                    "bad-limit", f"n_parts={op.n_parts} must be >= 1",
                    op, where)
            env = penvs[0] if penvs else None
            if env is not None:
                for k in op.keys:
                    if k not in env:
                        raise PlanVerifyError(
                            "unknown-column",
                            f"partition key {k!r} not in relation "
                            f"{sorted(env)}", op, where)
        elif isinstance(op, MemorySinkOp):
            env = penvs[0] if penvs else None
            if env is not None and op.columns:
                for c in op.columns:
                    if c not in env:
                        raise PlanVerifyError(
                            "unknown-column",
                            f"sink column {c!r} not in relation "
                            f"{sorted(env)}", op, where)
        elif isinstance(op, OTelExportSinkOp):
            env = penvs[0] if penvs else None
        else:  # unknown op kinds pass their parent's env through
            env = penvs[0] if penvs else None
        envs[op.id] = env
    return envs


# ------------------------------------------------------- distributed checks


def _sink_parent_env(plan: Plan, sink, envs: dict):
    parents = plan.parents(sink)
    return envs.get(parents[0].id) if parents else None


def _fragment_sig(plan: Plan, sink) -> str:
    """Content signature of the single-parent chain feeding `sink` (op
    dicts minus runtime ids) — what all producers of one channel must agree
    on, and what the matview registry's prefix canonicalization is a
    function of."""
    import json as _json

    sigs = []
    cur = sink
    while True:
        d = cur.to_dict()
        d.pop("id", None)
        sigs.append(d)
        ps = plan.parents(cur)
        if len(ps) != 1:
            sigs.append({"parents": len(ps)})
            break
        cur = ps[0]
    return _json.dumps(sigs, sort_keys=True, default=str)


def verify_distributed(dp, schemas: dict, registry=None) -> None:
    """Verify a DistributedPlan end to end: every agent fragment, the
    channel topology, cross-producer shuffle consistency, join-stage
    partition counts, matview prefix agreement, and the merger plan (fed
    the channel relations its producers actually ship)."""
    if registry is None:
        from pixie_tpu.udf import registry as registry  # noqa: PLW0127
    agent_envs: dict[str, dict] = {}
    #: channel id -> {agent: env shipped on that channel}
    produced: dict[str, dict] = {}
    #: channel id -> {agent: the partial AggOp the producer runs}
    produced_agg: dict[str, dict] = {}
    for name, plan in dp.agent_plans.items():
        envs = verify_plan(plan, schemas, registry, where=f"agent {name}")
        agent_envs[name] = envs
        for op in plan.ops():
            if isinstance(op, ResultSinkOp):
                if op.channel not in dp.channels:
                    raise PlanVerifyError(
                        "unknown-channel",
                        f"sink ships to undeclared channel {op.channel!r}",
                        op, f"agent {name}")
                produced.setdefault(op.channel, {})[name] = \
                    _sink_parent_env(plan, op, envs)
                if op.payload == "agg_state":
                    produced_agg.setdefault(op.channel, {})[name] = \
                        plan.parents(op)[0]
            elif isinstance(op, PartitionSinkOp):
                env = envs.get(plan.parents(op)[0].id) if plan.parents(op) \
                    else None
                for i in range(op.n_parts):
                    cid = f"{op.prefix}{i}"
                    if cid not in dp.channels:
                        raise PlanVerifyError(
                            "missing-bucket-channel",
                            f"partition bucket channel {cid!r} is not "
                            "declared", op, f"agent {name}")
                    produced.setdefault(cid, {})[name] = env

    # ---- join stages: shard-axis consistency across the exchange
    stage_out_env: dict[str, Optional[dict]] = {}
    for si, stage in enumerate(getattr(dp, "join_stages", None) or []):
        where = f"join stage {si}"
        side_env: dict[str, Optional[dict]] = {}
        for chan_name, prefix in (("left", stage.left_prefix),
                                  ("right", stage.right_prefix)):
            envs_seen = []
            for name, plan in dp.agent_plans.items():
                for op in plan.ops():
                    if isinstance(op, PartitionSinkOp) \
                            and op.prefix == prefix:
                        if op.n_parts != stage.n_parts:
                            raise PlanVerifyError(
                                "partition-count-mismatch",
                                f"agent {name} partitions {prefix!r} "
                                f"{op.n_parts}-way but the stage joins "
                                f"{stage.n_parts} partitions", op, where)
                        ps = plan.parents(op)
                        envs_seen.append(
                            agent_envs[name].get(ps[0].id) if ps else None)
            if not envs_seen:
                raise PlanVerifyError(
                    "partition-count-mismatch",
                    f"no producer partitions prefix {prefix!r}", None, where)
            known = [e for e in envs_seen if e is not None]
            side_env[chan_name] = known[0] if len(known) == len(envs_seen) \
                and known else None
        # stage output channels are synthesized by run_join_stages (they
        # are not declared Channels); their relation feeds the merger below
        frag_envs = verify_plan(
            stage.fragment, schemas, registry,
            channel_relations={stage.left_channel: side_env["left"],
                               stage.right_channel: side_env["right"]},
            where=where)
        for op in stage.fragment.ops():
            if isinstance(op, ResultSinkOp):
                stage_out_env[op.channel] = \
                    _sink_parent_env(stage.fragment, op, frag_envs)

    # ---- channels: producers exist, relations agree, aggs are mergeable
    channel_relations: dict[str, Optional[dict]] = {}
    for cid, ch in dp.channels.items():
        where = f"channel {cid}"
        if not ch.producers:
            raise PlanVerifyError(
                "unknown-producer", "channel has no producers", None, where)
        for p in ch.producers:
            if p not in dp.agent_plans:
                raise PlanVerifyError(
                    "unknown-producer",
                    f"producer {p!r} has no agent plan", None, where)
        by_agent = produced.get(cid, {})
        known = [(a, e) for a, e in sorted(by_agent.items())
                 if e is not None]
        for a, e in known[1:]:
            if e != known[0][1]:
                raise PlanVerifyError(
                    "shuffle-schema-mismatch",
                    f"producer {known[0][0]!r} ships "
                    f"{sorted(known[0][1].items())} but {a!r} ships "
                    f"{sorted(e.items())} — all producers of a channel "
                    "must agree on one relation", None, where)
        env = known[0][1] if known and len(known) == len(by_agent) else None
        if ch.kind == "agg_state":
            if ch.agg is None:
                raise PlanVerifyError(
                    "channel-agg-mismatch",
                    "agg_state channel carries no agg spec", None, where)
            _check_agg_mergeable(ch.agg, registry, None, where,
                                 cross_agent=True)
            for a, pagg in sorted(produced_agg.get(cid, {}).items()):
                if _agg_sig(pagg) != _agg_sig(ch.agg):
                    raise PlanVerifyError(
                        "channel-agg-mismatch",
                        f"producer {a!r} computes partial agg "
                        f"{_agg_sig(pagg)} but the merger finalizes "
                        f"{_agg_sig(ch.agg)}", pagg, where)
            # broker-side matview matcher and agent-side maintainers key
            # standing state off the SAME canonicalized prefix, and every
            # producer's fragment is a clone of ONE logical subgraph.
            # Divergent fragment content (a filter constant, a map expr —
            # invisible to dtype checks) means producers answer different
            # questions under one channel: the stale-matview miscompile.
            sigs = {}
            for p in ch.producers:
                plan = dp.agent_plans.get(p)
                if plan is None:
                    continue
                for op in plan.ops():
                    if isinstance(op, ResultSinkOp) and op.channel == cid:
                        sigs[p] = _fragment_sig(plan, op)
            uniq = set(sigs.values())
            if len(uniq) > 1:
                by_sig: dict = {}
                for p, s in sigs.items():
                    by_sig.setdefault(s, []).append(p)
                raise PlanVerifyError(
                    "matview-prefix-divergence",
                    f"producers of one agg_state channel compute "
                    f"{len(uniq)} distinct fragments "
                    f"({sorted(sorted(v) for v in by_sig.values())}) — "
                    "their standing-view prefixes cannot agree", None,
                    where)
            # what the MERGER receives on this channel is the finalized
            # relation — identical to the partial agg's output env (group
            # key dtypes + each UDA's declared out_type)
            channel_relations[cid] = env
        else:
            channel_relations[cid] = env
    channel_relations.update(stage_out_env)

    verify_plan(dp.merger_plan, schemas, registry,
                channel_relations=channel_relations, where="merger")


# -------------------------------------------------- fused multi-query form


def verify_fused_batch(dp, sink_map: dict) -> None:
    """The fused multi-query (batched) plan form — ran ON TOP of
    verify_distributed for a batch's merged split, once per batch signature
    (it rides the fused split cache, so warm batches pay zero
    re-verification).

    Per-slot invariants (each member query is one slot, its sinks renamed
    `q{slot}/{name}` by plan fusion):

      batch-slot-missing-sink   every slot sink the demux will read exists
          exactly once in the merger plan — a slot whose output was lost
          (or duplicated) in fusion would silently answer the wrong member
      batch-slot-overlap        two slots never claim the same fused sink —
          demux by prefix must partition the result set

    Per-slot schema flow and partial-agg mergeability need no extra pass:
    verify_distributed already types every fused chain op-by-op and checks
    each agg_state channel's combine/finalize path — the fused plan IS a
    plan."""
    sinks = [op.name for op in dp.merger_plan.ops()
             if isinstance(op, MemorySinkOp)]
    counts: dict[str, int] = {}
    for n in sinks:
        counts[n] = counts.get(n, 0) + 1
    claimed: dict[str, str] = {}
    for prefix, m in sorted(sink_map.items()):
        for orig, fused_name in sorted(m.items()):
            if counts.get(fused_name, 0) != 1:
                raise PlanVerifyError(
                    "batch-slot-missing-sink",
                    f"slot {prefix!r} output {orig!r} maps to fused sink "
                    f"{fused_name!r} which appears "
                    f"{counts.get(fused_name, 0)}x in the merger plan",
                    where=f"batch slot {prefix}")
            other = claimed.get(fused_name)
            if other is not None and other != prefix:
                raise PlanVerifyError(
                    "batch-slot-overlap",
                    f"fused sink {fused_name!r} claimed by slots "
                    f"{other!r} and {prefix!r}",
                    where=f"batch slot {prefix}")
            claimed[fused_name] = prefix


def maybe_verify_fused_batch(dp, sink_map: dict) -> None:
    """verify_fused_batch under the PX_PLAN_VERIFY flag (callers run
    maybe_verify on the merged split first, inside the same cache fill)."""
    if not enabled():
        return
    verify_fused_batch(dp, sink_map)


# ------------------------------------------------------------ dispatch hook


def maybe_verify(dp, schemas: dict, registry=None) -> None:
    """The pre-dispatch hook (broker / LocalCluster): verify a freshly
    computed split under the PX_PLAN_VERIFY flag.  Callers skip this for
    split-cache hits — a cached split was verified when computed, which is
    what makes warm-query re-verification zero-cost."""
    if not enabled():
        return
    from pixie_tpu import metrics as _metrics
    from pixie_tpu import trace

    with trace.span("plan_verify"):
        try:
            verify_distributed(dp, schemas, registry)
        except PlanVerifyError:
            _metrics.counter_inc(
                "px_plan_verify_failures_total",
                help_="compiled plans rejected by pre-dispatch verification")
            raise
        _metrics.counter_inc(
            "px_plan_verify_total",
            help_="distributed splits verified before dispatch")

from pixie_tpu.metadata.state import (
    ContainerInfo,
    K8sSnapshot,
    MetadataStateManager,
    PodInfo,
    ServiceInfo,
    global_manager,
    set_global_manager,
    snapshot,
)
from pixie_tpu.metadata.funcs import CTX_KEYS, register_metadata_funcs

__all__ = [
    "ContainerInfo",
    "K8sSnapshot",
    "MetadataStateManager",
    "PodInfo",
    "ServiceInfo",
    "global_manager",
    "set_global_manager",
    "snapshot",
    "CTX_KEYS",
    "register_metadata_funcs",
]

"""K8s metadata state — per-agent view of cluster objects.

Parity with the reference's AgentMetadataState (src/shared/metadata/
metadata_state.h, k8s_objects.h): pods/services/namespaces/containers plus the
PID→UPID and IP→pod indexes that the metadata UDFs consult.  The TPU twist is
*where* it is read: the reference resolves metadata per row inside UDF Exec
loops; here the resolution happens host-side over UPID/string dictionary values
only (O(unique), see pixie_tpu/table/dictionary.py), so this state never needs
to be device-resident.

Updates arrive as ResourceUpdate-like dicts (reference
src/shared/k8s/metadatapb/metadata.proto) and are applied copy-on-write: readers
grab an immutable snapshot via `current()`; a swap publishes the next epoch
(reference state_manager.h:84 PerformMetadataStateUpdate's atomic swap).
"""
from __future__ import annotations

import dataclasses
import threading
from typing import Optional

from pixie_tpu.types import UInt128


@dataclasses.dataclass(frozen=True)
class PodInfo:
    uid: str
    name: str
    namespace: str
    node: str = ""
    ip: str = ""
    phase: str = "RUNNING"
    labels: str = ""
    create_time_ns: int = 0
    stop_time_ns: int = 0
    owner_deployment: str = ""
    qos_class: str = ""  # Guaranteed | Burstable | BestEffort

    @property
    def qualified_name(self) -> str:
        return f"{self.namespace}/{self.name}"


@dataclasses.dataclass(frozen=True)
class ServiceInfo:
    uid: str
    name: str
    namespace: str
    cluster_ip: str = ""
    external_ips: tuple = ()

    @property
    def qualified_name(self) -> str:
        return f"{self.namespace}/{self.name}"


@dataclasses.dataclass(frozen=True)
class ContainerInfo:
    cid: str
    name: str
    pod_uid: str
    state: str = "RUNNING"
    start_time_ns: int = 0
    stop_time_ns: int = 0


@dataclasses.dataclass(frozen=True)
class K8sSnapshot:
    """Immutable metadata epoch. All maps are plain dicts, never mutated."""

    asid: int = 0
    pods_by_uid: dict = dataclasses.field(default_factory=dict)
    services_by_uid: dict = dataclasses.field(default_factory=dict)
    containers_by_id: dict = dataclasses.field(default_factory=dict)
    upid_to_pod_uid: dict = dataclasses.field(default_factory=dict)
    upid_to_container_id: dict = dataclasses.field(default_factory=dict)
    upid_to_cmdline: dict = dataclasses.field(default_factory=dict)
    ip_to_pod_uid: dict = dataclasses.field(default_factory=dict)
    ip_to_service_uid: dict = dataclasses.field(default_factory=dict)
    pod_uid_to_service_uids: dict = dataclasses.field(default_factory=dict)
    #: qualified ("ns/name") AND bare name → uid reverse indexes, so per-dict-value
    #: UDF lookups are O(1) instead of scanning all pods per unique string.
    pod_name_to_uid: dict = dataclasses.field(default_factory=dict)
    service_name_to_uid: dict = dataclasses.field(default_factory=dict)
    #: container name → cid.  Names duplicate across pods (sidecars); the
    #: qualified "pod_uid/name" key disambiguates, bare name keeps
    #: last-writer (documented ambiguity of the bare lookup).
    container_name_to_cid: dict = dataclasses.field(default_factory=dict)
    dns: dict = dataclasses.field(default_factory=dict)  # ip -> hostname
    node_name: str = ""

    # ------------------------------------------------------------- resolution
    def pod_of_upid(self, upid: UInt128) -> Optional[PodInfo]:
        uid = self.upid_to_pod_uid.get(upid)
        return self.pods_by_uid.get(uid) if uid else None

    def service_of_upid(self, upid: UInt128) -> Optional[ServiceInfo]:
        uid = self.upid_to_pod_uid.get(upid)
        if not uid:
            return None
        suids = self.pod_uid_to_service_uids.get(uid, ())
        for s in suids:
            svc = self.services_by_uid.get(s)
            if svc:
                return svc
        return None

    def pod_of_ip(self, ip: str) -> Optional[PodInfo]:
        uid = self.ip_to_pod_uid.get(ip)
        return self.pods_by_uid.get(uid) if uid else None

    def service_of_ip(self, ip: str) -> Optional[ServiceInfo]:
        uid = self.ip_to_service_uid.get(ip)
        return self.services_by_uid.get(uid) if uid else None

    def nslookup(self, ip: str) -> str:
        pod = self.pod_of_ip(ip)
        if pod:
            return pod.qualified_name
        svc = self.service_of_ip(ip)
        if svc:
            return svc.qualified_name
        return self.dns.get(ip, ip)


class MetadataStateManager:
    """Copy-on-write holder of the current K8sSnapshot (reference
    AgentMetadataStateManager, state_manager.h:60-139)."""

    def __init__(self, asid: int = 0, node_name: str = ""):
        self._lock = threading.Lock()
        self._snap = K8sSnapshot(asid=asid, node_name=node_name)
        self.epoch = 0

    def current(self) -> K8sSnapshot:
        return self._snap

    def apply_updates(self, updates: list[dict]) -> None:
        """Apply a batch of resource updates and publish a new epoch.

        Update kinds mirror metadata.proto ResourceUpdate: pod, service,
        container, process (upid binding), dns.
        """
        with self._lock:
            s = self._snap
            pods = dict(s.pods_by_uid)
            svcs = dict(s.services_by_uid)
            ctrs = dict(s.containers_by_id)
            upid_pod = dict(s.upid_to_pod_uid)
            upid_ctr = dict(s.upid_to_container_id)
            upid_cmd = dict(s.upid_to_cmdline)
            ip_pod = dict(s.ip_to_pod_uid)
            ip_svc = dict(s.ip_to_service_uid)
            pod_svc = dict(s.pod_uid_to_service_uids)
            pod_names = dict(s.pod_name_to_uid)
            svc_names = dict(s.service_name_to_uid)
            ctr_names = dict(s.container_name_to_cid)
            dns = dict(s.dns)
            for u in updates:
                kind = u["kind"]
                if kind == "pod":
                    p = PodInfo(**{k: v for k, v in u.items() if k != "kind"})
                    pods[p.uid] = p
                    if p.ip:
                        ip_pod[p.ip] = p.uid
                    pod_names[p.qualified_name] = p.uid
                    pod_names[p.name] = p.uid
                elif kind == "service":
                    sv = ServiceInfo(**{k: v for k, v in u.items() if k not in ("kind", "pod_uids")})
                    svcs[sv.uid] = sv
                    if sv.cluster_ip:
                        ip_svc[sv.cluster_ip] = sv.uid
                    svc_names[sv.qualified_name] = sv.uid
                    svc_names[sv.name] = sv.uid
                    for puid in u.get("pod_uids", ()):
                        pod_svc[puid] = tuple(set(pod_svc.get(puid, ())) | {sv.uid})
                elif kind == "container":
                    c = ContainerInfo(**{k: v for k, v in u.items() if k != "kind"})
                    ctrs[c.cid] = c
                    ctr_names[c.name] = c.cid
                    ctr_names[f"{c.pod_uid}/{c.name}"] = c.cid
                elif kind == "process":
                    upid = u["upid"]
                    if not isinstance(upid, UInt128):
                        upid = UInt128(*upid)
                    if "pod_uid" in u:
                        upid_pod[upid] = u["pod_uid"]
                    if "container_id" in u:
                        upid_ctr[upid] = u["container_id"]
                    if "cmdline" in u:
                        upid_cmd[upid] = u["cmdline"]
                elif kind == "dns":
                    dns[u["ip"]] = u["hostname"]
                else:
                    raise ValueError(f"unknown resource update kind {kind!r}")
            self._snap = K8sSnapshot(
                asid=s.asid,
                pods_by_uid=pods,
                services_by_uid=svcs,
                containers_by_id=ctrs,
                container_name_to_cid=ctr_names,
                upid_to_pod_uid=upid_pod,
                upid_to_container_id=upid_ctr,
                upid_to_cmdline=upid_cmd,
                ip_to_pod_uid=ip_pod,
                ip_to_service_uid=ip_svc,
                pod_uid_to_service_uids=pod_svc,
                pod_name_to_uid=pod_names,
                service_name_to_uid=svc_names,
                dns=dns,
                node_name=s.node_name,
            )
            self.epoch += 1


# Process-global manager, swapped in by the agent at startup; tests install
# their own fixture state (reference: ExecState carries the metadata state into
# UDF evaluation — ours is ambient because host UDF eval is single-process).
_manager = MetadataStateManager()


def global_manager() -> MetadataStateManager:
    return _manager


def set_global_manager(m: MetadataStateManager) -> None:
    global _manager
    _manager = m


def snapshot() -> K8sSnapshot:
    return _manager.current()

"""ResourceUpdate watch feed: apply externally-produced metadata updates.

Reference: the k8s watcher → ResourceUpdate fanout
(src/vizier/services/metadata/controllers/k8smeta/k8s_metadata_handler.go:
139-157 publishes watch deltas over NATS; each agent's
AgentMetadataStateManager applies them).  Here the feed is a JSONL file
(tailed incrementally — a kubectl-watch shim, an operator, or a test writes
it) or any iterable of update dicts; apply is the same
MetadataStateManager.apply_updates epoch swap either way.
"""
from __future__ import annotations

import json
import os
from typing import Optional

from pixie_tpu.types import UInt128


def _decode_update(u: dict) -> dict:
    if u.get("kind") == "process" and not isinstance(u.get("upid"), UInt128):
        v = u.get("upid")
        if isinstance(v, (list, tuple)) and len(v) == 2:
            u = {**u, "upid": UInt128(int(v[0]), int(v[1]))}
    return u


class ResourceUpdateFeed:
    """Tails a JSONL file of ResourceUpdates into a MetadataStateManager."""

    def __init__(self, manager, path: str):
        self.manager = manager
        self.path = path
        self._offset = 0
        self._partial = b""
        self.applied = 0
        self.errors = 0

    def poll(self) -> int:
        """Apply any new complete lines; returns updates applied."""
        try:
            size = os.path.getsize(self.path)
        except OSError:
            return 0
        if size < self._offset:  # truncated/rotated: start over
            self._offset = 0
            self._partial = b""
        if size == self._offset:
            return 0
        with open(self.path, "rb") as f:
            f.seek(self._offset)
            chunk = f.read()
            self._offset = f.tell()
        data = self._partial + chunk
        lines = data.split(b"\n")
        self._partial = lines.pop()  # incomplete tail (or empty)
        applied = 0
        for line in lines:
            line = line.strip()
            if not line:
                continue
            # apply per update: one malformed line must not abort (and
            # permanently lose — the offset already advanced) a whole batch
            try:
                self.manager.apply_updates([_decode_update(json.loads(line))])
                applied += 1
            except Exception:
                self.errors += 1
        self.applied += applied
        return applied


def apply_updates_json(manager, updates: list[dict]) -> None:
    """Apply a batch of wire-form (JSON-safe) updates."""
    manager.apply_updates([_decode_update(u) for u in updates])

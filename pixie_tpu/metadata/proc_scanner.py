"""/proc-scanning PID→UPID tracker.

Reference: src/shared/metadata/pids.cc (PID start-time from /proc/<pid>/stat
makes the UPID unique across pid reuse) + cgroup_metadata_reader.cc (the
cgroup path names the pod uid, binding a live process to its k8s pod).

The scanner feeds `process` ResourceUpdates into the MetadataStateManager so
metadata UDFs (`ctx['pod']`, upid_to_cmdline, ...) resolve for REAL local
processes — the same UPIDs the tap/tracer stamps on traffic, because both
derive the start time from the same /proc field.
"""
from __future__ import annotations

import os
import re
from typing import Callable, Optional

from pixie_tpu.types import UInt128

_POD_RE = re.compile(r"pod([0-9a-fA-F]{8}[-_][0-9a-fA-F]{4}[-_][0-9a-fA-F]{4}"
                     r"[-_][0-9a-fA-F]{4}[-_][0-9a-fA-F]{12})")


def _boot_time_ns(proc: str = "/proc") -> int:
    with open(os.path.join(proc, "stat")) as f:
        for line in f:
            if line.startswith("btime "):
                return int(line.split()[1]) * 1_000_000_000
    return 0


def pid_start_time_ns(pid: int, proc: str = "/proc",
                      _cache: dict = {}) -> int:
    """Monotonic-unique process start time in ns since epoch (reference
    pids.cc: /proc/<pid>/stat field 22, clock ticks since boot)."""
    key = ("boot", proc)
    if key not in _cache:
        _cache[key] = (_boot_time_ns(proc), os.sysconf("SC_CLK_TCK"))
    boot_ns, hz = _cache[key]
    try:
        with open(os.path.join(proc, str(pid), "stat"), "rb") as f:
            raw = f.read().decode("latin-1")
    except OSError:
        return 0
    # comm (field 2) may contain spaces/parens: fields resume after the LAST
    # ')'; starttime is overall field 22 → index 19 of the remainder.
    rest = raw.rsplit(")", 1)[-1].split()
    if len(rest) < 20:
        return 0
    ticks = int(rest[19])
    return boot_ns + ticks * 1_000_000_000 // hz


def pid_cmdline(pid: int, proc: str = "/proc") -> str:
    try:
        with open(os.path.join(proc, str(pid), "cmdline"), "rb") as f:
            return f.read().replace(b"\x00", b" ").decode(
                "utf-8", "replace").strip()
    except OSError:
        return ""


def pid_pod_uid(pid: int, proc: str = "/proc") -> Optional[str]:
    """Pod uid from the process's cgroup path (reference
    cgroup_metadata_reader.cc: .../pod<uid>/<container-id>/...)."""
    try:
        with open(os.path.join(proc, str(pid), "cgroup")) as f:
            text = f.read()
    except OSError:
        return None
    m = _POD_RE.search(text)
    return m.group(1).replace("_", "-") if m else None


class ProcScanner:
    """Periodically scans /proc and binds live PIDs to UPIDs (+pods).

    `classifier(pid, cmdline) -> pod_uid | None` supplements the cgroup
    reader for non-k8s hosts (tests, bare-metal demos): whatever it returns
    binds the process to that pod in the metadata state.
    """

    def __init__(self, asid: int = 0, proc: str = "/proc",
                 classifier: Optional[Callable[[int, str],
                                               Optional[str]]] = None):
        self.asid = asid
        self.proc = proc
        self.classifier = classifier
        self.last_scanned = 0
        #: previous scan's applied updates, keyed by upid — periodic scans
        #: only re-apply CHANGED bindings so an idle system doesn't bump the
        #: metadata epoch (which would invalidate every epoch-keyed kernel
        #: cache) every period.  Exited PIDs' entries linger in the state
        #: (the reference also keeps terminated UPIDs resolvable for a
        #: retention window; rows referencing them still need names).
        self._prev: dict = {}

    def upid_of(self, pid: int) -> UInt128:
        return UInt128.make_upid(self.asid, pid,
                                 pid_start_time_ns(pid, self.proc))

    def scan_updates(self) -> list[dict]:
        """One full scan → `process` ResourceUpdates for every live PID."""
        updates = []
        try:
            pids = [int(d) for d in os.listdir(self.proc) if d.isdigit()]
        except OSError:
            return updates
        for pid in pids:
            start = pid_start_time_ns(pid, self.proc)
            if start == 0:
                continue  # raced exit
            cmd = pid_cmdline(pid, self.proc)
            u = {"kind": "process",
                 "upid": UInt128.make_upid(self.asid, pid, start),
                 "cmdline": cmd or f"[pid {pid}]"}
            pod = pid_pod_uid(pid, self.proc)
            if pod is None and self.classifier is not None:
                pod = self.classifier(pid, cmd)
            if pod is not None:
                u["pod_uid"] = pod
            updates.append(u)
        self.last_scanned = len(updates)
        return updates

    def scan_into(self, manager) -> int:
        """Scan and apply CHANGED bindings to a MetadataStateManager;
        returns updates applied."""
        updates = self.scan_updates()
        fresh = {}
        changed = []
        for u in updates:
            key = u["upid"]
            fresh[key] = (u.get("pod_uid"), u.get("cmdline"))
            if self._prev.get(key) != fresh[key]:
                changed.append(u)
        self._prev = fresh
        if changed:
            manager.apply_updates(changed)
        return len(changed)

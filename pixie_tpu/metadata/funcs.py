"""Metadata scalar UDFs (reference src/carnot/funcs/metadata/metadata_ops.h).

All are *host* UDFs: they evaluate over dictionary values (unique UPIDs /
strings), never over rows — see pixie_tpu/engine/eval.py host-call path.  Each
resolves against the current K8sSnapshot at query-compile time, which matches
the reference's semantics of resolving against the agent's metadata state at
execution time (state is epoch-swapped; a query sees one epoch).
"""
from __future__ import annotations

from pixie_tpu.metadata import state as mdstate
from pixie_tpu.types import DataType as DT, SemanticType as ST
from pixie_tpu.types import UInt128
from pixie_tpu.udf.udf import Registry, ScalarUDF

_S = DT.STRING
_U = DT.UINT128
_I = DT.INT64

#: declared output semantic types (reference metadata_ops.h declares these on
#: each UDF's ExecOutputType) — drives entity-aware result formatting
_OUT_STS = {
    "upid_to_pod_name": ST.ST_POD_NAME,
    "pod_id_to_pod_name": ST.ST_POD_NAME,
    "upid_to_namespace": ST.ST_NAMESPACE_NAME,
    "pod_id_to_namespace": ST.ST_NAMESPACE_NAME,
    "pod_name_to_namespace": ST.ST_NAMESPACE_NAME,
    "upid_to_node_name": ST.ST_NODE_NAME,
    "pod_id_to_node_name": ST.ST_NODE_NAME,
    "upid_to_hostname": ST.ST_NODE_NAME,
    "upid_to_service_name": ST.ST_SERVICE_NAME,
    "pod_id_to_service_name": ST.ST_SERVICE_NAME,
    "pod_name_to_service_name": ST.ST_SERVICE_NAME,
    "service_id_to_service_name": ST.ST_SERVICE_NAME,
    "ip_to_svc_name": ST.ST_SERVICE_NAME,
    "ip_to_service_name": ST.ST_SERVICE_NAME,
    "upid_to_container_name": ST.ST_CONTAINER_NAME,
    "container_id_to_status": ST.ST_CONTAINER_STATUS,
    "upid_to_pod_status": ST.ST_POD_STATUS,
    "pod_name_to_pod_status": ST.ST_POD_STATUS,
    "pod_name_to_status": ST.ST_POD_STATUS,
    "pod_name_to_pod_ip": ST.ST_IP_ADDRESS,
    "pod_name_to_start_time": ST.ST_TIME_NS,
}


def _pod(upid: UInt128):
    return mdstate.snapshot().pod_of_upid(upid)


def _svc(upid: UInt128):
    return mdstate.snapshot().service_of_upid(upid)


def _host(name, args, out, fn, volatile=True):
    # volatile: fns reading the ambient K8sSnapshot bake stale LUTs into
    # cached kernels when the metadata epoch advances (the cache signature
    # includes the epoch for chains that call them).  Pure fns (upid field
    # extractors, string splitters) pass volatile=False so epoch churn does
    # not force needless re-jits.
    return ScalarUDF(name=name, arg_types=args, out_type=out, fn=fn, device=False,
                     volatile=volatile, out_st=_OUT_STS.get(name))


def register_metadata_funcs(r: Registry) -> None:
    # ---- upid_to_* (reference metadata_ops.h UPIDTo*UDF)
    r.register(_host("upid_to_pod_name", (_U,), _S,
                     lambda u: (_pod(u).qualified_name if _pod(u) else "")))
    r.register(_host("upid_to_pod_id", (_U,), _S,
                     lambda u: (_pod(u).uid if _pod(u) else "")))
    r.register(_host("upid_to_namespace", (_U,), _S,
                     lambda u: (_pod(u).namespace if _pod(u) else "")))
    r.register(_host("upid_to_node_name", (_U,), _S,
                     lambda u: (_pod(u).node if _pod(u) else "")))
    r.register(_host("upid_to_service_name", (_U,), _S,
                     lambda u: (_svc(u).qualified_name if _svc(u) else "")))
    r.register(_host("upid_to_service_id", (_U,), _S,
                     lambda u: (_svc(u).uid if _svc(u) else "")))
    r.register(_host("upid_to_container_id", (_U,), _S,
                     lambda u: mdstate.snapshot().upid_to_container_id.get(u, "")))
    r.register(_host("upid_to_container_name", (_U,), _S, _upid_to_container_name))
    r.register(_host("upid_to_deployment_name", (_U,), _S,
                     lambda u: (_pod(u).owner_deployment if _pod(u) else "")))
    r.register(_host("upid_to_cmdline", (_U,), _S,
                     lambda u: mdstate.snapshot().upid_to_cmdline.get(u, "")))
    r.register(_host("upid_to_pid", (_U,), _I, lambda u: u.pid, volatile=False))
    r.register(_host("upid_to_asid", (_U,), _I, lambda u: u.asid, volatile=False))
    r.register(_host("upid_to_string", (_U,), _S, str, volatile=False))

    # ---- pod/service/ip lookups
    r.register(_host("pod_id_to_pod_name", (_S,), _S,
                     lambda uid: _qname(mdstate.snapshot().pods_by_uid.get(uid))))
    r.register(_host("pod_id_to_namespace", (_S,), _S,
                     lambda uid: _attr(mdstate.snapshot().pods_by_uid.get(uid), "namespace")))
    r.register(_host("pod_id_to_node_name", (_S,), _S,
                     lambda uid: _attr(mdstate.snapshot().pods_by_uid.get(uid), "node")))
    r.register(_host("pod_id_to_service_name", (_S,), _S, _pod_id_to_service_name))
    r.register(_host("pod_name_to_pod_id", (_S,), _S, _pod_name_to_pod_id))
    r.register(_host("pod_name_to_namespace", (_S,), _S, _qn_namespace,
                     volatile=False))
    r.register(_host("pod_name_to_service_name", (_S,), _S,
                     lambda qn: _pod_id_to_service_name(_pod_name_to_pod_id(qn))))
    r.register(_host("pod_name_to_pod_status", (_S,), _S,
                     lambda qn: _attr(mdstate.snapshot().pods_by_uid.get(_pod_name_to_pod_id(qn)), "phase")))
    r.register(_host("pod_name_to_pod_ip", (_S,), _S,
                     lambda qn: _attr(mdstate.snapshot().pods_by_uid.get(_pod_name_to_pod_id(qn)), "ip")))
    r.register(_host("service_id_to_service_name", (_S,), _S,
                     lambda uid: _qname(mdstate.snapshot().services_by_uid.get(uid))))
    r.register(_host("service_name_to_service_id", (_S,), _S, _service_name_to_service_id))
    r.register(_host("ip_to_pod_id", (_S,), _S,
                     lambda ip: _attr(mdstate.snapshot().pod_of_ip(ip), "uid")))
    r.register(_host("ip_to_svc_name", (_S,), _S,
                     lambda ip: _qname(mdstate.snapshot().service_of_ip(ip))))
    r.register(_host("nslookup", (_S,), _S, lambda ip: mdstate.snapshot().nslookup(ip)))
    r.register(_host("pod_name_to_start_time", (_S,), DT.TIME64NS,
                     lambda qn: _attr(mdstate.snapshot().pods_by_uid.get(_pod_name_to_pod_id(qn)),
                                      "create_time_ns", 0)))
    # Aliases + remaining lookups the bundled scripts call
    # (reference metadata_ops.h PodNameToPodStatusUDF, IPToServiceIDUDF,
    # ContainerIDToContainerStatusUDF, ServiceIDToClusterIPUDF).
    r.register(_host("pod_name_to_status", (_S,), _S,
                     lambda qn: _attr(mdstate.snapshot().pods_by_uid.get(_pod_name_to_pod_id(qn)), "phase")))
    r.register(_host("ip_to_service_id", (_S,), _S,
                     lambda ip: _attr(mdstate.snapshot().service_of_ip(ip), "uid")))
    r.register(_host("ip_to_service_name", (_S,), _S,
                     lambda ip: _qname(mdstate.snapshot().service_of_ip(ip))))
    r.register(_host("container_id_to_status", (_S,), _S,
                     lambda cid: _attr(mdstate.snapshot().containers_by_id.get(cid), "state")))

    # ---- remaining reference lookup set (metadata_ops.h: start/stop times,
    # qos/status, hostname, service ids/ips, container name index)
    r.register(_host("upid_to_pod_status", (_U,), _S,
                     lambda u: _attr(_pod(u), "phase")))
    r.register(_host("upid_to_pod_qos", (_U,), _S,
                     lambda u: _attr(_pod(u), "qos_class")))
    r.register(_host("upid_to_hostname", (_U,), _S,
                     lambda u: _attr(_pod(u), "node")))
    r.register(_host("pod_id_to_start_time", (_S,), DT.TIME64NS,
                     lambda uid: _attr(mdstate.snapshot().pods_by_uid.get(uid), "create_time_ns", 0)))
    r.register(_host("pod_id_to_stop_time", (_S,), DT.TIME64NS,
                     lambda uid: _attr(mdstate.snapshot().pods_by_uid.get(uid), "stop_time_ns", 0)))
    r.register(_host("pod_name_to_stop_time", (_S,), DT.TIME64NS,
                     lambda qn: _attr(mdstate.snapshot().pods_by_uid.get(_pod_name_to_pod_id(qn)), "stop_time_ns", 0)))
    r.register(_host("pod_id_to_service_id", (_S,), _S, _first_svc_uid))
    r.register(_host("pod_name_to_service_id", (_S,), _S,
                     lambda qn: _first_svc_uid(_pod_name_to_pod_id(qn))))
    r.register(_host("service_id_to_cluster_ip", (_S,), _S,
                     lambda uid: _attr(mdstate.snapshot().services_by_uid.get(uid), "cluster_ip")))
    r.register(_host("service_id_to_external_ips", (_S,), _S,
                     lambda uid: ",".join(_attr(mdstate.snapshot().services_by_uid.get(uid), "external_ips", ()))))
    r.register(_host("service_name_to_namespace", (_S,), _S, _qn_namespace,
                     volatile=False))
    r.register(_host("container_name_to_container_id", (_S,), _S, _cname_to_cid))
    r.register(_host("container_id_to_start_time", (_S,), DT.TIME64NS,
                     lambda cid: _attr(mdstate.snapshot().containers_by_id.get(cid), "start_time_ns", 0)))
    r.register(_host("container_id_to_stop_time", (_S,), DT.TIME64NS,
                     lambda cid: _attr(mdstate.snapshot().containers_by_id.get(cid), "stop_time_ns", 0)))
    r.register(_host("container_name_to_start_time", (_S,), DT.TIME64NS,
                     lambda n: _attr(mdstate.snapshot().containers_by_id.get(_cname_to_cid(n)), "start_time_ns", 0)))
    r.register(_host("container_name_to_stop_time", (_S,), DT.TIME64NS,
                     lambda n: _attr(mdstate.snapshot().containers_by_id.get(_cname_to_cid(n)), "stop_time_ns", 0)))

    # has_service_name/has_service_id: 1-arg form tests non-emptiness; the
    # 2-arg form used by drilldown scripts (px.has_service_name(col, 'ns/svc'))
    # tests membership, including the reference's grouped "svc1,svc2" encoding.
    r.register(_host("has_service_name", (_S,), DT.BOOLEAN, lambda qn: qn != "",
                     volatile=False))
    r.register(_host("has_service_id", (_S,), DT.BOOLEAN, lambda uid: uid != "",
                     volatile=False))
    r.register(_host("has_service_name", (_S, _S), DT.BOOLEAN, _has_value,
                     volatile=False))
    r.register(_host("has_service_id", (_S, _S), DT.BOOLEAN, _has_value,
                     volatile=False))

    # Current-context nullary helpers are provided by the compiler (px module)
    # because they need no column input: px.asid(), px.node_name().


def _has_value(col_val: str, target: str) -> bool:
    """Membership test tolerating the reference's multi-value encodings
    (comma-joined or JSON-list strings of qualified names)."""
    if not col_val:
        return False
    if col_val == target:
        return True
    if col_val.startswith("["):
        import json

        try:
            return target in json.loads(col_val)
        except ValueError:
            return False
    return target in col_val.split(",")


def _qname(obj) -> str:
    return obj.qualified_name if obj else ""


def _attr(obj, name, default=""):
    return getattr(obj, name) if obj else default


def _upid_to_container_name(u: UInt128) -> str:
    s = mdstate.snapshot()
    cid = s.upid_to_container_id.get(u, "")
    c = s.containers_by_id.get(cid)
    return c.name if c else ""


def _pod_id_to_service_name(uid: str) -> str:
    s = mdstate.snapshot()
    for suid in s.pod_uid_to_service_uids.get(uid, ()):
        svc = s.services_by_uid.get(suid)
        if svc:
            return svc.qualified_name
    return ""


def _qn_namespace(qualified: str) -> str:
    """'ns/name' → 'ns' (pod and service qualified names share the format)."""
    return qualified.split("/", 1)[0] if "/" in qualified else ""


def _first_svc_uid(pod_uid: str) -> str:
    for suid in mdstate.snapshot().pod_uid_to_service_uids.get(pod_uid, ()):
        return suid
    return ""


def _cname_to_cid(name: str) -> str:
    return mdstate.snapshot().container_name_to_cid.get(name, "")


def _pod_name_to_pod_id(qualified: str) -> str:
    return mdstate.snapshot().pod_name_to_uid.get(qualified, "")


def _service_name_to_service_id(qualified: str) -> str:
    return mdstate.snapshot().service_name_to_uid.get(qualified, "")


# Self-register into the process-global registry on import (pixie_tpu/__init__
# imports this package, so any use of the framework has metadata funcs).
from pixie_tpu.udf import registry as _global_registry  # noqa: E402

register_metadata_funcs(_global_registry)


#: ctx key → (udf name, required input column). Reference: the analyzer's
#: metadata-conversion rule rewrites df.ctx['pod'] into upid_to_pod_name(upid)
#: (planner/compiler/analyzer, metadata resolution).
#: ctx key → candidate (udf, source column) chain, tried in order against the
#: DataFrame's columns.  The reference's metadata-conversion rule does the
#: same: it picks whichever metadata key column the table carries (upid for
#: traced tables, pod_id for network_stats — metadata_ir.cc ResolveMetadata).
CTX_KEYS = {
    "pod": [("upid_to_pod_name", "upid"), ("pod_id_to_pod_name", "pod_id")],
    "pod_name": [("upid_to_pod_name", "upid"), ("pod_id_to_pod_name", "pod_id")],
    "pod_id": [("upid_to_pod_id", "upid"), ("pod_name_to_pod_id", "pod_name")],
    "service": [("upid_to_service_name", "upid"),
                ("pod_id_to_service_name", "pod_id")],
    "service_name": [("upid_to_service_name", "upid"),
                     ("pod_id_to_service_name", "pod_id")],
    "service_id": [("upid_to_service_id", "upid")],
    "namespace": [("upid_to_namespace", "upid"),
                  ("pod_id_to_namespace", "pod_id")],
    "node": [("upid_to_node_name", "upid"), ("pod_id_to_node_name", "pod_id")],
    "node_name": [("upid_to_node_name", "upid"),
                  ("pod_id_to_node_name", "pod_id")],
    "container": [("upid_to_container_name", "upid")],
    "container_name": [("upid_to_container_name", "upid")],
    "container_id": [("upid_to_container_id", "upid")],
    "deployment": [("upid_to_deployment_name", "upid")],
    "cmdline": [("upid_to_cmdline", "upid")],
    "cmd": [("upid_to_cmdline", "upid")],
    "pid": [("upid_to_pid", "upid")],
    "asid": [("upid_to_asid", "upid")],
}

"""Flag system: declared, typed, env-overridable configuration.

Reference: C++ gflags with PL_* env fallbacks
(gflags::Int32FromEnv("PL_TABLE_STORE_DATA_LIMIT_MB", 1280),
src/vizier/services/agent/pem/pem_manager.cc:24-35) and the Go side's
pflag+viper (src/shared/services/service_flags.go).

Usage:
    from pixie_tpu import flags
    FEED_ROWS = flags.define_int("PX_FEED_ROWS", 1 << 24, "feed coalescing")
    ... flags.get("PX_FEED_ROWS") ...
Values resolve env var > default; `flags.dump()` lists everything for
debugging/ops (the --help analog).
"""
from __future__ import annotations

import dataclasses
import os
import threading
from typing import Callable, Optional

from pixie_tpu.status import InvalidArgument


@dataclasses.dataclass
class Flag:
    name: str
    default: object
    parse: Callable
    help: str = ""  # noqa: A003
    value: object = None
    from_env: bool = False
    #: live flags re-read the environment on every get(): the declared,
    #: typed replacement for ad-hoc `os.environ.get` at call sites (wire
    #: compression, SPMD/native kill switches) whose callers toggle the
    #: env per-process at runtime.  Env wins over set_for_testing while
    #: present; the registry still documents/dumps the flag like any other.
    live: bool = False


_registry: dict[str, Flag] = {}
_lock = threading.Lock()


def _parse_bool(s: str) -> bool:
    return s.strip().lower() in ("1", "true", "yes", "on")


def _define(name: str, default, parse, help_: str, live: bool = False):
    with _lock:
        f = _registry.get(name)
        if f is not None:
            if f.default != default:
                raise InvalidArgument(
                    f"flag {name} redefined with different default"
                )
            return f.value
        raw = os.environ.get(name)
        value = parse(raw) if raw is not None else default
        _registry[name] = Flag(name, default, parse, help_, value,
                               raw is not None, live)
        return value


def define_int(name: str, default: int, help_: str = "", live: bool = False) -> int:
    return _define(name, int(default), int, help_, live)


def define_float(name: str, default: float, help_: str = "", live: bool = False) -> float:
    return _define(name, float(default), float, help_, live)


def define_str(name: str, default: str, help_: str = "", live: bool = False) -> str:
    return _define(name, str(default), str, help_, live)


def define_bool(name: str, default: bool, help_: str = "", live: bool = False) -> bool:
    return _define(name, bool(default), _parse_bool, help_, live)


def get(name: str):
    f = _registry.get(name)
    if f is None:
        raise InvalidArgument(f"unknown flag {name!r}")
    if f.live:
        raw = os.environ.get(name)
        if raw is not None:
            return f.parse(raw)
    return f.value


def set_for_testing(name: str, value) -> None:
    """Override in-process (tests/ops tooling)."""
    f = _registry.get(name)
    if f is None:
        raise InvalidArgument(f"unknown flag {name!r}")
    f.value = f.parse(str(value)) if not isinstance(value, type(f.default)) else value


def _effective(f: Flag):
    """The value get() would return — live flags re-consult the env."""
    if f.live:
        raw = os.environ.get(f.name)
        if raw is not None:
            return f.parse(raw)
    return f.value


def dump() -> dict[str, dict]:
    """Every declared flag with value/default/source (ops introspection).
    Live flags report their EFFECTIVE value (env re-read, like get())."""
    with _lock:
        return {
            name: {
                "value": _effective(f),
                "default": f.default,
                "from_env": f.from_env or (f.live
                                           and f.name in os.environ),
                "help": f.help,
            }
            for name, f in sorted(_registry.items())
        }


def env_exports() -> dict[str, str]:
    """Declared flags as a child-process environment fragment: every flag
    whose effective value differs from its default (env override or
    set_for_testing), stringified for re-parse by the child's registry.
    Subprocess harnesses (parallel/shard_bench workers) use this instead of
    forwarding raw os.environ reads — the flag registry stays the single
    config surface on both sides of the fork."""
    out: dict[str, str] = {}
    with _lock:
        for name, f in _registry.items():
            raw = os.environ.get(name) if f.live else None
            if raw is not None:
                out[name] = raw
            elif f.from_env or f.value != f.default:
                v = f.value
                out[name] = str(int(v)) if isinstance(v, bool) else str(v)
    return out


def reset_for_testing(name: Optional[str] = None) -> None:
    with _lock:
        if name is None:
            _registry.clear()
        else:
            _registry.pop(name, None)

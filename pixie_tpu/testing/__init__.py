from pixie_tpu.testing.datagen import demo_metadata, build_demo_store

__all__ = ["demo_metadata", "build_demo_store"]

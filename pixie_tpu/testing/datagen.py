"""Synthetic cluster data generator: k8s metadata + telemetry tables.

The script-execution tests, demos and benchmarks all need a plausible
mini-cluster: pods/services/processes in the metadata state and rows in the
canonical tables (collect.schemas).  The reference grows this from live eBPF
capture; here it is generated — same shape, deterministic seed.
"""
from __future__ import annotations

import numpy as np

from pixie_tpu.collect.schemas import SCHEMAS
from pixie_tpu.metadata.state import MetadataStateManager
from pixie_tpu.table.table import TableStore
from pixie_tpu.types import DataType as DT, UInt128

SEC = 1_000_000_000

_NAMESPACES = ["default", "payments"]
_SERVICES = ["frontend", "cart", "checkout"]
_PODS_PER_SVC = 2

_REQ_PATHS = ["/api/v1/items", "/api/v1/cart", "/healthz", "/api/v2/pay", "/login"]
_METHODS = ["GET", "POST", "PUT"]
_SQLS = [
    "SELECT * FROM users WHERE id=42",
    "INSERT INTO orders VALUES (1, 'x')",
    "SELECT count(*) FROM items",
]
_REDIS_CMDS = ["GET", "SET", "HGETALL", "EXPIRE"]
_DNS_NAMES = ["svc-a.default.svc.cluster.local", "example.com", "db.payments"]


def demo_metadata(asid: int = 1, node_name: str = "node-1"):
    """Build a MetadataStateManager with pods/services/processes + the UPID
    and IP universe the tables reference.  Returns (manager, upids, pod_ips)."""
    m = MetadataStateManager(asid=asid, node_name=node_name)
    updates = []
    upids: list[UInt128] = []
    ips: list[str] = []
    pid = 100
    for si, svc in enumerate(_SERVICES):
        ns = _NAMESPACES[si % len(_NAMESPACES)]
        svc_uid = f"svc-uid-{si}"
        pod_uids = []
        for pi in range(_PODS_PER_SVC):
            uid = f"pod-uid-{si}-{pi}"
            ip = f"10.0.{si}.{pi + 1}"
            ips.append(ip)
            pod_uids.append(uid)
            updates.append({
                "kind": "pod", "uid": uid, "name": f"{svc}-{pi}",
                "namespace": ns, "node": node_name, "ip": ip,
                "phase": "Running", "create_time_ns": 1 * SEC,
            })
            cid = f"ctr-{si}-{pi}"
            updates.append({
                "kind": "container", "cid": cid, "name": f"{svc}-ctr",
                "pod_uid": uid, "state": "Running",
            })
            u = UInt128.make_upid(asid, pid, 1 * SEC + pid)
            pid += 1
            upids.append(u)
            updates.append({
                "kind": "process", "upid": u, "pod_uid": uid,
                "container_id": cid, "cmdline": f"/bin/{svc} --serve",
            })
        updates.append({
            "kind": "service", "uid": svc_uid, "name": svc, "namespace": ns,
            "cluster_ip": f"10.96.0.{si + 1}", "pod_uids": pod_uids,
        })
        updates.append({"kind": "dns", "ip": f"10.96.0.{si + 1}",
                        "hostname": f"{svc}.{ns}.svc.cluster.local"})
    m.apply_updates(updates)
    return m, upids, ips


def _gen_column(name: str, dt: DT, n: int, rng, t0: int, t1: int, upids, ips):
    if name == "time_":
        return np.sort(rng.integers(t0, t1, n).astype(np.int64))
    if dt == DT.UINT128:
        return [upids[i] for i in rng.integers(0, len(upids), n)]
    if name == "remote_addr":
        pool = ips + ["192.168.9.9", "-"]
        return [pool[i] for i in rng.integers(0, len(pool), n)]
    if name == "pod_id":
        pool = [f"pod-uid-{s}-{p}" for s in range(len(_SERVICES))
                for p in range(_PODS_PER_SVC)]
        return [pool[i] for i in rng.integers(0, len(pool), n)]
    if name == "req_path":
        return [_REQ_PATHS[i] for i in rng.integers(0, len(_REQ_PATHS), n)]
    if name == "req_method":
        return [_METHODS[i] for i in rng.integers(0, len(_METHODS), n)]
    if name == "resp_status":
        return rng.choice([200, 200, 200, 404, 500], n).astype(np.int64)
    if name == "resp_message":
        return ["OK"] * n
    if name == "latency":
        return (rng.exponential(2e6, n)).astype(np.int64)  # ~2ms
    if name in ("req_body", "resp_body", "req", "resp"):
        return [_SQLS[i] for i in rng.integers(0, len(_SQLS), n)]
    if name == "req_cmd" and dt == DT.STRING:
        return [["Query", "Parse", "Execute"][i] for i in rng.integers(0, 3, n)]
    if name == "req_args":
        return ["key-%d" % i for i in rng.integers(0, 20, n)]
    if name in ("req_headers", "resp_headers", "req_header", "resp_header"):
        return ['{"host": "example.com"}'] * n
    if name == "stack_trace":
        pool = ["main;run;work", "main;run;idle", "main;gc"]
        return [pool[i] for i in rng.integers(0, 3, n)]
    if name in ("cmd",):
        return [_REDIS_CMDS[i] for i in rng.integers(0, len(_REDIS_CMDS), n)]
    if dt == DT.STRING:
        return ["x%d" % i for i in rng.integers(0, 10, n)]
    if dt == DT.BOOLEAN:
        return rng.integers(0, 2, n).astype(bool)
    if dt == DT.FLOAT64:
        return rng.exponential(10.0, n)
    if name in ("remote_port",):
        return rng.integers(1024, 60000, n).astype(np.int64)
    if name == "src_ip":
        return [ips[i] for i in rng.integers(0, len(ips), n)]
    if name == "dst_ip":
        # pod ips + service cluster ips (10.96.0.x, see demo_metadata) so
        # nslookup/ip_to_pod_id in the tcp_* scripts both resolve
        pool = ips + [f"10.96.0.{i + 1}" for i in range(len(_SERVICES))]
        return [pool[i] for i in rng.integers(0, len(pool), n)]
    if name == "state":
        pool = ["ESTABLISHED", "CLOSE_WAIT", "SYN_SENT"]
        return [pool[i] for i in rng.integers(0, len(pool), n)]
    if name == "trace_role":
        return rng.integers(1, 3, n).astype(np.int64)  # requestor/responder
    if name == "req_op" or (name == "req_cmd" and dt == DT.INT64):
        return rng.integers(0, 8, n).astype(np.int64)
    # generic int64 metric
    return rng.integers(0, 1 << 20, n).astype(np.int64)


def build_demo_store(
    tables=None, rows: int = 4000, seed: int = 0,
    now_ns: int = 600 * SEC, span_s: int = 300, batch_rows: int = 2048,
) -> TableStore:
    """TableStore with `rows` synthetic rows in each requested canonical
    table, spanning [now-span_s, now).  Pair with demo_metadata() installed as
    the global metadata manager so ctx[...] resolution finds the pods."""
    from pixie_tpu.metadata import state as mdstate

    mgr = mdstate.global_manager()
    snap = mgr.current()
    upids = sorted(snap.upid_to_pod_uid) or [UInt128.make_upid(1, 1, 1)]
    ips = sorted(snap.ip_to_pod_uid) or ["10.0.0.1"]
    rng = np.random.default_rng(seed)
    ts = TableStore()
    t0, t1 = now_ns - span_s * SEC, now_ns
    for name in (tables or list(SCHEMAS)):
        rel = SCHEMAS[name]
        t = ts.create(name, rel, batch_rows=batch_rows)
        data = {
            c.name: _gen_column(c.name, c.data_type, rows, rng, t0, t1, upids, ips)
            for c in rel
        }
        t.write(data)
    return ts

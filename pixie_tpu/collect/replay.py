"""Replay connector: streams a recorded/synthetic columnar dataset into the
table store at a configurable rate, rewriting timestamps to arrival time.

Reference role: SURVEY §7 step 8 names a "file/replay connector (enough for
all benchmarks)" as collection phase one; bench config #5 (100M-row streaming
replay, BASELINE.md) runs through this.  A dataset is either a dict of numpy
columns or a zero-arg generator yielding such dicts (synthetic generators
avoid materializing 100M rows up front).
"""
from __future__ import annotations

from typing import Callable, Iterator, Optional

import numpy as np

from pixie_tpu.collect.core import SourceConnector, TableSpec, now_ns
from pixie_tpu.status import InvalidArgument
from pixie_tpu.types import Relation


class ReplayConnector(SourceConnector):
    """Streams chunks of a dataset into one table.

    data: {col: np.ndarray} replayed in slices, OR an iterator/generator of
    such dicts (each yield = one transfer's batch).
    """

    name = "replay"

    def __init__(
        self,
        table: str,
        relation: Relation,
        data=None,
        batches: Optional[Iterator[dict]] = None,
        rows_per_transfer: int = 1 << 16,
        sample_period_s: float = 0.01,
        rewrite_time: bool = True,
        name: Optional[str] = None,
        max_bytes: int = 1 << 30,
    ):
        if (data is None) == (batches is None):
            raise InvalidArgument("replay: pass exactly one of data / batches")
        self.table = table
        self.relation = relation
        self._data = data
        self._batches = iter(batches) if batches is not None else None
        self.rows_per_transfer = rows_per_transfer
        self.sample_period_s = sample_period_s
        self.rewrite_time = rewrite_time
        self._off = 0
        self._max_bytes = max_bytes
        if name is not None:
            self.name = name
        self.rows_replayed = 0

    def tables(self) -> list[TableSpec]:
        return [TableSpec(self.table, self.relation,
                          sample_period_s=self.sample_period_s,
                          max_bytes=self._max_bytes)]

    def _next_chunk(self) -> Optional[dict]:
        if self._batches is not None:
            try:
                return dict(next(self._batches))
            except StopIteration:
                return None
        n = len(next(iter(self._data.values())))
        if self._off >= n:
            return None
        end = min(self._off + self.rows_per_transfer, n)
        out = {k: v[self._off:end] for k, v in self._data.items()}
        self._off = end
        return out

    def transfer_data(self) -> dict[str, dict]:
        chunk = self._next_chunk()
        if chunk is None:
            self.exhausted = True
            return {}
        if self.rewrite_time and "time_" in chunk:
            n = len(chunk["time_"])
            # Preserve intra-chunk ordering offsets, anchor at arrival time.
            t = np.asarray(chunk["time_"], dtype=np.int64)
            base = t[0] if n else 0
            chunk = dict(chunk)
            chunk["time_"] = now_ns() + (t - base)
        self.rows_replayed += len(next(iter(chunk.values()))) if chunk else 0
        return {self.table: chunk}

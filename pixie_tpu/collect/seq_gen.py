"""Deterministic sequence-generator connector — the test/bench workhorse.

Reference: src/stirling/source_connectors/seq_gen/seq_gen_connector.h:36 — two
tables of functional sequences (linear, modulo, quadratic, fibonacci) used to
test the collector runtime end-to-end without real tracing.
"""
from __future__ import annotations

import numpy as np

from pixie_tpu.collect.core import SourceConnector, TableSpec, now_ns
from pixie_tpu.types import DataType as DT, Relation


class SeqGenConnector(SourceConnector):
    """Emits `rows_per_transfer` rows of deterministic sequences per tick.

    seq0: time_, x (linear), xmod10, xsquared
    seq1: time_, fib
    """

    name = "seq_gen"

    def __init__(self, rows_per_transfer: int = 1024, sample_period_s: float = 0.1,
                 total_rows: int | None = None):
        self.rows_per_transfer = rows_per_transfer
        self.sample_period_s = sample_period_s
        self.total_rows = total_rows
        self._x = 0
        self._fib = (0, 1)

    def tables(self) -> list[TableSpec]:
        return [
            TableSpec(
                "seq0",
                Relation.of(
                    ("time_", DT.TIME64NS), ("x", DT.INT64),
                    ("xmod10", DT.INT64), ("xsquared", DT.INT64),
                ),
                sample_period_s=self.sample_period_s,
            ),
            TableSpec(
                "seq1",
                Relation.of(("time_", DT.TIME64NS), ("fib", DT.INT64)),
                sample_period_s=self.sample_period_s,
            ),
        ]

    def transfer_data(self) -> dict[str, dict]:
        n = self.rows_per_transfer
        if self.total_rows is not None:
            n = min(n, self.total_rows - self._x)
            if n <= 0:
                self.exhausted = True
                return {}
        x = np.arange(self._x, self._x + n, dtype=np.int64)
        self._x += n
        if self.total_rows is not None and self._x >= self.total_rows:
            self.exhausted = True
        fibs = np.empty(n, dtype=np.int64)
        a, b = self._fib
        for i in range(n):
            fibs[i] = a
            a, b = b, (a + b) % (1 << 62)
        self._fib = (a, b)
        t = np.full(n, now_ns(), dtype=np.int64) + np.arange(n, dtype=np.int64)
        return {
            "seq0": {"time_": t, "x": x, "xmod10": x % 10, "xsquared": x * x},
            "seq1": {"time_": t, "fib": fibs},
        }

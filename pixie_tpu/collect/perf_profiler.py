"""Always-on sampling profiler connector → stack_traces.beta.

Reference: src/stirling/source_connectors/perf_profiler/ — BPF stack sampling
into a dual-buffer table of folded stacks + counts, symbolized and shipped as
the `stack_traces.beta` table feeding px/perf_flamegraph.

Host-runtime redesign: the profiled substrate here is the agent PROCESS
itself (query engine, collectors, services) — sampling walks every Python
thread's frame stack (sys._current_frames) on a background thread at
`hz`, folds frames into "mod.fn;mod.fn;..." strings, and counts per stack.
transfer_data() drains the accumulated counts as rows, exactly the
reference's sample-continuously / push-periodically split
(perf_profile_connector.h:48 dual-buffer swap).
"""
from __future__ import annotations

import sys
import threading
import time
from collections import Counter

from pixie_tpu.collect.core import SourceConnector, TableSpec, now_ns
from pixie_tpu.types import DataType as DT, Relation, UInt128


def fold_stack(frame, max_depth: int = 64) -> str:
    """Frame chain → root-first 'module.func;module.func' folded string
    (the flamegraph input format the reference's stringifier produces)."""
    parts = []
    f = frame
    while f is not None and len(parts) < max_depth:
        code = f.f_code
        mod = f.f_globals.get("__name__", "?")
        parts.append(f"{mod}.{code.co_name}")
        f = f.f_back
    return ";".join(reversed(parts))


class PerfProfilerConnector(SourceConnector):
    """Samples this process's threads; publishes stack_traces.beta."""

    name = "perf_profiler"

    def __init__(self, hz: float = 99.0, push_period_s: float = 5.0,
                 asid: int = 0, pid: int | None = None):
        self.hz = hz
        self.push_period_s = push_period_s
        import os

        from pixie_tpu.metadata.proc_scanner import pid_start_time_ns

        rpid = pid if pid is not None else os.getpid()
        # /proc-derived start time, NOT time.time_ns(): the UPID must equal
        # the one the ProcScanner binds in the metadata state, or ctx['pod']
        # never joins profiler rows
        self._upid = UInt128.make_upid(
            asid, rpid, pid_start_time_ns(rpid) or time.time_ns())
        self._counts: Counter[str] = Counter()
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._stack_ids: dict[str, int] = {}
        self.samples_taken = 0
        #: lazily-built native symbolizer (obj_tools): resolves raw return
        #: addresses from externally-captured native stacks (perf-script
        #: replay, ptrace samplers) against /proc/<pid>/maps + ELF symtabs —
        #: the reference's symbolizer stage (perf_profiler/symbolizers/).
        self._native_sym = None

    # ------------------------------------------------------ native frames
    def _symbolizer(self):
        if self._native_sym is None:
            from pixie_tpu.obj_tools import NativeSymbolizer

            self._native_sym = NativeSymbolizer(self._upid.pid)
        return self._native_sym

    def fold_native_stack(self, addrs: list[int]) -> str:
        """Raw leaf-first return addresses → root-first folded symbol string
        (same format as the Python sampler's fold_stack)."""
        sym = self._symbolizer()
        return ";".join(sym.symbolize(a) for a in reversed(addrs))

    def add_native_sample(self, addrs: list[int], count: int = 1) -> None:
        """Ingest one externally-captured native stack (leaf-first raw
        addresses); symbolized + merged into the same folded-count table the
        Python sampler fills."""
        folded = self.fold_native_stack(addrs)
        with self._lock:
            self._counts[folded] += count

    def tables(self) -> list[TableSpec]:
        # reference stack_traces_table.h:31
        return [TableSpec(
            "stack_traces.beta",
            Relation.of(
                ("time_", DT.TIME64NS),
                ("upid", DT.UINT128),
                ("stack_trace_id", DT.INT64),
                ("stack_trace", DT.STRING),
                ("count", DT.INT64),
            ),
            sample_period_s=self.push_period_s,
        )]

    # ----------------------------------------------------------- sampling
    def _sample_loop(self):
        period = 1.0 / self.hz
        me = threading.get_ident()
        while not self._stop.wait(timeout=period):
            frames = sys._current_frames()
            folded = [
                fold_stack(f) for tid, f in frames.items() if tid != me
            ]
            with self._lock:
                for s in folded:
                    if s:
                        self._counts[s] += 1
                self.samples_taken += 1

    def init(self) -> None:
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._sample_loop, daemon=True, name="pixie-profiler"
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    # ------------------------------------------------------------ transfer
    def transfer_data(self) -> dict[str, dict]:
        with self._lock:
            counts, self._counts = self._counts, Counter()
        if not counts:
            return {}
        t = now_ns()
        stacks = sorted(counts)
        ids = [self._stack_ids.setdefault(s, len(self._stack_ids)) for s in stacks]
        return {"stack_traces.beta": {
            "time_": [t] * len(stacks),
            "upid": [self._upid] * len(stacks),
            "stack_trace_id": ids,
            "stack_trace": stacks,
            "count": [int(counts[s]) for s in stacks],
        }}

"""MySQL client/server protocol parser + stitcher.

Reference: socket_tracer/protocols/mysql/ (parse.cc packet framing — 3-byte
LE length + sequence id; stitcher.cc command→response-set matching;
types.h command codes and RespStatus).

Wire facts (MySQL protocol spec): every packet is
  [len:3 little-endian][seq:1][payload:len].
A request is a command packet (seq 0 from the client) whose first payload
byte is the command code; the response is a packet run terminated by an
OK (0x00) / ERR (0xff) / EOF (0xfe, len<9) packet or a complete resultset.
"""
from __future__ import annotations

import dataclasses
from collections import deque

from pixie_tpu.collect.protocols.base import (
    Frame,
    MessageType,
    ParseState,
    ProtocolParser,
)

# command codes (mysql protocol; reference mysql/types.h Command)
COM_QUIT = 0x01
COM_INIT_DB = 0x02
COM_QUERY = 0x03
COM_FIELD_LIST = 0x04
COM_PING = 0x0E
COM_STMT_PREPARE = 0x16
COM_STMT_EXECUTE = 0x17
COM_STMT_CLOSE = 0x19

#: commands whose payload after the code byte is human-readable text
_TEXT_COMMANDS = {COM_QUERY, COM_INIT_DB, COM_FIELD_LIST, COM_STMT_PREPARE}

# reference mysql/types.h RespStatus {kUnknown, kNone, kOK, kErr}
RESP_UNKNOWN = 0
RESP_NONE = 1
RESP_OK = 2
RESP_ERR = 3


@dataclasses.dataclass
class MySQLPacket(Frame):
    seq: int = 0
    payload: bytes = b""


class _State:
    """Cross-frame state: handshake progress, tracked per direction (the
    greeting lives on the response stream, the login on the request stream,
    and stream processing order must not couple them)."""

    def __init__(self):
        self.handshake_done = False  # client login seen or inferred
        self.greeting_done = False   # server greeting consumed


class MySQLParser(ProtocolParser):
    name = "mysql"
    table = "mysql_events"

    def new_state(self):
        return _State()

    def find_frame_boundary(self, msg_type, buf, start, state=None):
        # A request boundary is a packet with seq==0 and a valid command
        # byte; scan for that shape (reference mysql/parse.cc does the same
        # plausibility scan).
        for pos in range(start, max(len(buf) - 5, start)):
            ln = int.from_bytes(buf[pos:pos + 3], "little")
            seq = buf[pos + 3]
            if seq != 0 or ln == 0 or ln > 1 << 24:
                continue
            if msg_type is MessageType.REQUEST and buf[pos + 4] > 0x20:
                continue
            return pos
        return -1

    def parse_frame(self, msg_type, buf, state=None):
        if len(buf) < 4:
            return ParseState.NEEDS_MORE_DATA, None, 0
        ln = int.from_bytes(buf[:3], "little")
        seq = buf[3]
        if ln == 0:
            return ParseState.INVALID, None, 0
        if len(buf) < 4 + ln:
            return ParseState.NEEDS_MORE_DATA, None, 0
        payload = buf[4:4 + ln]
        # Handshake traffic: server greeting (protocol version 10, seq 0 on
        # the response stream) and the client login packet (seq 1 on the
        # request stream) — consume without emitting frames.
        if state is not None and msg_type is MessageType.RESPONSE \
                and not state.greeting_done:
            state.greeting_done = True
            if seq == 0 and payload[:1] == b"\x0a":
                return ParseState.IGNORE, None, 4 + ln
        if state is not None and not state.handshake_done:
            if msg_type is MessageType.REQUEST and seq == 1:
                state.handshake_done = True
                return ParseState.IGNORE, None, 4 + ln
        if msg_type is MessageType.REQUEST:
            if seq != 0 or payload[0] > 0x20:
                return ParseState.INVALID, None, 0
            if state is not None:
                state.handshake_done = True
        pkt = MySQLPacket(seq=seq, payload=bytes(payload))
        return ParseState.SUCCESS, pkt, 4 + ln

    # ------------------------------------------------------------- stitching
    @staticmethod
    def _is_eof(p: bytes) -> bool:
        return len(p) < 9 and p[:1] == b"\xfe"

    def _summarize_response(self, req_cmd: int, resps: list[MySQLPacket]):
        """Response packet run -> (status, body) per reference handler.cc."""
        if not resps:
            return RESP_NONE, ""
        first = resps[0].payload
        if first[:1] == b"\xff":
            # ERR packet: [0xff][code:2][#sqlstate:6][message]
            msg = first[9:].decode("latin1", "replace") if len(first) > 9 else ""
            return RESP_ERR, msg
        if first[:1] == b"\x00":
            return RESP_OK, ""
        if self._is_eof(first):
            return RESP_OK, ""
        # Resultset: [col_count][col defs...][EOF][rows...][EOF/OK]
        n_rows = 0
        seen_col_eof = False
        for p in resps[1:]:
            if self._is_eof(p.payload) or p.payload[:1] == b"\x00":
                if not seen_col_eof:
                    seen_col_eof = True
                continue
            if seen_col_eof:
                n_rows += 1
        return RESP_OK, f"Resultset rows = {n_rows}"

    def stitch(self, requests, responses, state=None):
        records = []
        errors = 0
        while requests:
            req = requests[0]
            # Responses predating the oldest request are orphans (the auth
            # ack to the login packet, or responses whose request was lost).
            while responses and responses[0].timestamp_ns < req.timestamp_ns:
                responses.popleft()
            cmd = req.payload[0]
            # Commands with no response at all.
            if cmd in (COM_QUIT, COM_STMT_CLOSE):
                requests.popleft()
                records.append((req, cmd, RESP_NONE, "", req.timestamp_ns))
                continue
            # This command's response run = the MINIMAL response-packet
            # prefix that forms a complete response (OK/ERR/EOF or full
            # resultset).  Packet SHAPE, not timestamps, frames the run:
            # MySQL serializes responses per connection, so shape framing
            # stays correct when the client pipelines requests (responses
            # arriving after the next request's timestamp).
            run = []
            complete = False
            terminators = 0
            for p in responses:
                run.append(p)
                if len(run) == 1:
                    first = p.payload
                    if first[:1] in (b"\xff", b"\x00") or self._is_eof(first):
                        complete = True
                        break
                    continue
                # resultset: column-def EOF then row-section EOF/OK
                if self._is_eof(p.payload) or p.payload[:1] == b"\x00":
                    terminators += 1
                    if terminators >= 2:
                        complete = True
                        break
            if not complete:
                break  # wait for more response packets
            for _ in run:
                responses.popleft()
            requests.popleft()
            status, body = self._summarize_response(cmd, run)
            end_ts = run[-1].timestamp_ns if run else req.timestamp_ns
            records.append((req, cmd, status, body, end_ts))
        return records, errors

    def record_row(self, record):
        req, cmd, status, body, end_ts = record
        req_body = ""
        if cmd in _TEXT_COMMANDS:
            req_body = req.payload[1:].decode("latin1", "replace")
        return {
            "time_": req.timestamp_ns,
            "latency": max(end_ts - req.timestamp_ns, 0),
            "req_cmd": cmd,
            "req_body": req_body,
            "resp_status": status,
            "resp_body": body,
        }

"""HTTP/2 + gRPC wire parser: frame state machine, HPACK (static + dynamic
table, Huffman), gRPC message framing, stream multiplexing.

Reference counterparts: socket_tracer/protocols/http2/ (stitcher.cc matches
req/resp by stream id; grpc.cc decodes gRPC framing; http2_streams_container
accumulates per-stream header/data events).  The reference collects HTTP/2
headers ALREADY-DECODED via Go-runtime uprobes (bcc_bpf/go_http2_trace.c) and
so never touches HPACK; this build captures raw bytes (tap/replay), so the
full RFC 7540 frame layer and RFC 7541 HPACK decoder live here.

Wire facts implemented (all standard):
  * RFC 7540 §4.1 frame header: [length:24][type:8][flags:8][R+stream:32].
  * Connection preface "PRI * HTTP/2.0\\r\\n\\r\\nSM\\r\\n\\r\\n" (client side).
  * HEADERS/CONTINUATION header-block assembly with END_HEADERS; PADDED and
    PRIORITY field stripping; DATA with padding; RST_STREAM; trailers.
  * RFC 7541 HPACK: indexed (§6.1), literal with/without incremental indexing
    (§6.2), dynamic-table size update (§6.3), static table (Appendix A),
    integer prefix coding (§5.1), Huffman-coded strings (§5.2, Appendix B —
    the printable-ASCII code set; a code outside it marks the string
    undecodable instead of desyncing).
  * gRPC: length-prefixed messages [compressed:1][len:4] (PROTOCOL-HTTP2.md),
    grpc-status from trailers, content-type application/grpc detection.
"""
from __future__ import annotations

import dataclasses
import json
from typing import Optional

from pixie_tpu.collect.protocols.base import (
    Frame,
    MessageType,
    ParseState,
    ProtocolParser,
)

PREFACE = b"PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n"

# frame types (RFC 7540 §6)
DATA = 0x0
HEADERS = 0x1
PRIORITY = 0x2
RST_STREAM = 0x3
SETTINGS = 0x4
PUSH_PROMISE = 0x5
PING = 0x6
GOAWAY = 0x7
WINDOW_UPDATE = 0x8
CONTINUATION = 0x9

# flags
F_END_STREAM = 0x1
F_END_HEADERS = 0x4
F_PADDED = 0x8
F_PRIORITY = 0x20

#: max frame length we accept (default SETTINGS_MAX_FRAME_SIZE is 16384; a
#: peer may raise it to 2^24-1 — cap at 1 MiB as a plausibility rail)
MAX_FRAME_LEN = 1 << 20

# ------------------------------------------------------------------- HPACK

#: RFC 7541 Appendix A static table (index 1..61)
STATIC_TABLE = [
    (":authority", ""),
    (":method", "GET"),
    (":method", "POST"),
    (":path", "/"),
    (":path", "/index.html"),
    (":scheme", "http"),
    (":scheme", "https"),
    (":status", "200"),
    (":status", "204"),
    (":status", "206"),
    (":status", "304"),
    (":status", "400"),
    (":status", "404"),
    (":status", "500"),
    ("accept-charset", ""),
    ("accept-encoding", "gzip, deflate"),
    ("accept-language", ""),
    ("accept-ranges", ""),
    ("accept", ""),
    ("access-control-allow-origin", ""),
    ("age", ""),
    ("allow", ""),
    ("authorization", ""),
    ("cache-control", ""),
    ("content-disposition", ""),
    ("content-encoding", ""),
    ("content-language", ""),
    ("content-length", ""),
    ("content-location", ""),
    ("content-range", ""),
    ("content-type", ""),
    ("cookie", ""),
    ("date", ""),
    ("etag", ""),
    ("expect", ""),
    ("expires", ""),
    ("from", ""),
    ("host", ""),
    ("if-match", ""),
    ("if-modified-since", ""),
    ("if-none-match", ""),
    ("if-range", ""),
    ("if-unmodified-since", ""),
    ("last-modified", ""),
    ("link", ""),
    ("location", ""),
    ("max-forwards", ""),
    ("proxy-authenticate", ""),
    ("proxy-authorization", ""),
    ("range", ""),
    ("referer", ""),
    ("refresh", ""),
    ("retry-after", ""),
    ("server", ""),
    ("set-cookie", ""),
    ("strict-transport-security", ""),
    ("transfer-encoding", ""),
    ("user-agent", ""),
    ("vary", ""),
    ("via", ""),
    ("www-authenticate", ""),
]

#: RFC 7541 Appendix B Huffman codes for the printable-ASCII symbols (32-126)
#: — the complete code space reachable by header NAMES and textual VALUES.
#: Control/obs-text symbols (rare; gRPC base64s binary metadata) are omitted:
#: hitting one of their (all-ones-prefixed) codes flags the string
#: undecodable rather than emitting garbage.
_HUFF_PRINTABLE = {
    ord(" "): (0x14, 6), ord("!"): (0x3F8, 10), ord('"'): (0x3F9, 10),
    ord("#"): (0xFFA, 12), ord("$"): (0x1FF9, 13), ord("%"): (0x15, 6),
    ord("&"): (0xF8, 8), ord("'"): (0x7FA, 11), ord("("): (0x3FA, 10),
    ord(")"): (0x3FB, 10), ord("*"): (0xF9, 8), ord("+"): (0x7FB, 11),
    ord(","): (0xFA, 8), ord("-"): (0x16, 6), ord("."): (0x17, 6),
    ord("/"): (0x18, 6), ord("0"): (0x0, 5), ord("1"): (0x1, 5),
    ord("2"): (0x2, 5), ord("3"): (0x19, 6), ord("4"): (0x1A, 6),
    ord("5"): (0x1B, 6), ord("6"): (0x1C, 6), ord("7"): (0x1D, 6),
    ord("8"): (0x1E, 6), ord("9"): (0x1F, 6), ord(":"): (0x5C, 7),
    ord(";"): (0xFB, 8), ord("<"): (0x7FFC, 15), ord("="): (0x20, 6),
    ord(">"): (0xFFB, 12), ord("?"): (0x3FC, 10), ord("@"): (0x3FFA, 14),
    ord("A"): (0x21, 6), ord("B"): (0x5D, 7), ord("C"): (0x5E, 7),
    ord("D"): (0x5F, 7), ord("E"): (0x60, 7), ord("F"): (0x61, 7),
    ord("G"): (0x62, 7), ord("H"): (0x63, 7), ord("I"): (0x64, 7),
    ord("J"): (0x65, 7), ord("K"): (0x66, 7), ord("L"): (0x67, 7),
    ord("M"): (0x68, 7), ord("N"): (0x69, 7), ord("O"): (0x6A, 7),
    ord("P"): (0x6B, 7), ord("Q"): (0x6C, 7), ord("R"): (0x6D, 7),
    ord("S"): (0x6E, 7), ord("T"): (0x6F, 7), ord("U"): (0x70, 7),
    ord("V"): (0x71, 7), ord("W"): (0x72, 7), ord("X"): (0xFC, 8),
    ord("Y"): (0x73, 7), ord("Z"): (0xFD, 8), ord("["): (0x1FFB, 13),
    ord("\\"): (0x7FFF0, 19), ord("]"): (0x1FFC, 13), ord("^"): (0x3FFC, 14),
    ord("_"): (0x22, 6), ord("`"): (0x7FFD, 15), ord("a"): (0x3, 5),
    ord("b"): (0x23, 6), ord("c"): (0x4, 5), ord("d"): (0x24, 6),
    ord("e"): (0x5, 5), ord("f"): (0x25, 6), ord("g"): (0x26, 6),
    ord("h"): (0x27, 6), ord("i"): (0x6, 5), ord("j"): (0x74, 7),
    ord("k"): (0x75, 7), ord("l"): (0x28, 6), ord("m"): (0x29, 6),
    ord("n"): (0x2A, 6), ord("o"): (0x7, 5), ord("p"): (0x2B, 6),
    ord("q"): (0x76, 7), ord("r"): (0x2C, 6), ord("s"): (0x8, 5),
    ord("t"): (0x9, 5), ord("u"): (0x2D, 6), ord("v"): (0x77, 7),
    ord("w"): (0x78, 7), ord("x"): (0x79, 7), ord("y"): (0x7A, 7),
    ord("z"): (0x7B, 7), ord("{"): (0x7FFE, 15), ord("|"): (0x7FC, 11),
    ord("}"): (0x3FFD, 14), ord("~"): (0x1FFD, 13),
}


def _build_huff_decode() -> dict:
    """(code, nbits) → symbol decode map."""
    out = {}
    for sym, (code, nbits) in _HUFF_PRINTABLE.items():
        out[(code, nbits)] = sym
    return out


_HUFF_DECODE = _build_huff_decode()
_HUFF_MAX_BITS = 19


def huffman_decode(data: bytes) -> Optional[str]:
    """RFC 7541 §5.2 decode; None when a code outside the printable set (or
    a non-EOS-padded tail) appears."""
    out = []
    code = 0
    nbits = 0
    for byte in data:
        for bit in range(7, -1, -1):
            code = (code << 1) | ((byte >> bit) & 1)
            nbits += 1
            sym = _HUFF_DECODE.get((code, nbits))
            if sym is not None:
                out.append(sym)
                code = 0
                nbits = 0
            elif nbits > _HUFF_MAX_BITS:
                return None
    # padding must be the EOS prefix: all ones, < 8 bits
    if nbits >= 8 or code != (1 << nbits) - 1:
        return None
    return "".join(chr(c) for c in out)


def huffman_encode(s: str) -> bytes:
    """Encoder twin (tests + tap fixtures)."""
    acc = 0
    nbits = 0
    for ch in s:
        code, n = _HUFF_PRINTABLE[ord(ch)]
        acc = (acc << n) | code
        nbits += n
    # pad with EOS prefix (all ones) to a byte boundary
    pad = (-nbits) % 8
    acc = (acc << pad) | ((1 << pad) - 1)
    nbits += pad
    return acc.to_bytes(nbits // 8, "big") if nbits else b""


class HpackDecoder:
    """RFC 7541 decoder with a bounded dynamic table."""

    def __init__(self, max_size: int = 4096):
        self.dynamic: list[tuple[str, str]] = []  # newest first
        self.max_size = max_size
        self.size = 0

    @staticmethod
    def _entry_size(name: str, value: str) -> int:
        return len(name) + len(value) + 32  # §4.1

    def _evict(self) -> None:
        while self.size > self.max_size and self.dynamic:
            n, v = self.dynamic.pop()
            self.size -= self._entry_size(n, v)

    def _add(self, name: str, value: str) -> None:
        self.dynamic.insert(0, (name, value))
        self.size += self._entry_size(name, value)
        self._evict()

    def _lookup(self, idx: int) -> tuple[str, str]:
        if 1 <= idx <= len(STATIC_TABLE):
            return STATIC_TABLE[idx - 1]
        didx = idx - len(STATIC_TABLE) - 1
        if 0 <= didx < len(self.dynamic):
            return self.dynamic[didx]
        raise ValueError(f"HPACK index {idx} out of range")

    @staticmethod
    def _read_int(data, pos: int, prefix_bits: int) -> tuple[int, int]:
        """§5.1 integer: returns (value, new_pos)."""
        mask = (1 << prefix_bits) - 1
        v = data[pos] & mask
        pos += 1
        if v < mask:
            return v, pos
        shift = 0
        while True:
            if pos >= len(data):
                raise ValueError("truncated HPACK integer")
            b = data[pos]
            pos += 1
            v += (b & 0x7F) << shift
            shift += 7
            if not (b & 0x80):
                return v, pos

    def _read_string(self, data, pos: int) -> tuple[str, int]:
        if pos >= len(data):
            raise ValueError("truncated HPACK string")
        huff = bool(data[pos] & 0x80)
        ln, pos = self._read_int(data, pos, 7)
        if pos + ln > len(data):
            raise ValueError("truncated HPACK string body")
        raw = bytes(data[pos: pos + ln])
        pos += ln
        if huff:
            s = huffman_decode(raw)
            if s is None:
                s = "<huffman:" + raw.hex() + ">"
            return s, pos
        return raw.decode("latin-1"), pos

    def decode(self, block: bytes) -> list[tuple[str, str]]:
        """Header block fragment → [(name, value)].  MUST be called exactly
        once per block in connection order (the dynamic table is stateful)."""
        out = []
        pos = 0
        data = memoryview(block)
        while pos < len(data):
            b = data[pos]
            if b & 0x80:  # §6.1 indexed
                idx, pos = self._read_int(data, pos, 7)
                if idx == 0:
                    raise ValueError("HPACK indexed field with index 0")
                out.append(self._lookup(idx))
            elif b & 0x40:  # §6.2.1 literal with incremental indexing
                idx, pos = self._read_int(data, pos, 6)
                name = (self._lookup(idx)[0] if idx
                        else None)
                if name is None:
                    name, pos = self._read_string(data, pos)
                value, pos = self._read_string(data, pos)
                self._add(name, value)
                out.append((name, value))
            elif b & 0x20:  # §6.3 dynamic table size update
                sz, pos = self._read_int(data, pos, 5)
                # Clamp: a corrupt/adversarial stream could raise max_size
                # to 2^32 and grow the table unboundedly in a passive
                # observer; rail it like MAX_FRAME_LEN rails frame lengths.
                self.max_size = min(sz, 64 * 1024)
                self._evict()
            else:  # §6.2.2/§6.2.3 literal without indexing / never indexed
                idx, pos = self._read_int(data, pos, 4)
                name = self._lookup(idx)[0] if idx else None
                if name is None:
                    name, pos = self._read_string(data, pos)
                value, pos = self._read_string(data, pos)
                out.append((name, value))
        return out


# ------------------------------------------------------------ frame objects


@dataclasses.dataclass
class H2Frame(Frame):
    type: int = 0
    flags: int = 0
    stream_id: int = 0
    #: decoded headers for HEADERS (+ absorbed CONTINUATIONs); None otherwise
    headers: Optional[list] = None
    #: DATA payload (padding stripped); None otherwise
    data: Optional[bytes] = None


@dataclasses.dataclass
class _StreamHalf:
    headers: dict = dataclasses.field(default_factory=dict)
    trailers: dict = dataclasses.field(default_factory=dict)
    data: bytearray = dataclasses.field(default_factory=bytearray)
    saw_headers: bool = False
    ended: bool = False
    t_first: int = 0
    t_last: int = 0


@dataclasses.dataclass
class _Stream:
    req: _StreamHalf = dataclasses.field(default_factory=_StreamHalf)
    resp: _StreamHalf = dataclasses.field(default_factory=_StreamHalf)
    reset: bool = False


class _ConnState:
    """Shared connection state: per-direction HPACK decoders + pending
    header-block assembly + the stream map."""

    def __init__(self):
        self.hpack = {MessageType.REQUEST: HpackDecoder(),
                      MessageType.RESPONSE: HpackDecoder()}
        #: per-direction in-flight header block (HEADERS without END_HEADERS)
        self.pending_block: dict = {MessageType.REQUEST: None,
                                    MessageType.RESPONSE: None}
        self.preface_seen = False
        self.streams: dict[int, _Stream] = {}
        self.hpack_errors = 0

    def stream(self, sid: int) -> _Stream:
        st = self.streams.get(sid)
        if st is None:
            st = self.streams[sid] = _Stream()
        return st


#: drop streams beyond this many concurrently tracked (lost-END safety rail,
#: mirrors ConnTracker.MAX_PENDING_FRAMES)
MAX_TRACKED_STREAMS = 512


class HTTP2Parser(ProtocolParser):
    """RFC 7540 frame parser + stream stitcher producing http_events rows
    (major_version=2; gRPC fields filled when content-type is grpc)."""

    name = "http2"
    table = "http_events"

    def new_state(self):
        return _ConnState()

    # ------------------------------------------------------------- parsing
    def find_frame_boundary(self, msg_type, buf, start, state=None):
        # resync on a plausible frame header: known type, sane length.
        # A header needs bytes pos..pos+8, so the last scannable position is
        # len(buf) - 9 inclusive.
        for pos in range(start, len(buf) - 8):
            ln = int.from_bytes(buf[pos:pos + 3], "big")
            ftype = buf[pos + 3]
            if ftype <= CONTINUATION and ln <= MAX_FRAME_LEN:
                return pos
        return -1

    def parse_frame(self, msg_type, buf, state=None):
        if state is None:
            state = _ConnState()
        b = bytes(buf[:24])
        if msg_type is MessageType.REQUEST and not state.preface_seen:
            if PREFACE.startswith(b[: len(PREFACE)]) or b[:3] == b"PRI":
                if len(buf) < len(PREFACE):
                    return ParseState.NEEDS_MORE_DATA, None, 0
                if bytes(buf[: len(PREFACE)]) == PREFACE:
                    state.preface_seen = True
                    return ParseState.IGNORE, None, len(PREFACE)
        if len(buf) < 9:
            return ParseState.NEEDS_MORE_DATA, None, 0
        ln = int.from_bytes(buf[0:3], "big")
        ftype = buf[3]
        flags = buf[4]
        sid = int.from_bytes(buf[5:9], "big") & 0x7FFFFFFF
        if ftype > CONTINUATION or ln > MAX_FRAME_LEN:
            return ParseState.INVALID, None, 0
        if len(buf) < 9 + ln:
            return ParseState.NEEDS_MORE_DATA, None, 0
        payload = bytes(buf[9: 9 + ln])
        consumed = 9 + ln

        if ftype in (SETTINGS, PING, GOAWAY, WINDOW_UPDATE, PRIORITY,
                     PUSH_PROMISE):
            return ParseState.IGNORE, None, consumed

        if ftype == DATA:
            pad = payload[0] if (flags & F_PADDED) and payload else 0
            body = payload[1: len(payload) - pad] if (flags & F_PADDED) \
                else payload
            return ParseState.SUCCESS, H2Frame(
                type=DATA, flags=flags, stream_id=sid, data=body), consumed

        if ftype == RST_STREAM:
            return ParseState.SUCCESS, H2Frame(
                type=RST_STREAM, flags=flags, stream_id=sid), consumed

        if ftype == HEADERS:
            frag = payload
            if flags & F_PADDED:
                pad = frag[0] if frag else 0
                frag = frag[1: len(frag) - pad]
            if flags & F_PRIORITY:
                frag = frag[5:]
            if not (flags & F_END_HEADERS):
                state.pending_block[msg_type] = (sid, flags, bytearray(frag))
                return ParseState.IGNORE, None, consumed
            return self._emit_headers(state, msg_type, sid, flags, frag,
                                      consumed)

        if ftype == CONTINUATION:
            pend = state.pending_block[msg_type]
            if pend is None or pend[0] != sid:
                return ParseState.IGNORE, None, consumed
            pend[2].extend(payload)
            if not (flags & F_END_HEADERS):
                return ParseState.IGNORE, None, consumed
            state.pending_block[msg_type] = None
            return self._emit_headers(state, msg_type, sid, pend[1],
                                      bytes(pend[2]), consumed)

        return ParseState.IGNORE, None, consumed

    def _emit_headers(self, state, msg_type, sid, flags, frag, consumed):
        try:
            hdrs = state.hpack[msg_type].decode(bytes(frag))
        except ValueError:
            state.hpack_errors += 1
            return ParseState.IGNORE, None, consumed
        return ParseState.SUCCESS, H2Frame(
            type=HEADERS, flags=flags, stream_id=sid, headers=hdrs), consumed

    # ----------------------------------------------------------- stitching
    def stitch(self, requests, responses, state=None):
        if state is None:
            state = _ConnState()
        for deque_, half_name in ((requests, "req"), (responses, "resp")):
            while deque_:
                fr = deque_.popleft()
                st = state.stream(fr.stream_id)
                half = getattr(st, half_name)
                if half.t_first == 0:
                    half.t_first = fr.timestamp_ns
                half.t_last = max(half.t_last, fr.timestamp_ns)
                if fr.type == RST_STREAM:
                    st.reset = True
                    st.req.ended = st.resp.ended = True
                elif fr.type == HEADERS:
                    hd = dict(fr.headers)
                    if half.saw_headers:
                        half.trailers.update(hd)  # trailers (gRPC status)
                    else:
                        half.headers = hd
                        half.saw_headers = True
                    if fr.flags & F_END_STREAM:
                        half.ended = True
                elif fr.type == DATA:
                    half.data.extend(fr.data or b"")
                    if fr.flags & F_END_STREAM:
                        half.ended = True
        records = []
        errors = state.hpack_errors
        state.hpack_errors = 0
        done = [sid for sid, st in state.streams.items()
                if (st.req.ended and st.resp.ended)
                or (st.reset and st.req.saw_headers)]
        for sid in done:
            st = state.streams.pop(sid)
            if st.req.saw_headers or st.resp.saw_headers:
                records.append((sid, st))
            else:
                errors += 1
        # lost-END safety: evict oldest half-open streams beyond the rail
        if len(state.streams) > MAX_TRACKED_STREAMS:
            for sid in sorted(state.streams)[:-MAX_TRACKED_STREAMS]:
                del state.streams[sid]
                errors += 1
        return records, errors

    # ------------------------------------------------------------- records
    @staticmethod
    def _grpc_messages(data: bytes) -> list[bytes]:
        """Split gRPC length-prefixed messages (PROTOCOL-HTTP2.md framing)."""
        out = []
        pos = 0
        while pos + 5 <= len(data):
            ln = int.from_bytes(data[pos + 1: pos + 5], "big")
            if pos + 5 + ln > len(data):
                break
            out.append(data[pos + 5: pos + 5 + ln])
            pos += 5 + ln
        return out

    def record_row(self, record):
        sid, st = record
        req_h = dict(st.req.headers)
        resp_h = dict(st.resp.headers)
        resp_h.update({k: v for k, v in st.resp.trailers.items()})
        is_grpc = "grpc" in req_h.get("content-type", "")
        req_body = bytes(st.req.data)
        resp_body = bytes(st.resp.data)
        if is_grpc:
            req_msgs = self._grpc_messages(req_body)
            resp_msgs = self._grpc_messages(resp_body)
            req_body = b"".join(req_msgs) or req_body
            resp_body = b"".join(resp_msgs) or resp_body
        try:
            status = int(resp_h.get(":status", "0"))
        except ValueError:
            status = 0
        t_req = st.req.t_first or st.resp.t_first
        t_resp = st.resp.t_last or st.req.t_last
        return {
            "time_": t_resp,
            "latency": max(t_resp - t_req, 0),
            "major_version": 2,
            "minor_version": 0,
            "content_type": 2 if is_grpc else 0,
            "req_headers": json.dumps(req_h, sort_keys=True),
            "req_method": req_h.get(":method", ""),
            "req_path": req_h.get(":path", ""),
            "req_body": req_body.decode("latin-1"),
            "req_body_size": len(st.req.data),
            "resp_headers": json.dumps(resp_h, sort_keys=True),
            "resp_status": status,
            "resp_message": ("grpc-status: " + resp_h["grpc-status"]
                             if "grpc-status" in resp_h else ""),
            "resp_body": resp_body.decode("latin-1"),
            "resp_body_size": len(st.resp.data),
        }

"""Cassandra (CQL native protocol) parser + stream-id stitcher.

Reference: socket_tracer/protocols/cql/ (frame_body_decoder.cc, stitcher
matching by stream id; cass_table.h columns req_op/req_body/resp_op/resp_body).

Wire facts (CQL native protocol v3/v4): 9-byte header
  [version:1][flags:1][stream:2 BE][opcode:1][length:4 BE] + body.
Request frames have version 0x03/0x04; responses have the 0x80 bit set.
"""
from __future__ import annotations

import dataclasses
from collections import deque

from pixie_tpu.collect.protocols.base import (
    Frame,
    MessageType,
    ParseState,
    ProtocolParser,
)

# opcodes (cql spec §2.4; reference cql/types.h ReqOp/RespOp)
OP_ERROR = 0x00
OP_STARTUP = 0x01
OP_READY = 0x02
OP_AUTHENTICATE = 0x03
OP_OPTIONS = 0x05
OP_SUPPORTED = 0x06
OP_QUERY = 0x07
OP_RESULT = 0x08
OP_PREPARE = 0x09
OP_EXECUTE = 0x0A
OP_REGISTER = 0x0B
OP_EVENT = 0x0C
OP_BATCH = 0x0D
OP_AUTH_CHALLENGE = 0x0E
OP_AUTH_RESPONSE = 0x0F
OP_AUTH_SUCCESS = 0x10

_RESULT_KINDS = {1: "Void", 2: "Rows", 3: "Set keyspace", 4: "Prepared",
                 5: "Schema change"}


@dataclasses.dataclass
class CQLFrame(Frame):
    version: int = 0
    stream: int = 0
    opcode: int = 0
    body: bytes = b""


def _long_string(b: bytes) -> str:
    if len(b) < 4:
        return ""
    n = int.from_bytes(b[:4], "big")
    return b[4:4 + n].decode("latin1", "replace")


class CQLParser(ProtocolParser):
    name = "cql"
    table = "cql_events"

    def find_frame_boundary(self, msg_type, buf, start, state=None):
        want_resp = msg_type is MessageType.RESPONSE
        for pos in range(start, max(len(buf) - 9, start)):
            v = buf[pos]
            base = v & 0x7F
            if base not in (3, 4, 5) or bool(v & 0x80) != want_resp:
                continue
            ln = int.from_bytes(buf[pos + 5:pos + 9], "big")
            if ln <= 1 << 28:
                return pos
        return -1

    def parse_frame(self, msg_type, buf, state=None):
        if len(buf) < 9:
            return ParseState.NEEDS_MORE_DATA, None, 0
        version = buf[0]
        base = version & 0x7F
        is_resp = bool(version & 0x80)
        if base not in (3, 4, 5) or is_resp != (msg_type is MessageType.RESPONSE):
            return ParseState.INVALID, None, 0
        opcode = buf[4]
        if opcode > 0x10:
            return ParseState.INVALID, None, 0
        ln = int.from_bytes(buf[5:9], "big")
        if ln > 1 << 28:
            return ParseState.INVALID, None, 0
        if len(buf) < 9 + ln:
            return ParseState.NEEDS_MORE_DATA, None, 0
        frame = CQLFrame(
            version=base,
            stream=int.from_bytes(buf[2:4], "big", signed=True),
            opcode=opcode,
            body=bytes(buf[9:9 + ln]),
        )
        return ParseState.SUCCESS, frame, 9 + ln

    # ------------------------------------------------------------- stitching
    def stitch(self, requests, responses, state=None):
        records = []
        errors = 0
        # FIFO queue per stream id: two in-flight requests reusing one stream
        # id within a round must match their responses in order (latest-wins
        # would pair the newer request with the older response's latency).
        pending: dict[int, deque] = {}
        for r in requests:
            pending.setdefault(r.stream, deque()).append(r)
        matched_req = set()
        for resp in responses:
            if resp.opcode == OP_EVENT:  # server push, no request
                records.append((None, resp))
                continue
            q = pending.get(resp.stream)
            if not q:
                errors += 1
                continue
            req = q.popleft()
            # Self-heal after a lost response: a NEWER request strictly older
            # than this response on the same stream id means the head's
            # response was dropped (CQL forbids two in-flight per id) — the
            # stale head must not shift every later pairing on this stream.
            while q and req.timestamp_ns and \
                    req.timestamp_ns < q[0].timestamp_ns <= resp.timestamp_ns:
                errors += 1
                matched_req.add(id(req))  # abandoned: leave the deque too
                req = q.popleft()
            matched_req.add(id(req))
            records.append((req, resp))
        # Every response resolves this round (matched, push, or orphan);
        # rebuild the request deque once — O(n), not per-item remove.
        responses.clear()
        if matched_req:
            kept = [r for r in requests if id(r) not in matched_req]
            requests.clear()
            requests.extend(kept)
        return records, errors

    @staticmethod
    def _req_body(frame: CQLFrame) -> str:
        if frame.opcode in (OP_QUERY, OP_PREPARE):
            return _long_string(frame.body)
        if frame.opcode == OP_STARTUP:
            return "STARTUP"
        return ""

    @staticmethod
    def _resp_body(frame: CQLFrame) -> str:
        if frame.opcode == OP_RESULT and len(frame.body) >= 4:
            kind = int.from_bytes(frame.body[:4], "big")
            out = _RESULT_KINDS.get(kind, f"kind={kind}")
            if kind == 2 and len(frame.body) >= 12:
                # Rows: [metadata flags:4][col count:4] … row count follows
                # metadata; report column count which is cheap to decode.
                ncols = int.from_bytes(frame.body[8:12], "big")
                out = f"Rows ({ncols} columns)"
            return out
        if frame.opcode == OP_ERROR and len(frame.body) >= 6:
            # [code:4][message: SHORT string — 2-byte length (spec §3)]
            n = int.from_bytes(frame.body[4:6], "big")
            return frame.body[6:6 + n].decode("latin1", "replace")
        if frame.opcode == OP_READY:
            return "READY"
        return ""

    def record_row(self, record):
        req, resp = record
        req_ts = req.timestamp_ns if req is not None else resp.timestamp_ns
        return {
            "time_": resp.timestamp_ns,
            "latency": max(resp.timestamp_ns - req_ts, 0),
            "req_op": req.opcode if req is not None else -1,
            "req_body": self._req_body(req) if req is not None else "",
            "resp_op": resp.opcode,
            "resp_body": self._resp_body(resp),
        }

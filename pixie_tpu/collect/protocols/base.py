"""Core abstractions for protocol stream parsing.

Reference counterparts:
  * ParseState / message_type — src/stirling/utils/parse_state.h,
    socket_tracer/bcc_bpf_intf/common.h (message_type_t).
  * DataStream — socket_tracer/data_stream.h:50 (per-direction reassembly
    buffer that repeatedly parses frames and resyncs past garbage).
  * ConnTracker — socket_tracer/conn_tracker.h:87 (per-connection state:
    two DataStreams + stitching + conn stats).

Redesign notes: the reference parses into protocol-templated C++ deques and
transfers via per-protocol TransferSpecs; here frames are plain dataclasses
and stitched records are dict rows appended columnarly by the tracer
(collect/tracer.py), which matches this build's columnar ingest path.
"""
from __future__ import annotations

import dataclasses
import enum
from collections import deque
from typing import Any, Deque, Optional


class MessageType(enum.Enum):
    REQUEST = "request"
    RESPONSE = "response"


class ParseState(enum.Enum):
    #: frame parsed; consume `consumed` bytes and keep the frame
    SUCCESS = "success"
    #: not enough bytes yet; stop parsing this stream until more data
    NEEDS_MORE_DATA = "needs_more_data"
    #: bytes are not a valid frame start; resync via find_frame_boundary
    INVALID = "invalid"
    #: valid frame but not interesting (e.g. handshake); consume and drop
    IGNORE = "ignore"


@dataclasses.dataclass
class Frame:
    """Base parsed frame; protocol modules subclass with their own fields."""

    timestamp_ns: int = 0


class ProtocolParser:
    """The per-protocol contract (reference protocols/common/interface.h).

    Stateless w.r.t. connections: any cross-frame state lives in the object
    returned by new_state(), owned by the ConnTracker (reference state_type
    with global/send/recv members).
    """

    #: registry key, e.g. "mysql"
    name: str = ""
    #: destination table in collect/schemas.py
    table: str = ""
    #: True for datagram protocols (each data event is one message — DNS)
    datagram: bool = False

    def new_state(self) -> Any:
        return None

    def find_frame_boundary(self, msg_type: MessageType, buf: bytes,
                            start: int, state: Any = None) -> int:
        """Position > 0 of a plausible frame start, or -1 if none found."""
        return -1

    def parse_frame(self, msg_type: MessageType, buf: bytes,
                    state: Any = None):
        """-> (ParseState, frame_or_None, consumed_bytes)."""
        raise NotImplementedError

    def stitch(self, requests: Deque[Frame], responses: Deque[Frame],
               state: Any = None):
        """Match frames into records -> (list_of_records, error_count).

        Must consume matched/abandoned frames from the deques; unmatched
        trailing frames stay for the next round (streaming semantics).
        """
        raise NotImplementedError

    def record_row(self, record: Any) -> dict:
        """One stitched record -> column dict for `self.table` (protocol
        columns only; the tracer adds time_/upid/remote_addr/... common
        columns)."""
        raise NotImplementedError


#: safety rails mirroring the reference's buffer/retention limits
MAX_BUFFER_BYTES = 1 << 20
MAX_PARSED_FRAMES = 4096


class DataStream:
    """One direction of one connection: reassembly buffer + parsed frames.

    Timestamps: each appended chunk carries its capture timestamp; a frame
    gets the timestamp of the chunk containing its first byte (reference
    DataStream attaches BPF event timestamps the same way).
    """

    def __init__(self, parser: ProtocolParser, msg_type: MessageType):
        self.parser = parser
        self.msg_type = msg_type
        self._buf = bytearray()
        #: (offset_in_buf, timestamp_ns) markers, ascending offsets
        self._ts_marks: Deque[tuple[int, int]] = deque()
        self.frames: Deque[Frame] = deque()
        self.bytes_seen = 0
        self.invalid_frames = 0
        self.truncated_bytes = 0

    def add_data(self, data: bytes, timestamp_ns: int) -> None:
        if not data:
            return
        self._ts_marks.append((len(self._buf), timestamp_ns))
        self._buf += data
        self.bytes_seen += len(data)
        if len(self._buf) > MAX_BUFFER_BYTES:
            # Drop the oldest bytes (reference: retention-capped stream).
            drop = len(self._buf) - MAX_BUFFER_BYTES
            self._advance(drop)
            self.truncated_bytes += drop

    def _ts_at_head(self) -> int:
        return self._ts_marks[0][1] if self._ts_marks else 0

    def _advance(self, n: int) -> None:
        del self._buf[:n]
        marks = self._ts_marks
        while len(marks) > 1 and marks[1][0] <= n:
            marks.popleft()
        self._ts_marks = deque((max(off - n, 0), ts) for off, ts in marks)

    def process(self, state: Any = None) -> None:
        """Parse as many frames as possible off the buffer."""
        parser = self.parser
        while self._buf and len(self.frames) < MAX_PARSED_FRAMES:
            view = bytes(self._buf)
            st, frame, consumed = parser.parse_frame(self.msg_type, view, state)
            if st is ParseState.NEEDS_MORE_DATA:
                break
            if st in (ParseState.SUCCESS, ParseState.IGNORE):
                if consumed <= 0:  # defensive: a parser bug must not loop
                    break
                if st is ParseState.SUCCESS and frame is not None:
                    if frame.timestamp_ns == 0:
                        frame.timestamp_ns = self._ts_at_head()
                    self.frames.append(frame)
                self._advance(consumed)
                continue
            # INVALID: skip to the next plausible boundary.
            self.invalid_frames += 1
            pos = parser.find_frame_boundary(self.msg_type, view, 1, state)
            if pos <= 0:
                self._advance(len(self._buf))
            else:
                self._advance(pos)


class ConnTracker:
    """Per-connection state machine (reference conn_tracker.h:87).

    role: 1 = client-side capture (send = requests), 2 = server-side capture
    (recv = requests) — reference endpoint_role_t kRoleClient/kRoleServer.
    """

    ROLE_CLIENT = 1
    ROLE_SERVER = 2

    def __init__(self, parser: ProtocolParser, role: int = ROLE_SERVER,
                 upid=None, remote_addr: str = "", remote_port: int = 0):
        self.parser = parser
        self.role = role
        self.upid = upid
        self.remote_addr = remote_addr
        self.remote_port = remote_port
        self.state = parser.new_state()
        req_dir = MessageType.REQUEST
        resp_dir = MessageType.RESPONSE
        if role == self.ROLE_CLIENT:
            self.send = DataStream(parser, req_dir)
            self.recv = DataStream(parser, resp_dir)
        else:
            self.send = DataStream(parser, resp_dir)
            self.recv = DataStream(parser, req_dir)
        self.records_emitted = 0
        self.stitch_errors = 0
        self.closed = False

    @property
    def req_stream(self) -> DataStream:
        return self.send if self.role == self.ROLE_CLIENT else self.recv

    @property
    def resp_stream(self) -> DataStream:
        return self.recv if self.role == self.ROLE_CLIENT else self.send

    def add_data(self, direction: str, data: bytes, timestamp_ns: int) -> None:
        stream = self.send if direction == "send" else self.recv
        stream.add_data(data, timestamp_ns)

    #: unmatched frames kept after a stitch round; beyond this the oldest are
    #: expired (a lost peer event must not wedge the connection at
    #: MAX_PARSED_FRAMES and halt parsing forever)
    MAX_PENDING_FRAMES = 1024

    def process(self) -> list:
        """Parse both streams and stitch -> list of (record, row_dict)."""
        self.req_stream.process(self.state)
        self.resp_stream.process(self.state)
        records, errors = self.parser.stitch(
            self.req_stream.frames, self.resp_stream.frames, self.state
        )
        for frames in (self.req_stream.frames, self.resp_stream.frames):
            while len(frames) > self.MAX_PENDING_FRAMES:
                frames.popleft()
                errors += 1
        self.stitch_errors += errors
        self.records_emitted += len(records)
        return records

"""HTTP/1.x frame parser + stitcher.

Reference: src/stirling/source_connectors/socket_tracer/protocols/http/
(parse.cc pico-http-parser based frame parse; stitcher.cc FIFO req/resp
matching; http_table.h column semantics).
"""
from __future__ import annotations

import dataclasses
import json
from collections import deque

from pixie_tpu.collect.protocols.base import (
    Frame,
    MessageType,
    ParseState,
    ProtocolParser,
)

_METHODS = (b"GET ", b"POST ", b"PUT ", b"DELETE ", b"HEAD ", b"OPTIONS ",
            b"PATCH ", b"TRACE ", b"CONNECT ")

#: reference http/types.h ContentType enum
CONTENT_TYPE_UNKNOWN = 0
CONTENT_TYPE_JSON = 1

#: cap stored bodies like the reference's FLAGS_http_body_limit_bytes default
BODY_LIMIT = 512


@dataclasses.dataclass
class HTTPMessage(Frame):
    is_request: bool = True
    major: int = 1
    minor: int = 1
    method: str = ""
    path: str = ""
    status: int = 0
    message: str = ""
    headers: dict = dataclasses.field(default_factory=dict)
    body: str = ""
    body_size: int = 0


def _parse_headers(lines: list[bytes]) -> dict:
    headers: dict[str, str] = {}
    for ln in lines:
        if b":" not in ln:
            continue
        k, v = ln.split(b":", 1)
        headers[k.decode("latin1").strip().lower()] = v.decode("latin1").strip()
    return headers


def _parse_chunked(buf: bytes, start: int):
    """-> (body_bytes, end_offset) or None if incomplete, or -1 invalid."""
    pos = start
    body = bytearray()
    while True:
        nl = buf.find(b"\r\n", pos)
        if nl < 0:
            return None
        size_tok = buf[pos:nl].split(b";", 1)[0].strip()
        try:
            size = int(size_tok, 16)
        except ValueError:
            return -1
        pos = nl + 2
        if size == 0:
            # trailers until blank line
            end = buf.find(b"\r\n", pos)
            if end < 0:
                return None
            while end != pos:  # skip trailer lines
                pos = end + 2
                end = buf.find(b"\r\n", pos)
                if end < 0:
                    return None
            return bytes(body), end + 2
        if len(buf) < pos + size + 2:
            return None
        body += buf[pos:pos + size]
        pos += size + 2


class _State:
    """Response body semantics depend on the REQUEST (RFC 9110: HEAD
    responses carry no body even with Content-Length) — track the in-flight
    request methods in order.  The request stream is processed before the
    response stream each round (ConnTracker.process), so order holds."""

    def __init__(self):
        from collections import deque as _dq

        self.pending_methods = _dq()


class HTTPParser(ProtocolParser):
    name = "http"
    table = "http_events"

    def new_state(self):
        return _State()

    def find_frame_boundary(self, msg_type, buf, start, state=None):
        if msg_type is MessageType.RESPONSE:
            pos = buf.find(b"HTTP/1.", start)
            return pos if pos > 0 else -1
        best = -1
        for m in _METHODS:
            pos = buf.find(m, start)
            if pos > 0 and (best < 0 or pos < best):
                best = pos
        return best

    def parse_frame(self, msg_type, buf, state=None):
        hdr_end = buf.find(b"\r\n\r\n")
        if hdr_end < 0:
            if len(buf) > 64 * 1024:  # header section too big: not HTTP
                return ParseState.INVALID, None, 0
            return ParseState.NEEDS_MORE_DATA, None, 0
        head = buf[:hdr_end]
        lines = head.split(b"\r\n")
        start_line = lines[0].split(b" ", 2)
        msg = HTTPMessage()
        try:
            if msg_type is MessageType.REQUEST:
                if len(start_line) != 3 or not start_line[2].startswith(b"HTTP/"):
                    return ParseState.INVALID, None, 0
                msg.is_request = True
                msg.method = start_line[0].decode("latin1")
                msg.path = start_line[1].decode("latin1")
                ver = start_line[2][5:]
            else:
                if not start_line[0].startswith(b"HTTP/"):
                    return ParseState.INVALID, None, 0
                msg.is_request = False
                msg.status = int(start_line[1])
                msg.message = (start_line[2].decode("latin1")
                               if len(start_line) > 2 else "")
                ver = start_line[0][5:]
            mj, _, mn = ver.partition(b".")
            msg.major, msg.minor = int(mj), int(mn or 0)
        except (ValueError, IndexError):
            return ParseState.INVALID, None, 0
        msg.headers = _parse_headers(lines[1:])
        body_start = hdr_end + 4

        if not msg.is_request:
            # Peek (pop happens only on SUCCESS): NEEDS_MORE_DATA re-parses.
            head_req = (state is not None and state.pending_methods
                        and state.pending_methods[0] == "HEAD")
            # Bodiless responses (HEAD, 1xx, 204, 304) end at the headers no
            # matter what Content-Length claims — waiting for the declared
            # body would stall the stream forever.
            if head_req or msg.status in (204, 304) or 100 <= msg.status < 200:
                if state is not None and state.pending_methods:
                    state.pending_methods.popleft()
                return ParseState.SUCCESS, msg, body_start

        te = msg.headers.get("transfer-encoding", "")
        if "chunked" in te:
            res = _parse_chunked(buf, body_start)
            if res is None:
                return ParseState.NEEDS_MORE_DATA, None, 0
            if res == -1:
                return ParseState.INVALID, None, 0
            body, end = res
        else:
            try:
                clen = int(msg.headers.get("content-length", "0"))
            except ValueError:
                return ParseState.INVALID, None, 0
            if clen < 0:
                return ParseState.INVALID, None, 0
            if len(buf) < body_start + clen:
                return ParseState.NEEDS_MORE_DATA, None, 0
            body = buf[body_start:body_start + clen]
            end = body_start + clen
        msg.body_size = len(body)
        msg.body = body[:BODY_LIMIT].decode("latin1")
        # Method bookkeeping only on SUCCESS: partial parses return
        # NEEDS_MORE_DATA and re-run, which must not double-count.
        if state is not None:
            if msg.is_request:
                state.pending_methods.append(msg.method)
            elif state.pending_methods:
                state.pending_methods.popleft()
        return ParseState.SUCCESS, msg, end

    def stitch(self, requests, responses, state=None):
        records = []
        errors = 0
        while requests and responses:
            req = requests.popleft()
            # Drop responses that predate the oldest request (lost request).
            while responses and responses[0].timestamp_ns < req.timestamp_ns:
                responses.popleft()
                errors += 1
            if not responses:
                requests.appendleft(req)
                break
            records.append((req, responses.popleft()))
        return records, errors

    def record_row(self, record):
        req, resp = record
        ctype = CONTENT_TYPE_UNKNOWN
        if "json" in resp.headers.get("content-type", ""):
            ctype = CONTENT_TYPE_JSON
        return {
            # reference socket_trace_connector.cc AppendMessage: time_ is the
            # RESPONSE timestamp; latency = resp_ts - req_ts
            "time_": resp.timestamp_ns,
            "latency": max(resp.timestamp_ns - req.timestamp_ns, 0),
            "major_version": req.major,
            "minor_version": req.minor,
            "content_type": ctype,
            "req_headers": json.dumps(req.headers, sort_keys=True),
            "req_method": req.method,
            "req_path": req.path,
            "req_body": req.body,
            "req_body_size": req.body_size,
            "resp_headers": json.dumps(resp.headers, sort_keys=True),
            "resp_status": resp.status,
            "resp_message": resp.message,
            "resp_body": resp.body,
            "resp_body_size": resp.body_size,
        }

"""NATS text-protocol parser + stitcher.

Reference: socket_tracer/protocols/nats/ (parse.cc line-oriented command
parse with PUB/MSG payloads; nats_table.h columns cmd/body/resp).

Wire facts (NATS protocol): commands are CRLF-terminated lines —
INFO/CONNECT carry a JSON option block inline; PUB/HPUB/MSG/HMSG declare a
payload byte count on the line, followed by the payload and CRLF.
"""
from __future__ import annotations

import dataclasses
import json
from collections import deque

from pixie_tpu.collect.protocols.base import (
    Frame,
    MessageType,
    ParseState,
    ProtocolParser,
)

_PAYLOAD_CMDS = {"PUB", "HPUB", "MSG", "HMSG"}
_KNOWN = {"INFO", "CONNECT", "PUB", "HPUB", "SUB", "UNSUB", "MSG", "HMSG",
          "PING", "PONG", "+OK", "-ERR"}


@dataclasses.dataclass
class NATSCommand(Frame):
    cmd: str = ""
    args: list = dataclasses.field(default_factory=list)
    payload: str = ""


class NATSParser(ProtocolParser):
    name = "nats"
    table = "nats_events.beta"

    def find_frame_boundary(self, msg_type, buf, start, state=None):
        pos = buf.find(b"\r\n", start)
        return pos + 2 if pos >= 0 and pos + 2 < len(buf) else -1

    def parse_frame(self, msg_type, buf, state=None):
        nl = buf.find(b"\r\n")
        if nl < 0:
            if len(buf) > 1 << 16:
                return ParseState.INVALID, None, 0
            return ParseState.NEEDS_MORE_DATA, None, 0
        line = buf[:nl].decode("latin1", "replace")
        toks = line.split()
        if not toks:
            return ParseState.IGNORE, None, nl + 2
        cmd = toks[0].upper()
        if cmd not in _KNOWN:
            return ParseState.INVALID, None, 0
        frame = NATSCommand(cmd=cmd, args=toks[1:])
        end = nl + 2
        if cmd in _PAYLOAD_CMDS:
            try:
                size = int(toks[-1])
            except (ValueError, IndexError):
                return ParseState.INVALID, None, 0
            if size < 0 or size > 1 << 26:
                return ParseState.INVALID, None, 0
            if len(buf) < end + size + 2:
                return ParseState.NEEDS_MORE_DATA, None, 0
            frame.payload = buf[end:end + size].decode("latin1", "replace")
            end += size + 2
        return ParseState.SUCCESS, frame, end

    def new_state(self):
        class _State:
            #: id() of a request already held back one round awaiting its ack
            held = None
            #: connection has shown +OK/-ERR acks (CONNECT verbose mode)
            verbose = False

        return _State()

    # ------------------------------------------------------------- stitching
    def stitch(self, requests, responses, state=None):
        """NATS is not strictly request/response: most commands are one-way.
        Each frame (either direction) becomes a record; +OK/-ERR responses
        attach to the most recent unacked client command (verbose mode).
        On VERBOSE connections (ones that have shown acks) the newest
        unanswered command is held back for one round so an ack landing in
        the next transfer interval can still attach; non-verbose connections
        (the common mode — servers never ack) emit immediately."""
        records = []
        errors = 0
        if state is not None and not state.verbose:
            state.verbose = any(r.cmd in ("+OK", "-ERR") for r in responses)
        while requests:
            req = requests[0]
            resp = ""
            if responses and responses[0].cmd in ("+OK", "-ERR") \
                    and responses[0].timestamp_ns >= req.timestamp_ns:
                r = responses.popleft()
                resp = r.cmd if not r.args else f"{r.cmd} {' '.join(r.args)}"
            elif len(requests) == 1 and state is not None and state.verbose \
                    and state.held != id(req):
                state.held = id(req)
                break  # wait one round for a possible late ack
            requests.popleft()
            if state is not None and state.held == id(req):
                state.held = None
            records.append((req, resp))
        while responses:
            r = responses.popleft()
            if r.cmd in ("+OK",):  # stray ack with no visible command
                continue
            records.append((r, ""))
        return records, errors

    def record_row(self, record):
        frame, resp = record
        body: dict[str, object] = {}
        c, a = frame.cmd, frame.args
        if c in ("INFO", "CONNECT") and a:
            body["options"] = " ".join(a)
        elif c in ("PUB", "HPUB") and a:
            body = {"subject": a[0], "payload": frame.payload}
            if len(a) > 2:
                body["reply_to"] = a[1]
        elif c in ("MSG", "HMSG") and len(a) >= 2:
            body = {"subject": a[0], "sid": a[1], "payload": frame.payload}
            if len(a) > 3:
                body["reply_to"] = a[2]
        elif c == "SUB" and a:
            body = {"subject": a[0], "sid": a[-1]}
        elif c == "UNSUB" and a:
            body = {"sid": a[0]}
        elif c == "-ERR" and a:
            body = {"error": " ".join(a)}
        return {
            "time_": frame.timestamp_ns,
            "cmd": c,
            "body": json.dumps(body, separators=(",", ":")),
            "resp": resp,
        }

"""DNS message parser + txid stitcher.

Reference: socket_tracer/protocols/dns/ (parse.cc full message decode with
name compression; stitcher.cc txid matching + rapidjson record formatting —
the JSON shapes here mirror stitcher.cc:37-130 so `px/dns_data` renders
identically).

Datagram protocol: each capture event is one complete DNS message (header
12 bytes: txid, flags, qd/an/ns/ar counts, then sections).
"""
from __future__ import annotations

import dataclasses
import json
from collections import deque

from pixie_tpu.collect.protocols.base import (
    Frame,
    MessageType,
    ParseState,
    ProtocolParser,
)

_TYPE_A = 1
_TYPE_NS = 2
_TYPE_CNAME = 5
_TYPE_SOA = 6
_TYPE_PTR = 12
_TYPE_MX = 15
_TYPE_TXT = 16
_TYPE_AAAA = 28


@dataclasses.dataclass
class DNSMessage(Frame):
    txid: int = 0
    flags: int = 0
    num_queries: int = 0
    num_answers: int = 0
    num_auth: int = 0
    num_addl: int = 0
    #: [(name, qtype)] questions
    queries: list = dataclasses.field(default_factory=list)
    #: [{"name":…, "type":…, "addr"/"cname":…}] answers
    answers: list = dataclasses.field(default_factory=list)

    @property
    def is_response(self) -> bool:
        return bool(self.flags >> 15)


def _read_name(buf: bytes, pos: int, depth: int = 0):
    """DNS name with compression pointers -> (name, next_pos) or None."""
    if depth > 10:
        return None
    labels = []
    while True:
        if pos >= len(buf):
            return None
        ln = buf[pos]
        if ln == 0:
            pos += 1
            break
        if ln & 0xC0 == 0xC0:  # compression pointer
            if pos + 2 > len(buf):
                return None
            ptr = int.from_bytes(buf[pos:pos + 2], "big") & 0x3FFF
            if ptr >= pos:
                return None
            tail = _read_name(buf, ptr, depth + 1)
            if tail is None:
                return None
            labels.append(tail[0])
            pos += 2
            return ".".join(x for x in labels if x), pos
        if ln & 0xC0:
            return None
        if pos + 1 + ln > len(buf):
            return None
        labels.append(buf[pos + 1:pos + 1 + ln].decode("latin1", "replace"))
        pos += 1 + ln
    return ".".join(labels), pos


def _type_name(t: int) -> str:
    # reference DNSRecordTypeName: A/AAAA from addr family, "" otherwise
    return {_TYPE_A: "A", _TYPE_AAAA: "AAAA", _TYPE_CNAME: "CNAME"}.get(t, "")


def _ipv4(b: bytes) -> str:
    return ".".join(str(x) for x in b)


def _ipv6(b: bytes) -> str:
    import ipaddress

    return str(ipaddress.IPv6Address(b))


class DNSParser(ProtocolParser):
    name = "dns"
    table = "dns_events"
    datagram = True

    def parse_frame(self, msg_type, buf, state=None):
        if len(buf) < 12:
            return ParseState.NEEDS_MORE_DATA, None, 0
        msg = DNSMessage(
            txid=int.from_bytes(buf[0:2], "big"),
            flags=int.from_bytes(buf[2:4], "big"),
            num_queries=int.from_bytes(buf[4:6], "big"),
            num_answers=int.from_bytes(buf[6:8], "big"),
            num_auth=int.from_bytes(buf[8:10], "big"),
            num_addl=int.from_bytes(buf[10:12], "big"),
        )
        if msg.num_queries > 100 or msg.num_answers > 1000:
            return ParseState.INVALID, None, 0
        pos = 12
        for _ in range(msg.num_queries):
            got = _read_name(buf, pos)
            if got is None or got[1] + 4 > len(buf):
                return ParseState.INVALID, None, 0
            name, pos = got
            qtype = int.from_bytes(buf[pos:pos + 2], "big")
            pos += 4  # type + class
            msg.queries.append((name, qtype))
        for _ in range(msg.num_answers):
            got = _read_name(buf, pos)
            if got is None or got[1] + 10 > len(buf):
                return ParseState.INVALID, None, 0
            name, pos = got
            rtype = int.from_bytes(buf[pos:pos + 2], "big")
            rdlen = int.from_bytes(buf[pos + 8:pos + 10], "big")
            pos += 10
            if pos + rdlen > len(buf):
                return ParseState.INVALID, None, 0
            rdata = buf[pos:pos + rdlen]
            pos += rdlen
            ans = {"name": name, "type": _type_name(rtype)}
            if rtype == _TYPE_A and rdlen == 4:
                ans["addr"] = _ipv4(rdata)
            elif rtype == _TYPE_AAAA and rdlen == 16:
                ans["addr"] = _ipv6(rdata)
            elif rtype == _TYPE_CNAME:
                got = _read_name(buf, pos - rdlen)
                ans["cname"] = got[0] if got else ""
            msg.answers.append(ans)
        # Authority/additional sections are counted in the header and SKIPPED
        # (not decoded into records — reference behavior), but must still be
        # walked so `consumed` lands on the true message end: consuming
        # len(buf) would swallow any further messages queued in the stream.
        for _ in range(msg.num_auth + msg.num_addl):
            got = _read_name(buf, pos)
            if got is None or got[1] + 10 > len(buf):
                return ParseState.INVALID, None, 0
            _name, pos = got
            rdlen = int.from_bytes(buf[pos + 8:pos + 10], "big")
            pos += 10 + rdlen
            if pos > len(buf):
                return ParseState.INVALID, None, 0
        return ParseState.SUCCESS, msg, pos

    # ------------------------------------------------------------- stitching
    def stitch(self, requests, responses, state=None):
        records = []
        errors = 0
        by_txid = {}
        for req in requests:
            by_txid.setdefault(req.txid, deque()).append(req)
        matched_reqs = set()
        for resp in responses:
            q = by_txid.get(resp.txid)
            if not q:
                errors += 1  # orphan response (request lost / mid-attach)
                continue
            req = q.popleft()
            matched_reqs.add(id(req))
            records.append((req, resp))
        # Rebuild (O(n)) instead of per-item remove (O(n^2)); ALL responses
        # drain — matched ones are recorded, orphans counted and dropped.
        responses.clear()
        if matched_reqs:
            kept = [r for r in requests if id(r) not in matched_reqs]
            requests.clear()
            requests.extend(kept)
        return records, errors

    @staticmethod
    def _header_json(msg: DNSMessage) -> str:
        f = msg.flags
        d = {
            "txid": msg.txid,
            "qr": (f >> 15) & 1, "opcode": (f >> 11) & 0xF,
            "aa": (f >> 10) & 1, "tc": (f >> 9) & 1, "rd": (f >> 8) & 1,
            "ra": (f >> 7) & 1, "ad": (f >> 5) & 1, "cd": (f >> 4) & 1,
            "rcode": f & 0xF,
            "num_queries": msg.num_queries, "num_answers": msg.num_answers,
            "num_auth": msg.num_auth, "num_addl": msg.num_addl,
        }
        return json.dumps(d, separators=(",", ":"))

    def record_row(self, record):
        req, resp = record
        queries = [{"name": n, "type": _type_name(t)} for n, t in req.queries]
        return {
            "time_": resp.timestamp_ns,
            "latency": max(resp.timestamp_ns - req.timestamp_ns, 0),
            "req_header": self._header_json(req),
            "req_body": json.dumps({"queries": queries}, separators=(",", ":")),
            "resp_header": self._header_json(resp),
            "resp_body": json.dumps({"answers": resp.answers},
                                    separators=(",", ":")),
        }

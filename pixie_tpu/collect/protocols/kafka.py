"""Kafka wire-protocol parser + correlation-id stitcher.

Reference: socket_tracer/protocols/kafka/ (decoder framework under
decoder/, stitcher by correlation_id; kafka_table.h columns req_cmd,
client_id, req_body, resp).

Wire facts (Kafka protocol): every message is [length:4 BE][payload].
Request payload: [api_key:2][api_version:2][correlation_id:4]
[client_id: int16-length string (nullable, -1)] [request body].
Response payload: [correlation_id:4][response body].
"""
from __future__ import annotations

import dataclasses
import json
from collections import deque

from pixie_tpu.collect.protocols.base import (
    Frame,
    MessageType,
    ParseState,
    ProtocolParser,
)

#: api_key → name (Kafka protocol spec; reference kafka/common/types.h)
API_KEYS = {
    0: "Produce", 1: "Fetch", 2: "ListOffsets", 3: "Metadata",
    8: "OffsetCommit", 9: "OffsetFetch", 10: "FindCoordinator",
    11: "JoinGroup", 12: "Heartbeat", 13: "LeaveGroup", 14: "SyncGroup",
    15: "DescribeGroups", 16: "ListGroups", 17: "SaslHandshake",
    18: "ApiVersions", 19: "CreateTopics", 20: "DeleteTopics",
    22: "InitProducerId", 32: "DescribeConfigs", 36: "SaslAuthenticate",
}


@dataclasses.dataclass
class KafkaFrame(Frame):
    is_request: bool = True
    api_key: int = 0
    api_version: int = 0
    correlation_id: int = 0
    client_id: str = ""
    body_size: int = 0


class _State:
    """Stitching needs request api metadata to interpret responses, and the
    set of outstanding correlation ids to frame the response stream."""

    def __init__(self):
        self.outstanding: dict[int, KafkaFrame] = {}


class KafkaParser(ProtocolParser):
    name = "kafka"
    table = "kafka_events.beta"

    def new_state(self):
        return _State()

    def find_frame_boundary(self, msg_type, buf, start, state=None):
        for pos in range(start, max(len(buf) - 8, start)):
            ln = int.from_bytes(buf[pos:pos + 4], "big")
            if not 8 <= ln <= 1 << 24:
                continue
            if msg_type is MessageType.REQUEST:
                api_key = int.from_bytes(buf[pos + 4:pos + 6], "big")
                if api_key in API_KEYS:
                    return pos
            else:
                return pos
        return -1

    def parse_frame(self, msg_type, buf, state=None):
        if len(buf) < 4:
            return ParseState.NEEDS_MORE_DATA, None, 0
        ln = int.from_bytes(buf[:4], "big")
        if not 4 <= ln <= 1 << 26:
            return ParseState.INVALID, None, 0
        if len(buf) < 4 + ln:
            return ParseState.NEEDS_MORE_DATA, None, 0
        p = bytes(buf[4:4 + ln])
        frame = KafkaFrame(body_size=ln)
        if msg_type is MessageType.REQUEST:
            if len(p) < 8:
                return ParseState.INVALID, None, 0
            frame.is_request = True
            frame.api_key = int.from_bytes(p[0:2], "big", signed=True)
            frame.api_version = int.from_bytes(p[2:4], "big", signed=True)
            frame.correlation_id = int.from_bytes(p[4:8], "big", signed=True)
            if frame.api_key not in API_KEYS or frame.api_version > 20:
                return ParseState.INVALID, None, 0
            cid_len = int.from_bytes(p[8:10], "big", signed=True) \
                if len(p) >= 10 else -1
            if cid_len > 0 and len(p) >= 10 + cid_len:
                frame.client_id = p[10:10 + cid_len].decode("latin1", "replace")
        else:
            if len(p) < 4:
                return ParseState.INVALID, None, 0
            frame.is_request = False
            frame.correlation_id = int.from_bytes(p[0:4], "big", signed=True)
        return ParseState.SUCCESS, frame, 4 + ln

    # ------------------------------------------------------------- stitching
    def stitch(self, requests, responses, state=None):
        records = []
        errors = 0
        pending: dict[int, KafkaFrame] = {}
        for req in requests:
            pending[req.correlation_id] = req
        matched_req = set()
        for resp in responses:
            req = pending.pop(resp.correlation_id, None)
            if req is None:
                errors += 1
                continue
            matched_req.add(id(req))
            records.append((req, resp))
        responses.clear()
        if matched_req:
            kept = [r for r in requests if id(r) not in matched_req]
            requests.clear()
            requests.extend(kept)
        return records, errors

    def record_row(self, record):
        req, resp = record
        return {
            "time_": resp.timestamp_ns,
            "latency": max(resp.timestamp_ns - req.timestamp_ns, 0),
            "req_cmd": req.api_key,
            "client_id": req.client_id,
            "req_body": json.dumps(
                {"api": API_KEYS.get(req.api_key, str(req.api_key)),
                 "api_version": req.api_version,
                 "size": req.body_size},
                separators=(",", ":")),
            "resp": json.dumps({"size": resp.body_size},
                               separators=(",", ":")),
        }

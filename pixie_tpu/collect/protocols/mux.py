"""Twitter Mux (Finagle RPC) protocol parser + tag stitcher.

Reference: socket_tracer/protocols/mux/ (parse.cc 4-byte-length framing,
stitcher by 3-byte tag; mux_table.h columns req_type + latency only).

Wire facts (mux spec): every message is [length:4 BE][type:1 signed][tag:3].
Positive types are sent Tmessages (requests); their negative counterpart is
the Rmessage reply carrying the same tag.
"""
from __future__ import annotations

import dataclasses
from collections import deque

from pixie_tpu.collect.protocols.base import (
    Frame,
    MessageType,
    ParseState,
    ProtocolParser,
)

#: mux message types (spec; reference mux/types.h)
T_TYPES = {1: "Treq", 2: "Tdispatch", 64: "Tinit", 65: "Tping",
           66: "Tdiscarded", 67: "Tlease", 68: "Tdrain"}
_VALID_TYPES = set(T_TYPES) | {-t for t in T_TYPES} | {127, -128, -62}


@dataclasses.dataclass
class MuxFrame(Frame):
    type_: int = 0
    tag: int = 0
    length: int = 0


class MuxParser(ProtocolParser):
    name = "mux"
    table = "mux_events"

    def find_frame_boundary(self, msg_type, buf, start, state=None):
        for pos in range(start, max(len(buf) - 8, start)):
            ln = int.from_bytes(buf[pos:pos + 4], "big")
            t = int.from_bytes(buf[pos + 4:pos + 5], "big", signed=True)
            if 4 <= ln <= 1 << 24 and t in _VALID_TYPES:
                return pos
        return -1

    def parse_frame(self, msg_type, buf, state=None):
        if len(buf) < 8:
            return ParseState.NEEDS_MORE_DATA, None, 0
        ln = int.from_bytes(buf[:4], "big")
        if not 4 <= ln <= 1 << 24:
            return ParseState.INVALID, None, 0
        t = int.from_bytes(buf[4:5], "big", signed=True)
        if t not in _VALID_TYPES:
            return ParseState.INVALID, None, 0
        if len(buf) < 4 + ln:
            return ParseState.NEEDS_MORE_DATA, None, 0
        frame = MuxFrame(
            type_=t,
            tag=int.from_bytes(buf[5:8], "big"),
            length=ln,
        )
        return ParseState.SUCCESS, frame, 4 + ln

    # ------------------------------------------------------------- stitching
    def stitch(self, requests, responses, state=None):
        records = []
        errors = 0
        pending: dict[int, MuxFrame] = {}
        for req in requests:
            pending[req.tag] = req
        matched_req = set()
        for resp in responses:
            req = pending.pop(resp.tag, None)
            if req is None:
                errors += 1
                continue
            # The tag is answered either way; a type mismatch is an error
            # record dropped, and the request must not linger forever.
            matched_req.add(id(req))
            if resp.type_ != -req.type_:
                errors += 1
                continue
            records.append((req, resp))
        responses.clear()
        if matched_req:
            kept = [r for r in requests if id(r) not in matched_req]
            requests.clear()
            requests.extend(kept)
        return records, errors

    def record_row(self, record):
        req, resp = record
        return {
            "time_": resp.timestamp_ns,
            "latency": max(resp.timestamp_ns - req.timestamp_ns, 0),
            "req_type": req.type_,
        }

"""Redis RESP protocol parser + stitcher.

Reference: socket_tracer/protocols/redis/ (parse.cc recursive RESP decode,
stitcher matching with pub/sub push handling, cmd table formatting.cc).

Wire facts (RESP2): values are
  +simple\r\n  -error\r\n  :int\r\n  $len\r\n<bytes>\r\n  *n\r\n<values>
A client request is an array of bulk strings; `$-1` / `*-1` are nulls.
"""
from __future__ import annotations

import dataclasses
import json
from collections import deque

from pixie_tpu.collect.protocols.base import (
    Frame,
    MessageType,
    ParseState,
    ProtocolParser,
)

#: two-token commands (subset of reference redis/cmd_args.json keys)
_COMPOSITE_CMDS = {
    "CLUSTER", "CLIENT", "CONFIG", "COMMAND", "MEMORY", "LATENCY", "OBJECT",
    "SCRIPT", "SLOWLOG", "XGROUP", "XINFO", "ACL", "DEBUG", "FUNCTION",
    "PUBSUB",
}
#: server→client push message kinds (reference stitcher: published messages)
_PUSH_KINDS = {"message", "pmessage", "subscribe", "unsubscribe",
               "psubscribe", "punsubscribe"}


@dataclasses.dataclass
class RedisValue(Frame):
    #: decoded python value: str | int | None | list
    value: object = None
    is_error: bool = False


def _parse_value(buf: bytes, pos: int, depth: int = 0):
    """-> (value, is_error, next_pos) or None (need more) or False (invalid)."""
    if depth > 32:
        return False
    if pos >= len(buf):
        return None
    t = buf[pos:pos + 1]
    nl = buf.find(b"\r\n", pos + 1)
    if t not in b"+-:$*":
        return False
    if nl < 0:
        return None if len(buf) - pos < 1 << 16 else False
    head = buf[pos + 1:nl]
    if t == b"+":
        return head.decode("latin1", "replace"), False, nl + 2
    if t == b"-":
        return head.decode("latin1", "replace"), True, nl + 2
    if t == b":":
        try:
            return int(head), False, nl + 2
        except ValueError:
            return False
    try:
        n = int(head)
    except ValueError:
        return False
    if t == b"$":
        if n == -1:
            return None, False, nl + 2
        if n < 0 or n > 512 * 1024 * 1024:
            return False
        end = nl + 2 + n
        if len(buf) < end + 2:
            return None
        if buf[end:end + 2] != b"\r\n":
            return False
        return buf[nl + 2:end].decode("latin1", "replace"), False, end + 2
    # array
    if n == -1:
        return None, False, nl + 2
    if n < 0 or n > 1 << 20:
        return False
    items = []
    p = nl + 2
    for _ in range(n):
        got = _parse_value(buf, p, depth + 1)
        if got is None or got is False:
            return got
        v, _err, p = got
        items.append(v)
    return items, False, p


def _fmt(value) -> str:
    """Human formatting like the reference's formatting.cc."""
    if value is None:
        return "<NULL>"
    if isinstance(value, list):
        return json.dumps([_fmt(v) if not isinstance(v, str) else v
                           for v in value], separators=(",", ":"))
    return str(value)


class RedisParser(ProtocolParser):
    name = "redis"
    table = "redis_events"

    def find_frame_boundary(self, msg_type, buf, start, state=None):
        for pos in range(start, len(buf)):
            if buf[pos:pos + 1] in b"+-:$*":
                return pos
        return -1

    def parse_frame(self, msg_type, buf, state=None):
        got = _parse_value(bytes(buf), 0)
        if got is None:
            return ParseState.NEEDS_MORE_DATA, None, 0
        if got is False:
            return ParseState.INVALID, None, 0
        value, is_err, consumed = got
        return ParseState.SUCCESS, RedisValue(value=value, is_error=is_err), consumed

    # ------------------------------------------------------------- stitching
    @staticmethod
    def _is_push(resp: RedisValue) -> bool:
        v = resp.value
        return (isinstance(v, list) and v
                and isinstance(v[0], str) and v[0].lower() in _PUSH_KINDS)

    def stitch(self, requests, responses, state=None):
        records = []
        errors = 0
        while responses:
            resp = responses[0]
            if self._is_push(resp) and (
                    not requests
                    or requests[0].timestamp_ns > resp.timestamp_ns):
                # Server push with no outstanding request (reference: the
                # stitcher emits pub/sub messages with an empty request).
                responses.popleft()
                records.append((None, resp))
                continue
            if not requests:
                break
            req = requests.popleft()
            responses.popleft()
            records.append((req, resp))
        return records, errors

    def record_row(self, record):
        req, resp = record
        cmd = ""
        args = []
        ts_req = resp.timestamp_ns
        if req is not None:
            ts_req = req.timestamp_ns
            v = req.value
            if isinstance(v, list) and v:
                toks = [str(x) for x in v]
                cmd = toks[0].upper()
                rest = toks[1:]
                if cmd in _COMPOSITE_CMDS and rest:
                    cmd = f"{cmd} {rest[0].upper()}"
                    rest = rest[1:]
                args = rest
            else:
                cmd = _fmt(v)
        elif self._is_push(resp):
            cmd = "PUSH PUB"
        return {
            "time_": resp.timestamp_ns,
            "latency": max(resp.timestamp_ns - ts_req, 0),
            "req_cmd": cmd,
            "req_args": json.dumps(args, separators=(",", ":")),
            "resp": _fmt(resp.value),
        }

"""PostgreSQL wire-protocol parser + stitcher.

Reference: socket_tracer/protocols/pgsql/ (parse.cc tag+length framing,
stitcher.cc query→response-group matching up to ReadyForQuery).

Wire facts (PostgreSQL frontend/backend protocol v3): regular messages are
  [tag:1][len:4 big-endian, includes itself][payload:len-4].
The startup message and SSLRequest have no tag byte. Responses to a simple
Query run until ReadyForQuery ('Z').
"""
from __future__ import annotations

import dataclasses
from collections import deque

from pixie_tpu.collect.protocols.base import (
    Frame,
    MessageType,
    ParseState,
    ProtocolParser,
)

#: tag → reference-style command name (pgsql/types.h ToString(tag))
_REQ_TAGS = {
    b"Q": "Query", b"P": "Parse", b"B": "Bind", b"E": "Execute",
    b"D": "Describe", b"C": "Close", b"S": "Sync", b"F": "Fcall",
    b"X": "Terminate", b"H": "Flush", b"d": "CopyData", b"c": "CopyDone",
    b"f": "CopyFail", b"p": "Password",
}
_RESP_TAGS = {
    b"R": "Auth", b"K": "KeyData", b"S": "ParamStatus", b"T": "RowDesc",
    b"D": "DataRow", b"C": "CmdComplete", b"E": "ErrResp", b"N": "Notice",
    b"Z": "ReadyForQuery", b"I": "EmptyQuery", b"1": "ParseComplete",
    b"2": "BindComplete", b"3": "CloseComplete", b"n": "NoData",
    b"t": "ParamDesc", b"A": "Notification", b"G": "CopyIn", b"H": "CopyOut",
    b"d": "CopyData", b"c": "CopyDone", b"W": "CopyBoth", b"s": "PortalSuspend",
}

_SSL_REQUEST_CODE = 80877103
_PROTO_V3 = 196608


@dataclasses.dataclass
class PgMessage(Frame):
    tag: bytes = b""
    payload: bytes = b""


def _cstr(b: bytes) -> str:
    end = b.find(b"\x00")
    return (b[:end] if end >= 0 else b).decode("latin1", "replace")


def _err_fields(payload: bytes) -> str:
    """ErrorResponse payload: sequence of [code:1][value\\0]; return the
    human message (severity + M field) like the reference stitcher."""
    sev = msg = ""
    pos = 0
    while pos < len(payload) and payload[pos:pos + 1] != b"\x00":
        code = payload[pos:pos + 1]
        end = payload.find(b"\x00", pos + 1)
        if end < 0:
            break
        val = payload[pos + 1:end].decode("latin1", "replace")
        if code == b"S":
            sev = val
        elif code == b"M":
            msg = val
        pos = end + 1
    return f"{sev} {msg}".strip()


class _State:
    def __init__(self):
        self.startup_done = False   # request stream consumed the startup
        #: response stream saw its first bytes (the SSLRequest answer is a
        #: single tagless byte and may only be the FIRST thing the server
        #: sends — keyed per-stream, since the request stream processes
        #: first each round and must not flip response-side state)
        self.resp_started = False


class PgSQLParser(ProtocolParser):
    name = "pgsql"
    table = "pgsql_events"

    def new_state(self):
        return _State()

    def find_frame_boundary(self, msg_type, buf, start, state=None):
        tags = _REQ_TAGS if msg_type is MessageType.REQUEST else _RESP_TAGS
        for pos in range(start, max(len(buf) - 5, start)):
            if buf[pos:pos + 1] in tags:
                ln = int.from_bytes(buf[pos + 1:pos + 5], "big")
                if 4 <= ln <= 1 << 24:
                    return pos
        return -1

    def parse_frame(self, msg_type, buf, state=None):
        # Startup / SSLRequest (request stream, before startup_done): no tag.
        if (msg_type is MessageType.REQUEST and state is not None
                and not state.startup_done):
            if len(buf) < 8:
                return ParseState.NEEDS_MORE_DATA, None, 0
            ln = int.from_bytes(buf[:4], "big")
            code = int.from_bytes(buf[4:8], "big")
            if code in (_PROTO_V3, _SSL_REQUEST_CODE) and 8 <= ln <= 1 << 16:
                if len(buf) < ln:
                    return ParseState.NEEDS_MORE_DATA, None, 0
                if code == _PROTO_V3:
                    state.startup_done = True
                return ParseState.IGNORE, None, ln
            state.startup_done = True  # mid-stream attach: no startup seen
        # One server byte 'S'/'N' answers SSLRequest with NO length field.
        # This must be checked BEFORE the tagged-message path ('S' and 'N'
        # are also valid response tags), keyed on the response stream's OWN
        # first-bytes state plus an implausible would-be length.
        if msg_type is MessageType.RESPONSE and state is not None \
                and not state.resp_started:
            state.resp_started = True
            if buf[:1] in (b"S", b"N"):
                ln_guess = (int.from_bytes(buf[1:5], "big")
                            if len(buf) >= 5 else -1)
                if ln_guess < 4 or ln_guess > 1 << 24:
                    return ParseState.IGNORE, None, 1
        if len(buf) < 5:
            return ParseState.NEEDS_MORE_DATA, None, 0
        tag = buf[:1]
        tags = _REQ_TAGS if msg_type is MessageType.REQUEST else _RESP_TAGS
        if tag not in tags:
            return ParseState.INVALID, None, 0
        ln = int.from_bytes(buf[1:5], "big")
        if ln < 4 or ln > 1 << 24:
            return ParseState.INVALID, None, 0
        if len(buf) < 1 + ln:
            return ParseState.NEEDS_MORE_DATA, None, 0
        payload = bytes(buf[5:1 + ln])
        # Async/noise messages that are not part of any exchange.
        if msg_type is MessageType.RESPONSE and tag in (b"S", b"K", b"R",
                                                        b"N", b"A"):
            if state is not None and tag == b"R":
                state.startup_done = True
            return ParseState.IGNORE, None, 1 + ln
        return ParseState.SUCCESS, PgMessage(tag=tag, payload=payload), 1 + ln

    # ------------------------------------------------------------- stitching
    def stitch(self, requests, responses, state=None):
        records = []
        errors = 0
        while requests:
            req = requests[0]
            if req.tag in (b"X", b"c", b"d", b"f", b"H"):
                requests.popleft()  # no paired response
                continue
            # The response group for the oldest request: frames up to and
            # including ReadyForQuery that belong to it (i.e. before the
            # next request's timestamp).
            nxt_ts = requests[1].timestamp_ns if len(requests) > 1 else None
            group = []
            done = False
            for m in responses:
                if nxt_ts is not None and m.timestamp_ns >= nxt_ts and group:
                    done = True  # next request started: close this group
                    break
                group.append(m)
                if m.tag == b"Z":
                    done = True
                    break
            if not done:
                break
            for _ in group:
                responses.popleft()
            requests.popleft()
            records.append((req, group))
        return records, errors

    def record_row(self, record):
        req, group = record
        req_text = ""
        if req.tag in (b"Q", b"P"):
            # Parse: [stmt\0][query\0]; Query: [query\0]
            p = req.payload
            if req.tag == b"P":
                first = p.find(b"\x00")
                p = p[first + 1:] if first >= 0 else p
            req_text = _cstr(p)
        resp_text = ""
        n_rows = 0
        end_ts = req.timestamp_ns
        for m in group:
            end_ts = max(end_ts, m.timestamp_ns)
            if m.tag == b"D":
                n_rows += 1
            elif m.tag == b"C":
                resp_text = _cstr(m.payload)
            elif m.tag == b"E":
                resp_text = _err_fields(m.payload)
            elif m.tag == b"I":
                resp_text = resp_text or "EmptyQueryResponse"
        if n_rows and resp_text:
            resp_text = f"{resp_text} ({n_rows} rows)"
        return {
            "time_": end_ts,
            "latency": max(end_ts - req.timestamp_ns, 0),
            "req_cmd": _REQ_TAGS.get(req.tag, "Unknown"),
            "req": req_text,
            "resp": resp_text,
        }

"""Protocol traffic parsers — the userspace half of the reference's socket
tracer (src/stirling/source_connectors/socket_tracer/protocols/).

Each protocol module implements the three-function contract of the reference
(protocols/common/interface.h:75-103) as a ProtocolParser subclass:

  * find_frame_boundary — resync position after garbage bytes
  * parse_frame         — one frame off the front of a byte stream
  * stitch              — match request/response frames into records

The kernel eBPF capture half is host-specific and out of environment; byte
streams arrive instead from capture replays, live tap proxies, or test
fixtures (the reference itself unit-tests this layer on captured byte
streams — protocols/http/parse_test.cc).
"""
from __future__ import annotations

from pixie_tpu.collect.protocols.base import (
    ConnTracker,
    DataStream,
    MessageType,
    ParseState,
    ProtocolParser,
)


def parser_registry():
    """name → ProtocolParser instance for every supported protocol."""
    from pixie_tpu.collect.protocols import (
        cql,
        dns,
        http,
        http2,
        kafka,
        mux,
        mysql,
        nats,
        pgsql,
        redis,
    )

    parsers = [
        http.HTTPParser(),
        http2.HTTP2Parser(),
        mysql.MySQLParser(),
        pgsql.PgSQLParser(),
        dns.DNSParser(),
        redis.RedisParser(),
        cql.CQLParser(),
        kafka.KafkaParser(),
        nats.NATSParser(),
        mux.MuxParser(),
    ]
    return {p.name: p for p in parsers}


__all__ = [
    "ConnTracker",
    "DataStream",
    "MessageType",
    "ParseState",
    "ProtocolParser",
    "parser_registry",
]

"""Canonical telemetry table schemas.

These are the public data contracts of the reference's collection layer — the
tables every bundled PxL script queries.  Column lists/types/semantic types are
transcribed from the reference's Stirling table definitions (cited per table);
they are wire-format facts, not code.

Used by: the script-parity tests (compile all bundled scripts), the collection
connectors that will eventually populate them, and schema introspection UDTFs.
"""
from __future__ import annotations

from pixie_tpu.types import DataType as DT, Relation, SemanticType as ST


def _rel(*cols) -> Relation:
    return Relation.of(*cols)


#: reference src/stirling/core/canonical_types.h + socket_tracer/canonical_types.h
_TIME = ("time_", DT.TIME64NS, ST.ST_TIME_NS)
_UPID = ("upid", DT.UINT128, ST.ST_UPID)
_REMOTE_ADDR = ("remote_addr", DT.STRING, ST.ST_IP_ADDRESS)
_REMOTE_PORT = ("remote_port", DT.INT64, ST.ST_PORT)
_TRACE_ROLE = ("trace_role", DT.INT64)
_LATENCY = ("latency", DT.INT64, ST.ST_DURATION_NS)


SCHEMAS: dict[str, Relation] = {
    # reference src/stirling/source_connectors/socket_tracer/http_table.h:41
    "http_events": _rel(
        _TIME, _UPID, _REMOTE_ADDR, _REMOTE_PORT, _TRACE_ROLE,
        ("major_version", DT.INT64),
        ("minor_version", DT.INT64),
        ("content_type", DT.INT64),
        ("req_headers", DT.STRING),
        ("req_method", DT.STRING, ST.ST_HTTP_REQ_METHOD),
        ("req_path", DT.STRING),
        ("req_body", DT.STRING),
        ("req_body_size", DT.INT64, ST.ST_BYTES),
        ("resp_headers", DT.STRING),
        ("resp_status", DT.INT64, ST.ST_HTTP_RESP_STATUS),
        ("resp_message", DT.STRING, ST.ST_HTTP_RESP_MESSAGE),
        ("resp_body", DT.STRING),
        ("resp_body_size", DT.INT64, ST.ST_BYTES),
        _LATENCY,
    ),
    # reference socket_tracer/conn_stats_table.h:29
    "conn_stats": _rel(
        _TIME, _UPID, _REMOTE_ADDR, _REMOTE_PORT, _TRACE_ROLE,
        ("addr_family", DT.INT64),
        ("protocol", DT.INT64),
        ("ssl", DT.BOOLEAN),
        ("conn_open", DT.INT64),
        ("conn_close", DT.INT64),
        ("conn_active", DT.INT64),
        ("bytes_sent", DT.INT64, ST.ST_BYTES),
        ("bytes_recv", DT.INT64, ST.ST_BYTES),
    ),
    # reference socket_tracer/mysql_table.h:37
    "mysql_events": _rel(
        _TIME, _UPID, _REMOTE_ADDR, _REMOTE_PORT, _TRACE_ROLE,
        ("req_cmd", DT.INT64),
        ("req_body", DT.STRING),
        ("resp_status", DT.INT64),
        ("resp_body", DT.STRING),
        _LATENCY,
    ),
    # reference socket_tracer/pgsql_table.h:29
    "pgsql_events": _rel(
        _TIME, _UPID, _REMOTE_ADDR, _REMOTE_PORT, _TRACE_ROLE,
        ("req_cmd", DT.STRING),
        ("req", DT.STRING),
        ("resp", DT.STRING),
        _LATENCY,
    ),
    # reference socket_tracer/redis_table.h:32
    "redis_events": _rel(
        _TIME, _UPID, _REMOTE_ADDR, _REMOTE_PORT, _TRACE_ROLE,
        ("req_cmd", DT.STRING),
        ("req_args", DT.STRING),
        ("resp", DT.STRING),
        _LATENCY,
    ),
    # reference socket_tracer/cass_table.h:37
    "cql_events": _rel(
        _TIME, _UPID, _REMOTE_ADDR, _REMOTE_PORT, _TRACE_ROLE,
        ("req_op", DT.INT64),
        ("req_body", DT.STRING),
        ("resp_op", DT.INT64),
        ("resp_body", DT.STRING),
        _LATENCY,
    ),
    # reference socket_tracer/dns_table.h:32
    "dns_events": _rel(
        _TIME, _UPID, _REMOTE_ADDR, _REMOTE_PORT, _TRACE_ROLE,
        ("req_header", DT.STRING),
        ("req_body", DT.STRING),
        ("resp_header", DT.STRING),
        ("resp_body", DT.STRING),
        _LATENCY,
    ),
    # reference socket_tracer/kafka_table.h:35
    "kafka_events.beta": _rel(
        _TIME, _UPID, _REMOTE_ADDR, _REMOTE_PORT, _TRACE_ROLE,
        ("req_cmd", DT.INT64),
        ("client_id", DT.STRING),
        ("req_body", DT.STRING),
        ("resp", DT.STRING),
        _LATENCY,
    ),
    # reference socket_tracer/nats_table.h:29
    "nats_events.beta": _rel(
        _TIME, _UPID, _REMOTE_ADDR, _REMOTE_PORT, _TRACE_ROLE,
        ("cmd", DT.STRING),
        ("body", DT.STRING),
        ("resp", DT.STRING),
    ),
    # reference socket_tracer/mux_table.h:32
    "mux_events": _rel(
        _TIME, _UPID, _REMOTE_ADDR, _REMOTE_PORT, _TRACE_ROLE,
        ("req_type", DT.INT64),
        _LATENCY,
    ),
    # reference source_connectors/process_stats/process_stats_table.h:38
    "process_stats": _rel(
        _TIME, _UPID,
        ("major_faults", DT.INT64),
        ("minor_faults", DT.INT64),
        ("cpu_utime_ns", DT.INT64, ST.ST_DURATION_NS),
        ("cpu_ktime_ns", DT.INT64, ST.ST_DURATION_NS),
        ("num_threads", DT.INT64),
        ("vsize_bytes", DT.INT64, ST.ST_BYTES),
        ("rss_bytes", DT.INT64, ST.ST_BYTES),
        ("rchar_bytes", DT.INT64, ST.ST_BYTES),
        ("wchar_bytes", DT.INT64, ST.ST_BYTES),
        ("read_bytes", DT.INT64, ST.ST_BYTES),
        ("write_bytes", DT.INT64, ST.ST_BYTES),
    ),
    # reference source_connectors/network_stats/network_stats_table.h:38
    "network_stats": _rel(
        _TIME,
        ("pod_id", DT.STRING),
        ("rx_bytes", DT.INT64, ST.ST_BYTES),
        ("rx_packets", DT.INT64),
        ("rx_errors", DT.INT64),
        ("rx_drops", DT.INT64),
        ("tx_bytes", DT.INT64, ST.ST_BYTES),
        ("tx_packets", DT.INT64),
        ("tx_errors", DT.INT64),
        ("tx_drops", DT.INT64),
    ),
    # reference source_connectors/jvm_stats/jvm_stats_table.h:36
    "jvm_stats": _rel(
        _TIME, _UPID,
        ("young_gc_time", DT.INT64, ST.ST_DURATION_NS),
        ("full_gc_time", DT.INT64, ST.ST_DURATION_NS),
        ("used_heap_size", DT.INT64, ST.ST_BYTES),
        ("total_heap_size", DT.INT64, ST.ST_BYTES),
        ("max_heap_size", DT.INT64, ST.ST_BYTES),
    ),
    # reference source_connectors/perf_profiler/stack_traces_table.h:31
    "stack_traces.beta": _rel(
        _TIME, _UPID,
        ("stack_trace_id", DT.INT64),
        ("stack_trace", DT.STRING),
        ("count", DT.INT64),
    ),
    # reference source_connectors/proc_exit/proc_exit_events_table.h:36
    "proc_exit_events": _rel(
        _TIME, _UPID,
        ("exit_code", DT.INT64),
        ("signal", DT.INT64),
        ("comm", DT.STRING),
    ),
    # TCP monitor tables.  The reference materializes these dynamically from
    # bpftrace programs embedded in px/tcp_drops/data.pxl:90 and
    # px/tcp_retransmits/data.pxl:92-93 (columns = the programs' printf
    # fields); this build declares them as canonical connector schemas so the
    # scripts run against a netlink//proc-based drops monitor or replayed
    # captures without a kernel probe.
    "tcp_drop_table": _rel(
        _TIME,
        ("pid", DT.INT64),
        ("pid_start_time", DT.INT64),
        ("src_ip", DT.STRING, ST.ST_IP_ADDRESS),
        ("src_port", DT.INT64, ST.ST_PORT),
        ("dst_ip", DT.STRING, ST.ST_IP_ADDRESS),
        ("dst_port", DT.INT64, ST.ST_PORT),
        ("state", DT.STRING),
    ),
    "tcp_retransmissions": _rel(
        _TIME,
        ("pid", DT.INT64),
        ("pid_start_time", DT.INT64),
        ("src_ip", DT.STRING, ST.ST_IP_ADDRESS),
        ("src_port", DT.INT64, ST.ST_PORT),
        ("dst_ip", DT.STRING, ST.ST_IP_ADDRESS),
        ("dst_port", DT.INT64, ST.ST_PORT),
        ("state", DT.STRING),
    ),
}


def _self_telemetry_schemas() -> dict[str, Relation]:
    # self-telemetry (pixie_tpu observing itself): trace spans of the query
    # path (pixie_tpu.trace) plus the query flight recorder's tables
    # (pixie_tpu.observe: per-query profiles, per-op stats, sampled
    # metrics, SLO alerts) — all written on agent stores through the
    # normal ingest path and queryable like any connector table
    from pixie_tpu.observe import SELF_TABLES
    from pixie_tpu.trace import SPANS_RELATION, SPANS_TABLE

    return {SPANS_TABLE: SPANS_RELATION, **SELF_TABLES}


def all_schemas() -> dict[str, Relation]:
    return {**SCHEMAS, **_self_telemetry_schemas()}

"""/proc scrapers: process + network stats connectors.

Reference: src/stirling/source_connectors/process_stats (1s cadence,
process_stats_connector.h) and network_stats — per-process CPU/memory and
per-interface traffic counters scraped from procfs.  No eBPF required, so
these run anywhere Linux does; they are the first REAL telemetry sources of
the TPU build (seq_gen/replay are synthetic).
"""
from __future__ import annotations

import os
import time

import numpy as np

from pixie_tpu.collect.core import SourceConnector, TableSpec, now_ns
from pixie_tpu.types import DataType as DT, Relation

_CLK_TCK = os.sysconf("SC_CLK_TCK") if hasattr(os, "sysconf") else 100
_PAGE = os.sysconf("SC_PAGE_SIZE") if hasattr(os, "sysconf") else 4096


class ProcessStatsConnector(SourceConnector):
    """Samples /proc/<pid>/stat for every visible process.

    Table process_stats: time_, pid, cmd, utime_ns, stime_ns, rss_bytes,
    vsize_bytes, num_threads (reference process_stats_connector.h table).
    """

    name = "process_stats"

    def __init__(self, sample_period_s: float = 1.0):
        self.sample_period_s = sample_period_s

    def tables(self) -> list[TableSpec]:
        return [
            TableSpec(
                "process_stats",
                Relation.of(
                    ("time_", DT.TIME64NS),
                    ("pid", DT.INT64),
                    ("cmd", DT.STRING),
                    ("utime_ns", DT.INT64),
                    ("stime_ns", DT.INT64),
                    ("rss_bytes", DT.INT64),
                    ("vsize_bytes", DT.INT64),
                    ("num_threads", DT.INT64),
                ),
                sample_period_s=self.sample_period_s,
            )
        ]

    def transfer_data(self) -> dict[str, dict]:
        rows = {k: [] for k in ("pid", "cmd", "utime_ns", "stime_ns",
                                "rss_bytes", "vsize_bytes", "num_threads")}
        for entry in os.listdir("/proc"):
            if not entry.isdigit():
                continue
            try:
                with open(f"/proc/{entry}/stat", "rb") as f:
                    raw = f.read().decode("ascii", "replace")
            except OSError:
                continue  # process exited between listdir and open
            # comm may contain spaces/parens: split around the LAST ')'.
            lp, rp = raw.find("("), raw.rfind(")")
            if lp < 0 or rp < 0:
                continue
            cmd = raw[lp + 1 : rp]
            fields = raw[rp + 2 :].split()
            # fields[0] is state; utime=11, stime=12, num_threads=17,
            # vsize=20, rss=21 (0-based within the post-comm fields).
            try:
                utime, stime = int(fields[11]), int(fields[12])
                nthreads = int(fields[17])
                vsize, rss = int(fields[20]), int(fields[21])
            except (IndexError, ValueError):
                continue
            rows["pid"].append(int(entry))
            rows["cmd"].append(cmd)
            rows["utime_ns"].append(utime * (1_000_000_000 // _CLK_TCK))
            rows["stime_ns"].append(stime * (1_000_000_000 // _CLK_TCK))
            rows["rss_bytes"].append(rss * _PAGE)
            rows["vsize_bytes"].append(vsize)
            rows["num_threads"].append(nthreads)
        n = len(rows["pid"])
        if n == 0:
            return {}
        out = {"time_": np.full(n, now_ns(), dtype=np.int64)}
        out.update(rows)
        return {"process_stats": out}


class NetworkStatsConnector(SourceConnector):
    """Samples /proc/net/dev per-interface counters.

    Table network_stats: time_, interface, rx_bytes, rx_packets, tx_bytes,
    tx_packets (reference network_stats_connector.h, 1s cadence).
    """

    name = "network_stats"

    def __init__(self, sample_period_s: float = 1.0):
        self.sample_period_s = sample_period_s

    def tables(self) -> list[TableSpec]:
        return [
            TableSpec(
                "network_stats",
                Relation.of(
                    ("time_", DT.TIME64NS),
                    ("interface", DT.STRING),
                    ("rx_bytes", DT.INT64),
                    ("rx_packets", DT.INT64),
                    ("tx_bytes", DT.INT64),
                    ("tx_packets", DT.INT64),
                ),
                sample_period_s=self.sample_period_s,
            )
        ]

    def transfer_data(self) -> dict[str, dict]:
        try:
            with open("/proc/net/dev", "r") as f:
                lines = f.readlines()[2:]  # skip 2 header lines
        except OSError:
            return {}
        rows = {k: [] for k in ("interface", "rx_bytes", "rx_packets",
                                "tx_bytes", "tx_packets")}
        for line in lines:
            if ":" not in line:
                continue
            iface, rest = line.split(":", 1)
            f = rest.split()
            if len(f) < 12:
                continue
            rows["interface"].append(iface.strip())
            rows["rx_bytes"].append(int(f[0]))
            rows["rx_packets"].append(int(f[1]))
            rows["tx_bytes"].append(int(f[8]))
            rows["tx_packets"].append(int(f[9]))
        n = len(rows["interface"])
        if n == 0:
            return {}
        out = {"time_": np.full(n, now_ns(), dtype=np.int64)}
        out.update(rows)
        return {"network_stats": out}

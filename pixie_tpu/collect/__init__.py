"""Collection layer: source connectors + poll-loop runtime (Stirling analog)."""
from pixie_tpu.collect.core import (
    Collector,
    FrequencyManager,
    SourceConnector,
    TableSpec,
)
from pixie_tpu.collect.proc_stats import NetworkStatsConnector, ProcessStatsConnector
from pixie_tpu.collect.replay import ReplayConnector
from pixie_tpu.collect.seq_gen import SeqGenConnector

__all__ = [
    "Collector",
    "FrequencyManager",
    "SourceConnector",
    "TableSpec",
    "SeqGenConnector",
    "ReplayConnector",
    "ProcessStatsConnector",
    "NetworkStatsConnector",
]
